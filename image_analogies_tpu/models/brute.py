"""Brute-force exact nearest-neighbor matcher (SURVEY.md §2 C7).

The reference's brute-force NN is a NumPy full-distance scan
[BASELINE.json config 1 "brute-force NN"].  The TPU formulation turns it
into tiled MXU matmuls:

    ||b - a||^2 = ||b||^2 - 2 b.a^T + ||a||^2

so the hot loop is one (chunk, D) x (D, N_A) contraction per query chunk —
exactly what the systolic array wants — followed by an argmin reduction.
Queries are processed in chunks of `cfg.brute_chunk` rows via `lax.map`, so
peak HBM for the distance tile is chunk * N_A * 4 bytes regardless of image
size.

This matcher is the correctness oracle: the "CPU ref" of the north-star
PSNR metric [BASELINE.json:2] is this exact path run on the CPU backend
(SURVEY.md §6).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import Matcher, flat_to_nnf, register_matcher


def exact_nn(
    f_b_flat: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    chunk: int,
    match_dtype=jnp.float32,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact argmin_{p} ||f_b[q] - f_a[p]||^2 for every query row.

    Returns (idx (N,), dist (N,)).  `dist` is recomputed exactly (float32,
    direct subtraction) for the winning index so downstream accept tests
    (coherence kappa rule) see the same metric as `candidate_dist`, immune
    to the matmul expansion's cancellation error.
    """
    n = f_b_flat.shape[0]
    fa = f_a_flat.astype(match_dtype)
    a_sq = jnp.sum(
        f_a_flat.astype(jnp.float32) * f_a_flat.astype(jnp.float32), axis=-1
    )

    n_pad = (-n) % chunk
    fb_padded = jnp.pad(f_b_flat, ((0, n_pad), (0, 0)))
    fb_chunks = fb_padded.reshape(-1, chunk, f_b_flat.shape[-1])

    def one_chunk(fb):
        # (chunk, D) x (D, N_A) on the MXU; f32 accumulation.
        cross = jax.lax.dot_general(
            fb.astype(match_dtype),
            fa,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = a_sq[None, :] - 2.0 * cross  # ||b||^2 constant per row: skip
        return jnp.argmin(d, axis=-1)

    idx = jax.lax.map(one_chunk, fb_chunks).reshape(-1)[:n]
    # Winner re-rank in f32 regardless of table dtype: with bf16 lean
    # tables (lean_brute_em_step) a same-dtype subtract/sum would
    # accumulate the distance itself in bf16, while the Pallas twin
    # (nn_brute.exact_nn_pallas) re-ranks in f32 — the two backends
    # must stay interchangeable oracles.  Chunked like the Pallas
    # twin's re-rank so the gathered-rows + upcast temps peak at
    # ~512 MB instead of 2x a full-table f32 copy (the lean-brute
    # fallback hands giant bf16 tables through here).
    d_feat = f_b_flat.shape[1]
    rerank_rows = max(1, (256 << 20) // max(1, d_feat * 4))
    dists = []
    for c in range(0, n, rerank_rows):
        sl = idx[c : c + rerank_rows]
        rows = jnp.take(f_a_flat, sl, axis=0).astype(jnp.float32)
        diff = f_b_flat[c : c + rerank_rows].astype(jnp.float32) - rows
        dists.append(jnp.sum(diff * diff, axis=-1))
    dist = dists[0] if len(dists) == 1 else jnp.concatenate(dists, axis=0)
    return idx, dist


class BruteForceMatcher(Matcher):
    """Exact NN; streaming Pallas kernel on TPU, chunked XLA twin on CPU."""

    name = "brute"

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig,
              raw=None, polish_iters=None, temporal=None):
        from ..kernels import resolve_pallas
        from ..kernels.nn_brute import exact_nn_pallas

        h, w, d = f_b.shape
        ha, wa = f_a.shape[:2]
        match_dtype = jnp.dtype(cfg.match_dtype)
        interpret = resolve_pallas(cfg)
        if interpret is None:
            idx, dist = exact_nn(
                f_b.reshape(-1, d),
                f_a.reshape(-1, d),
                chunk=min(cfg.brute_chunk, h * w),
                match_dtype=match_dtype,
            )
        else:
            idx, dist = exact_nn_pallas(
                f_b.reshape(-1, d),
                f_a.reshape(-1, d),
                match_dtype=match_dtype,
                interpret=interpret,
            )
        return flat_to_nnf(idx, wa, (h, w)), dist.reshape(h, w)


register_matcher("brute", BruteForceMatcher())
