"""Coarse-to-fine analogy synthesis driver (SURVEY.md §2 C11, §3.1).

Implements `create_image_analogy(A, A', B) -> B'` — the reference's main
entry point [Hertzmann Fig. 1].  The pyramid loop stays a thin Python
driver (5 levels => negligible host overhead [north star]); each EM step at
a level is one jitted function (feature assembly + matcher sweeps + B'
recomposition), so the per-pixel hot loop of the reference becomes a
handful of whole-image compiled calls per level (SURVEY.md §3 hot loops).

TPU reformulation of the scan-order loop (SURVEY.md §7 "hard parts"):
instead of synthesizing B' pixel-by-pixel with causal windows, each level
alternates
    1. match:  NN-field from full-window features of the current B',
    2. render: B'(q) <- A'(s(q)),
for `em_iters` rounds (an EM fixed point).  Coherence enters through the
matcher (fused propagation candidates / the kappa rule).  The s-map is
upsampled between levels with doubled offsets, exactly the reference's
s(q) bookkeeping.

Luminance-only transfer (C12): matching runs on Y (optionally + steerable
responses of Y); chroma is copied from B at the end (Hertzmann §3.4).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig
from ..telemetry.metrics import get_registry
from ..telemetry.spans import as_tracer
from ..ops.color import luminance, rgb_to_yiq, yiq_to_rgb
from ..ops.features import assemble_features
from ..ops.pca import fit_and_project as pca_fit_and_project, project as pca_project
from ..ops.pyramid import build_pyramid, upsample
from ..ops.remap import remap_luminance
from ..ops.steerable import steerable_responses
from .matcher import clamp_nnf, get_matcher
from .patchmatch import random_init

# Ensure built-in matchers are registered on import.
from . import brute as _brute  # noqa: F401
from . import coherence as _coherence  # noqa: F401
from . import patchmatch as _patchmatch  # noqa: F401
from . import ann as _ann  # noqa: F401


def _with_steerable(y: jnp.ndarray, cfg: SynthConfig) -> jnp.ndarray:
    """Source-side match channels: luminance (+ steerable bank of Y).

    Steerable responses augment the *unfiltered* images only (Hertzmann
    §3.1); filtered images (A', B') match on raw intensity so the evolving
    B' estimate never needs its filter bank recomputed mid-EM.
    """
    if not cfg.steerable:
        return y
    # In rgb mode the oriented filters still run on luminance — responses
    # are contrast features, not per-channel ones (Hertzmann §3.1).
    resp = steerable_responses(luminance(y), cfg.n_orientations)
    if y.ndim == 2:
        y = y[..., jnp.newaxis]
    return jnp.concatenate([y, resp], axis=-1)


def _gather_image(img: jnp.ndarray, nnf: jnp.ndarray) -> jnp.ndarray:
    """B'(q) = img(s(q)): row-gather of copy channels at the match field."""
    ha, wa = img.shape[:2]
    flat = img.reshape(ha * wa, -1)
    idx = nnf[..., 0] * wa + nnf[..., 1]
    out = jnp.take(flat, idx.reshape(-1), axis=0)
    out = out.reshape(*nnf.shape[:2], -1)
    return out[..., 0] if img.ndim == 2 else out


def upsample_nnf(nnf: jnp.ndarray, target_shape, ha: int, wa: int) -> jnp.ndarray:
    """s-map to the next finer level: parent offsets doubled + child parity
    (SURVEY.md §3.1 'upsample s_l -> init s_{l-1}')."""
    h, w = target_shape
    up = jnp.repeat(jnp.repeat(nnf, 2, axis=0), 2, axis=1)[:h, :w] * 2
    py = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0) % 2
    px = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1) % 2
    up = up + jnp.stack([py, px], axis=-1)
    return clamp_nnf(up, ha, wa)


def upsample_nnf_planes(py, px, target_shape, ha: int, wa: int):
    """`upsample_nnf` for the lean plane-pair field: same doubling +
    child parity, per (H, W) plane — a stacked (H, W, 2) int32 array
    pads its trailing dim 2 -> 128 lanes on TPU (8 GB at 4096^2), so
    lean levels never stack the field (patchmatch_sweeps_lean)."""
    h, w = target_shape
    uy = jnp.repeat(jnp.repeat(py, 2, axis=0), 2, axis=1)[:h, :w] * 2
    ux = jnp.repeat(jnp.repeat(px, 2, axis=0), 2, axis=1)[:h, :w] * 2
    uy = uy + jax.lax.broadcasted_iota(jnp.int32, (h, w), 0) % 2
    ux = ux + jax.lax.broadcasted_iota(jnp.int32, (h, w), 1) % 2
    return jnp.clip(uy, 0, ha - 1), jnp.clip(ux, 0, wa - 1)


def random_init_planes(key: jax.Array, h: int, w: int, ha: int, wa: int):
    """`random_init` returning separate (H, W) planes — the lean field
    representation — without ever materializing the stacked (H, W, 2)
    array (whose 2 -> 128 lane pad is multi-GB at 4096^2)."""
    ky, kx = jax.random.split(key)
    return (
        jax.random.randint(ky, (h, w), 0, ha),
        jax.random.randint(kx, (h, w), 0, wa),
    )


def _level_state_glue(lean: bool, prev_kind: str, prev_nnf, prev_bp,
                      raw_b_l, h: int, w: int, ha: int, wa: int, init_key,
                      *, batched: bool = False):
    """Incoming-state glue for one level: upsample the coarser level's
    (nnf, B') into this level's frame, or draw the coarsest level's
    random-init field.  Shared verbatim by the single-image level body
    (`_level_fn_cached`) and the batch level body
    (`parallel/batch._batch_level_fn_cached`): `batched=True` lifts
    every per-frame op with jax.vmap and `init_key` is then the
    per-frame key stack.  Returns (nnf, flt_bp, flt_bp_coarse).

    ADVICE r2: at a lean coarsest level the stacked (H, W, 2) init
    would materialize the exact lane-padded allocation the lean
    representation avoids — draw the planes directly (bit-identical
    streams: same key split, same shapes).

    prev_kind "direct" (video subsystem): the incoming state is a
    SAME-RESOLUTION converged field — the previous frame's field at
    THIS level — so it seeds this level verbatim (clamped) instead of
    being upsampled; B' starts from prev_bp at this resolution.  At a
    non-coarsest level prev_bp is the tuple (bp_fine, bp_coarse) — the
    previous frame's converged B' at this level and the one below —
    because the EM features consume the coarse plane at its own
    resolution.  Only the video driver requests "direct" (plan_level
    never produces it)."""
    vm = jax.vmap if batched else (lambda f: f)
    if prev_kind == "direct":
        if lean:
            p_py, p_px = (
                prev_nnf if isinstance(prev_nnf, tuple)
                else (prev_nnf[..., 0], prev_nnf[..., 1])
            )
            nnf = (
                vm(lambda p: jnp.clip(p, 0, ha - 1))(p_py),
                vm(lambda p: jnp.clip(p, 0, wa - 1))(p_px),
            )
        else:
            from .matcher import clamp_nnf

            nnf = vm(lambda n: clamp_nnf(n, ha, wa))(prev_nnf)
        if isinstance(prev_bp, tuple):
            flt_bp, flt_bp_coarse = prev_bp
        else:
            flt_bp = flt_bp_coarse = prev_bp
        return nnf, flt_bp, flt_bp_coarse
    if prev_kind != "none":
        if lean:
            p_py, p_px = (
                prev_nnf if prev_kind == "planes"
                else (prev_nnf[..., 0], prev_nnf[..., 1])
            )
            nnf = vm(
                lambda py, px: upsample_nnf_planes(py, px, (h, w), ha, wa)
            )(p_py, p_px)
        elif prev_kind == "planes":
            def stack_up(py, px):
                uy, ux = upsample_nnf_planes(py, px, (h, w), ha, wa)
                return jnp.stack([uy, ux], axis=-1)

            nnf = vm(stack_up)(prev_nnf[0], prev_nnf[1])
        else:
            nnf = vm(lambda n: upsample_nnf(n, (h, w), ha, wa))(prev_nnf)
        flt_bp_coarse = prev_bp
        flt_bp = vm(lambda x: upsample(x, (h, w)))(prev_bp)
    else:
        init = random_init_planes if lean else random_init
        nnf = vm(lambda k: init(k, h, w, ha, wa))(init_key)
        flt_bp = raw_b_l
        flt_bp_coarse = flt_bp
    return nnf, flt_bp, flt_bp_coarse


def lean_em_step(cfg: SynthConfig, level: int, has_coarse: bool,
                 polish_iters, src_b, flt_b, src_b_c, flt_b_c, f_a,
                 copy_a, nnf, key, a_planes, *, interpret: bool,
                 dist_fn=None, bounds=None, sweep_merge=None):
    """One lean EM step: chunk-assembled bf16 B table, plane-pair
    field, kernel + exact-metric merge + polish, gather render.

    The SINGLE body behind both the single-device lean path
    (make_em_step's lean closure) and the band-sharded-A runner
    (parallel/sharded_a.py), which passes the three sharded hooks
    through to tile_patchmatch_lean — the sharded runner's bit-identity
    contract holds precisely because the ops live here once.

    In lean steps the `f_a` slot carries the (Na, D) bf16 A-side table
    (assemble_features_lean; the sharded runner passes its band's
    slice) and the `nnf` slot a (py, px) plane pair; `a_planes` is the
    kernel A-plane band tuple.
    """
    from ..kernels.patchmatch_tile import plan_channels
    from .patchmatch import RawPlanes, tile_patchmatch_lean

    py, px = nnf
    h, w = src_b.shape[:2]
    ha, wa = copy_a.shape[:2]
    n_src = 1 if src_b.ndim == 2 else src_b.shape[-1]
    n_flt = 1 if flt_b.ndim == 2 else flt_b.shape[-1]
    plan = plan_channels(n_src, n_flt, cfg, has_coarse, h, w, ha, wa)
    with jax.named_scope("tlm_assemble"):
        f_b_tab = assemble_features_lean(
            src_b,
            flt_b,
            cfg,
            src_b_c if has_coarse else None,
            flt_b_c if has_coarse else None,
        )
    raw = RawPlanes(
        src_b,
        flt_b,
        src_b_c if has_coarse else None,
        flt_b_c if has_coarse else None,
        a_planes,
    )
    if dist_fn is not None:
        dist_fn = dist_fn(f_b_tab)
    with jax.named_scope("tlm_match"):
        py, px, dist = tile_patchmatch_lean(
            f_b_tab, f_a, py, px, key, raw=raw, cfg=cfg, level=level,
            interpret=interpret, plan=plan,
            ha=ha, wa=wa, polish_iters=polish_iters,
            dist_fn=dist_fn, bounds=bounds, sweep_merge=sweep_merge,
        )
    with jax.named_scope("tlm_render"):
        flat = copy_a.reshape(ha * wa, -1)
        out = jnp.take(
            flat, (py * wa + px).reshape(-1), axis=0
        ).reshape(h, w, -1)
        bp = out[..., 0] if copy_a.ndim == 2 else out
    return (py, px), dist, bp


# Max lane-padded bf16 B-band table co-resident with the A table in the
# lean-brute oracle (see lean_brute_em_step "B-side row banding"): 2 GiB
# puts the 4096^2 oracle at 4 bands of ~1.07 GB next to the 4.3 GB A
# table — the measured-survivable regime; <= 2048^2 single-bands (their
# oracles use the standard f32 path anyway).
_B_BAND_TABLE_BYTES = 2 * 1024**3


def lean_brute_em_step(cfg: SynthConfig, level: int, has_coarse: bool,
                       src_b, flt_b, src_b_c, flt_b_c, f_a_tab, copy_a,
                       nnf, key):
    """One exact-NN EM step on lean bf16 tables (plane-pair field).

    The brute matcher is the PSNR oracle (SURVEY.md §6), and round 3/4
    capped its full-synthesis runs at 2048^2: the standard path's two
    lane-padded f32 tables are 17.2 GB at 4096^2 against 16 GB of HBM.
    This step is the scale-robust oracle: both tables assembled
    chunk-wise into bf16 (4.3 GB each at 4096^2) and searched EXACTLY
    with the streaming kernel — exact argmin over bf16-quantized
    features with f32 accumulation and f32 winner re-rank, the same
    metric the lean patchmatch path matches in at these sizes.  Driver
    selection: `_feature_table_bytes > cfg.brute_lean_bytes`; such
    levels also run unfused (`_SAFE_EXEC_DIST_ELEMS`), so each query
    chunk of the search is its own device execution and no execution
    outlives the worker's kill boundary (kernels/nn_brute.py
    _MAX_TILE_ELEMS).

    Giant-A tile choice: the kernel's A-side traffic is
    (N_B/tq) * |A|, so calls against a >= 1M-row database use the
    largest compiling query tile, (tq=2048, ta=256) — the measured
    scoped-VMEM ceiling (see exact_nn_pallas; same tiles as the
    recorded 2048^2 oracle, SCALE_r04).

    B-side row banding (`_B_BAND_TABLE_BYTES`): co-hosting BOTH full
    lane-padded tables (2 x 4.3 GB at 4096^2) next to the pipeline's
    other residents exceeded what the worker actually grants — the
    round-4 oracle died of RESOURCE_EXHAUSTED twice, once at a 268 MB
    a_sq chunk, i.e. the pool was already spent.  The B table is
    therefore assembled and searched in row bands: only the A table
    stays resident; each band's table is assembled from a generously
    row-sliced input (window reach covered by `slab_halo` rows, edge
    clamping identical to full assembly because slices at the image
    boundary ARE the boundary), core rows trimmed, searched, freed.
    Bit-identical to the unbanded search (exact NN is per-query;
    banding cannot change any row's features or argmin — tested with
    a forced-tiny band budget).
    """
    from ..kernels import resolve_pallas
    from ..kernels.nn_brute import exact_nn_pallas
    from ..parallel.spatial import slab_halo

    h, w = src_b.shape[:2]
    ha, wa = copy_a.shape[:2]
    interpret = resolve_pallas(cfg)

    n_src = 1 if src_b.ndim == 2 else src_b.shape[-1]
    n_flt = 1 if flt_b.ndim == 2 else flt_b.shape[-1]
    d_feat = (n_src + n_flt) * cfg.patch_size**2
    if has_coarse:
        d_feat += (n_src + n_flt) * cfg.coarse_patch_size**2
    row_bytes = (-(-d_feat // 128)) * 128 * 2  # padded bf16 row
    n_b = 1
    while (
        # '>=': at exactly 4096^2 defaults the estimate is exactly
        # 4 GiB, and a strict '>' would stop at 2 GiB bands — whose
        # trim transient co-hosts ~2x that next to the A table, the
        # unmeasured regime this loop exists to avoid.
        h * w * row_bytes // n_b >= _B_BAND_TABLE_BYTES
        and h % (n_b * 2) == 0
        and (h // (n_b * 2)) % 2 == 0
    ):
        n_b *= 2
    band_rows = h // n_b
    halo = slab_halo(cfg)

    def band_table(r0, r1):
        """bf16 lane-padded feature rows for B rows [r0, r1)."""
        lo = max(r0 - halo, 0)
        hi = min(r1 + halo, h)
        tab = assemble_features_lean(
            src_b[lo:hi],
            flt_b[lo:hi],
            cfg,
            src_b_c[lo // 2 : -(-hi // 2)] if has_coarse else None,
            flt_b_c[lo // 2 : -(-hi // 2)] if has_coarse else None,
            pad_lanes=True,
        )
        if lo == 0 and hi == h:
            return tab
        start = (r0 - lo) * w
        return jax.lax.slice(
            tab, (start, 0), (start + (r1 - r0) * w, tab.shape[1])
        )

    def search(tab):
        if interpret is None:
            from .brute import exact_nn

            return exact_nn(
                tab,
                f_a_tab,
                chunk=min(cfg.brute_chunk, tab.shape[0]),
                match_dtype=_LEAN_TABLE_DTYPE,
            )
        tiles = (
            dict(tq=2048, ta=256)
            if f_a_tab.shape[0] >= (1 << 20)
            else {}
        )
        return exact_nn_pallas(
            tab,
            f_a_tab,
            match_dtype=_LEAN_TABLE_DTYPE,
            interpret=interpret,
            **tiles,
        )

    def _drain(x):
        """Scalar-readback barrier between the eager oracle path's big
        executions: the axon tunnel wedges when many large executions
        queue async (round-5 wedge hunt, tools/full_oracle.py
        beat_chunk) — the search chunks are synced by the oracle's
        heartbeat hook, but the inter-phase work (band assembly,
        concat, render) must not pile up either.  Walls don't matter
        on this path (it exists for the exact oracle, not production
        synthesis), so the lost overlap is free correctness.

        Under a FUSED lean-brute level (small distance work,
        plan.fuse=True) this body runs inside jit where a readback is
        both impossible (tracer) and meaningless (one execution) — so
        tracers pass through."""
        if not isinstance(x, jax.core.Tracer):
            float(jnp.asarray(x).ravel()[0])  # readback: the reliable
        return x                              # barrier on this platform

    if n_b == 1:
        idx, dist = search(_drain(band_table(0, h)))
    else:
        idx_parts, dist_parts = [], []
        for i in range(n_b):
            idx_i, dist_i = search(
                _drain(band_table(i * band_rows, (i + 1) * band_rows))
            )
            idx_parts.append(idx_i)
            dist_parts.append(dist_i)
        idx = jnp.concatenate(idx_parts, axis=0)
        dist = jnp.concatenate(dist_parts, axis=0)
    _drain(idx)
    py = (idx // wa).reshape(h, w)
    px = (idx % wa).reshape(h, w)
    dist = dist.reshape(h, w)
    if cfg.kappa > 0.0:
        # The registered 'brute' matcher is CoherenceWrapper(brute)
        # (models/coherence.py): kappa>0 runs Ashikhmin adoption
        # sweeps after the exact search.  The lean oracle keeps the
        # same semantics on the plane-pair field — same rule, same
        # sweep count, distances in the lean bf16 metric the exact
        # search itself re-ranked in (candidate_dist_lean: bf16 rows,
        # f32 accumulation).  The adoption pass gathers B rows for
        # every query, so it needs one full-height B table: assembled
        # NARROW (no lane pad — physically ~half the padded table) to
        # stay within the banded path's memory ceiling.
        from .coherence import coherence_sweeps_lean
        from .matcher import candidate_dist_lean
        from .patchmatch import kappa_factor

        f_b_coh = assemble_features_lean(
            src_b,
            flt_b,
            cfg,
            src_b_c if has_coarse else None,
            flt_b_c if has_coarse else None,
        )
        py, px, dist = coherence_sweeps_lean(
            py, px, dist, ha=ha, wa=wa,
            factor=kappa_factor(cfg.kappa, level),
            sweeps=2,
            dist_fn=lambda i: candidate_dist_lean(f_b_coh, f_a_tab, i),
        )
        idx = (py * wa + px).reshape(-1)
    flat = copy_a.reshape(ha * wa, -1)
    out = jnp.take(flat, idx, axis=0).reshape(h, w, -1)
    bp = out[..., 0] if copy_a.ndim == 2 else out
    return (py, px), dist, bp


def make_em_step(cfg: SynthConfig, level: int, has_coarse: bool,
                 lean: bool = False, polish_iters=None):
    """One EM step at one pyramid level: features -> match -> render.

    `polish_iters` overrides cfg.pm_polish_iters for the matcher's
    per-pixel polish (the level loop passes 0 on non-final EM
    iterations when cfg.pm_polish_final_only — see config.py for the
    measured rationale).

    Pure function of its array arguments (vmap-able over a frame axis for
    the batched runner, SURVEY.md C15).  With `cfg.pca_dims`, `f_a` is
    the already-projected database and `proj` the (D, k) basis applied to
    the B-side features in-step (Hertzmann §3.1 PCA).

    `lean=True` (driver-selected for levels whose ROW-MAJOR feature
    tables would not fit HBM, see `_feature_table_bytes`): feature
    tables are assembled chunk-wise into bf16 form
    (`assemble_features_lean` — the f_a slot carries the A-side table)
    and distance evaluations are chunked, so the output contract
    matches the standard kernel path up to bf16 quantization.
    """
    matcher = get_matcher(cfg.matcher)

    if lean:
        if cfg.matcher == "brute":
            def em_step_lean_brute(src_b, flt_b, src_b_c, flt_b_c, f_a,
                                   copy_a, nnf, key, proj=None,
                                   a_planes=None, temporal=None):
                return lean_brute_em_step(
                    cfg, level, has_coarse,
                    src_b, flt_b, src_b_c, flt_b_c, f_a, copy_a, nnf, key,
                )

            return em_step_lean_brute

        from ..kernels import resolve_pallas

        def em_step_lean(src_b, flt_b, src_b_c, flt_b_c, f_a, copy_a, nnf,
                         key, proj=None, a_planes=None, temporal=None):
            return lean_em_step(
                cfg, level, has_coarse, polish_iters,
                src_b, flt_b, src_b_c, flt_b_c, f_a, copy_a, nnf, key,
                a_planes, interpret=bool(resolve_pallas(cfg)),
            )

        return em_step_lean

    def em_step(src_b, flt_b, src_b_c, flt_b_c, f_a, copy_a, nnf, key,
                proj=None, a_planes=None, temporal=None):
        # tlm_* named scopes: trace-time-only phase tags that thread
        # through to profiler op names, which is how the run report
        # attributes device time to matcher phases
        # (telemetry/report.py via xplane.device_scope_totals).
        with jax.named_scope("tlm_assemble"):
            f_b = assemble_features(
                src_b,
                flt_b,
                cfg,
                src_b_c if has_coarse else None,
                flt_b_c if has_coarse else None,
            )
            if cfg.pca_dims:
                f_b = pca_project(f_b, proj)
        raw = None
        if a_planes is not None:
            from .patchmatch import RawPlanes

            raw = RawPlanes(
                src_b,
                flt_b,
                src_b_c if has_coarse else None,
                flt_b_c if has_coarse else None,
                a_planes,
            )
        with jax.named_scope("tlm_match"):
            nnf, dist = matcher.match(
                f_b, f_a, nnf, key=key, level=level, cfg=cfg, raw=raw,
                polish_iters=polish_iters, temporal=temporal,
            )
        with jax.named_scope("tlm_render"):
            bp = _gather_image(copy_a, nnf)
        return nnf, dist, bp

    return em_step


@functools.lru_cache(maxsize=64)
def _em_step_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                lean: bool = False):
    """Compiled EM step for one pyramid level (cached per config+level)."""
    return jax.jit(make_em_step(cfg, level, has_coarse, lean))


def _strip_noncompute(cfg: SynthConfig) -> SynthConfig:
    """Drop knobs that don't shape the compiled computation from a cfg
    used as a jit-cache key (parallel/batch.py does the same): two runs
    differing only in the checkpoint directory must share compilations."""
    import dataclasses

    return dataclasses.replace(cfg, save_level_artifacts=None)


def _prologue_fn(cfg: SynthConfig, levels: int):
    return _prologue_fn_cached(_strip_noncompute(cfg), levels)


@functools.lru_cache(maxsize=32)
def _prologue_fn_cached(cfg: SynthConfig, levels: int):
    """Whole run prologue as ONE compiled call: channel resolve +
    luminance remap + every pyramid + steerable banks.

    Dispatched eagerly this is ~200 separate device calls; on the
    tunnelled axon platform that cost ~0.9 s of the round-2 headline
    wall (tools/profile_phases.py) against ~50 ms of actual device work.
    """

    def prologue(a, ap, b):
        # tlm_prologue: device-attribution tag (telemetry/report.py).
        with jax.named_scope("tlm_prologue"):
            src_a, flt_a, src_b, copy_a, yiq_b = _resolve_channels(
                a, ap, b, cfg
            )
            pyr_src_a = tuple(
                _with_steerable(x, cfg) for x in build_pyramid(src_a, levels)
            )
            pyr_flt_a = tuple(build_pyramid(flt_a, levels))
            pyr_src_b = tuple(
                _with_steerable(x, cfg) for x in build_pyramid(src_b, levels)
            )
            pyr_copy_a = tuple(build_pyramid(copy_a, levels))
            pyr_raw_b = tuple(build_pyramid(src_b, levels))
        return pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, pyr_raw_b, yiq_b

    return jax.jit(prologue)


def _level_plan(cfg: SynthConfig, src_a_l, flt_a_l, has_coarse: bool,
                h: int, w: int):
    """The ONE kernel-dispatch decision: the channel/band plan for this
    level, or None when the Pallas tile kernel will not engage
    (non-patchmatch matcher, pallas resolved off, or no plan fits).
    Every runner (single, batch, spatial) and the fused level function
    derive eligibility from here so the rule cannot drift between
    call sites."""
    if cfg.matcher != "patchmatch":
        return None
    from ..kernels import resolve_pallas

    if resolve_pallas(cfg) is None:
        return None
    from ..kernels.patchmatch_tile import plan_channels

    n_src = 1 if src_a_l.ndim == 2 else src_a_l.shape[-1]
    n_flt = 1 if flt_a_l.ndim == 2 else flt_a_l.shape[-1]
    ha, wa = src_a_l.shape[:2]
    plan = plan_channels(n_src, n_flt, cfg, has_coarse, h, w, ha, wa)
    if plan is not None:
        _warn_kernel_noop_knobs(cfg)
    return plan


def _kernel_eligible(cfg: SynthConfig, src_a_l, flt_a_l, has_coarse: bool,
                     h: int, w: int) -> bool:
    return _level_plan(cfg, src_a_l, flt_a_l, has_coarse, h, w) is not None


_warned_kernel_noop = False


def _warn_kernel_noop_knobs(cfg: SynthConfig) -> None:
    """ADVICE r2: `pm_random_candidates` only tunes the XLA-path sweeps;
    the Pallas kernel's candidate budget is static (K_LOCAL/K_GLOBAL).
    Tuning it at kernel-eligible sizes silently changes nothing, so say
    so once instead of leaving the fact buried in a config comment."""
    global _warned_kernel_noop
    if _warned_kernel_noop:
        return
    default = type(cfg)().pm_random_candidates
    if cfg.pm_random_candidates != default:
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "pm_random_candidates=%d has no effect on the Pallas kernel "
            "path (static K_LOCAL/K_GLOBAL budget); it only tunes "
            "XLA-path sweeps.  Kernel-path search is tuned by pm_iters "
            "and the polish by pm_polish_iters/pm_polish_random.",
            cfg.pm_random_candidates,
        )
        _warned_kernel_noop = True


# Standard-path levels whose single f32 feature table exceeds this run
# the A-side assembly as its OWN jit call (round-2 staging): fusing it
# into the level graph makes XLA hold the A assembly's layout-padded
# temps (fine-res coarse blocks pad 14x) concurrently with both EM
# steps' — measured 20 GB of HLO temp at 2048^2 against 15.75 GB of
# HBM.  Split, the temps die with the assembly call.
_SPLIT_ASSEMBLY_BYTES = 1536 * 1024**2


def _fa_external(ha: int, wa: int, lean: bool) -> bool:
    return not lean and ha * wa * 128 * 4 > _SPLIT_ASSEMBLY_BYTES


def _assemble_fa_fn(cfg: SynthConfig, has_coarse: bool):
    return _assemble_fa_fn_cached(_strip_noncompute(cfg), has_coarse)


@functools.lru_cache(maxsize=32)
def _assemble_fa_fn_cached(cfg: SynthConfig, has_coarse: bool):
    """Standalone compiled A-side feature assembly (+PCA) for levels
    where `_fa_external` splits it out of the fused level graph."""

    def assemble(src_a_l, flt_a_l, src_a_c, flt_a_c):
        f_a = assemble_features(src_a_l, flt_a_l, cfg, src_a_c, flt_a_c)
        return pca_fit_and_project(f_a, cfg.pca_dims)

    return jax.jit(assemble)


# Per-execution distance-work ceiling for a FUSED brute level
# (em_iters * N_B * N_A distance elements in one jit execution).  The
# axon TPU worker kills executions past ~100 s (kernels/nn_brute.py
# _MAX_TILE_ELEMS); the fused 1024^2 oracle level (2.2e12 elements,
# ~50 s) is measured-safe, the 2048^2 one (35e12) is far past the
# boundary.  Brute levels above this run the SAME level function
# eagerly: every jnp op and each `exact_nn_pallas` query chunk
# dispatches as its own execution, so no single execution outgrows the
# safe regime.  Walls don't matter on this path — it exists for the
# full-synthesis exact oracle at >= 2048^2 (SCALE_r04), not for
# production synthesis (patchmatch lean covers that).
_SAFE_EXEC_DIST_ELEMS = 2_400_000_000_000


@dataclasses.dataclass(frozen=True)
class LevelPlan:
    """Per-level dispatch plan — the ONE place the level-loop glue
    decisions live (round-5; previously hand-mirrored across the four
    runners with 'must be mirrored' maintenance notes).

    lean:        assemble bf16 chunked tables / plane-pair field instead
                 of the standard f32 tables (the decision must precede
                 assembly — assembly is what OOMs).
    prev_kind:   static layout of the incoming coarser-level NN field
                 ('none' | 'stacked' | 'planes').
    fa_external: A-side features assembled by the standalone
                 `_assemble_fa_fn` jit instead of fused into the level
                 graph (`_SPLIT_ASSEMBLY_BYTES`).
    fuse:        level runs as one jitted call; False = oversized brute
                 levels dispatch eagerly so no single execution outlives
                 the TPU worker's kill boundary (`_SAFE_EXEC_DIST_ELEMS`).
    """

    lean: bool
    prev_kind: str
    fa_external: bool
    fuse: bool


def plan_level(cfg: SynthConfig, level: int, src_a_l, flt_a_l,
               has_coarse: bool, h: int, w: int, *, prev_nnf=None,
               eligible_shape=None, table_bytes=None, work_scale: int = 1,
               brute_lean: bool = True) -> LevelPlan:
    """Compute the `LevelPlan` for one pyramid level.

    Shared by all four runners (single `create_image_analogy`, batch
    `synthesize_batch`, `synthesize_sharded_a`, `synthesize_spatial`) so
    the dispatch rules cannot drift between them.  Runner-specific
    inputs parameterize the differences instead of forking the logic:

    `eligible_shape`: the (h, w) the kernel-eligibility probe should
        plan against when it differs from the level's B shape — the
        spatial runner plans against the SLAB the vmapped step will see
        (core + halos), not the global B'.
    `table_bytes`: override for the resident-feature-table estimate —
        the batch runner counts one B table per resident frame
        (`_batch_feature_table_bytes`).
    `work_scale`: per-execution work multiplier for the brute unfuse
        rule — the batch runner's resident frame count scales every
        chunk execution's work.
    `brute_lean`: whether the brute matcher may take the lean-brute
        oracle path past `cfg.brute_lean_bytes` (single-image runner
        only; the batch/sharded runners keep brute on the standard
        path, where the oversized-work rule unfuses it).
    """
    ha, wa = src_a_l.shape[:2]
    if table_bytes is None:
        table_bytes = _feature_table_bytes(h, w, ha, wa)
    eh, ew = eligible_shape if eligible_shape is not None else (h, w)
    if cfg.matcher == "brute":
        # Brute keeps the exact f32 metric as long as the tables fit
        # (it is the oracle: cfg.brute_lean_bytes, not the tighter
        # kernel-path budget) and goes lean-brute past that —
        # bf16-table exact search, lean_brute_em_step.
        lean = brute_lean and table_bytes > cfg.brute_lean_bytes
    else:
        lean = (
            _kernel_eligible(cfg, src_a_l, flt_a_l, has_coarse, eh, ew)
            and table_bytes > cfg.feature_bytes_budget
        )
    if lean and cfg.pca_dims:
        import logging

        knob = (
            "brute_lean_bytes" if cfg.matcher == "brute"
            else "feature_bytes_budget"
        )
        logging.getLogger("image_analogies_tpu").warning(
            "level %d exceeds %s: lean path matches in full-D bf16 "
            "space, pca_dims=%s is not applied at this level",
            level, knob, cfg.pca_dims,
        )
    prev_kind = (
        "none" if not has_coarse
        else ("planes" if isinstance(prev_nnf, tuple) else "stacked")
    )
    # Oversized brute levels run unfused (_SAFE_EXEC_DIST_ELEMS): one
    # fused execution of their exact search would outlive the TPU
    # worker's per-execution tolerance.
    fuse = (
        cfg.matcher != "brute"
        or work_scale * cfg.em_iters * (h * w) * (ha * wa)
        <= _SAFE_EXEC_DIST_ELEMS
    )
    return LevelPlan(lean, prev_kind, _fa_external(ha, wa, lean), fuse)


def _level_fn(cfg: SynthConfig, level: int, has_coarse: bool, lean: bool,
              prev_kind: str, fa_external: bool = False, fuse: bool = True):
    return _level_fn_cached(
        _strip_noncompute(cfg), level, has_coarse, lean, prev_kind,
        fa_external, fuse,
    )


@functools.lru_cache(maxsize=64)
def _level_fn_cached(cfg: SynthConfig, level: int, has_coarse: bool,
                     lean: bool, prev_kind: str, fa_external: bool = False,
                     fuse: bool = True):
    """One pyramid level as ONE compiled call: state upsampling glue +
    A-side feature assembly (+PCA) + kernel A-plane prep + all
    `cfg.em_iters` EM steps.

    The round-2 driver issued ~6-10 dispatches per level plus eager
    glue ops; through the high-latency tunnel the host-side overhead
    exceeded the device time (tools/profile_phases.py).  `prev_kind`
    ('none' | 'stacked' | 'planes') is the static layout of the
    incoming coarser-level NN field.
    """
    step_final = make_em_step(cfg, level, has_coarse, lean)
    # Non-final EM iterations skip the per-pixel polish (gather-bound,
    # ~320 ms of the ~410 ms level-0 EM step at 1024^2 — config.py
    # pm_polish_final_only); their field feeds the next EM search, not
    # the level's output.
    step_mid = (
        make_em_step(cfg, level, has_coarse, lean, polish_iters=0)
        if cfg.pm_polish_final_only
        else step_final
    )

    def run_level(src_a_l, flt_a_l, src_a_c, flt_a_c, src_b_l, src_b_c,
                  raw_b_l, copy_a_l, prev_nnf, prev_bp, level_key,
                  f_a_ext=None, proj_ext=None):
        h, w = src_b_l.shape[:2]
        ha, wa = src_a_l.shape[:2]

        if fa_external:
            f_a, proj = f_a_ext, proj_ext
        elif lean:
            # Lean-brute oracle tables assemble straight into a
            # 128-lane buffer (see assemble_features_lean: padding
            # after the fact transiently doubles the table).
            f_a = assemble_features_lean(
                src_a_l, flt_a_l, cfg, src_a_c, flt_a_c,
                pad_lanes=cfg.matcher == "brute",
            )
            proj = None
        else:
            f_a = assemble_features(src_a_l, flt_a_l, cfg, src_a_c, flt_a_c)
            f_a, proj = pca_fit_and_project(f_a, cfg.pca_dims)

        a_planes = None
        plan = _level_plan(cfg, src_a_l, flt_a_l, has_coarse, h, w)
        if plan is not None:
            from ..kernels.patchmatch_tile import prepare_a_planes

            specs, use_coarse, n_bands = plan
            a_planes = prepare_a_planes(
                src_a_l,
                flt_a_l,
                src_a_c if use_coarse else None,
                flt_a_c if use_coarse else None,
                specs,
                n_bands=n_bands,
            )

        nnf, flt_bp, flt_bp_coarse = _level_state_glue(
            lean, prev_kind, prev_nnf, prev_bp, raw_b_l, h, w, ha, wa,
            level_key,
        )

        dist = bp = None
        for em in range(cfg.em_iters):
            step = step_final if em == cfg.em_iters - 1 else step_mid
            # tlm_em<i>: EM-iteration tag for the device-time join —
            # the host cannot clock iterations inside this one fused
            # call, so the report recovers their cost from profiler op
            # names instead (telemetry/report.py).
            with jax.named_scope(f"tlm_em{em}"):
                nnf, dist, bp = step(
                    src_b_l,
                    flt_bp,
                    src_b_c if has_coarse else src_b_l,
                    flt_bp_coarse if has_coarse else flt_bp,
                    f_a,
                    copy_a_l,
                    nnf,
                    jax.random.fold_in(level_key, em),
                    proj,
                    a_planes,
                )
            flt_bp = bp
        return nnf, dist, bp

    def run_level_tagged(*args, **kw):
        # tlm_L<level>: the per-level device-attribution tag.  A
        # wrapper (not an in-body with-block) so the tag encloses the
        # WHOLE level graph — state glue, assembly, every EM step.
        with jax.named_scope(f"tlm_L{level}"):
            return run_level(*args, **kw)

    # fuse=False (oversized brute levels, _SAFE_EXEC_DIST_ELEMS): the
    # same function eagerly — exact_nn_pallas then execution-chunks its
    # query axis itself.
    return jax.jit(run_level_tagged) if fuse else run_level_tagged


_prologue_fn.cache_clear = _prologue_fn_cached.cache_clear
_level_fn.cache_clear = _level_fn_cached.cache_clear


def _feature_table_bytes(h: int, w: int, ha: int, wa: int) -> int:
    """HBM cost estimate of the assembled feature tables at a level.

    TPU lays an (N, D) f32 table out as T(8, 128) tiles, so any D <= 128
    costs N * 128 * 4 bytes regardless of the logical D — at 4096^2 the
    two tables alone are ~17 GB against 16 GB of HBM (and the im2col
    temps are larger still), which is what the lean path exists for."""
    return (h * w + ha * wa) * 128 * 4


# Lean-path feature chunking: rows of B (or A) assembled per slab, which
# bounds the im2col temps; bf16 halves the resident table cost at a
# quantization the polish's accept tests absorb.
_LEAN_CHUNK_ROWS = 256
_LEAN_TABLE_DTYPE = jnp.bfloat16


def assemble_features_lean(src, flt, cfg: SynthConfig, src_c, flt_c,
                           pad_lanes: bool = False):
    """Feature table assembled slab-by-slab into one (N, D) bf16 buffer.

    `pad_lanes=True` (the lean-brute oracle) allocates the buffer at
    the next 128-lane multiple and writes each slab's rows into its
    left columns — zero columns add zero to every distance, and
    `exact_nn_pallas` then skips its pad/cast working copies.  Padding
    AFTER assembly instead costs a transient second full-table copy
    next to the original (2 x 4.3 GB at 4096^2 — the round-4 oracle's
    first attempt died of exactly that, RESOURCE_EXHAUSTED at level 0).

    A whole-image f32 assembly is unaffordable at 4096^2 twice over:
    the T(8, 128) layout pads D to 128 lanes (8.5 GB per table) and the
    im2col materializes multi-GB temps.  This variant splits the image
    into row slabs with window halos (the same geometry the spatial
    runner proves bit-exact) and a `fori_loop` writes each slab's rows
    straight into the single bf16 buffer, so peak memory is the 4.3 GB
    table plus one slab's temps.  bf16 row-major is deliberate: it is
    the layout XLA's gathers want (forcing a (D, N) layout was measured
    to re-materialize relayout copies bigger than the saving).

    Matches `assemble_features` exactly up to the bf16 cast (slab cores
    with halo >= window reach see identical windows)."""
    from ..parallel.spatial import _split_slabs, slab_halo

    h, w = src.shape[:2]
    halo = slab_halo(cfg)
    n_chunks = max(1, -(-h // _LEAN_CHUNK_ROWS))
    grain = n_chunks * 2
    pad_h = (-h) % grain

    def padded(x, scale=1):
        p = [(0, pad_h // scale)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, p, mode="edge") if pad_h else x

    has_coarse = src_c is not None
    slabs = [
        _split_slabs(padded(src), n_chunks, halo),
        _split_slabs(padded(flt), n_chunks, halo),
    ]
    if has_coarse:
        slabs += [
            _split_slabs(padded(src_c, 2), n_chunks, halo // 2),
            _split_slabs(padded(flt_c, 2), n_chunks, halo // 2),
        ]

    def one(slab):
        s_src, s_flt = slab[0], slab[1]
        s_src_c = slab[2] if has_coarse else None
        s_flt_c = slab[3] if has_coarse else None
        f = assemble_features(s_src, s_flt, cfg, s_src_c, s_flt_c)
        core = f[halo : f.shape[0] - halo]
        return core.reshape(-1, core.shape[-1]).astype(_LEAN_TABLE_DTYPE)

    slab_stacks = tuple(slabs)
    d_feat = jax.eval_shape(
        one, tuple(jax.ShapeDtypeStruct(s.shape[1:], s.dtype) for s in slab_stacks)
    ).shape[1]
    rows_core = slab_stacks[0].shape[1] - 2 * halo
    rw = rows_core * w

    d_buf = (-(-d_feat // 128)) * 128 if pad_lanes else d_feat

    def body(i, f_tab):
        slab = tuple(
            jax.lax.dynamic_index_in_dim(s, i, keepdims=False)
            for s in slab_stacks
        )
        return jax.lax.dynamic_update_slice(f_tab, one(slab), (i * rw, 0))

    f_tab = jax.lax.fori_loop(
        0,
        n_chunks,
        body,
        jnp.zeros((n_chunks * rw, d_buf), _LEAN_TABLE_DTYPE),
    )
    return f_tab[: h * w]


def _maybe_a_planes(cfg, pyr_src_a, pyr_flt_a, level, has_coarse, b_shape):
    """A-side raw planes for the Pallas tile kernel, when the level
    qualifies (patchmatch matcher, pallas enabled, tile-eligible shapes)
    — None otherwise, which routes the matcher to its pure-XLA path.
    Eligibility comes from `_level_plan`, the shared chokepoint."""
    src = pyr_src_a[level]
    flt = pyr_flt_a[level]
    h, w = b_shape
    plan = _level_plan(cfg, src, flt, has_coarse, h, w)
    if plan is None:
        return None
    from ..kernels.patchmatch_tile import prepare_a_planes

    specs, use_coarse, n_bands = plan
    return prepare_a_planes(
        src,
        flt,
        pyr_src_a[level + 1] if use_coarse else None,
        pyr_flt_a[level + 1] if use_coarse else None,
        specs,
        n_bands=n_bands,
    )


def _resolve_channels(a, ap, b, cfg: SynthConfig):
    """Split inputs into (match-src, match-flt, copy) channel images."""
    if cfg.color_mode == "luminance":
        color = b.ndim == 3
        yiq_b = rgb_to_yiq(b) if color else None
        y_b = yiq_b[..., 0] if color else b
        y_a = rgb_to_yiq(a)[..., 0] if a.ndim == 3 else a
        y_ap = rgb_to_yiq(ap)[..., 0] if ap.ndim == 3 else ap
        if cfg.luminance_remap:
            y_a, y_ap = remap_luminance(y_a, y_ap, y_b)
        # copy channels == A' luminance; chroma recombined at the end.
        return y_a, y_ap, y_b, y_ap, yiq_b
    # rgb: match and copy full color, no remapping.
    return a, ap, b, ap, None


def record_prologue(tracer, pyr_raw_b, levels: int, t0: float,
                    cfg: Optional[SynthConfig] = None,
                    a_hw=None, batched: bool = False,
                    runner: str = "single",
                    mesh_plan: Optional[dict] = None) -> None:
    """Drain the async prologue and record its span — shared by every
    runner so the sync barrier lives in ONE place.

    The drain must happen before the first level's clock starts so the
    prologue wall is charged to its own span, not the coarsest level
    (the round-2 bench charged 3.4 s of prologue to a 64^2 level).
    The scalar readback is the reliable barrier on the tunnelled
    platform (block_until_ready can return early — bench.py _sync).

    Round 10: with `cfg`, additionally declares the RUN PLAN as an
    untimed `run_plan` mark — total levels, per-level shapes, and the
    modeled per-level cost units (`level_eta_cost_units`) the live
    /progress endpoint calibrates its ETA from (telemetry/live.py).
    `batched` says pyr_raw_b entries carry a leading frame axis;
    `a_hw` is the finest A shape (the sharded runners' comms term);
    `runner` names which collective model applies; `mesh_plan` (the
    2-D runner) is the parallel/plan2d.py verdict — chosen shape plus
    rejected alternatives — carried verbatim on the run plan so flight
    dumps show why THIS mesh."""
    if not tracer.enabled:
        return
    float(jnp.sum(pyr_raw_b[levels - 1]))
    tracer.record(
        "prologue", round((time.perf_counter() - t0) * 1000, 3)
    )
    if cfg is None:
        return
    shapes = []
    for lvl in range(levels):
        s = pyr_raw_b[lvl].shape
        hw = s[1:3] if batched else s[:2]
        shapes.append([int(hw[0]), int(hw[1])])
    extra = {"mesh_plan": mesh_plan} if mesh_plan else {}
    tracer.annotate(
        "run_plan",
        levels=levels,
        shapes=shapes,
        em_iters=cfg.em_iters,
        matcher=cfg.matcher,
        runner=runner,
        eta_cost_units=level_eta_cost_units(cfg, shapes, a_hw, runner),
        **extra,
    )


def level_eta_cost_units(cfg: SynthConfig, shapes, a_hw=None,
                         runner: str = "single") -> Dict[str, float]:
    """Modeled RELATIVE cost of every pyramid level, for the live
    /progress ETA: {str(level): units}.  Only ratios are consumed —
    telemetry/live.py calibrates an absolute seconds-per-unit rate
    from the measured walls of completed levels, so the model shapes
    the projection and the measurement scales it.

    The patchmatch term prices the kernel's dominant traffic with the
    SAME candidate-DMA byte model the bench and sentinel use
    (kernels.patchmatch_tile.candidate_dma_bytes_per_fetch): per pixel,
    em_iters x pm_iters x K_TOTAL candidate fetches at the level's
    channel count (coarse context doubles the channels below the top
    level); the brute matcher is O(pixels x A-pixels) per EM instead.
    Sharded runners add the parallel/comms.py collective count times
    the per-merge plane bytes — a small term at the published scales,
    included so the two analytic models both feed the projection (and
    so a collective-bound future mesh reprices correctly).  Geometry
    details the host can't know without the arrays (exact channel
    specs, tile heights) are approximated — this is an ETA, and the
    per-level RATIOS are dominated by the 4x pixel scaling the model
    gets exactly."""
    from ..kernels.patchmatch_tile import (
        K_TOTAL,
        candidate_dma_bytes_per_fetch,
    )

    base_chan = 2 if cfg.color_mode == "luminance" else 6
    if cfg.steerable:
        base_chan += cfg.n_orientations
    units: Dict[str, float] = {}
    for level, (h, w) in enumerate(shapes):
        px = float(h) * float(w)
        has_coarse = level < len(shapes) - 1
        n_chan = base_chan * (2 if has_coarse else 1)
        if cfg.matcher == "brute":
            ah, aw = a_hw if a_hw is not None else (h, w)
            # A pyramid level l is 4^-l of the finest A side.
            cost = cfg.em_iters * px * (
                float(ah) * float(aw) / 4.0 ** level
            )
        else:
            moved, _ = candidate_dma_bytes_per_fetch(n_chan, 8)
            cost = cfg.em_iters * cfg.pm_iters * K_TOTAL * px * (
                moved / 8.0  # per-fetch bytes per covered row
            )
        if runner in ("sharded_a", "spatial-banded") and a_hw is not None:
            from ..parallel.comms import (
                sharded_a_allreduce_count,
                sharded_a_band_merge_bytes,
            )

            ah = max(1, int(a_hw[0]) // 2 ** level)
            aw = max(1, int(a_hw[1]) // 2 ** level)
            try:
                n_coll = sharded_a_allreduce_count(cfg, ah, aw)
                merge = sharded_a_band_merge_bytes(cfg, h, w)
                cost += n_coll * merge["bytes_per_merge"]
            except Exception:  # noqa: BLE001 - ETA must never block a run
                pass
        units[str(level)] = cost
    return units


def shard_sync_walls(level_t0: float, parts) -> List[float]:
    """Per-shard completion walls (ms since the level's clock started):
    one scalar-readback barrier per shard slice, in shard order — the
    straggler watch's raw signal (round 10).

    Each readback blocks until THAT shard's computation has finished
    (the reliable barrier on the tunnelled platform — bench.py _sync),
    so on an asynchronously-dispatching backend the walls are each
    shard's true completion time relative to the level start.  Walls
    are CUMULATIVE completion stamps, not deltas: shards that finished
    before an earlier-in-order straggler read back almost instantly
    once reached, so max/median over these stamps isolates the slow
    shard.  On the synchronous CPU test mesh every stamp lands
    together and the ratio degenerates to ~1 — by design (no fake
    skew).  Caveat: a shard EARLIER in read order than the straggler
    cannot be charged less than its own dispatch tail; the ratio is a
    lower bound on true skew, never an overstatement."""
    walls = []
    for p in parts:
        float(jnp.sum(p))
        walls.append(round((time.perf_counter() - level_t0) * 1000, 3))
    return walls


def record_level_span(tracer, cfg: SynthConfig, level_t0: float,
                      level: int, h, w, nnf_energy: Optional[float],
                      shard_walls: Optional[List[float]] = None,
                      shard_axis: Optional[str] = None,
                      extra_shard_walls=None, **attrs):
    """Timed `level` span + declared em_iter children — the shared
    form for the parallel runners (batch/spatial/sharded-A), whose
    level wall is clocked around one already-synced runner call.  The
    single-device driver records the same structure through its
    context-managed span + `_record_level_telemetry` instead.  The
    `em_iters` declaration and matching untimed children are what the
    run sentinel's span-tree completeness check holds every runner
    to.

    Round-10 straggler watch: with `shard_walls` (per-shard completion
    walls from `shard_sync_walls`) the level additionally publishes
    `ia_shard_level_wall_ms{level, shard, axis}` gauges and the
    `ia_shard_imbalance_ratio{level, axis}` max/median ratio the
    sentinel's `straggler_skew` check reads, and carries both on the
    span's attrs so flight dumps and reports show them too.

    Round-17: `extra_shard_walls` ({axis: walls}) publishes the same
    gauge/ratio pair for further mesh axes — the 2-D runner stamps the
    slabs walls as the primary set and the bands-axis assembly walls
    here, so the straggler sentinel watches both axes of the
    bands x slabs mesh.  Extra axes annotate the span as
    `shard_walls_ms_<axis>` / `shard_imbalance_<axis>`."""
    wall_sets = []
    if shard_walls:
        wall_sets.append((shard_axis or "shard", shard_walls, True))
    for ax, walls in (extra_shard_walls or {}).items():
        if walls:
            wall_sets.append((ax, walls, False))
    for axis, walls, primary in wall_sets:
        # True median (even counts average the two middles): the upper
        # middle alone IS the max on a 2-shard mesh, which would pin
        # the ratio at 1.0 and blind the straggler watch exactly where
        # skew is most common.
        s = sorted(walls)
        n = len(s)
        med = s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0
        ratio = round(max(walls) / med, 4) if med > 0 else 1.0
        if primary:
            attrs["shard_walls_ms"] = walls
            attrs["shard_imbalance"] = ratio
        else:
            attrs[f"shard_walls_ms_{axis}"] = walls
            attrs[f"shard_imbalance_{axis}"] = ratio
        reg = (
            tracer.registry if tracer.registry is not None
            else get_registry()
        )
        wall_g = reg.gauge(
            "ia_shard_level_wall_ms",
            "per-shard completion wall per pyramid level (ms since "
            "level start; post-hoc readback stamps — straggler watch)",
        )
        for i, wall in enumerate(walls):
            wall_g.set(wall, labels={
                "level": str(level), "shard": str(i), "axis": axis,
            })
        reg.gauge(
            "ia_shard_imbalance_ratio",
            "max/median per-shard level wall (1.0 = balanced; the "
            "sentinel flags sustained skew)",
        ).set(ratio, labels={"level": str(level), "axis": axis})
    if nnf_energy is not None:
        # A lean run tracer (serving) skips the energy readback; the
        # attr is omitted rather than recorded as null.
        attrs["nnf_energy"] = nnf_energy
    sp = tracer.record(
        "level",
        round((time.perf_counter() - level_t0) * 1000, 3),
        level=level,
        shape=[int(h), int(w)],
        em_iters=cfg.em_iters,
        **attrs,
    )
    for em in range(cfg.em_iters):
        tracer.annotate("em_iter", parent=sp, em=em)
    return sp


def _record_level_telemetry(tracer, cfg: SynthConfig, level: int,
                            lvl_span, plan: LevelPlan) -> None:
    """Span-tree structure + metrics-registry updates for one finished
    level.

    EM iterations and matcher phases execute inside ONE jitted level
    call (the dispatch-fusion design), so the host cannot clock them;
    they are recorded as UNTIMED child spans and their device cost is
    recovered from the xplane trace via the tlm_* scope tags
    (telemetry/report.py).  Counters are host-driven statically-known
    quantities (see telemetry/metrics.py on the jit trace-time caveat):
    em_iters per executed level, one level per level.
    """
    from . import patchmatch as _pm_mod
    from ..kernels import patchmatch_tile as _pt_mod

    # Declare the expected EM-child count on the span itself so the
    # run sentinel's span-tree completeness check (telemetry/sentinel)
    # can hold children == declaration without knowing the config.
    lvl_span.set(em_iters=cfg.em_iters)
    prune = _pt_mod.resolve_prune()
    for em in range(cfg.em_iters):
        # polish_mode: which polish engine the matcher compiled in
        # (models/patchmatch._POLISH_MODE — sequential cascade, jump
        # flood, or the round-8 DMA stream); recorded per em_iter so a
        # report from an A/B run says which arm it measured.
        # cand_dtype/cand_prune (round 11): the compressed-candidate
        # mode the matcher compiled in — same rationale, the A/B
        # record must say which arm a span measured.
        em_sp = tracer.annotate(
            "em_iter", parent=lvl_span, em=em, fused=plan.fuse,
            polish_mode=_pm_mod._POLISH_MODE,
            cand_dtype=_pt_mod.resolve_cand_dtype(),
            cand_prune=(
                "off" if prune is None else f"{prune[0]}:{prune[1]}"
            ),
        )
        for phase in ("assemble", "match", "render"):
            tracer.annotate(phase, parent=em_sp)
    reg = tracer.registry if tracer.registry is not None else get_registry()
    reg.counter("ia_levels_total", "pyramid levels executed").inc()
    reg.counter(
        "ia_em_iters_total",
        "EM iterations executed (em_iters per executed level)",
    ).inc(cfg.em_iters)
    energy = lvl_span.attrs.get("nnf_energy")
    if energy is not None:
        reg.gauge(
            "ia_nnf_energy",
            "final NNF mean match distance per pyramid level "
            "(the PatchMatch convergence monitor)",
        ).set(energy, labels={"level": str(level)})
    if lvl_span.wall_ms is not None:
        reg.histogram(
            "ia_level_wall_ms", "host wall-clock per pyramid level (ms)"
        ).observe(lvl_span.wall_ms)


def create_image_analogy(
    a,
    ap,
    b,
    cfg: Optional[SynthConfig] = None,
    return_aux: bool = False,
    progress=None,
    resume_from: Optional[str] = None,
    resume_strict: bool = False,
):
    """Synthesize B' such that A : A' :: B : B'.

    `a`, `ap`, `b`: float arrays in [0,1], (H,W,3) RGB or (H,W) gray; `a`
    and `ap` must share a shape.  Returns B' shaped like `b` (or a dict of
    auxiliary per-level artifacts when `return_aux`; at lean levels —
    past cfg.feature_bytes_budget — the per-level `nnf` entry is a
    (py, px) plane pair rather than a stacked (H, W, 2) array).

    `progress`: optional observability hook — a
    `utils.progress.ProgressWriter` (the historic JSONL interface: one
    timed `level_done` event per pyramid level) or a
    `telemetry.Tracer` (span tree + metrics; the JSONL stream is then
    the tracer's backward-compatible view).  Either way the loop pays
    exactly one host sync per level; None pays none.

    `resume_from`: directory of per-level artifacts written by a prior
    run with `cfg.save_level_artifacts` (SURVEY.md §5 checkpoint/resume).
    Synthesis restarts from the finest completed level's (nnf, B') state;
    with the same cfg/seed the result is identical to an uninterrupted
    run (per-level keys derive from the level index, not the path here).
    `resume_strict=True` turns an unusable `resume_from` (missing
    directory, zero intact artifacts, every fingerprint mismatched)
    into a `ResumeError` instead of a warned from-scratch recompute.
    """
    cfg = cfg or SynthConfig()
    tracer = as_tracer(progress)
    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if a.shape != ap.shape:
        raise ValueError(f"A {a.shape} and A' {ap.shape} must match")

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    with tracer.span(
        "run", matcher=cfg.matcher, levels=levels,
        shape=[int(s) for s in b.shape[:2]],
    ):
        return _synthesize_single(
            a, ap, b, cfg, levels, return_aux, tracer, resume_from,
            resume_strict,
        )


def _synthesize_single(a, ap, b, cfg: SynthConfig, levels: int,
                       return_aux: bool, tracer, resume_from,
                       resume_strict: bool = False):
    """`create_image_analogy` body, running under its `run` span."""
    from ..runtime.faults import fire as _fault_fire

    # xfer injection point: the prologue dispatch is the run's
    # host->device transfer boundary (runtime/faults.py).
    _fault_fire("xfer", 0)
    prologue_t0 = time.perf_counter()
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, pyr_raw_b, yiq_b
    ) = _prologue_fn(cfg, levels)(a, ap, b)

    key = jax.random.PRNGKey(cfg.seed)
    aux: Dict[str, List] = {"nnf": [None] * levels, "dist": [None] * levels}

    bp = None  # synthesized copy-channel image at current level
    nnf = None

    start_level = levels - 1
    resumed = resume_prologue(
        resume_from, levels, cfg, b.shape, tracer, strict=resume_strict
    )
    if resumed is not None:
        start_level, nnf, bp, aux_fill = resumed
        if return_aux:
            # Same gate as the level loop: checkpointed levels' arrays
            # are only worth holding when the caller asked for aux.
            for lvl, (n, d) in aux_fill.items():
                aux["nnf"][lvl] = n
                aux["dist"][lvl] = d
        if start_level < 0:
            out = _finalize(bp, yiq_b, b, cfg)
            if return_aux:
                return {"bp": out, "nnf": aux["nnf"], "dist": aux["dist"]}
            return out

    record_prologue(
        tracer, pyr_raw_b, levels, prologue_t0, cfg=cfg,
        a_hw=pyr_src_a[0].shape[:2], runner="single",
    )

    for level in range(start_level, -1, -1):
        # level injection point + supervisor abort checkpoint.
        _fault_fire("level", level)
        with tracer.span("level", level=level) as lvl_span:
            h, w = pyr_src_b[level].shape[:2]
            ha, wa = pyr_src_a[level].shape[:2]
            has_coarse = level < levels - 1
            lvl_span.set(shape=[int(h), int(w)])

            # All dispatch decisions for the level come from the shared
            # planner (the lean decision must precede assembly —
            # assembly is what OOMs).
            plan = plan_level(
                cfg, level, pyr_src_a[level], pyr_flt_a[level], has_coarse,
                h, w, prev_nnf=nnf,
            )
            f_a_ext = proj_ext = None
            if plan.fa_external:
                f_a_ext, proj_ext = _assemble_fa_fn(cfg, has_coarse)(
                    pyr_src_a[level],
                    pyr_flt_a[level],
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                )
            run = _level_fn(
                cfg, level, has_coarse, plan.lean, plan.prev_kind,
                plan.fa_external, plan.fuse,
            )
            # kernel injection point: the compiled level executable is
            # about to launch.
            _fault_fire("kernel", level)
            nnf, dist, bp = run(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else None,
                pyr_raw_b[level],
                pyr_copy_a[level],
                nnf,
                bp,
                jax.random.fold_in(key, level),
                f_a_ext,
                proj_ext,
            )

            if return_aux:
                # Only keep per-level device state alive when the caller
                # asked for it: at oracle sizes the accumulated fields
                # are hundreds of MB held until function exit for
                # nothing.
                aux["nnf"][level] = nnf
                aux["dist"][level] = dist
            if tracer.enabled:
                # One device sync per level — the only host sync in the
                # loop (north-star: minimize host round trips).  The
                # sync is the scalar readback itself, evaluated BEFORE
                # the span closes its clock: block_until_ready can
                # return before remote execution completes on the
                # tunnelled axon platform, which would charge this
                # level's tail to the next level's window.
                lvl_span.set(nnf_energy=float(dist.mean()))
        # Span closed: the legacy `level_done` event (wall_ms included)
        # has been emitted; now attach the compiled-in structure and
        # update the registry.
        if tracer.enabled:
            _record_level_telemetry(tracer, cfg, level, lvl_span, plan)
        if cfg.save_level_artifacts:
            nnf_save = nnf
            if isinstance(nnf, tuple):
                # Stack the lean plane pair on the HOST: checkpoints
                # keep the standard (H, W, 2) schema without ever
                # materializing the lane-padded stack on device.
                nnf_save = np.stack(
                    [np.asarray(nnf[0]), np.asarray(nnf[1])], axis=-1
                )
            _save_level(
                cfg.save_level_artifacts, level, nnf_save, dist, bp, cfg,
                b.shape,
            )

    out = _finalize(bp, yiq_b, b, cfg)
    if return_aux:
        return {"bp": out, "nnf": aux["nnf"], "dist": aux["dist"]}
    return out


def _finalize(bp, yiq_b, b, cfg: SynthConfig):
    """Recombine chroma (luminance mode) and clip to [0,1]."""
    if cfg.color_mode == "luminance" and b.ndim == 3:
        yiq = jnp.concatenate([bp[..., None], yiq_b[..., 1:]], axis=-1)
        out = yiq_to_rgb(yiq)
    else:
        out = bp
    return jnp.clip(out, 0.0, 1.0)


def _ckpt_fingerprint(cfg: SynthConfig, b_shape) -> str:
    """Identity of a checkpointed run: the result-shaping knobs plus the
    target shape.  Excluded as non-result-shaping: `save_level_artifacts`
    (the save-run sets it, the resume-run usually doesn't),
    `pallas_mode`/`brute_chunk`/`match_dtype` (dispatch/precision/perf
    knobs — the saved per-level (nnf, dist, bp) state is valid input for
    any of them, so flipping one between save and resume must not force
    a from-scratch recompute).  Saves stamp the TRUE config; knobs that
    cannot shape a particular run's results are relaxed at COMPARE time
    instead (`_fingerprint_matches`), so the stamp keeps full
    information and the accept rule carries the justification."""
    import dataclasses

    cfg_id = dataclasses.replace(
        cfg,
        save_level_artifacts=None,
        pallas_mode="auto",
        brute_chunk=0,
        match_dtype="float32",
    )
    return f"{tuple(b_shape)}|{cfg_id!r}"


def _fingerprint_matches(saved: str, expected: str, cfg) -> bool:
    """Whether a saved checkpoint stamp identifies the same run as the
    current config's expected fingerprint.

    Exact string compare, except that under a non-brute matcher
    `brute_lean_bytes=<n>` is wildcarded on BOTH sides before comparing:
    the budget only selects the lean-brute path under `matcher="brute"`
    (`_level_plan`), so retuning the oracle budget must not invalidate
    multi-hour patchmatch/ann checkpoints it cannot affect (ADVICE r4) —
    including checkpoints stamped with any historical budget value."""
    if saved == expected:
        return True
    if cfg.matcher == "brute":
        return False
    import re

    def wild(fp: str) -> str:
        return re.sub(r"brute_lean_bytes=\d+", "brute_lean_bytes=*", fp)

    return wild(saved) == wild(expected)


def _save_level(path: str, level: int, nnf, dist, bp, cfg, b_shape) -> None:
    """Per-level checkpoint artifacts (SURVEY.md §5 checkpoint/resume).

    Written to a temp file and renamed so a kill mid-write never leaves a
    truncated .npz where resume would trip over it; stamped with the run
    fingerprint so resume can reject stale/mismatched checkpoints."""
    from ..runtime.faults import fire as _fault_fire

    # ckpt injection point (runtime/faults.py): 'raise'/'hang' fire
    # here before the write; 'truncate' is interpreted below, after
    # the atomic rename — the partial-write-survived-on-disk case the
    # resume loader must skip.
    act = _fault_fire("ckpt", level)
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, f"level_{level}.npz")
    tmp = f"{final}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            nnf=np.asarray(nnf),
            dist=np.asarray(dist),
            bp=np.asarray(bp),
            fingerprint=np.asarray(_ckpt_fingerprint(cfg, b_shape)),
        )
    os.replace(tmp, final)
    if act == "truncate":
        size = os.path.getsize(final)
        with open(final, "r+b") as f:
            f.truncate(max(1, size // 3))


class ResumeError(RuntimeError):
    """An explicitly-requested resume found nothing usable and the
    caller demanded strictness (round-12 hardening): the message names
    the directory and every rejection — including fingerprint
    mismatches — so the operator can tell a wrong path from a stale
    checkpoint without re-running."""


def resume_prologue(resume_from, levels: int, cfg, b_shape, progress,
                    strict: bool = False):
    """Shared resume entry for every synthesis runner.

    Returns None (no usable checkpoint — start fresh) or
    (start_level, nnf, bp, {level: (nnf, dist)}): start from
    `start_level` (-1 = every level was checkpointed; finalize `bp`
    directly) with the loaded state as the incoming coarse state.

    `strict=True` (the CLI's --strict-resume): an unusable
    `resume_from` raises `ResumeError` naming the directory and each
    rejection reason instead of warning and recomputing from scratch —
    the explicit outcome a multi-hour resume deserves."""
    if not resume_from:
        return None
    reasons: List[str] = []
    loaded = _load_resume_state(
        resume_from, levels, _ckpt_fingerprint(cfg, b_shape), cfg,
        reasons=reasons,
    )
    if loaded is None:
        if not os.path.isdir(resume_from):
            reasons.insert(
                0, f"directory {resume_from!r} does not exist"
            )
        elif not reasons:
            reasons.insert(0, "no level_*.npz artifacts found")
        if strict:
            raise ResumeError(
                f"resume: no usable checkpoint under {resume_from!r}: "
                + "; ".join(reasons)
            )
        # ADVICE r2: an explicitly-requested resume that silently
        # recomputes from scratch hides a multi-hour surprise — corrupt
        # or mismatched files warn inside _load_resume_state, but an
        # absent/empty directory (or a chunked/unchunked layout
        # mismatch) otherwise would not.
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "resume: no usable checkpoint under %r (%s) — recomputing "
            "from scratch", resume_from, "; ".join(reasons),
        )
        return None
    resumed_level, nnf, _dist, bp, aux_fill = loaded
    if progress is not None:
        progress.emit("resume", from_level=resumed_level)
    return resumed_level - 1, nnf, bp, aux_fill


def _load_resume_state(path: str, levels: int, fingerprint: str, cfg,
                       reasons: Optional[List[str]] = None):
    """Resume state from a checkpoint dir: (finest_loadable_level, nnf,
    dist, bp, {level: (nnf, dist)} for every loadable level), or None
    when nothing usable exists.

    Artifacts are skipped (with a logged warning, falling back to the
    next-coarser intact level) when they are corrupt/truncated — resume
    must survive exactly the crashes it exists for — or when their
    fingerprint does not match the current run (different input shape,
    seed, matcher, or any other result-shaping knob): silently resuming
    a stale checkpoint would produce a wrong image with exit code 0.
    `reasons` (round-12 hardening) collects one line per rejection so
    strict callers can raise an actionable error."""
    import logging
    import re
    import zipfile

    log = logging.getLogger("image_analogies_tpu")
    if reasons is None:
        reasons = []
    loadable = {}
    if os.path.isdir(path):
        for name in os.listdir(path):
            m = re.fullmatch(r"level_(\d+)\.npz", name)
            if not m or int(m.group(1)) >= levels:
                continue
            lvl = int(m.group(1))
            try:
                data = np.load(os.path.join(path, name))
                if "fingerprint" not in data.files:
                    log.warning(
                        "resume: skipping %s (no run fingerprint — written "
                        "by an older version; re-save to make it resumable)",
                        name,
                    )
                    reasons.append(f"{name}: no run fingerprint")
                    continue
                saved_fp = str(data["fingerprint"])
                if not _fingerprint_matches(saved_fp, fingerprint, cfg):
                    log.warning(
                        "resume: skipping %s (checkpoint from a different "
                        "run: %s != %s)", name, saved_fp, fingerprint,
                    )
                    reasons.append(
                        f"{name}: fingerprint mismatch (saved "
                        f"{saved_fp!r} != expected {fingerprint!r})"
                    )
                    continue
                loadable[lvl] = (
                    jnp.asarray(data["nnf"]),
                    jnp.asarray(data["dist"]),
                    jnp.asarray(data["bp"]),
                )
            except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                log.warning("resume: skipping unreadable artifact %s", name)
                reasons.append(f"{name}: unreadable/corrupt artifact")
                continue
    if not loadable:
        return None
    best = min(loadable)
    nnf, dist, bp = loadable[best]
    aux_fill = {lvl: (n, d) for lvl, (n, d, _) in loadable.items()}
    return best, nnf, dist, bp, aux_fill
