"""`Matcher` plugin interface + registry (SURVEY.md §2 C6).

The reference selects its search strategy (brute-force NN vs ANN) through a
`Matcher` plugin interface [BASELINE.json north star]; this module is that
interface for the TPU build.  Where the reference splits the contract into
`index(A_features)` + `query(q)` [SURVEY.md §3.2], the TPU formulation fuses
indexing into `match`: brute needs no index (the MXU streams the whole
table), PatchMatch's "index" is the NN-field state threaded through the
call, and the native ANN matcher caches its kd-tree per feature table
host-side — each strategy keeps the reference's per-level index economics
without a stateful two-phase API that jit would fight.  A matcher maps
feature fields to a nearest-neighbor field:

    match(f_b (H,W,D), f_a (Ha,Wa,D), nnf (H,W,2), key, level) -> (nnf, dist)

where nnf[q] = (py, px) into A and dist[q] is the (weighted, squared) L2
feature distance of that correspondence.  Matchers are pure functions of
their inputs — jit-safe, vmap-able for the batched runner (SURVEY.md C15).

Shared distance helpers live here so every matcher (and the coherence
wrapper) agrees on the metric exactly.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig

# ---------------------------------------------------------------------------
# Shared geometry / distance helpers

# TPU lane width: lean-path chunk shapes keep a 128-minor axis so layout
# assignment never pads a unit axis (see candidate_dist_lean).
LANES = 128


def flatten_field(f: jnp.ndarray) -> jnp.ndarray:
    """(H, W, D) -> (H*W, D)."""
    return f.reshape(-1, f.shape[-1])


def nnf_to_flat(nnf: jnp.ndarray, wa: int) -> jnp.ndarray:
    """(H, W, 2) int (py, px) -> (H*W,) flat row-major indices into A."""
    return (nnf[..., 0] * wa + nnf[..., 1]).reshape(-1)


def flat_to_nnf(idx: jnp.ndarray, wa: int, shape) -> jnp.ndarray:
    """(H*W,) flat A indices -> (H, W, 2)."""
    return jnp.stack([idx // wa, idx % wa], axis=-1).reshape(*shape, 2)


def clamp_nnf(nnf: jnp.ndarray, ha: int, wa: int) -> jnp.ndarray:
    return jnp.stack(
        [
            jnp.clip(nnf[..., 0], 0, ha - 1),
            jnp.clip(nnf[..., 1], 0, wa - 1),
        ],
        axis=-1,
    )


def candidate_dist(
    f_b_flat: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    idx: jnp.ndarray,
    gather_fn=None,
) -> jnp.ndarray:
    """Distance between each query row and A-row `idx[q]`; (N,).

    Math runs in f32 regardless of table dtype (casts fuse into the
    gather), so callers may pass bf16 tables to halve the gather's HBM
    traffic — a (N, D<=128) table gathers 128-lane-padded rows, so the
    bytes depend only on the dtype, and the random-row access pattern
    runs at ~16-19 GB/s (profiled 2026-07-31), which makes these
    gathers the polish pass's whole cost.

    `gather_fn(table, flat_idx) -> rows` swaps the gather engine while
    keeping the distance arithmetic BITWISE identical (the streamed
    polish passes the Pallas DMA row gather,
    kernels/polish_stream.gather_rows, closed over its LANE-padded
    table copy — rows wider than the B side are sliced back to the
    feature width, which drops only zero pad columns)."""
    take = gather_fn or (lambda tab, ix: jnp.take(tab, ix, axis=0))
    rows = take(f_a_flat, idx)
    d = f_b_flat.shape[-1]
    if rows.shape[-1] != d:
        rows = jax.lax.slice(rows, (0, 0), (rows.shape[0], d))
    rows = rows.astype(jnp.float32)
    diff = f_b_flat.astype(jnp.float32) - rows
    return jnp.sum(diff * diff, axis=-1)


def nnf_dist(
    f_b: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    nnf: jnp.ndarray,
    wa: int,
) -> jnp.ndarray:
    """Squared feature distance of each correspondence; (H, W)."""
    h, w, d = f_b.shape
    idx = nnf_to_flat(nnf, wa)
    return candidate_dist(f_b.reshape(-1, d), f_a_flat, idx).reshape(h, w)


def candidate_dist_lean(
    f_b_tab: jnp.ndarray,
    f_a_tab: jnp.ndarray,
    idx: jnp.ndarray,
    chunk: int = 1 << 20,
    gather_fn=None,
) -> jnp.ndarray:
    """`candidate_dist` for the lean path: bf16 tables, evaluated in
    pixel chunks so the gathered-rows temp never reaches field size
    (a whole-field (N, 128-lane-padded) gather is 4 GB bf16 at 4096^2,
    on top of the two resident tables).

    `idx` may carry leading CANDIDATE axes — shape (..., N), query row
    i pairing with idx[..., i] — and the result matches it: the Jacobi
    polish (models/patchmatch.polish_sweeps_planes) evaluates all ~12
    candidates of a sweep as ONE (K, N) call, whose per-chunk gather
    moves K*chunk rows in one `jnp.take` (measured 1.8x cheaper per
    candidate row than K separate N-row gathers,
    tools/profile_gather.py — the gather floor is per-call, not
    per-byte-pattern).

    `gather_fn(table, flat_idx) -> rows` swaps the per-chunk gather
    engine (same hook as `candidate_dist`): the streamed polish passes
    the Pallas DMA row gather closed over a LANE-padded table copy,
    and the existing wider-rows slice below restores the exact feature
    width, so every distance stays bitwise identical to the jnp.take
    path.

    Chunking is a static Python unroll over `lax.slice`s, NOT
    `lax.map`: the map formulation carried (n_chunks, chunk) operands
    whose per-step (1, chunk) slices were laid out lane-minor on the
    unit axis — a 128x padding expansion (measured: ten 512 MB temps
    for 4 MB of data in the fused 2048^2 level graph).  The query rows
    are CONSECUTIVE along the last axis (b row i pairs with
    idx[..., i]), so the B side is a slice, not a gather — only the A
    side pays gather cost.  Distances accumulate in f32 regardless of
    table dtype."""
    take = gather_fn or (lambda tab, ix: jnp.take(tab, ix, axis=0))
    lead = idx.shape[:-1]
    n = idx.shape[-1]
    n_lead = int(np.prod(lead)) if lead else 1
    idx2 = idx.reshape(n_lead, n)
    # The chunk bound is a TEMP-SIZE bound: with K leading candidates
    # every chunk gathers K*chunk rows, so divide the budget by K or
    # the batched polish would materialize K full-size temps at once —
    # the exact allocation the chunking exists to prevent.
    chunk = max(1 << 14, chunk // n_lead)
    # Width comes from the B side: the lean-brute oracle pairs a NARROW
    # B table with the 128-lane-padded A table (models/analogy.py —
    # the pad columns are zeros, so truncating gathered A rows to the
    # B width leaves every distance exactly unchanged).  Equal-width
    # callers see a no-op slice.
    d_feat = f_b_tab.shape[1]
    assert f_a_tab.shape[1] >= d_feat, (f_a_tab.shape, f_b_tab.shape)
    # The chunk loop unrolls in Python (n_chunks is static and small),
    # so every slice is a STATIC lax.slice: the B side is sliced from
    # the resident table without ever copying/padding the whole table
    # (only the small final ragged chunk pads, to a 128 multiple).
    # Every intermediate keeps a 128-lane minor axis: 1-D (chunk,)
    # forms were bitcast by layout assignment to (1, chunk)
    # lane-minor-on-the-unit-axis — a 128x padding expansion that
    # turned 4 MB distance chunks into 512 MB temps (measured in the
    # fused 2048^2 level graph).
    outs = []
    for start in range(0, n, chunk):
        end = min(start + chunk, n)
        m = end - start
        m_pad = -(-m // LANES) * LANES
        ix = jax.lax.slice(idx2, (0, start), (n_lead, end))
        rows_b = jax.lax.slice(f_b_tab, (start, 0), (end, d_feat))
        if m_pad != m:
            ix = jnp.pad(ix, ((0, 0), (0, m_pad - m)))
            rows_b = jnp.pad(rows_b, ((0, m_pad - m), (0, 0)))
        rows2 = m_pad // LANES
        a_rows = take(f_a_tab, ix.reshape(-1))
        if a_rows.shape[1] != d_feat:
            a_rows = jax.lax.slice(
                a_rows, (0, 0), (a_rows.shape[0], d_feat)
            )
        a4 = a_rows.astype(jnp.float32).reshape(
            n_lead, rows2, LANES, d_feat
        )
        b3 = rows_b.astype(jnp.float32).reshape(1, rows2, LANES, d_feat)
        outs.append(jnp.sum((b3 - a4) ** 2, axis=-1))  # (K, rows2, LANES)
    d = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    return d.reshape(n_lead, -1)[:, :n].reshape(*lead, n)


# ---------------------------------------------------------------------------
# Registry

MatchFn = Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]
_REGISTRY: Dict[str, "Matcher"] = {}


class Matcher:
    """Base class: subclasses implement `match` (pure, jit-safe)."""

    name: str = "base"

    def match(
        self,
        f_b: jnp.ndarray,
        f_a: jnp.ndarray,
        nnf: jnp.ndarray,
        *,
        key: jax.Array,
        level: int,
        cfg: SynthConfig,
        raw=None,
        polish_iters=None,
        temporal=None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """`raw` optionally carries the raw channel planes
        (models.patchmatch.RawPlanes) backing the Pallas tile kernel;
        matchers that work on assembled features ignore it.
        `polish_iters` overrides cfg.pm_polish_iters for this call (the
        driver passes 0 on non-final EM iterations when
        cfg.pm_polish_final_only); exact-search matchers ignore it.
        `temporal` optionally carries the previous frame's converged
        (H, W, 2) field (video subsystem): with cfg.tau > 0 the
        candidate metric gains the temporal-coherence penalty
        (models.patchmatch.temporal_penalty_fn); matchers without a
        penalized-metric formulation ignore it (the video driver only
        routes temporal fields to matchers that honor them)."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


def register_matcher(name: str, matcher: Matcher) -> None:
    _REGISTRY[name] = matcher


def get_matcher(name: str) -> Matcher:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown matcher {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def available_matchers():
    return sorted(_REGISTRY)
