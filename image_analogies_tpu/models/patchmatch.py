"""PatchMatch NN-field matcher (SURVEY.md §2 C9 + C10; Barnes 2009).

The reference accelerates matching with a host-side ANN library (kd-tree
family, C++) [SURVEY.md C8].  Pointer-chasing trees are anti-idiomatic on
TPU; the TPU-native ANN for nearest-neighbor *fields* is PatchMatch, whose
sweeps are whole-image vectorized ops (SURVEY.md §2 C8->C9 mapping).

Each sweep evaluates, per pixel, a fixed-size candidate set (TPU wants no
divergence — SURVEY.md §7 "ragged candidate sets"):

  - 4 propagation candidates  nnf(q -/+ delta) + delta  — these are exactly
    Ashikhmin's coherence candidates r* = s(r) + (q - r) (Hertzmann §3.2),
    so coherence search is fused into propagation rather than bolted on;
  - `pm_random_candidates` random-search candidates at exponentially
    shrinking radii around the current match (Barnes §3.2).

The kappa rule (Hertzmann §3.2): a *non-coherent* (random-search) candidate
must beat the incumbent by the factor 1 + 2^-level * kappa (level 0 =
finest, so the coherence bias is strongest at full resolution).  With
kappa=0 this is plain PatchMatch and converges to the exact NN field — the
basis of the PSNR-vs-brute oracle tests (SURVEY.md §4).

This module is the pure-JAX (XLA gather) formulation; it is both the
reference implementation for the Pallas kernel (kernels/) and the portable
path for CPU tests.  Sweeps are a `lax.scan` over iteration keys, so the
whole per-level matching is one compiled loop [north star: no per-pixel
Python steps].
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import (
    Matcher,
    candidate_dist,
    candidate_dist_lean,
    clamp_nnf,
    flat_to_nnf,
    nnf_dist,
    nnf_to_flat,
    register_matcher,
)


class RawPlanes(NamedTuple):
    """Raw channel images backing the Pallas tile kernel's metric
    (kernels/patchmatch_tile.py): the kernel computes windowed SSDs from
    these planes directly instead of gathering assembled feature rows."""

    src_b: jnp.ndarray
    flt_b: jnp.ndarray
    src_b_coarse: Optional[jnp.ndarray]
    flt_b_coarse: Optional[jnp.ndarray]
    # Tuple of A row-band arrays from prepare_a_planes — packed layout
    # (rows, Wq-1, 2C, 128) f32 by default, the legacy (rows, Wq, C,
    # 128) behind packed=False; one entry on single-device plans,
    # several when A ownership is split into bands (sharded-A).
    a_planes: tuple

# Propagation neighborhood: left, right, up, down.
_DELTAS = ((0, -1), (0, 1), (-1, 0), (1, 0))


def random_init(key: jax.Array, h: int, w: int, ha: int, wa: int) -> jnp.ndarray:
    """Uniform random NNF (H, W, 2) over A's domain."""
    ky, kx = jax.random.split(key)
    py = jax.random.randint(ky, (h, w), 0, ha)
    px = jax.random.randint(kx, (h, w), 0, wa)
    return jnp.stack([py, px], axis=-1)


def _shifted(nnf: jnp.ndarray, dy: int, dx: int) -> jnp.ndarray:
    """Propagation candidate field: nnf(q - delta) + delta.

    Implemented as a roll; wrapped-around rows/cols produce harmless
    candidates that simply lose the accept test after clamping.
    """
    cand = jnp.roll(nnf, shift=(dy, dx), axis=(0, 1))
    return cand + jnp.array([dy, dx], dtype=nnf.dtype)


def temporal_penalty_fn(temporal, tau: float, ha: int, wa: int):
    """Additive candidate penalty toward the previous frame's mapping
    (video subsystem): candidate (cy, cx) at pixel q pays
    tau * ((cy-ty)^2 + (cx-tx)^2) / (ha^2 + wa^2) where (ty, tx) is the
    previous frame's converged match at q.  Normalizing by the squared
    A diagonal makes tau the penalty of a full-diagonal divergence, so
    the weight is resolution-independent.  Returns a function of a flat
    candidate index array (N,) -> penalty (N,) f32, or None when the
    term is disabled (tau == 0 or no previous field) — callers gate at
    trace time so tau=0 graphs stay bit-identical to the pre-video
    engine."""
    if temporal is None or tau <= 0.0:
        return None
    t_flat = nnf_to_flat(clamp_nnf(temporal, ha, wa), wa)
    ty = (t_flat // wa).astype(jnp.float32)
    tx = (t_flat % wa).astype(jnp.float32)
    scale = float(tau) / float(ha * ha + wa * wa)

    def penalty(idx):
        cy = (idx // wa).astype(jnp.float32)
        cx = (idx % wa).astype(jnp.float32)
        return scale * ((cy - ty) ** 2 + (cx - tx) ** 2)

    return penalty


def patchmatch_sweeps(
    f_b: jnp.ndarray,
    f_a: jnp.ndarray,
    nnf: jnp.ndarray,
    key: jax.Array,
    *,
    iters: int,
    n_random: int,
    coh_factor: float,
    gather_fn=None,
    temporal=None,
    tau: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run `iters` propagate+random-search sweeps; returns (nnf, dist).

    `coh_factor` >= 1 biases acceptance toward coherent (propagation)
    candidates: random candidates must satisfy d * coh_factor < d_current.

    `gather_fn` swaps the candidate-row gather engine inside
    `candidate_dist` (matcher.py) while keeping every distance — and
    therefore every accept/tie decision — bitwise identical: the
    streamed polish (`_POLISH_MODE == "stream"`) passes the Pallas DMA
    row gather here, so the streamed path IS this cascade with only
    the fetch mechanism replaced.  None keeps the XLA `jnp.take`
    lowering (the default path, bit-for-bit the historical behavior).

    `temporal`/`tau` (video subsystem): when temporal is a previous
    frame's (H, W, 2) converged field and tau > 0, every candidate
    distance — incumbent included — carries the temporal_penalty_fn
    term, so accept/tie decisions and the returned dist are in the
    penalized metric.  tau == 0 or temporal None leaves the graph
    untouched (Python-level gate).
    """
    h, w, d = f_b.shape
    ha, wa = f_a.shape[:2]
    f_b_flat = f_b.reshape(-1, d)
    f_a_flat = f_a.reshape(-1, d)
    base_fn = lambda idx: candidate_dist(  # noqa: E731
        f_b_flat, f_a_flat, idx, gather_fn=gather_fn
    )
    pen_fn = temporal_penalty_fn(temporal, tau, ha, wa)
    if pen_fn is None:
        d_fn = base_fn
    else:
        d_fn = lambda idx: base_fn(idx) + pen_fn(idx)  # noqa: E731

    nnf = clamp_nnf(nnf, ha, wa)
    dist = d_fn(nnf_to_flat(nnf, wa)).reshape(h, w)

    # Exponential random-search radii: max dim, halving per scale (Barnes
    # alpha = 0.5), floored at 1 px.
    max_radius = max(ha, wa)
    radii = [max(1, int(max_radius * (0.5**s))) for s in range(n_random)]

    def try_candidates(state, cand, factor):
        nnf_cur, dist_cur = state
        cand = clamp_nnf(cand, ha, wa)
        idx = nnf_to_flat(cand, wa)
        d_cand = d_fn(idx).reshape(h, w)
        # Exact ties break toward the lower flat index — the same canonical
        # representative `jnp.argmin` picks in the brute-force oracle.  In
        # flat feature regions (ubiquitous in texture-by-numbers label maps)
        # ties are massive, and without a shared canonicalization the
        # approximate and exact paths would diverge on valid-but-different
        # matches, sinking the PSNR-vs-oracle metric for no quality reason.
        idx_cur = nnf_to_flat(nnf_cur, wa).reshape(h, w)
        better = d_cand * factor < dist_cur
        tie_lower = (d_cand == dist_cur) & (idx.reshape(h, w) < idx_cur)
        accept = better | tie_lower
        nnf_new = jnp.where(accept[..., None], cand, nnf_cur)
        dist_new = jnp.where(accept, d_cand, dist_cur)
        return nnf_new, dist_new

    def sweep(state, it_key):
        # Propagation (= fused Ashikhmin coherence candidates): unbiased.
        for dy, dx in _DELTAS:
            state = try_candidates(state, _shifted(state[0], dy, dx), 1.0)
        # Unshifted neighbor matches: in tied (flat) regions the canonical
        # lowest-index match floods outward through these, mirroring the
        # uniform assignment the exact oracle produces there.
        for dy, dx in _DELTAS:
            cand = jnp.roll(state[0], shift=(dy, dx), axis=(0, 1))
            state = try_candidates(state, cand, 1.0)
        # Random search around the current best: kappa-biased.
        keys = jax.random.split(it_key, len(radii))
        for r, rk in zip(radii, keys):
            off = jax.random.randint(rk, (h, w, 2), -r, r + 1)
            state = try_candidates(state, state[0] + off, coh_factor)
        return state, None

    (nnf, dist), _ = jax.lax.scan(
        sweep, (nnf, dist), jax.random.split(key, iters)
    )
    return nnf, dist


def kappa_factor(kappa: float, level: int) -> float:
    """Hertzmann §3.2 acceptance factor, level 0 = finest."""
    return 1.0 + kappa * (2.0 ** (-level))


def tile_patchmatch(
    f_b: jnp.ndarray,
    f_a: jnp.ndarray,
    nnf: jnp.ndarray,
    key: jax.Array,
    *,
    raw: RawPlanes,
    cfg: SynthConfig,
    level: int,
    interpret: bool,
    plan,
    polish_iters: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pallas tile-kernel PatchMatch (kernels/patchmatch_tile.py).

    Sweeps run in the kernel's raw-plane metric (bulk global search); the
    result is then merged with the incoming field under the exact
    feature metric (so the field never regresses) and polished with
    per-pixel XLA sweeps, which restores the pure-XLA twin's output
    contract: exact f32 distances and canonical tie-breaking.

    The merge and polish ACCEPT decisions run on bf16 copies of the
    feature tables: every candidate evaluation gathers all H*W query
    rows, each padded to 128 lanes regardless of D, and the random-row
    gather runs at ~16-19 GB/s (profiled 2026-07-31 — the polish was
    ~320 of the ~410 ms level-0 EM step at 1024^2 on f32 tables), so
    bf16 halves the dominant cost while distances still accumulate in
    f32 (matcher.candidate_dist casts after the gather).  The RETURNED
    dist is re-ranked exactly (f32 tables) after the polish, preserving
    the output contract up to accept decisions made on bf16-quantized
    metrics.

    `plan` is the (specs, use_coarse, n_bands) channel/banding plan the
    dispatcher already resolved (kernels.patchmatch_tile.plan_channels)
    — passed through so dispatch and kernel cannot disagree.
    `polish_iters` overrides cfg.pm_polish_iters (the driver passes 0
    on non-final EM iterations when cfg.pm_polish_final_only — the
    final dist is then the bf16-metric merge value, consumed only as
    the next EM iteration's incoming field).
    """
    from ..kernels.patchmatch_tile import (
        band_bounds,
        channel_images,
        prune_candidates,
        resolve_cand_dtype,
        resolve_prune,
        sample_candidates_blocked,
        tile_geometry,
        tile_sweep,
        to_blocked,
        from_blocked,
    )

    h, w, _ = f_b.shape
    ha, wa = f_a.shape[:2]
    f_a_flat = f_a.reshape(-1, f_a.shape[-1])
    specs, use_coarse, n_bands = plan
    bounds = band_bounds(ha, n_bands)
    geom = tile_geometry(h, w, specs)
    coh = kappa_factor(cfg.kappa, level)
    pm_iters = _pm_iters_for(cfg, ha, wa)
    polish_iters, polish_random = _polish_schedule_for(
        cfg, ha, wa, polish_iters
    )
    # Round-11 compressed-candidate pipeline: both knobs resolve ONCE
    # per call (the resolve_packed discipline) so driver-prepared
    # a_planes and the sweeps below agree on the mode.
    cand_dtype = resolve_cand_dtype()
    prune = resolve_prune()
    prune_state = _prune_setup(
        prune, f_b.reshape(-1, f_b.shape[-1]), f_a_flat, geom, h, w
    )
    # bf16 accept-metric tables (see docstring); candidate_dist does its
    # math in f32 after the gather, so only quantization enters.
    f_b16 = f_b.astype(jnp.bfloat16)
    f_a16 = f_a.astype(jnp.bfloat16)
    f_a16_flat = f_a16.reshape(-1, f_a16.shape[-1])

    chans_b = channel_images(
        raw.src_b,
        raw.flt_b,
        raw.src_b_coarse if use_coarse else None,
        raw.flt_b_coarse if use_coarse else None,
    )
    b_blocked = jnp.stack(
        [to_blocked(c.astype(jnp.float32), geom) for c in chans_b]
    )

    nnf = clamp_nnf(nnf, ha, wa)
    qy = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
    qx = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
    off_y = nnf[..., 0] - qy
    off_x = nnf[..., 1] - qx
    dist0 = nnf_dist(f_b16, f_a16_flat, nnf, wa)

    oy_b = to_blocked(off_y, geom)
    ox_b = to_blocked(off_x, geom)
    # Incumbent distances start at +inf, NOT at dist0: dist0 lives in the
    # (possibly PCA-projected, exactly coarse-sampled) feature metric,
    # which is not the kernel's raw-plane metric — mixing them would make
    # the accept test incoherent (with PCA, projected distances are
    # systematically smaller, so raw-metric candidates would almost never
    # win).  The incoming field still defends itself: its offsets are in
    # every sweep's own-tile candidate samples (evaluated under the
    # kernel metric), and the final merge below is exact-metric.
    d_b = jnp.full(
        (geom.n_ty * geom.thp, geom.n_tx * 128), jnp.inf, jnp.float32
    )
    for t in range(pm_iters):
        # Candidates sampled straight from the blocked state: the
        # compact layout is never rebuilt inside the loop (round-2
        # VERDICT item — from_blocked ran twice per pm iteration just
        # to feed a 4x4-subgrid-per-tile sampler).
        cand_y, cand_x, cand_valid = sample_candidates_blocked(
            oy_b, ox_b, jax.random.fold_in(key, t), geom, ha, wa
        )
        if prune_state is not None:
            # Stage-2 coarse pre-prune: only the top-M candidates by
            # projected distance keep a valid mask, so the kernel's
            # pl.when(ok) skip never moves the rest's window bytes.
            proj_b_tiles, qy_s, qx_s, proj_a, m_keep = prune_state
            cand_valid = prune_candidates(
                cand_y, cand_x, cand_valid, proj_b_tiles, qy_s, qx_s,
                proj_a, ha, wa, m_keep,
            )
        # One call per A band; the carried per-pixel best makes the union
        # over bands a global search (single call when A fits VMEM).
        for band_planes, band in zip(raw.a_planes, bounds):
            oy_b, ox_b, d_b = tile_sweep(
                band_planes, b_blocked, cand_y, cand_x, oy_b, ox_b, d_b,
                band, cand_valid,
                specs=specs, geom=geom, ha=ha, wa=wa, coh_factor=coh,
                interpret=interpret, cand_dtype=cand_dtype,
                cand_budget=prune[1] if prune else None,
            )
    off_y = from_blocked(oy_b, geom, h, w)
    off_x = from_blocked(ox_b, geom, h, w)

    nnf_k = clamp_nnf(
        jnp.stack([qy + off_y, qx + off_x], axis=-1), ha, wa
    )
    # Feature-metric merge: adopt the kernel's match only where it wins
    # (bf16 tables, f32 math — same metric as dist0 above).
    d_k = nnf_dist(f_b16, f_a16_flat, nnf_k, wa)
    better = d_k < dist0
    nnf_m = jnp.where(better[..., None], nnf_k, nnf)
    d_m = jnp.where(better, d_k, dist0)
    if polish_iters == 0:
        return nnf_m, d_m
    # Per-pixel polish sweeps (propagation + ties canonicalization) on
    # the bf16 accept metric, then one exact f32 re-rank of the final
    # correspondences (the output contract's dist).  Default: the
    # sequential cascade (_POLISH_MODE — the A/B at the selector's
    # definition); "stream" is the SAME cascade with the row fetches
    # routed through the Pallas DMA gather (bit-identical output;
    # only the engine differs); _CAND_DTYPE="int8" swaps the row table
    # for the per-patch-quantized one (_polish_gather_fn) on either
    # engine; random-probe count comes from the scale-aware schedule
    # above.
    if _POLISH_MODE in ("sequential", "stream"):
        gf = _polish_gather_fn(f_a16_flat, f_a16.shape[-1], interpret)
        nnf_p, d_p = patchmatch_sweeps(
            f_b16,
            f_a16,
            nnf_m,
            jax.random.fold_in(key, pm_iters),
            iters=polish_iters,
            n_random=polish_random,
            coh_factor=coh,
            gather_fn=gf,
        )
    else:
        nnf_p, d_p = polish_sweeps(
            f_b16,
            f_a16,
            nnf_m,
            d_m,
            jax.random.fold_in(key, pm_iters),
            iters=polish_iters,
            n_random=polish_random,
            coh_factor=coh,
        )
    if cfg.kappa > 0.0:
        # Ashikhmin adoption pass — the SAME coherence_sweeps the
        # kappa-aware brute oracle runs (models/coherence.py), on the
        # bf16 accept metric.  The polish above only adopts coherent
        # candidates that are strictly BETTER; Hertzmann §3.2's rule
        # adopts the best coherent candidate even when worse, as long
        # as it clears the kappa ceiling over the approximate match.
        # Without this pass the kernel path systematically under-adopts
        # coherence relative to the oracle (round-3 VERDICT: configs
        # 2/5 sat ~3 dB below the kappa=0 configs).
        from .coherence import coherence_sweeps

        nnf_p, _ = coherence_sweeps(
            f_b16, f_a16, nnf_p, d_p, factor=coh, sweeps=2
        )
    return nnf_p, nnf_dist(f_b, f_a_flat, nnf_p, wa)


def patchmatch_sweeps_lean(
    f_b_tab: jnp.ndarray,
    f_a_tab: jnp.ndarray,
    py: jnp.ndarray,
    px: jnp.ndarray,
    key: jax.Array,
    *,
    ha: int,
    wa: int,
    iters: int,
    n_random: int,
    coh_factor: float,
    dist_fn=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """`patchmatch_sweeps` over the lean (N, D) bf16 tables and a
    PLANE-PAIR field; returns (py, px, dist).

    Same sweep structure, candidates, kappa rule, and canonical
    tie-breaking as the full-precision twin, with two memory changes
    that make 4096^2+ affordable: distances go through
    `candidate_dist_lean` (bf16 tables, chunk-wise evaluation so the
    gathered-rows temp never reaches field size), and the field is
    carried as separate (H, W) int32 planes — a stacked (H, W, 2) array
    tiles as T(8, 128) on its trailing dims, padding 2 -> 128 lanes
    (64x, 8 GB at 4096^2).

    `dist_fn` (flat idx (N,) -> dist (N,)) overrides the candidate
    metric; the band-sharded-A runner (parallel/sharded_a.py) passes a
    masked local-shard evaluation merged by cross-device pmin, which is
    value-identical to the default because every flat index has exactly
    one owning band.
    """
    h, w = py.shape
    if dist_fn is None:
        dist_fn = lambda idx: candidate_dist_lean(  # noqa: E731
            f_b_tab, f_a_tab, idx
        )
    py = jnp.clip(py, 0, ha - 1)
    px = jnp.clip(px, 0, wa - 1)
    dist = dist_fn((py * wa + px).reshape(-1)).reshape(h, w)

    max_radius = max(ha, wa)
    radii = [max(1, int(max_radius * (0.5**s))) for s in range(n_random)]

    def try_candidates(state, cy, cx, factor):
        py_c, px_c, dist_cur = state
        cy = jnp.clip(cy, 0, ha - 1)
        cx = jnp.clip(cx, 0, wa - 1)
        idx = cy * wa + cx
        d_cand = dist_fn(idx.reshape(-1)).reshape(h, w)
        idx_cur = py_c * wa + px_c
        better = d_cand * factor < dist_cur
        tie_lower = (d_cand == dist_cur) & (idx < idx_cur)
        accept = better | tie_lower
        return (
            jnp.where(accept, cy, py_c),
            jnp.where(accept, cx, px_c),
            jnp.where(accept, d_cand, dist_cur),
        )

    def sweep(state, it_key):
        for dy, dx in _DELTAS:
            cy = jnp.roll(state[0], (dy, dx), (0, 1)) + dy
            cx = jnp.roll(state[1], (dy, dx), (0, 1)) + dx
            state = try_candidates(state, cy, cx, 1.0)
        for dy, dx in _DELTAS:
            cy = jnp.roll(state[0], (dy, dx), (0, 1))
            cx = jnp.roll(state[1], (dy, dx), (0, 1))
            state = try_candidates(state, cy, cx, 1.0)
        keys = jax.random.split(it_key, len(radii))
        for r, rk in zip(radii, keys):
            ky, kx = jax.random.split(rk)
            oy = jax.random.randint(ky, (h, w), -r, r + 1)
            ox = jax.random.randint(kx, (h, w), -r, r + 1)
            state = try_candidates(
                state, state[0] + oy, state[1] + ox, coh_factor
            )
        return state, None

    (py, px, dist), _ = jax.lax.scan(
        sweep, (py, px, dist), jax.random.split(key, iters)
    )
    return py, px, dist


# Pure-roll steps of the polish's canonical-tie flood per sweep (4
# directions each): 16 single-pixel hops lets the lowest-index
# representative cross tied regions ~2x faster per sweep than the
# sequential polish's ~8-deep accept chain, for the cost of one extra
# N-row verification gather (the rolls themselves are VPU-free next to
# the gathers).
_TIE_FLOOD_STEPS = 16

# Jump-flooding propagation distances (coarse-to-fine, per sweep): a
# neighbor at distance s proposes its match shifted by s — so one
# BATCHED candidate gather reaches as far as an 8-deep sequential
# accept chain, without any chain.
_JUMP_STEPS = (8, 4, 2, 1)

# Size-aware search schedule (round 5, VERDICT r4 missing 4): pm_iters
# is constant in the config while the A search domain grows 16x from
# 1024^2 to 4096^2, and the measured consequence was quality drift
# (SCALE dist_ratio_vs_exact 1.50 -> 1.69 at fixed pm_iters=6).
# Levels whose A domain exceeds _PM_BOOST_AREA run _PM_ITERS_BOOST
# extra kernel sweeps.  Implemented at the matcher-call level (the A
# shape is known right here), so every runner — single, batch,
# spatial slabs, sharded-A bands — inherits the same rule with no
# per-runner plumbing; cross-runner bit-identity is preserved because
# the rule is a pure function of (cfg, A shape).
_PM_BOOST_AREA = 4 * 1024 * 1024
_PM_ITERS_BOOST = 2


def _pm_iters_for(cfg: SynthConfig, ha: int, wa: int) -> int:
    return cfg.pm_iters + (
        _PM_ITERS_BOOST if ha * wa > _PM_BOOST_AREA else 0
    )


# Polish implementation selector (module-level, not a config knob: the
# choice is a measured performance decision, not user surface).
# "sequential": the chained per-candidate cascade
# (patchmatch_sweeps/_lean) — 12 XLA gathers per sweep.  "jump":
# batched jump-flooding polish (polish_sweeps_planes) — 3 gathers per
# sweep; REJECTED by its own TPU A/B (tools/polish_ab.py, 1024^2,
# 2026-08-01: jump 0.725 s / 35.34 dB vs sequential 0.551 s /
# 35.56 dB min-over-seeds — the 1.8x-per-candidate batched gather did
# not compose into a faster level 0), kept selectable as the recorded
# negative.  "stream" (round 8): the SAME sequential cascade with the
# candidate-row fetches routed through the Pallas DMA row gather
# (kernels/polish_stream.py) instead of XLA's 16-19 GB/s per-row
# gather lowering — identical candidates, accept rules, and PRNG
# streams, so streamed output is BIT-IDENTICAL to sequential
# (tests/test_polish_stream.py pins it in interpret mode); only the
# fetch engine differs.  Default stays "sequential": no accelerator
# was reachable in round 8, so the stream arm's rate claim is modeled,
# not measured — tools/polish_stream_ab.py carries the hardware A/B
# recipe and its pre-stated kill criterion (POLISH_r08.json), and the
# env override IA_POLISH_MODE lets that A/B flip modes without a code
# edit.  Tests may mock.patch any mode.
_POLISH_MODE = os.environ.get("IA_POLISH_MODE", "sequential")

_POLISH_MODES = ("sequential", "jump", "stream")


def set_polish_mode(mode: str) -> None:
    """Install a polish engine process-wide (round 12: the
    supervisor's stream->sequential degradation rung; also usable by
    the hardware A/B): validates, assigns the module global, and
    clears the driver's cached level/EM compilations — the
    `set_cand_compression` discipline, because every cached level
    function resolved the mode at trace time and a flip must never
    reuse a stale graph.  The stream and sequential engines are
    bit-identical (tests/test_polish_stream.py), so this rung of the
    degradation ladder is bit-safe by construction."""
    global _POLISH_MODE
    if mode not in _POLISH_MODES:
        raise ValueError(
            f"polish mode {mode!r} names none of {_POLISH_MODES}"
        )
    if mode == _POLISH_MODE:
        return
    _POLISH_MODE = mode
    from ..kernels.patchmatch_tile import clear_compiled_level_caches

    clear_compiled_level_caches()

# Scale-aware polish budget (round 8, the other half of VERDICT r5
# task 4): the polish's shrinking-radius random probes re-search
# globally at 12-gather prices, duplicating work the kernel's bulk
# sweeps already do MORE of at large sizes (_PM_ITERS_BOOST adds 2
# sweeps past the same area bound).  Above _POLISH_TRIM_AREA the
# random-probe count is capped at _POLISH_RANDOM_LARGE; propagation
# and tie canonicalization — the polish's actual job on a
# kernel-converged field — are untouched.  Same threshold and
# call-level placement as _pm_iters_for, so every runner inherits the
# rule as a pure function of (cfg, A shape) and published families at
# <= 2048^2 (area == the bound, not above it) are bit-unchanged; the
# 4096^2 effect is recorded as a projection + small-scale PSNR
# measurement in POLISH_r08.json, hardware confirmation owed.
_POLISH_TRIM_AREA = _PM_BOOST_AREA
_POLISH_RANDOM_LARGE = 2


def _polish_schedule_for(
    cfg: SynthConfig, ha: int, wa: int, polish_iters=None
) -> Tuple[int, int]:
    """(iters, n_random) of the per-pixel polish at this A domain:
    cfg values (with the driver's polish_iters override) below
    _POLISH_TRIM_AREA, random probes capped above it."""
    iters = cfg.pm_polish_iters if polish_iters is None else polish_iters
    n_random = cfg.pm_polish_random
    if ha * wa > _POLISH_TRIM_AREA:
        n_random = min(n_random, _POLISH_RANDOM_LARGE)
    return iters, n_random


def _stream_gather_fn(f_a_tab: jnp.ndarray, d_useful: int,
                      interpret: bool):
    """`gather_fn` for the streamed polish: the Pallas DMA row gather
    closed over a LANE-padded copy of the table (built once per polish
    call, outside the per-candidate loop).  The returned rows are
    LANE wide; candidate_dist{,_lean} slice them back to the feature
    width, which drops only zero pad — distances stay bitwise equal
    to the jnp.take path."""
    from ..kernels.polish_stream import gather_rows, prepare_polish_table

    f_a_pad = prepare_polish_table(f_a_tab)
    return lambda _tab, ix: gather_rows(
        f_a_pad, ix, interpret=interpret, useful_width=d_useful
    )


def _polish_gather_fn(f_a_tab: jnp.ndarray, d_useful: int,
                      interpret: bool):
    """Polish candidate-row gather engine under the
    (_POLISH_MODE, _CAND_DTYPE) pair — None means the default
    `jnp.take` (bf16 + sequential: today's graph, bit-identical).

    "int8" (round 11, stage 1): the per-patch-quantized row table
    (kernels/polish_stream.quantize_rows) with the fetched rows
    dequantized right next to the distance math — candidate_dist's f32
    accumulation sees q * scale rows, so only the quantization enters
    the accept metric (the exact-metric re-rank downstream is
    untouched; quality is pinned by the dist-ratio/PSNR proxy gates).
    Under "stream" the int8 rows ride the Pallas DMA gather (half the
    bf16 row bytes plus the scale — polish_dma_bytes_per_fetch); under
    "sequential" the XLA take path fetches the same rows and THIS
    closure books the same counters, so the sentinel's polish ledger
    stays exact in every mode.  NOTE: the jump-flood polish
    (polish_sweeps_planes) keeps its exact tables — _CAND_DTYPE does
    not reroute it (the mode lost its A/B; compressing a rejected arm
    buys nothing)."""
    from ..kernels.patchmatch_tile import resolve_cand_dtype
    from ..kernels.polish_stream import (
        gather_rows,
        polish_dma_bytes_per_fetch,
        prepare_polish_table,
        quantize_rows,
    )

    cand_dtype = resolve_cand_dtype()
    stream = _POLISH_MODE == "stream"
    if cand_dtype != "int8":
        return (
            _stream_gather_fn(f_a_tab, d_useful, interpret)
            if stream else None
        )
    q_tab, scales = quantize_rows(f_a_tab)
    if stream:
        q_pad = prepare_polish_table(q_tab)

        def gf(_tab, ix):
            rows = gather_rows(
                q_pad, ix, interpret=interpret, useful_width=d_useful,
                cand_dtype="int8",
            )
            s = jnp.take(scales, ix.reshape(-1), axis=0)
            return rows.astype(jnp.float32) * s

        return gf

    def gf(_tab, ix):
        from ..telemetry.metrics import (
            count_polish_dma_bytes,
            count_polish_dma_rows,
        )

        flat = ix.reshape(-1)
        m = flat.shape[0]
        moved, useful = polish_dma_bytes_per_fetch(d_useful, 1, "int8")
        count_polish_dma_bytes(
            useful=m * useful, padded=m * (moved - useful), dtype="int8"
        )
        count_polish_dma_rows(m, d_useful, 1, "int8")
        rows = jnp.take(q_tab, flat, axis=0).astype(jnp.float32)
        return rows * jnp.take(scales, flat, axis=0)

    return gf


def _prune_setup(prune, f_b_flat, f_a_flat, geom, h, w):
    """Per-call coarse-prune state (round 11, stage 2), or None when
    the prune is off: fit the level's pca_basis on the A-side table
    (ops/pca.py — the Hertzmann §3.1 machinery the repo already
    carries), project both sides to the prune's k dims, and precompute
    the per-tile sample-pixel rows the per-sweep ranking compares
    against (kernels.patchmatch_tile.prune_candidates)."""
    if prune is None:
        return None
    from ..kernels.patchmatch_tile import tile_sample_positions
    from ..ops.pca import pca_basis, project

    k_dims, m_keep = prune
    # Width comes from the B side (the candidate_dist rule): a wider A
    # table only carries zero pad columns, which must not enter the
    # basis fit.
    d = f_b_flat.shape[-1]
    f_a_flat = jax.lax.slice(
        f_a_flat, (0, 0), (f_a_flat.shape[0], d)
    )
    basis = pca_basis(f_a_flat.astype(jnp.float32), k_dims)
    proj_a = project(f_a_flat.astype(jnp.float32), basis)
    proj_b = project(f_b_flat.astype(jnp.float32), basis)
    qy, qx = tile_sample_positions(geom, h, w)
    proj_b_tiles = jnp.take(
        proj_b, (qy * w + qx).reshape(-1), axis=0
    ).reshape(*qy.shape, proj_b.shape[-1])
    return proj_b_tiles, qy, qx, proj_a, m_keep


def _lex_min(d: jnp.ndarray, idx: jnp.ndarray):
    """Lexicographic (distance, flat-index) argmin over axis 0: the
    canonical representative `jnp.argmin` picks in the brute oracle —
    min distance, ties to the lowest flat index."""
    d_min = jnp.min(d, axis=0)
    i_min = jnp.min(
        jnp.where(d == d_min, idx, jnp.iinfo(jnp.int32).max), axis=0
    )
    return d_min, i_min


def polish_sweeps_planes(
    py: jnp.ndarray,
    px: jnp.ndarray,
    dist: jnp.ndarray,
    key: jax.Array,
    *,
    ha: int,
    wa: int,
    iters: int,
    n_random: int,
    coh_factor: float,
    dist_fn,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched jump-flooding polish: 3 dist_fn calls per sweep instead
    of the sequential cascade's 12 single-candidate gathers.

    The gathers ARE the polish's cost (~320 ms of the ~410 ms level-0
    EM step at 1024^2, tools/profile_phases.py; the per-row rate is a
    pattern-independent issue floor, but a BATCHED multi-candidate
    gather is measured 1.8x cheaper per candidate row,
    tools/profile_gather.py).  Plain Jacobi batching of the sequential
    polish's candidate set measured ~5 dB below it on the lean-path
    oracle-tracking content (TestLeanPath) — one-hop accepts lose the
    sequential chain's propagation depth — so this variant puts the
    depth INTO THE CANDIDATE SET instead of into a chain.  Per sweep:

      1. JUMP-FLOODING propagation: coherent candidates from neighbors
         at `_JUMP_STEPS` distances (a neighbor at distance s proposes
         its match shifted by s — Ashikhmin's r* = s(r) + (q - r) for
         r at distance s), 4 directions x len(_JUMP_STEPS) scales in
         ONE batched gather.  Best-of-K by lexicographic (dist, flat
         idx) — the canonical tie-breaking of the sequential chain's
         fixed point — accepted against the incumbent at factor 1.
         Scale combinations give up to 15 px of travel per sweep
         vs the sequential cascade's ~8-deep chain.
      2. The `n_random` exponential random-search probes in ONE
         batched (R, N) gather, best-of-R, accepted under the kappa
         factor — the kernel's best-coherent-vs-best-approximate
         merge rule.
      3. Canonical-tie flooding through flat regions, GATHER-FREE:
         equal-distance neighbors propose their lower flat index
         through `_TIE_FLOOD_STEPS` pure-roll steps — in a flat region
         the neighbor's own distance IS the candidate's distance at
         this pixel, so distance equality is the flood criterion —
         then one dist_fn call applies the exact accept rule
         (better | equal-and-lower-index), reverting any proposal the
         flat-region assumption got wrong.

    Every accept applies the exact accept rule against the live
    incumbent, so the output is a member of the same accept family as
    `patchmatch_sweeps` (canonical ties included); what differs is the
    proposal mechanism (long-range jumps instead of chained one-hop
    accepts) and, at kappa > 0, best-of-set random merging instead of
    the chain's first-survivor — the same trade the band-sharded
    runner's cross-band pmin makes (parallel/sharded_a.py
    'Equivalence').  The A/B against the sequential cascade (wall +
    PSNR over 3 seeds at the headline) picks `_POLISH_MODE`.

    `dist_fn` takes flat indices shaped (..., N) with query rows
    pairing along the LAST axis (candidate_dist_lean's contract), so
    the band-sharded masked-pmin hook works unchanged.
    """
    h, w = py.shape
    max_radius = max(ha, wa)
    radii = [max(1, int(max_radius * (0.5**s))) for s in range(n_random)]

    def sweep(state, it_key):
        py_c, px_c, d_c = state

        # 1. Jump-flooding propagation: coherent candidates from
        # neighbors at log-stepped distances (s*delta shifted by
        # s*delta — Ashikhmin's r* = s(r) + (q - r) for r at distance
        # s), all in ONE batched gather.  Depth is in the CANDIDATE
        # SET (up to 15 px of travel per sweep through scale
        # combinations), not in an accept chain.
        cys, cxs = [], []
        for s in _JUMP_STEPS:
            for dy, dx in _DELTAS:
                cys.append(
                    jnp.roll(py_c, (s * dy, s * dx), (0, 1)) + s * dy
                )
                cxs.append(
                    jnp.roll(px_c, (s * dy, s * dx), (0, 1)) + s * dx
                )
        n_coh = len(cys)
        cy = jnp.clip(jnp.stack(cys), 0, ha - 1)
        cx = jnp.clip(jnp.stack(cxs), 0, wa - 1)
        idx = cy * wa + cx  # (K, H, W)
        d_all = dist_fn(idx.reshape(n_coh, h * w)).reshape(idx.shape)
        i_cur = py_c * wa + px_c
        d_coh, i_coh = _lex_min(d_all, idx)
        accept = (d_coh < d_c) | ((d_coh == d_c) & (i_coh < i_cur))
        d1 = jnp.where(accept, d_coh, d_c)
        i1 = jnp.where(accept, i_coh, i_cur)
        py_c, px_c = i1 // wa, i1 % wa

        # 2. Random probes: one batched (R, N) gather, best-of-R.
        if radii:
            keys = jax.random.split(it_key, len(radii))
            cys, cxs = [], []
            for r, rk in zip(radii, keys):
                ky, kx = jax.random.split(rk)
                cys.append(
                    py_c + jax.random.randint(ky, (h, w), -r, r + 1)
                )
                cxs.append(
                    px_c + jax.random.randint(kx, (h, w), -r, r + 1)
                )
            cy = jnp.clip(jnp.stack(cys), 0, ha - 1)
            cx = jnp.clip(jnp.stack(cxs), 0, wa - 1)
            idx = cy * wa + cx  # (R, H, W)
            d_all = dist_fn(idx.reshape(len(cys), h * w)).reshape(idx.shape)
            d_rnd, i_rnd = _lex_min(d_all, idx)
            accept = (d_rnd * coh_factor < d1) | (
                (d_rnd == d1) & (i_rnd < i1)
            )
            d1 = jnp.where(accept, d_rnd, d1)
            i1 = jnp.where(accept, i_rnd, i1)

        # 3. Gather-free canonical-tie flood + one verifying gather.
        i_prop = i1
        for _ in range(_TIE_FLOOD_STEPS):
            for dy, dx in _DELTAS:
                n_i = jnp.roll(i_prop, (dy, dx), (0, 1))
                n_d = jnp.roll(d1, (dy, dx), (0, 1))
                take = (n_d == d1) & (n_i < i_prop)
                i_prop = jnp.where(take, n_i, i_prop)
        d_prop = dist_fn(i_prop.reshape(-1)).reshape(h, w)
        accept = (d_prop < d1) | ((d_prop == d1) & (i_prop < i1))
        d1 = jnp.where(accept, d_prop, d1)
        i1 = jnp.where(accept, i_prop, i1)
        return (i1 // wa, i1 % wa, d1), None

    (py, px, dist), _ = jax.lax.scan(
        sweep, (py, px, dist), jax.random.split(key, iters)
    )
    return py, px, dist


def polish_sweeps(
    f_b16: jnp.ndarray,
    f_a16: jnp.ndarray,
    nnf: jnp.ndarray,
    dist: jnp.ndarray,
    key: jax.Array,
    *,
    iters: int,
    n_random: int,
    coh_factor: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """`polish_sweeps_planes` for the stacked-field standard path:
    flattens the bf16 feature images to lean-shaped tables, carries the
    field as planes internally, and restacks.  `dist` is the incoming
    field's distance in the SAME bf16 accept metric (the exact-metric
    merge's output), so no entry re-evaluation gather is needed."""
    h, w, d = f_b16.shape
    ha, wa = f_a16.shape[:2]
    f_b_tab = f_b16.reshape(-1, d)
    f_a_tab = f_a16.reshape(-1, d)
    py, px, dist = polish_sweeps_planes(
        nnf[..., 0], nnf[..., 1], dist, key, ha=ha, wa=wa, iters=iters,
        n_random=n_random, coh_factor=coh_factor,
        dist_fn=lambda idx: candidate_dist_lean(f_b_tab, f_a_tab, idx),
    )
    return jnp.stack([py, px], axis=-1), dist


def tile_patchmatch_lean(
    f_b_tab: jnp.ndarray,
    f_a_tab: jnp.ndarray,
    py: jnp.ndarray,
    px: jnp.ndarray,
    key: jax.Array,
    *,
    raw: RawPlanes,
    cfg: SynthConfig,
    level: int,
    interpret: bool,
    plan,
    ha: int,
    wa: int,
    polish_iters: Optional[int] = None,
    dist_fn=None,
    bounds=None,
    sweep_merge=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """PatchMatch for levels whose ROW-MAJOR feature tables would not
    fit HBM (models/analogy.py `_feature_table_bytes`); the field is a
    (py, px) plane pair in and out (returns (py, px, dist)).

    Identical staging to `tile_patchmatch` — kernel bulk search in the
    raw-plane metric, exact-feature-metric merge, per-pixel polish —
    with the lean memory rules: feature tables are bf16 and assembled
    chunk-wise (models/analogy.py `assemble_features_lean`), distance
    evaluations are chunked (matcher.candidate_dist_lean), and the
    field stays in (H, W) planes (a stacked (H, W, 2) int32 pads
    2 -> 128 lanes = 8 GB at 4096^2).
    Output contract matches the standard kernel path up to bf16
    quantization of the features, INCLUDING the kappa>0 Ashikhmin
    adoption pass: `coherence_sweeps_lean` runs after the polish with
    the same rule/sweep count as the standard path's
    `coherence_sweeps` (bit-identical on equal tables — tested), so
    kappa acceptance semantics hold above the feature budget too.

    Band-sharded-A hooks (parallel/sharded_a.py; defaults reproduce
    the single-device behavior exactly):
    - `dist_fn` — see patchmatch_sweeps_lean; used for the incumbent,
      merge, and polish evaluations.
    - `bounds` — overrides the band row-bounds derived from the plan
      (each shard_map device passes ITS band's (lo, hi) with
      raw.a_planes holding only that band's planes).
    - `sweep_merge((oy, ox, d) blocked planes) -> same` — called after
      every pm iteration; the sharded runner cross-device
      argmin-merges here so the next iteration's candidates sample
      from the GLOBAL best field, mirroring the sequential banded
      search's carried state (strict-improvement accepts make the
      merge order-equivalent — tests/test_sharded_a.py
      test_sharded_a_band_search_matches_sequential).
    """
    from ..kernels.patchmatch_tile import (
        band_bounds,
        channel_images,
        prune_candidates,
        resolve_cand_dtype,
        resolve_prune,
        sample_candidates_blocked,
        tile_geometry,
        tile_sweep,
        to_blocked,
        from_blocked,
    )

    h, w = raw.src_b.shape[:2]
    specs, use_coarse, n_bands = plan
    if bounds is None:
        bounds = band_bounds(ha, n_bands)
    geom = tile_geometry(h, w, specs)
    coh = kappa_factor(cfg.kappa, level)
    pm_iters = _pm_iters_for(cfg, ha, wa)
    polish_iters, polish_random = _polish_schedule_for(
        cfg, ha, wa, polish_iters
    )
    # Stream-mode polish only replaces the DEFAULT local gather: a
    # caller-supplied dist_fn (the band-sharded masked-pmin hook) owns
    # its own fetch path, and streaming a shard's local gather is a
    # separate (unprobed) composition — those callers keep the XLA
    # cascade.
    default_dist = dist_fn is None
    cand_dtype = resolve_cand_dtype()
    # The coarse prune follows the same rule as the stream hook: a
    # caller-supplied dist_fn means f_a_tab is a shard-LOCAL table
    # (parallel/sharded_a.py) while candidates index global A — a
    # local basis fit would rank against the wrong rows, so sharded
    # callers keep the full candidate set (composition unprobed,
    # recorded in QUANT_r11.json).
    prune = resolve_prune() if default_dist else None
    prune_state = _prune_setup(prune, f_b_tab, f_a_tab, geom, h, w)
    if default_dist:
        dist_fn = lambda idx: candidate_dist_lean(  # noqa: E731
            f_b_tab, f_a_tab, idx
        )

    chans_b = channel_images(
        raw.src_b,
        raw.flt_b,
        raw.src_b_coarse if use_coarse else None,
        raw.flt_b_coarse if use_coarse else None,
    )
    b_blocked = jnp.stack(
        [to_blocked(c.astype(jnp.float32), geom) for c in chans_b]
    )

    py = jnp.clip(py, 0, ha - 1)
    px = jnp.clip(px, 0, wa - 1)
    qy = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
    qx = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
    off_y = py - qy
    off_x = px - qx
    dist0 = dist_fn((py * wa + px).reshape(-1)).reshape(h, w)

    oy_b = to_blocked(off_y, geom)
    ox_b = to_blocked(off_x, geom)
    # Kernel-metric incumbents start at +inf, exactly as in
    # tile_patchmatch: the raw-plane metric and the feature metric must
    # not be mixed in one accept test.
    d_b = jnp.full(
        (geom.n_ty * geom.thp, geom.n_tx * 128), jnp.inf, jnp.float32
    )
    for t in range(pm_iters):
        cand_y, cand_x, cand_valid = sample_candidates_blocked(
            oy_b, ox_b, jax.random.fold_in(key, t), geom, ha, wa
        )
        if prune_state is not None:
            proj_b_tiles, qy_s, qx_s, proj_a, m_keep = prune_state
            cand_valid = prune_candidates(
                cand_y, cand_x, cand_valid, proj_b_tiles, qy_s, qx_s,
                proj_a, ha, wa, m_keep,
            )
        for band_planes, band in zip(raw.a_planes, bounds):
            oy_b, ox_b, d_b = tile_sweep(
                band_planes, b_blocked, cand_y, cand_x, oy_b, ox_b, d_b,
                band, cand_valid,
                specs=specs, geom=geom, ha=ha, wa=wa, coh_factor=coh,
                interpret=interpret, cand_dtype=cand_dtype,
                cand_budget=prune[1] if prune else None,
            )
        if sweep_merge is not None:
            oy_b, ox_b, d_b = sweep_merge(oy_b, ox_b, d_b)
    off_y = from_blocked(oy_b, geom, h, w)
    off_x = from_blocked(ox_b, geom, h, w)

    ky = jnp.clip(qy + off_y, 0, ha - 1)
    kx = jnp.clip(qx + off_x, 0, wa - 1)
    # Exact-metric merge: adopt the kernel's match only where it wins.
    d_k = dist_fn((ky * wa + kx).reshape(-1)).reshape(h, w)
    better = d_k < dist0
    py_m = jnp.where(better, ky, py)
    px_m = jnp.where(better, kx, px)
    d_m = jnp.where(better, d_k, dist0)
    if polish_iters == 0:
        return py_m, px_m, d_m
    # Per-pixel polish under _POLISH_MODE: the sequential cascade by
    # default (the A/B at the selector's definition), "stream" the
    # same cascade with the default gather routed through the Pallas
    # DMA row gather (bit-identical; sharded callers keep their own
    # dist_fn — see `default_dist` above), the batched jump-flooding
    # variant (3 dist_fn calls per sweep, polish_sweeps_planes)
    # selectable; d_m is already in the accept metric, so no entry
    # re-evaluation is needed.  The sharded dist_fn hook works
    # unchanged: candidate indices arrive (K, N) with query rows
    # pairing along the last axis.
    if _POLISH_MODE in ("sequential", "stream"):
        polish_dist = dist_fn
        if default_dist:
            gf = _polish_gather_fn(f_a_tab, f_b_tab.shape[1], interpret)
            if gf is not None:
                polish_dist = (
                    lambda idx: candidate_dist_lean(  # noqa: E731
                        f_b_tab, f_a_tab, idx, gather_fn=gf
                    )
                )
        py_p, px_p, d_p = patchmatch_sweeps_lean(
            f_b_tab,
            f_a_tab,
            py_m,
            px_m,
            jax.random.fold_in(key, pm_iters),
            ha=ha,
            wa=wa,
            iters=polish_iters,
            n_random=polish_random,
            coh_factor=coh,
            dist_fn=polish_dist,
        )
    else:
        py_p, px_p, d_p = polish_sweeps_planes(
            py_m,
            px_m,
            d_m,
            jax.random.fold_in(key, pm_iters),
            ha=ha,
            wa=wa,
            iters=polish_iters,
            n_random=polish_random,
            coh_factor=coh,
            dist_fn=dist_fn,
        )
    if cfg.kappa > 0.0:
        # Ashikhmin adoption pass on the plane-pair field — the same
        # rule tile_patchmatch runs after ITS polish (the kappa-aware
        # oracle's semantics; see the standard path's comment), so the
        # kappa acceptance behavior no longer diverges above the
        # feature budget.
        from .coherence import coherence_sweeps_lean

        py_p, px_p, d_p = coherence_sweeps_lean(
            py_p, px_p, d_p, ha=ha, wa=wa, factor=coh, sweeps=2,
            dist_fn=dist_fn,
        )
    return py_p, px_p, d_p


class PatchMatchMatcher(Matcher):
    """PatchMatch NN-field matcher; seeds from the incoming NNF (upsampled
    from the coarser level by the driver, or random at the coarsest
    level).  Dispatch (kernels/__init__.py contract): the Pallas tile
    kernel when raw planes are provided, the level is tile-eligible, and
    pallas_mode resolves to compiled/interpret; the pure-XLA sweeps
    otherwise."""

    name = "patchmatch"

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig,
              raw: Optional[RawPlanes] = None, polish_iters=None,
              temporal=None):
        from ..kernels import resolve_pallas

        interpret = resolve_pallas(cfg)
        # Temporal-coherence term (video subsystem): an active term
        # routes through the XLA sweeps — the reference formulation of
        # the penalized metric; the tile kernel's SMEM candidate tables
        # have no previous-frame field, so dispatching it there would
        # silently drop the term.  Inactive (temporal None or tau == 0)
        # falls through to the unchanged dispatch below, bit-identical
        # to the pre-video graphs.
        if temporal is not None and cfg.tau > 0.0:
            nnf, dist = patchmatch_sweeps(
                f_b,
                f_a,
                nnf,
                key,
                iters=_pm_iters_for(cfg, *f_a.shape[:2]),
                n_random=cfg.pm_random_candidates,
                coh_factor=kappa_factor(cfg.kappa, level),
                temporal=temporal,
                tau=cfg.tau,
            )
            if cfg.kappa > 0.0:
                from .coherence import coherence_sweeps

                nnf, dist = coherence_sweeps(
                    f_b, f_a, nnf, dist,
                    factor=kappa_factor(cfg.kappa, level), sweeps=2,
                )
            return nnf, dist
        if raw is not None and interpret is not None:
            from ..kernels.patchmatch_tile import plan_channels

            h, w = f_b.shape[:2]
            ha, wa = f_a.shape[:2]
            n_src = 1 if raw.src_b.ndim == 2 else raw.src_b.shape[-1]
            n_flt = 1 if raw.flt_b.ndim == 2 else raw.flt_b.shape[-1]
            plan = plan_channels(
                n_src, n_flt, cfg, raw.src_b_coarse is not None,
                h, w, ha, wa,
            )
            if plan is not None:
                return tile_patchmatch(
                    f_b, f_a, nnf, key,
                    raw=raw, cfg=cfg, level=level, interpret=interpret,
                    plan=plan, polish_iters=polish_iters,
                )
        coh = kappa_factor(cfg.kappa, level)
        nnf, dist = patchmatch_sweeps(
            f_b,
            f_a,
            nnf,
            key,
            iters=_pm_iters_for(cfg, *f_a.shape[:2]),
            n_random=cfg.pm_random_candidates,
            coh_factor=coh,
        )
        if cfg.kappa > 0.0:
            # Same Ashikhmin adoption pass as the kernel path (see
            # tile_patchmatch) so the twin paths keep one output
            # contract.
            from .coherence import coherence_sweeps

            nnf, dist = coherence_sweeps(
                f_b, f_a, nnf, dist, factor=coh, sweeps=2
            )
        return nnf, dist


register_matcher("patchmatch", PatchMatchMatcher())
