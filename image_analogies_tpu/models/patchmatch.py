"""PatchMatch NN-field matcher (SURVEY.md §2 C9 + C10; Barnes 2009).

The reference accelerates matching with a host-side ANN library (kd-tree
family, C++) [SURVEY.md C8].  Pointer-chasing trees are anti-idiomatic on
TPU; the TPU-native ANN for nearest-neighbor *fields* is PatchMatch, whose
sweeps are whole-image vectorized ops (SURVEY.md §2 C8->C9 mapping).

Each sweep evaluates, per pixel, a fixed-size candidate set (TPU wants no
divergence — SURVEY.md §7 "ragged candidate sets"):

  - 4 propagation candidates  nnf(q -/+ delta) + delta  — these are exactly
    Ashikhmin's coherence candidates r* = s(r) + (q - r) (Hertzmann §3.2),
    so coherence search is fused into propagation rather than bolted on;
  - `pm_random_candidates` random-search candidates at exponentially
    shrinking radii around the current match (Barnes §3.2).

The kappa rule (Hertzmann §3.2): a *non-coherent* (random-search) candidate
must beat the incumbent by the factor 1 + 2^-level * kappa (level 0 =
finest, so the coherence bias is strongest at full resolution).  With
kappa=0 this is plain PatchMatch and converges to the exact NN field — the
basis of the PSNR-vs-brute oracle tests (SURVEY.md §4).

This module is the pure-JAX (XLA gather) formulation; it is both the
reference implementation for the Pallas kernel (kernels/) and the portable
path for CPU tests.  Sweeps are a `lax.scan` over iteration keys, so the
whole per-level matching is one compiled loop [north star: no per-pixel
Python steps].
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import (
    Matcher,
    candidate_dist,
    clamp_nnf,
    flat_to_nnf,
    nnf_dist,
    nnf_to_flat,
    register_matcher,
)

# Propagation neighborhood: left, right, up, down.
_DELTAS = ((0, -1), (0, 1), (-1, 0), (1, 0))


def random_init(key: jax.Array, h: int, w: int, ha: int, wa: int) -> jnp.ndarray:
    """Uniform random NNF (H, W, 2) over A's domain."""
    ky, kx = jax.random.split(key)
    py = jax.random.randint(ky, (h, w), 0, ha)
    px = jax.random.randint(kx, (h, w), 0, wa)
    return jnp.stack([py, px], axis=-1)


def _shifted(nnf: jnp.ndarray, dy: int, dx: int) -> jnp.ndarray:
    """Propagation candidate field: nnf(q - delta) + delta.

    Implemented as a roll; wrapped-around rows/cols produce harmless
    candidates that simply lose the accept test after clamping.
    """
    cand = jnp.roll(nnf, shift=(dy, dx), axis=(0, 1))
    return cand + jnp.array([dy, dx], dtype=nnf.dtype)


def patchmatch_sweeps(
    f_b: jnp.ndarray,
    f_a: jnp.ndarray,
    nnf: jnp.ndarray,
    key: jax.Array,
    *,
    iters: int,
    n_random: int,
    coh_factor: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run `iters` propagate+random-search sweeps; returns (nnf, dist).

    `coh_factor` >= 1 biases acceptance toward coherent (propagation)
    candidates: random candidates must satisfy d * coh_factor < d_current.
    """
    h, w, d = f_b.shape
    ha, wa = f_a.shape[:2]
    f_b_flat = f_b.reshape(-1, d)
    f_a_flat = f_a.reshape(-1, d)

    nnf = clamp_nnf(nnf, ha, wa)
    dist = nnf_dist(f_b, f_a_flat, nnf, wa)

    # Exponential random-search radii: max dim, halving per scale (Barnes
    # alpha = 0.5), floored at 1 px.
    max_radius = max(ha, wa)
    radii = [max(1, int(max_radius * (0.5**s))) for s in range(n_random)]

    def try_candidates(state, cand, factor):
        nnf_cur, dist_cur = state
        cand = clamp_nnf(cand, ha, wa)
        idx = nnf_to_flat(cand, wa)
        d_cand = candidate_dist(f_b_flat, f_a_flat, idx).reshape(h, w)
        # Exact ties break toward the lower flat index — the same canonical
        # representative `jnp.argmin` picks in the brute-force oracle.  In
        # flat feature regions (ubiquitous in texture-by-numbers label maps)
        # ties are massive, and without a shared canonicalization the
        # approximate and exact paths would diverge on valid-but-different
        # matches, sinking the PSNR-vs-oracle metric for no quality reason.
        idx_cur = nnf_to_flat(nnf_cur, wa).reshape(h, w)
        better = d_cand * factor < dist_cur
        tie_lower = (d_cand == dist_cur) & (idx.reshape(h, w) < idx_cur)
        accept = better | tie_lower
        nnf_new = jnp.where(accept[..., None], cand, nnf_cur)
        dist_new = jnp.where(accept, d_cand, dist_cur)
        return nnf_new, dist_new

    def sweep(state, it_key):
        # Propagation (= fused Ashikhmin coherence candidates): unbiased.
        for dy, dx in _DELTAS:
            state = try_candidates(state, _shifted(state[0], dy, dx), 1.0)
        # Unshifted neighbor matches: in tied (flat) regions the canonical
        # lowest-index match floods outward through these, mirroring the
        # uniform assignment the exact oracle produces there.
        for dy, dx in _DELTAS:
            cand = jnp.roll(state[0], shift=(dy, dx), axis=(0, 1))
            state = try_candidates(state, cand, 1.0)
        # Random search around the current best: kappa-biased.
        keys = jax.random.split(it_key, len(radii))
        for r, rk in zip(radii, keys):
            off = jax.random.randint(rk, (h, w, 2), -r, r + 1)
            state = try_candidates(state, state[0] + off, coh_factor)
        return state, None

    (nnf, dist), _ = jax.lax.scan(
        sweep, (nnf, dist), jax.random.split(key, iters)
    )
    return nnf, dist


def kappa_factor(kappa: float, level: int) -> float:
    """Hertzmann §3.2 acceptance factor, level 0 = finest."""
    return 1.0 + kappa * (2.0 ** (-level))


class PatchMatchMatcher(Matcher):
    """Pure-JAX PatchMatch; seeds from the incoming NNF (upsampled from the
    coarser level by the driver, or random at the coarsest level)."""

    name = "patchmatch"

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig):
        return patchmatch_sweeps(
            f_b,
            f_a,
            nnf,
            key,
            iters=cfg.pm_iters,
            n_random=cfg.pm_random_candidates,
            coh_factor=kappa_factor(cfg.kappa, level),
        )


register_matcher("patchmatch", PatchMatchMatcher())
