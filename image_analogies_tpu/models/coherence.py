"""Ashikhmin coherence search as a composable wrapper (SURVEY.md §2 C10).

The reference composes coherence search on top of its approximate matcher
[BASELINE.json north star: "ANN/PatchMatch ... plus Ashikhmin coherence
search"].  Here it is a `Matcher` wrapper: run the base matcher, then do
Jacobi sweeps in which each pixel considers its neighbors' matches shifted
by the relative offset (r* = s(r) + (q - r), Hertzmann §3.2 / Ashikhmin
2001) and adopts one when

    d_coherent < d_incumbent_effective

where an *approximate* incumbent defends with d * (1 + 2^-level * kappa)
and a *coherent* incumbent defends with its raw distance.  This is the
paper's acceptance rule with scan-order recursion replaced by parallel
sweeps (SURVEY.md §7 "sequential-vs-parallel tension").

For the PatchMatch matcher coherence is already fused into propagation
(models/patchmatch.py), so this wrapper is registered over the brute-force
matcher only — giving the exact-NN + coherence combination the reference
reaches with `--matcher brute --kappa K`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import (
    Matcher,
    candidate_dist,
    clamp_nnf,
    nnf_to_flat,
    register_matcher,
)
from .brute import BruteForceMatcher
from .patchmatch import _DELTAS, _shifted, kappa_factor


def coherence_sweeps(
    f_b: jnp.ndarray,
    f_a: jnp.ndarray,
    nnf: jnp.ndarray,
    dist: jnp.ndarray,
    *,
    factor: float,
    sweeps: int,
) -> tuple:
    """Bias an existing match field toward coherent source regions.

    Faithful parallelization of the per-pixel rule: the approximate match
    distance d_app fixes a per-pixel acceptance *ceiling* factor * d_app; a
    coherent candidate is adopted iff it (a) clears the ceiling and (b)
    beats the best coherent candidate seen so far (raw distance).  Jacobi
    sweeps extend coherent chains the way scan order does — candidates in
    later sweeps derive from already-adopted coherent matches.
    """
    h, w, d = f_b.shape
    ha, wa = f_a.shape[:2]
    f_b_flat = f_b.reshape(-1, d)
    f_a_flat = f_a.reshape(-1, d)

    ceiling = dist * factor
    best_coh = jnp.full_like(dist, jnp.inf)

    for _ in range(sweeps):
        for dy, dx in _DELTAS:
            cand = clamp_nnf(_shifted(nnf, dy, dx), ha, wa)
            d_cand = candidate_dist(
                f_b_flat, f_a_flat, nnf_to_flat(cand, wa)
            ).reshape(h, w)
            accept = (d_cand < best_coh) & (d_cand <= ceiling)
            nnf = jnp.where(accept[..., None], cand, nnf)
            dist = jnp.where(accept, d_cand, dist)
            best_coh = jnp.where(accept, d_cand, best_coh)
    return nnf, dist


def coherence_sweeps_lean(
    py: jnp.ndarray,
    px: jnp.ndarray,
    dist: jnp.ndarray,
    *,
    ha: int,
    wa: int,
    factor: float,
    sweeps: int,
    dist_fn,
) -> tuple:
    """`coherence_sweeps` for the lean plane-pair field: identical
    candidates, ceiling, and accept rule, with distances through the
    caller's `dist_fn` (flat idx -> d; chunked bf16 tables on the lean
    path, masked pmin-merged shard lookups on the sharded-A runner).
    Bit-identical to the stacked twin on equal tables (tested)."""
    ceiling = dist * factor
    best_coh = jnp.full_like(dist, jnp.inf)

    for _ in range(sweeps):
        for dy, dx in _DELTAS:
            cy = jnp.clip(
                jnp.roll(py, (dy, dx), (0, 1)) + dy, 0, ha - 1
            )
            cx = jnp.clip(
                jnp.roll(px, (dy, dx), (0, 1)) + dx, 0, wa - 1
            )
            d_cand = dist_fn((cy * wa + cx).reshape(-1)).reshape(py.shape)
            accept = (d_cand < best_coh) & (d_cand <= ceiling)
            py = jnp.where(accept, cy, py)
            px = jnp.where(accept, cx, px)
            dist = jnp.where(accept, d_cand, dist)
            best_coh = jnp.where(accept, d_cand, best_coh)
    return py, px, dist


class CoherenceWrapper(Matcher):
    """base matcher + kappa-biased coherence sweeps (no-op at kappa=0)."""

    def __init__(self, base: Matcher, sweeps: int = 2):
        self.base = base
        self.name = base.name
        self.sweeps = sweeps

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig,
              raw=None, polish_iters=None, temporal=None):
        nnf, dist = self.base.match(
            f_b, f_a, nnf, key=key, level=level, cfg=cfg, raw=raw,
            polish_iters=polish_iters, temporal=temporal,
        )
        if cfg.kappa > 0.0:
            nnf, dist = coherence_sweeps(
                f_b,
                f_a,
                nnf,
                dist,
                factor=kappa_factor(cfg.kappa, level),
                sweeps=self.sweeps,
            )
        return nnf, dist


# 'brute' resolves to exact NN with the kappa rule available on top —
# matching the reference's matcher x kappa flag matrix.
register_matcher("brute", CoherenceWrapper(BruteForceMatcher()))
