"""Synthesis driver + matcher implementations (SURVEY.md §2 C6-C11)."""

from .matcher import (
    Matcher,
    available_matchers,
    get_matcher,
    register_matcher,
)
from .brute import BruteForceMatcher, exact_nn
from .patchmatch import PatchMatchMatcher, patchmatch_sweeps, random_init
from .coherence import CoherenceWrapper, coherence_sweeps
from .ann import AnnMatcher
from .analogy import create_image_analogy, upsample_nnf

__all__ = [
    "Matcher",
    "available_matchers",
    "get_matcher",
    "register_matcher",
    "BruteForceMatcher",
    "exact_nn",
    "AnnMatcher",
    "PatchMatchMatcher",
    "patchmatch_sweeps",
    "random_init",
    "CoherenceWrapper",
    "coherence_sweeps",
    "create_image_analogy",
    "upsample_nnf",
]
