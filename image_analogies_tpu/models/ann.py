"""Native kd-tree ANN matcher (SURVEY.md §2 C8).

The reference backs its approximate search with a host-side C++ ANN
library (FLANN/cKDTree family) [SURVEY.md C8].  The TPU-native mapping of
that component is the Pallas PatchMatch kernel (C9) — trees don't map to
the MXU — but the CPU backend keeps a faithful native equivalent: the
C++ kd-tree in native/ann.cpp (built via g++ + ctypes, utils/native.py),
reached from inside the jitted EM step through `jax.pure_callback` (the
JAX-idiomatic host-code embedding; on TPU this is a host round trip and
is anti-idiomatic — use it with `--device cpu`, as the reference would).

Hertzmann §3.1 pairs ANN search with PCA-projected features; combine
`matcher="ann"` with `pca_dims` for the same effect.  At `ann_eps=0` the
tree search is exact and the matcher is interchangeable with `brute`
(same metric, near-identical fields modulo argmin ties); larger eps
trades quality for speed with the classic (1+eps) distance guarantee.
Kappa coherence composes on top through the same CoherenceWrapper the
brute matcher uses.  If g++ or OpenMP is unavailable the matcher falls
back to the exact XLA path with a logged warning, keeping configs
portable.
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import Matcher, flat_to_nnf, register_matcher
from .brute import exact_nn
from .coherence import CoherenceWrapper

log = logging.getLogger(__name__)


# Host-side tree cache: f_a is constant for a whole pyramid level but the
# jitted EM step calls the matcher em_iters times, so without a cache the
# O(N log N) build (and nothing else) would re-run per iteration.  Keyed
# on a full-content hash — hashing is ~10x cheaper than building and a
# false hit would silently corrupt matches, so no fingerprint shortcuts.
_TREE_CACHE: "dict" = {}
_TREE_CACHE_CAP = 4
_tree_lock = __import__("threading").Lock()


def _tree_for(f_a: np.ndarray):
    from ..utils.native import load_ann

    lib = load_ann()
    key = (f_a.shape, hash(f_a.tobytes()))
    with _tree_lock:
        if key in _TREE_CACHE:
            return _TREE_CACHE[key][1]
        while len(_TREE_CACHE) >= _TREE_CACHE_CAP:
            _, (keep, old) = _TREE_CACHE.popitem()
            lib.ann_free(old)
        f32p = ctypes.POINTER(ctypes.c_float)
        tree = lib.ann_build(
            f_a.ctypes.data_as(f32p), f_a.shape[0], f_a.shape[1]
        )
        # The C++ Tree owns a copy of the data; f_a is retained only so
        # the hash key can be re-derived for debugging.
        _TREE_CACHE[key] = (f_a, tree)
        return tree


def _host_ann_query(f_b_flat: np.ndarray, f_a_flat: np.ndarray, eps: float):
    """Query the (cached) tree on the host (numpy in/out)."""
    from ..utils.native import load_ann

    lib = load_ann()
    f_a = np.ascontiguousarray(f_a_flat, np.float32)
    f_b = np.ascontiguousarray(f_b_flat, np.float32)
    n_q = f_b.shape[0]
    idx = np.empty(n_q, np.int32)
    dist = np.empty(n_q, np.float32)
    f32p = ctypes.POINTER(ctypes.c_float)
    tree = _tree_for(f_a)
    lib.ann_query(
        tree,
        f_b.ctypes.data_as(f32p),
        n_q,
        ctypes.c_float(eps),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        dist.ctypes.data_as(f32p),
    )
    return idx, dist


class AnnMatcher(Matcher):
    """C++ kd-tree NN via pure_callback; exact-XLA fallback if unbuilt."""

    name = "ann"

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig,
              raw=None):
        from ..utils.native import ann_available

        h, w, d = f_b.shape
        ha, wa = f_a.shape[:2]
        f_b_flat = f_b.reshape(-1, d).astype(jnp.float32)
        f_a_flat = f_a.reshape(-1, d).astype(jnp.float32)
        if not ann_available():
            log.warning(
                "native ANN library unavailable; ann matcher falling back "
                "to exact XLA search"
            )
            idx, dist = exact_nn(
                f_b_flat, f_a_flat, chunk=min(cfg.brute_chunk, h * w)
            )
        else:
            eps = float(cfg.ann_eps)

            def host(fb, fa):
                return _host_ann_query(fb, fa, eps)

            idx, dist = jax.pure_callback(
                host,
                (
                    jax.ShapeDtypeStruct((h * w,), jnp.int32),
                    jax.ShapeDtypeStruct((h * w,), jnp.float32),
                ),
                f_b_flat,
                f_a_flat,
                vmap_method="sequential",
            )
        return flat_to_nnf(idx, wa, (h, w)), dist.reshape(h, w)


# Like 'brute': kappa coherence composes on top (reference matcher x
# kappa flag matrix).
register_matcher("ann", CoherenceWrapper(AnnMatcher()))
