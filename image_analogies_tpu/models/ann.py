"""Native kd-tree ANN matcher (SURVEY.md §2 C8).

The reference backs its approximate search with a host-side C++ ANN
library (FLANN/cKDTree family) [SURVEY.md C8].  The TPU-native mapping of
that component is the Pallas PatchMatch kernel (C9) — trees don't map to
the MXU — but the CPU backend keeps a faithful native equivalent: the
C++ kd-tree in native/ann.cpp (built via g++ + ctypes, utils/native.py),
reached from inside the jitted EM step through `jax.pure_callback` (the
JAX-idiomatic host-code embedding; on TPU this is a host round trip and
is anti-idiomatic — use it with `--device cpu`, as the reference would).

Hertzmann §3.1 pairs ANN search with PCA-projected features; combine
`matcher="ann"` with `pca_dims` for the same effect.  At `ann_eps=0` the
tree search is exact and the matcher is interchangeable with `brute`
(same metric, near-identical fields modulo argmin ties); larger eps
trades quality for speed with the classic (1+eps) distance guarantee.
Kappa coherence composes on top through the same CoherenceWrapper the
brute matcher uses.  If g++ or OpenMP is unavailable the matcher falls
back to the exact XLA path with a logged warning, keeping configs
portable.
"""

from __future__ import annotations

import collections
import ctypes
import logging
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig
from .matcher import Matcher, flat_to_nnf, register_matcher
from .brute import exact_nn
from .coherence import CoherenceWrapper

log = logging.getLogger(__name__)


class _TreeEntry:
    """A cached kd-tree plus the bookkeeping that makes eviction safe:
    `refs` counts in-flight queries (JAX may run pure_callbacks on
    several threads at once), and an entry evicted while referenced is
    freed by the *last* releaser instead of the evictor — ann_query runs
    outside the cache lock, so freeing eagerly would be a use-after-free
    on the querying thread."""

    __slots__ = ("tree", "refs", "evicted")

    def __init__(self, tree):
        self.tree = tree
        self.refs = 0
        self.evicted = False


# Host-side tree cache: f_a is constant for a whole pyramid level but the
# jitted EM step calls the matcher em_iters times, so without a cache the
# O(N log N) build (and nothing else) would re-run per iteration.  Keyed
# on a full-content hash — hashing is ~10x cheaper than building and a
# false hit would silently corrupt matches, so no fingerprint shortcuts.
# Only the key and the native handle are stored (the C++ Tree owns its
# own copy of the data); LRU order, oldest evicted first.
_TREE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_TREE_CACHE_CAP = 4
_tree_lock = threading.Lock()


def _free_tree(lib, tree) -> None:
    """Single funnel for native frees (tests monkeypatch this)."""
    lib.ann_free(tree)


def _acquire_tree(f_a: np.ndarray) -> _TreeEntry:
    """Look up (or build) the tree for `f_a` and take a query reference.

    Callers must pair with `_release_tree`.  The build runs under the
    lock — simpler than racing builders, and builds are rare (once per
    pyramid level)."""
    from ..utils.native import load_ann

    lib = load_ann()
    key = (f_a.shape, hash(f_a.tobytes()))
    with _tree_lock:
        entry = _TREE_CACHE.get(key)
        if entry is None:
            f32p = ctypes.POINTER(ctypes.c_float)
            tree = lib.ann_build(
                f_a.ctypes.data_as(f32p), f_a.shape[0], f_a.shape[1]
            )
            entry = _TreeEntry(tree)
            _TREE_CACHE[key] = entry
            while len(_TREE_CACHE) > _TREE_CACHE_CAP:
                _, old = _TREE_CACHE.popitem(last=False)  # LRU: oldest out
                if old.refs == 0:
                    _free_tree(lib, old.tree)
                else:
                    old.evicted = True
        else:
            _TREE_CACHE.move_to_end(key)
        entry.refs += 1
        return entry


def _release_tree(entry: _TreeEntry) -> None:
    from ..utils.native import load_ann

    with _tree_lock:
        entry.refs -= 1
        if entry.evicted and entry.refs == 0:
            _free_tree(load_ann(), entry.tree)


def _host_ann_query(f_b_flat: np.ndarray, f_a_flat: np.ndarray, eps: float):
    """Query the (cached) tree on the host (numpy in/out)."""
    from ..utils.native import load_ann

    lib = load_ann()
    f_a = np.ascontiguousarray(f_a_flat, np.float32)
    f_b = np.ascontiguousarray(f_b_flat, np.float32)
    n_q = f_b.shape[0]
    idx = np.empty(n_q, np.int32)
    dist = np.empty(n_q, np.float32)
    f32p = ctypes.POINTER(ctypes.c_float)
    entry = _acquire_tree(f_a)
    try:
        lib.ann_query(
            entry.tree,
            f_b.ctypes.data_as(f32p),
            n_q,
            ctypes.c_float(eps),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dist.ctypes.data_as(f32p),
        )
    finally:
        _release_tree(entry)
    return idx, dist


class AnnMatcher(Matcher):
    """C++ kd-tree NN via pure_callback; exact-XLA fallback if unbuilt."""

    name = "ann"

    def match(self, f_b, f_a, nnf, *, key, level, cfg: SynthConfig,
              raw=None, polish_iters=None, temporal=None):
        from ..utils.native import ann_available

        h, w, d = f_b.shape
        ha, wa = f_a.shape[:2]
        f_b_flat = f_b.reshape(-1, d).astype(jnp.float32)
        f_a_flat = f_a.reshape(-1, d).astype(jnp.float32)
        if not ann_available():
            log.warning(
                "native ANN library unavailable; ann matcher falling back "
                "to exact XLA search"
            )
            idx, dist = exact_nn(
                f_b_flat, f_a_flat, chunk=min(cfg.brute_chunk, h * w)
            )
        else:
            eps = float(cfg.ann_eps)

            def host(fb, fa):
                return _host_ann_query(fb, fa, eps)

            idx, dist = jax.pure_callback(
                host,
                (
                    jax.ShapeDtypeStruct((h * w,), jnp.int32),
                    jax.ShapeDtypeStruct((h * w,), jnp.float32),
                ),
                f_b_flat,
                f_a_flat,
                vmap_method="sequential",
            )
        return flat_to_nnf(idx, wa, (h, w)), dist.reshape(h, w)


# Like 'brute': kappa coherence composes on top (reference matcher x
# kappa flag matrix).
register_matcher("ann", CoherenceWrapper(AnnMatcher()))
