"""image_analogies_tpu — a TPU-native Image Analogies framework.

A from-scratch JAX/XLA/Pallas rebuild of the capability surface of
`flair2005/image-analogies-python` (Hertzmann et al., Image Analogies,
SIGGRAPH 2001): texture-by-numbers, artistic filters, super-resolution
analogies and luminance-only transfer, driven by a coarse-to-fine pyramid
synthesizer whose per-level best-match step runs as PatchMatch sweeps
(jitted XLA sweeps; Pallas kernels in progress) behind a `Matcher` plugin
interface.  See SURVEY.md for the blueprint and component inventory.

The package name is the importable form of the task's
`image-analogies-python_tpu` (hyphens are not valid in Python modules).
"""

from .config import SynthConfig
from .models import (
    available_matchers,
    create_image_analogy,
    get_matcher,
    register_matcher,
)
from .utils import load_image, psnr, save_image

__version__ = "0.1.0"

__all__ = [
    "SynthConfig",
    "create_image_analogy",
    "available_matchers",
    "get_matcher",
    "register_matcher",
    "load_image",
    "save_image",
    "psnr",
    "__version__",
]
