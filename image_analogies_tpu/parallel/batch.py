"""Batched NPR runner — config 5 (SURVEY.md §2 C15, §3.4).

Synthesizes B' for a batch of video frames against one shared (A, A')
style pair: frames are sharded over the mesh's "batch" axis (ICI moves
nothing per-frame — synthesis is embarrassingly parallel), the A-side
feature tables are replicated once.  The per-level EM step is the same
pure function the single-image driver uses, `vmap`-ed over the frame axis
and jitted with `NamedSharding` constraints — XLA/pjit partitions it over
the mesh [north star: data-parallel on v5e-8].

Degrades to a 1-chip mesh on a single device; tested on the 8-virtual-CPU
mesh (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from ..config import SynthConfig
from ..models.analogy import (
    _finalize,
    _save_level,
    _with_steerable,
    make_em_step,
    resume_prologue,
)
from ..ops.color import rgb_to_yiq
from ..ops.features import assemble_features
from ..ops.pyramid import build_pyramid
from ..ops.remap import luminance_stats
from .mesh import BATCH_AXIS, batch_sharding, make_mesh, replicated


def _batch_step_fn(cfg: SynthConfig, level: int, has_coarse: bool, mesh_key,
                   polish_iters=None, axis: str = BATCH_AXIS):
    # save_level_artifacts is not step-shaping (it only names a host-side
    # checkpoint dir); stripping it keeps one compiled step per
    # (cfg, level) even when chunked runs vary the per-chunk subdir.
    cfg = dataclasses.replace(cfg, save_level_artifacts=None)
    return _batch_step_fn_cached(
        cfg, level, has_coarse, mesh_key, polish_iters, axis
    )


@functools.lru_cache(maxsize=64)
def _batch_step_fn_cached(
    cfg: SynthConfig, level: int, has_coarse: bool, mesh_key,
    polish_iters=None, axis: str = BATCH_AXIS,
):
    mesh = _MESHES[mesh_key]
    step = make_em_step(cfg, level, has_coarse, polish_iters=polish_iters)
    # Frame-carried args are vmapped; the A-side (f_a, copy_a), the PCA
    # basis, and the kernel's A planes are shared across frames.  The
    # Pallas tile kernel batches under vmap (the frame axis becomes a
    # leading grid dimension), so the kernel path works per shard.
    # `axis` names the mesh axis the frame/slab stack shards over
    # ('slabs' on the 2-D bands x slabs spatial runner).
    in_axes = (0, 0, 0, 0, None, None, 0, 0, None, None)
    shard = batch_sharding(mesh, axis)
    repl = replicated(mesh)
    shardings = (
        shard, shard, shard, shard, repl, repl, shard, shard, repl, repl,
    )
    vstep = jax.vmap(step, in_axes=in_axes)
    return jax.jit(
        vstep,
        in_shardings=shardings,
        out_shardings=(shard, shard, shard),
    )


def _lean_step_fn(cfg: SynthConfig, level: int, has_coarse: bool, mesh_key,
                  polish_iters=None, axis: str = BATCH_AXIS):
    """Vmapped LEAN em step (plane-pair NN field, bf16 chunked tables)
    for the sharded runners — same sharding layout as `_batch_step_fn`
    but with the field carried as a (py, px) tuple per slab/frame."""
    cfg = dataclasses.replace(cfg, save_level_artifacts=None)
    return _lean_step_fn_cached(
        cfg, level, has_coarse, mesh_key, polish_iters, axis
    )


@functools.lru_cache(maxsize=64)
def _lean_step_fn_cached(
    cfg: SynthConfig, level: int, has_coarse: bool, mesh_key,
    polish_iters=None, axis: str = BATCH_AXIS,
):
    mesh = _MESHES[mesh_key]
    step = make_em_step(
        cfg, level, has_coarse, lean=True, polish_iters=polish_iters
    )
    in_axes = (0, 0, 0, 0, None, None, (0, 0), 0, None, None)
    shard = batch_sharding(mesh, axis)
    repl = replicated(mesh)
    shardings = (
        shard, shard, shard, shard, repl, repl, (shard, shard), shard,
        repl, repl,
    )
    vstep = jax.vmap(step, in_axes=in_axes)
    return jax.jit(
        vstep,
        in_shardings=shardings,
        out_shardings=((shard, shard), shard, shard),
    )


# Round 18: the serving tier's persistent executable cache interposes
# here.  When a hook is installed (`set_persist_hook` — the daemon's
# serving/excache.DiskExecCache), the prologue/level jit factories
# return a thin wrapper that consults the hook AT CALL TIME: the hook
# either runs a restored (deserialized) executable, or AOT-compiles
# the jit function itself (lower().compile()) so the cold path's one
# compile produces a serializable artifact — `jax.jit`'s internal
# executable cache is NOT reused by AOT lowering, so the hook must own
# compilation or the cold path would compile twice.  With no hook
# installed the factories return the plain jit functions: non-serving
# paths are bit-and-perf unchanged.  The hook key is (role, ident) —
# ident is the SAME stripped-config tuple the lru caches key on, so
# the persisted identity can never split or alias entries the in-
# process caches share.
_PERSIST_HOOK = None


def set_persist_hook(hook) -> None:
    """Install (or clear, with None) the process-wide executable
    persist hook.  Caller contract: the hook's `call(role, ident,
    jit_fn, args)` must return exactly `jit_fn(*args)`'s value and
    must fall back to `jit_fn` on any persistence failure — the hook
    is a cache, never a semantic layer."""
    global _PERSIST_HOOK
    _PERSIST_HOOK = hook


def get_persist_hook():
    return _PERSIST_HOOK


def clear_persist_loaded() -> None:
    """Epoch-eviction funnel (kernels.patchmatch_tile
    .clear_compiled_level_caches): drop the hook's in-memory loaded-
    executable table alongside the jit lru caches, leaving the DISK
    tier intact — a demoted key's next use either restores from disk
    or recompiles, both honest."""
    hook = _PERSIST_HOOK
    if hook is not None:
        hook.clear_loaded()


class _PersistWrap:
    """Callable facade over one jit function: routes through the
    persist hook when one is installed at call time (the hook can be
    installed/removed between factory call and invocation — daemons
    start after import), else calls the jit function directly."""

    __slots__ = ("role", "ident", "jit_fn")

    def __init__(self, role, ident, jit_fn):
        self.role = role
        self.ident = ident
        self.jit_fn = jit_fn

    def __call__(self, *args):
        hook = _PERSIST_HOOK
        if hook is None:
            return self.jit_fn(*args)
        return hook.call(self.role, self.ident, self.jit_fn, args)


def _batch_prologue_fn(cfg: SynthConfig, levels: int, mesh_key):
    from ..models.analogy import _strip_noncompute

    cfg_s = _strip_noncompute(cfg)
    fn = _batch_prologue_fn_cached(cfg_s, levels, mesh_key)
    if _PERSIST_HOOK is not None:
        return _PersistWrap(
            "batch_prologue", (cfg_s, levels, mesh_key), fn
        )
    return fn


@functools.lru_cache(maxsize=32)
def _batch_prologue_fn_cached(cfg: SynthConfig, levels: int, mesh_key):
    """Whole batch-chunk prologue as ONE compiled call: channel split +
    shared-stack remap + every pyramid (A side replicated, frame side
    vmapped/sharded).  Dispatched eagerly this was ~100 device calls
    per chunk; on the tunnelled platform host dispatch overhead made
    the 8x1024^2 config's wall 2.5-3.5x its device time."""
    mesh = _MESHES[mesh_key]
    shard = batch_sharding(mesh)
    repl = replicated(mesh)

    def prologue(a, ap, frames, b_stats):
        src_a, flt_a, src_b, copy_a, yiq_b = _batched_channels(
            a, ap, frames, cfg, b_stats=b_stats
        )
        pyr_src_a = tuple(
            _with_steerable(x, cfg) for x in build_pyramid(src_a, levels)
        )
        pyr_flt_a = tuple(build_pyramid(flt_a, levels))
        pyr_copy_a = tuple(build_pyramid(copy_a, levels))
        vpyr = jax.vmap(lambda x: tuple(build_pyramid(x, levels)))
        raw_b = vpyr(src_b)
        pyr_src_b = tuple(
            jax.vmap(lambda x: _with_steerable(x, cfg))(lvl)
            for lvl in raw_b
        )
        return (
            pyr_src_a, pyr_flt_a, pyr_copy_a, pyr_src_b, tuple(raw_b),
            yiq_b,
        )

    return jax.jit(
        prologue, in_shardings=(repl, repl, shard, repl)
    )


def _batch_feature_table_bytes(
    n_frames: int, h: int, w: int, ha: int, wa: int
) -> int:
    """HBM cost of a batch level's assembled f32 feature tables: one
    128-lane-padded B table per resident frame plus the shared A table
    (see models/analogy._feature_table_bytes for the padding law)."""
    return (n_frames * h * w + ha * wa) * 128 * 4


def _batch_level_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                    mesh_key, fa_external: bool = False,
                    lean: bool = False, prev_kind: str = "stacked",
                    fuse: bool = True):
    from ..models.analogy import _strip_noncompute

    cfg_s = _strip_noncompute(cfg)
    fn = _batch_level_fn_cached(
        cfg_s, level, has_coarse, mesh_key, fa_external,
        lean, prev_kind, fuse,
    )
    # fuse=False returns an EAGER function (no .lower) — never wrapped.
    if fuse and _PERSIST_HOOK is not None:
        return _PersistWrap(
            "batch_level",
            (cfg_s, level, has_coarse, mesh_key, fa_external, lean,
             prev_kind, fuse),
            fn,
        )
    return fn


@functools.lru_cache(maxsize=64)
def _batch_level_fn_cached(cfg: SynthConfig, level: int, has_coarse: bool,
                           mesh_key, fa_external: bool = False,
                           lean: bool = False, prev_kind: str = "stacked",
                           fuse: bool = True):
    """One batch pyramid level as ONE compiled call: A-side feature
    assembly (+PCA) + kernel A-plane prep + vmapped state glue + all
    `cfg.em_iters` vmapped EM steps, with data-parallel shardings.

    `lean=True` mirrors the single driver's lean levels (bf16 chunked
    feature tables, per-frame (py, px) plane-pair fields — a stacked
    (F, H, W, 2) field pads 2 -> 128 lanes) for batch levels whose
    resident tables would exceed cfg.feature_bytes_budget
    (`_batch_feature_table_bytes`: F B-tables + the shared A table).
    `prev_kind` ('stacked' | 'planes') is the static layout of the
    incoming coarser level's field, exactly as in the single driver.

    The batch body IS models/analogy's level body: the dispatch
    decisions come from the shared `plan_level` and the state glue from
    the shared `_level_state_glue(batched=True)` (per-frame PRNG
    streams bit-identical to the unfused runner's `frame_keys`
    derivation); only the vmap wrapping, shardings, and per-frame key
    derivation live here.  `fa_external=True` takes the A-side features
    as arguments, assembled by the same standalone `_assemble_fa_fn`
    jit the single driver uses for big style pairs (fusing assembly
    with the EM steps measured 20 GB of HLO temp at 2048^2 —
    models/analogy._SPLIT_ASSEMBLY_BYTES)."""
    mesh = _MESHES[mesh_key]
    shard = batch_sharding(mesh)
    repl = replicated(mesh)
    step_final = make_em_step(cfg, level, has_coarse, lean)
    # Mirrors models/analogy._level_fn_cached: non-final EM iterations
    # skip the gather-bound per-pixel polish (config.py
    # pm_polish_final_only).
    step_mid = (
        make_em_step(cfg, level, has_coarse, lean, polish_iters=0)
        if cfg.pm_polish_final_only
        else step_final
    )

    def run_level(src_a_l, flt_a_l, src_a_c, flt_a_c, src_b_l, src_b_c,
                  raw_b_l, copy_a_l, prev_nnf, prev_bp, level_key,
                  frame_idx, f_a_ext=None, proj_ext=None):
        from ..models.analogy import (
            _level_plan,
            assemble_features_lean,
        )
        from ..ops.pca import fit_and_project

        h, w = src_b_l.shape[1:3]
        ha, wa = src_a_l.shape[:2]
        if fa_external:
            f_a, proj = f_a_ext, proj_ext
        elif lean:
            f_a = assemble_features_lean(
                src_a_l, flt_a_l, cfg, src_a_c, flt_a_c
            )
            proj = None
        else:
            f_a = assemble_features(
                src_a_l, flt_a_l, cfg, src_a_c, flt_a_c
            )
            f_a, proj = fit_and_project(f_a, cfg.pca_dims)

        a_planes = None
        plan = _level_plan(cfg, src_a_l, flt_a_l, has_coarse, h, w)
        if plan is not None:
            from ..kernels.patchmatch_tile import prepare_a_planes

            specs, use_coarse, n_bands = plan
            a_planes = prepare_a_planes(
                src_a_l,
                flt_a_l,
                src_a_c if use_coarse else None,
                flt_a_c if use_coarse else None,
                specs,
                n_bands=n_bands,
            )

        def frame_keys(base_key):
            return jax.vmap(
                lambda i: jax.random.fold_in(base_key, i)
            )(frame_idx)

        from ..models.analogy import _level_state_glue

        nnf, flt_bp, flt_bp_coarse = _level_state_glue(
            lean, prev_kind, prev_nnf, prev_bp, raw_b_l, h, w, ha, wa,
            frame_keys(jax.random.fold_in(level_key, 0x1217)),
            batched=True,
        )

        nnf_ax = (0, 0) if lean else 0
        mk_vstep = lambda s: jax.vmap(  # noqa: E731
            s, in_axes=(0, 0, 0, 0, None, None, nnf_ax, 0, None, None)
        )
        vstep_final, vstep_mid = mk_vstep(step_final), mk_vstep(step_mid)
        dist = bp = None
        for em in range(cfg.em_iters):
            vstep = (
                vstep_final if em == cfg.em_iters - 1 else vstep_mid
            )
            nnf, dist, bp = vstep(
                src_b_l,
                flt_bp,
                src_b_c if has_coarse else src_b_l,
                flt_bp_coarse if has_coarse else flt_bp,
                f_a,
                copy_a_l,
                nnf,
                frame_keys(jax.random.fold_in(level_key, em)),
                proj,
                a_planes,
            )
            flt_bp = bp
        return nnf, dist, bp

    # fuse=False (oversized brute levels — models/analogy
    # ._SAFE_EXEC_DIST_ELEMS): run eagerly so each jnp op and each
    # exact_nn_pallas query chunk dispatches as its own execution;
    # `synthesize_batch` forces frames_per_step=1 in this regime so the
    # vmap axis never multiplies the per-execution work.  Shardings are
    # moot there: the path exists for the single-chip full-synthesis
    # oracle at >= 2048^2 (SCALE_r04), never for production synthesis.
    if not fuse:
        return run_level
    return jax.jit(
        run_level,
        in_shardings=(
            repl, repl, repl, repl, shard, shard, shard, repl,
            shard, shard, repl, repl, repl, repl,
        ),
        out_shardings=(shard, shard, shard),
    )


# jit caches need hashable mesh handles; Mesh objects are hashable but we
# key the lru_cache on a stable token so reruns reuse compilations.
_MESHES = {}


def _mesh_token(mesh) -> tuple:
    # The mesh SHAPE is part of the identity: (2, 2) and (4, 1) meshes
    # over the same four devices with the same axis names compile
    # different programs (observed: the 2-D spatial runner reused a
    # (2, 2)-mesh step fn for a (4, 1) mesh and crashed on spec
    # mismatch — or worse, would silently mis-shard on agreeing shapes).
    token = (
        tuple(d.id for d in mesh.devices.flat),
        mesh.axis_names,
        tuple(mesh.devices.shape),
    )
    _MESHES[token] = mesh
    return token


def ingest_frame_dir(path: str, *, strict: bool = False):
    """Load a directory of frames with PER-FRAME fault isolation
    (round 12): one unreadable or undecodable frame is skipped with a
    recorded status instead of aborting the whole batch.

    Returns (frames, names, failures): `frames` a (F, H, W[, 3]) f32
    stack of the frames that loaded, `names` their filenames in sorted
    order, `failures` a list of {"path", "reason"} records for the
    skipped ones (the CLI prints them in the batch epilogue and books
    `ia_frames_failed_total{reason}`).  `strict=True` (the CLI's
    --strict-frames) restores abort-on-first-error.  Zero loadable
    frames raise regardless — there is no batch to run."""
    import numpy as np

    from ..utils.io import load_image

    names = sorted(
        f for f in os.listdir(path)
        if f.lower().endswith((".png", ".jpg", ".jpeg"))
    )
    decoded, failures = [], []
    for name in names:
        fpath = os.path.join(path, name)
        try:
            img = load_image(fpath)
        except Exception as e:  # noqa: BLE001 - isolate, record, go on
            if strict:
                raise RuntimeError(
                    f"batch ingest: frame {fpath!r} failed "
                    f"({e}) and --strict-frames is set"
                ) from e
            failures.append({
                "path": fpath,
                "reason": f"{type(e).__name__}: {e}",
            })
            continue
        decoded.append((name, fpath, img))
    if not decoded:
        raise RuntimeError(
            f"batch ingest: no loadable frames in {path!r} "
            f"({len(failures)} failed, {len(names)} candidates)"
        )
    stack, ok_names = _majority_shape_filter(
        [(name, fpath, img) for name, fpath, img in decoded],
        strict, failures, "--strict-frames is set",
    )
    return stack, ok_names, failures


def _majority_shape_filter(decoded, strict, failures, strict_hint):
    """Shared shape-consistency pass for both ingest front-ends.

    Shape reference: the MAJORITY shape of the decoded frames (ties
    -> first seen), not the first frame — a stray odd-sized
    thumbnail sorting first must be the skipped outlier, not the
    reference that silently discards the whole real batch with
    exit 0.  `decoded` is (label, ident, img) triples; `ident` is what
    failure records and strict errors name (a file path, or an
    in-memory index label)."""
    import numpy as np

    counts: dict = {}
    for _name, _ident, img in decoded:
        counts[img.shape] = counts.get(img.shape, 0) + 1
    ref_shape = max(counts, key=lambda s: counts[s])
    loaded, ok_names = [], []
    for name, ident, img in decoded:
        if img.shape != ref_shape:
            reason = (
                f"ValueError: frame shape {img.shape} != the batch's "
                f"majority shape {ref_shape}"
            )
            if strict:
                raise RuntimeError(
                    f"batch ingest: frame {ident!r} failed ({reason}) "
                    f"and {strict_hint}"
                )
            failures.append({"path": ident, "reason": reason})
            continue
        loaded.append(img)
        ok_names.append(name)
    return np.stack(loaded), ok_names


def ingest_frames(arrays, *, strict: bool = False):
    """In-memory twin of `ingest_frame_dir` (round 13: the serving
    daemon dispatches request payloads without tempfile round-trips).

    `arrays` is a sequence of per-frame arrays (H, W[, 3]) — or one
    already-stacked (F, H, W[, 3]) array, accepted as the trivial
    fast path.  Applies the same per-frame fault isolation and
    majority-shape rule as the file front-end: a non-array entry, a
    non-2D/3D shape, or a shape-minority frame is skipped with a
    recorded {"path": "frames[i]", "reason"} failure (`strict=True`
    raises on the first).  Returns (frames, names, failures) with
    `frames` a float32 stack and `names` the "frames[i]" labels of the
    kept entries.  Zero usable frames raise regardless."""
    import numpy as np

    if isinstance(arrays, np.ndarray) and arrays.ndim in (3, 4):
        arrays = list(arrays) if arrays.ndim == 4 else [arrays]
    decoded, failures = [], []
    for i, arr in enumerate(arrays):
        label = f"frames[{i}]"
        try:
            img = np.asarray(arr, dtype=np.float32)
            if img.ndim not in (2, 3) or min(img.shape[:2]) < 1:
                raise ValueError(
                    f"frame array has shape {img.shape}, expected "
                    "(H, W) or (H, W, C)"
                )
            if img.ndim == 3 and img.shape[2] not in (1, 3):
                raise ValueError(
                    f"frame array has {img.shape[2]} channels, "
                    "expected 1 or 3"
                )
        except Exception as e:  # noqa: BLE001 - isolate, record, go on
            if strict:
                raise RuntimeError(
                    f"batch ingest: frame {label!r} failed "
                    f"({e}) and strict ingest is set"
                ) from e
            failures.append({
                "path": label,
                "reason": f"{type(e).__name__}: {e}",
            })
            continue
        decoded.append((label, label, img))
    if not decoded:
        raise RuntimeError(
            f"batch ingest: no usable in-memory frames "
            f"({len(failures)} failed)"
        )
    stack, ok_names = _majority_shape_filter(
        decoded, strict, failures, "strict ingest is set"
    )
    return stack, ok_names, failures


def synthesize_batch(
    a,
    ap,
    frames,
    cfg: Optional[SynthConfig] = None,
    mesh=None,
    progress=None,
    frames_per_step: Optional[int] = None,
    resume_from: Optional[str] = None,
    resume_strict: bool = False,
    frame_indices=None,
    return_nnf: bool = False,
    _b_stats=None,
    _frame_offset: int = 0,
    _n_stack: Optional[int] = None,
):
    """B' for every frame in `frames` ((F,H,W,3) or (F,H,W)) against the
    shared style pair (a, ap).  Returns stacked B' shaped like `frames`.

    `return_nnf=True` returns `(outputs, nnf)` instead, where `nnf` is
    the per-frame converged finest-level field as one (F, H, W, 2) int
    array (lean plane pairs host-stacked, exactly the checkpoint
    writer's schema) — the video subsystem's warm-start producer
    (image_analogies_tpu/video); historically these fields were
    discarded after synthesis.

    Frame counts that don't divide the mesh are padded (last frame
    repeated) and trimmed after synthesis, so every device stays busy.
    `progress` is an optional `utils.progress.ProgressWriter`.

    `frames_per_step` bounds how many frames are resident at once: the
    full-scale NPR config (8x1024^2) budgets one frame per chip on a
    v5e-8; on fewer chips the same run exceeds HBM unless frames are
    processed in sequential microbatches.  Style luminance-remap
    statistics are computed over the WHOLE stack regardless of chunking
    (temporal coherence), and per-frame PRNG keys derive from the GLOBAL
    frame index, so outputs are invariant to the chosen chunking (a
    rerun on a different chip count must reproduce the same frames).

    `resume_from`: per-level checkpoint dir of a prior run with
    `cfg.save_level_artifacts` (SURVEY.md §5 checkpoint/resume) —
    restarts from the finest completed level's whole-batch (nnf, B')
    state, exactly the single-image scheme.  The fingerprint covers the
    *unpadded* frame-stack shape plus the whole-stack identity (total
    frame count, chunk offset), so checkpoints bind to the same frames
    AND the same overall stack — appending frames changes the
    whole-stack remap statistics, so a per-chunk checkpoint from the
    shorter stack must not be reused — but NOT to the mesh's padding
    grain: saves trim the padding duplicates and resumes re-pad for
    their own device count (round 12; the supervisor's mesh->single
    degradation rung resumes mesh-written checkpoints this way).
    Chunked runs write (and resume) per-chunk subdirectories.

    `frame_indices` (round 13, the serving daemon's isolation knob)
    overrides the PRNG identity of each frame: by default frame i's
    key stream derives from its global stack position (temporal
    batches — a rerun of the same video must reproduce itself), but a
    serving batch coalesces UNRELATED requests, and each request's
    output must match what a solo dispatch of that request would
    produce regardless of co-tenants.  Passing `frame_indices=[0]*F`
    gives every frame the key stream of a single-frame run, making
    outputs batch-composition-independent.  Length must equal the
    frame count; entries need not be distinct.

    `_b_stats` / `_frame_offset` / `_n_stack` are the internal
    whole-stack stats / global-frame-index / total-stack-length
    pass-throughs for chunked calls.
    """
    import time

    from ..telemetry.spans import as_tracer

    tracer = as_tracer(progress)
    cfg = cfg or SynthConfig()
    mesh = mesh or make_mesh()
    if frames_per_step is not None and frames_per_step < 1:
        raise ValueError("frames_per_step must be >= 1")
    if cfg.matcher == "brute" and frames.shape[0] > 1:
        # Oversized brute searches run UNFUSED (one execution per op /
        # query chunk — models/analogy._SAFE_EXEC_DIST_ELEMS); frames
        # must then microbatch one at a time, or the vmap axis would
        # multiply every chunk execution's work right back past the
        # budget the unfusing enforces.
        from ..models.analogy import _SAFE_EXEC_DIST_ELEMS

        h0, w0 = frames.shape[1:3]
        work = cfg.em_iters * (h0 * w0) * (a.shape[0] * a.shape[1])
        if (
            work * min(frames_per_step or frames.shape[0], frames.shape[0])
            > _SAFE_EXEC_DIST_ELEMS
        ):
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "brute matcher at this scale exceeds the safe "
                "per-execution work budget: forcing frames_per_step=1 "
                "(was %s) and unfused level dispatch", frames_per_step,
            )
            frames_per_step = 1
    if frame_indices is not None:
        frame_indices = [int(i) for i in frame_indices]
        if len(frame_indices) != frames.shape[0]:
            raise ValueError(
                f"frame_indices has {len(frame_indices)} entries for "
                f"{frames.shape[0]} frames"
            )
    n_stack = _n_stack if _n_stack is not None else frames.shape[0]
    if _b_stats is None and cfg.color_mode == "luminance" and cfg.luminance_remap:
        # One style normalization for the WHOLE (unpadded) stack: temporal
        # coherence must depend on neither the chunking nor the mesh's
        # padding grain, so chunked and unchunked paths compute the same
        # stats from the same frames, once, here.
        fr = jnp.asarray(frames, jnp.float32)
        y_all = rgb_to_yiq(fr)[..., 0] if fr.ndim == 4 else fr
        _b_stats = luminance_stats(y_all)
    if frames_per_step and frames_per_step < frames.shape[0]:
        outs = []
        nnfs = []
        n = frames.shape[0]
        for ci, i in enumerate(range(0, n, frames_per_step)):
            chunk = frames[i : i + frames_per_step]
            # Pad ragged final chunks (repeat last frame) so every chunk
            # compiles to the same shapes; trimmed below.
            n_chunk = chunk.shape[0]
            if n_chunk < frames_per_step:
                reps = [chunk[-1:]] * (frames_per_step - n_chunk)
                chunk = jnp.concatenate([jnp.asarray(chunk)] + reps, axis=0)
            chunk_cfg = cfg
            if cfg.save_level_artifacts:
                # Per-chunk artifact subdirectories: one shared path
                # would leave only the last chunk's checkpoint.
                chunk_cfg = dataclasses.replace(
                    cfg,
                    save_level_artifacts=os.path.join(
                        cfg.save_level_artifacts, f"frames_{i:05d}"
                    ),
                )
            chunk_resume = (
                os.path.join(resume_from, f"frames_{i:05d}")
                if resume_from
                else None
            )
            chunk_res = synthesize_batch(
                a, ap, chunk, chunk_cfg, mesh, progress,
                resume_from=chunk_resume,
                resume_strict=resume_strict,
                frame_indices=(
                    # Ragged final chunks pad with the last
                    # frame; its index rides along (ballast
                    # rows are trimmed above).
                    (lambda ch: ch + [ch[-1]] * (
                        frames_per_step - len(ch)
                    ))(frame_indices[i : i + frames_per_step])
                    if frame_indices is not None else None
                ),
                return_nnf=return_nnf,
                _b_stats=_b_stats, _frame_offset=i, _n_stack=n,
            )
            if return_nnf:
                chunk_res, chunk_nnf = chunk_res
                nnfs.append(chunk_nnf[:n_chunk])
            outs.append(jnp.asarray(chunk_res)[:n_chunk])
        out = jnp.concatenate(outs, axis=0)
        if return_nnf:
            import numpy as _np

            return out, _np.concatenate(nnfs, axis=0)
        return out
    token = _mesh_token(mesh)
    n_frames = frames.shape[0]
    n_pad = (-n_frames) % mesh.devices.size

    from ..runtime.faults import fire as _fault_fire

    # xfer injection point: the frame stack's host->device transfer.
    _fault_fire("xfer", 0)
    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    frames = jnp.asarray(frames, jnp.float32)
    if n_pad:
        frames = jnp.concatenate(
            [frames, jnp.repeat(frames[-1:], n_pad, axis=0)], axis=0
        )
    frames = jax.device_put(frames, batch_sharding(mesh))

    levels = cfg.clamp_levels(a.shape[:2], frames.shape[1:3])
    key = jax.random.PRNGKey(cfg.seed)
    bp = nnf = None
    # Global frame indices (offset by the chunk position) make per-frame
    # keys — and therefore outputs — invariant to frames_per_step (the
    # fused level function derives the per-frame key streams from these,
    # bit-identically to the old host-side frame_keys helper).  An
    # explicit frame_indices overrides the positional identity (serving
    # batches of unrelated requests, each keyed as its own frame 0);
    # mesh-padding ballast rows repeat the last real index, matching
    # the repeated last frame they carry.
    if frame_indices is not None:
        idx_list = frame_indices + [frame_indices[-1]] * n_pad
        frame_idx = jnp.asarray(idx_list)
    else:
        frame_idx = jnp.arange(frames.shape[0]) + _frame_offset

    # Checkpoint identity: the UNPADDED chunk shape plus the
    # whole-stack length and this chunk's offset — per-chunk state
    # depends on the whole stack through the shared remap statistics,
    # so a checkpoint from a different overall stack must not be
    # resumed.  The mesh's padding grain is deliberately NOT part of
    # the identity (round 12): checkpoints save the real frames only
    # and resumes re-pad below, so a run can resume onto a different
    # device count — the supervisor's mesh->single-device degradation
    # rung depends on exactly that.
    fp_shape = (
        (n_frames,) + tuple(frames.shape[1:]) + (n_stack, _frame_offset)
    )
    if frame_indices is not None:
        # Overridden PRNG identities are part of the checkpoint's
        # identity too: state computed under one index assignment must
        # not resume under another.
        fp_shape = fp_shape + tuple(frame_indices)

    start_level = levels - 1
    resumed = resume_prologue(
        resume_from, levels, cfg, fp_shape, tracer, strict=resume_strict
    )
    if resumed is not None:
        start_level, nnf, bp, _aux = resumed
        if n_pad:
            # Re-pad the resumed whole-batch state to THIS mesh's
            # grain.  Padded frames are synthesis ballast trimmed from
            # every output and the vmapped step is per-frame
            # independent, so seeding them with the last real frame's
            # state changes no real frame's result.
            def _pad_tail(x):
                return jnp.concatenate(
                    [x, jnp.repeat(x[-1:], n_pad, axis=0)], axis=0
                )

            nnf = (
                tuple(_pad_tail(p) for p in nnf)
                if isinstance(nnf, tuple) else _pad_tail(nnf)
            )
            bp = _pad_tail(bp)
        if start_level < 0:
            # Fully-checkpointed run: skip feature/pyramid construction
            # entirely — only the chroma planes are needed to finalize.
            yiq_b = (
                jax.vmap(rgb_to_yiq)(frames)
                if cfg.color_mode == "luminance" and frames.ndim == 4
                else None
            )
            out = _finalize_batch(bp, yiq_b, frames, cfg)[:n_frames]
            if return_nnf:
                return out, _nnf_host_stack(nnf, n_frames)
            return out

    prologue_t0 = time.perf_counter()
    (
        pyr_src_a, pyr_flt_a, pyr_copy_a, pyr_src_b, pyr_raw_b, yiq_b
    ) = _batch_prologue_fn(cfg, levels, token)(a, ap, frames, _b_stats)
    # Shared drain + span — uniform report phases across runners
    # (round 10: also declares the run plan the live /progress ETA
    # calibrates; batch pyramids carry a leading frame axis).
    from ..models.analogy import record_prologue

    record_prologue(
        tracer, pyr_raw_b, levels, prologue_t0, cfg=cfg,
        a_hw=a.shape[:2], batched=True, runner="batch",
    )

    for level in range(start_level, -1, -1):
        # level injection point + supervisor abort checkpoint.
        _fault_fire("level", level)
        level_t0 = time.perf_counter()
        h, w = pyr_src_b[level].shape[1:3]
        has_coarse = level < levels - 1

        from ..models.analogy import _assemble_fa_fn, plan_level

        ha, wa = pyr_src_a[level].shape[:2]
        # Shared planner, with the batch's per-frame multiplicity in
        # the byte estimate and in the brute unfuse rule (the resident
        # frame count scales every chunk execution's work); brute never
        # takes the lean-brute path here (the oracle runs per-frame,
        # frames_per_step=1).
        plan = plan_level(
            cfg, level, pyr_src_a[level], pyr_flt_a[level], has_coarse,
            h, w, prev_nnf=nnf,
            table_bytes=_batch_feature_table_bytes(
                frames.shape[0], h, w, ha, wa
            ),
            work_scale=frames.shape[0],
            brute_lean=False,
        )
        f_a_ext = proj_ext = None
        if plan.fa_external:
            f_a_ext, proj_ext = _assemble_fa_fn(cfg, has_coarse)(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
            )
        run = _batch_level_fn(
            cfg, level, has_coarse, token, plan.fa_external, plan.lean,
            plan.prev_kind, plan.fuse,
        )
        # kernel injection point: the compiled batch level launch.
        _fault_fire("kernel", level)
        nnf, dist, bp = run(
            pyr_src_a[level],
            pyr_flt_a[level],
            pyr_src_a[level + 1] if has_coarse else None,
            pyr_flt_a[level + 1] if has_coarse else None,
            pyr_src_b[level],
            pyr_src_b[level + 1] if has_coarse else None,
            pyr_raw_b[level],
            pyr_copy_a[level],
            nnf,
            bp,
            jax.random.fold_in(key, level),
            frame_idx,
            f_a_ext,
            proj_ext,
        )

        if tracer.enabled:
            # Per-device-shard completion walls FIRST (the straggler
            # watch's raw signal: frames shard over the mesh in
            # contiguous blocks, so each block's readback barrier is
            # one device's completion stamp), then the merged
            # nnf_energy readback — by then every shard is synced, so
            # the level span's own wall is unchanged.  A LEAN tracer
            # (the serving daemon's per-request run tracer) keeps the
            # level span but skips both readbacks: request tracing
            # must not add device syncs to the hot path.
            from ..models.analogy import (
                record_level_span,
                shard_sync_walls,
            )

            if getattr(tracer, "lean", False):
                record_level_span(
                    tracer, cfg, level_t0, level, h, w, None,
                    shard_axis=BATCH_AXIS,
                )
            else:
                n_sh = int(mesh.devices.size)
                per = dist.shape[0] // n_sh
                walls = shard_sync_walls(
                    level_t0,
                    [dist[i * per:(i + 1) * per] for i in range(n_sh)],
                ) if per else None
                record_level_span(
                    tracer, cfg, level_t0, level, h, w,
                    float(dist.mean()),
                    shard_walls=walls, shard_axis=BATCH_AXIS,
                )
        if cfg.save_level_artifacts:
            # Whole-batch per-level state through the single-image
            # writer: atomic tmp+rename and a fingerprint covering the
            # UNPADDED frame-stack shape (the arrays just carry a
            # frame axis).  Mesh-padding duplicates are trimmed before
            # saving — they are recomputable ballast, and keeping them
            # out of the artifact is what makes the checkpoint
            # mesh-invariant (resume re-pads for its own grain).
            nnf_save = nnf
            if isinstance(nnf, tuple):
                # Lean plane pair stacked on the HOST, exactly as the
                # single driver does: checkpoints keep the standard
                # (..., 2) schema without materializing the lane-padded
                # stack on device.
                import numpy as _np

                nnf_save = _np.stack(
                    [_np.asarray(nnf[0]), _np.asarray(nnf[1])], axis=-1
                )
            _save_level(
                cfg.save_level_artifacts, level, nnf_save[:n_frames],
                dist[:n_frames], bp[:n_frames], cfg, fp_shape,
            )

    out = _finalize_batch(bp, yiq_b, frames, cfg)[:n_frames]
    if return_nnf:
        return out, _nnf_host_stack(nnf, n_frames)
    return out


def _nnf_host_stack(nnf, n_frames: int):
    """Converged field as one host (F, H, W, 2) int array, padding
    ballast trimmed: lean plane pairs are stacked on the HOST, exactly
    as the checkpoint writer does, so the lane-padded (..., 2) stack is
    never materialized on device."""
    import numpy as _np

    if isinstance(nnf, tuple):
        return _np.stack(
            [_np.asarray(nnf[0]), _np.asarray(nnf[1])], axis=-1
        )[:n_frames]
    return _np.asarray(nnf)[:n_frames]


def _finalize_batch(bp, yiq_b, frames, cfg: SynthConfig):
    """Vmapped chroma recombination / clipping over the frame axis."""
    if yiq_b is not None:
        return jax.vmap(
            lambda bp_f, yiq_f, b_f: _finalize(bp_f, yiq_f, b_f, cfg)
        )(bp, yiq_b, frames)
    return jax.vmap(lambda bp_f, b_f: _finalize(bp_f, None, b_f, cfg))(
        bp, frames
    )


def _batched_channels(a, ap, frames, cfg: SynthConfig, b_stats=None):
    """Channel split with a leading frame axis on the B side.

    `b_stats` overrides the remap target statistics — the microbatching
    wrapper passes the WHOLE stack's stats so the shared style stays
    fixed across chunks (temporal coherence)."""
    if cfg.color_mode == "luminance":
        color = frames.ndim == 4
        yiq_b = jax.vmap(rgb_to_yiq)(frames) if color else None
        y_b = yiq_b[..., 0] if color else frames
        y_a = rgb_to_yiq(a)[..., 0] if a.ndim == 3 else a
        y_ap = rgb_to_yiq(ap)[..., 0] if ap.ndim == 3 else ap
        if cfg.luminance_remap:
            from ..ops.remap import remap_luminance

            # Remap A to the statistics of the whole frame stack (shared
            # style must stay fixed across frames for temporal coherence).
            y_a, y_ap = remap_luminance(y_a, y_ap, y_b, b_stats=b_stats)
        return y_a, y_ap, y_b, y_ap, yiq_b
    return a, ap, frames, ap, None
