"""Spatial parallelism: one large B' sharded across the mesh
(SURVEY.md §2 "Spatial (tensor) parallelism" row).

The reference is single-process and cannot scale past one image's
synthesis cost; this runner splits B' into row slabs — one per device —
and runs the per-level EM step vmapped over the slab axis, which pjit
shards over the mesh like the batch runner shards frames (parallel/
batch.py).  The analogy-specific twist is *halos*: feature windows
(5x5 at l, 3x3 at l+1, Hertzmann §3.1) read a few rows past each slab
boundary, so every slab carries `slab_halo(cfg)` extra rows per side, and
after every EM iteration the slab cores are re-stitched into the global
B' estimate and re-split with fresh halos.  Under `jit` + shardings that
stitch/split pair lowers to exactly the boundary-row exchanges between
ICI neighbors — the "halo exchange" is expressed as global-array
semantics and the compiler inserts the collectives (the XLA-idiomatic
formulation; no hand-written send/recv).

Exactness: with halo >= the feature-window reach, per-pixel matchers see
bit-identical features in slab cores, so the brute matcher's spatial
output equals the single-device output exactly (tested).  PatchMatch
propagation is slab-local between stitches (sweep chains don't cross a
boundary within one EM iteration), which the PSNR-based acceptance
absorbs [BASELINE.json metric].

A-side features are replicated: matches may land anywhere in A, and A'
style images are small next to B' at the scales this runner targets.

Scale ceiling: levels whose global feature tables exceed
`cfg.feature_bytes_budget` run the LEAN step per slab (plane-pair NN
field, bf16 chunk-assembled per-slab B tables), so per-device residency
is the slab's share of the B side plus the replicated A side — the
runner reaches the single-chip lean path's ceiling TIMES the mesh on
the B' axis (e.g. ~8192^2 B' on 4 chips that each handle lean 4096^2
slabs).  The remaining hard wall here is the replicated A side — and
for THAT, `parallel/sharded_a.py` is the runner (round-4): A's rows
split into ownership bands (`prepare_a_planes(n_bands=n)` +
`band_bounds` — each band evaluates only candidates whose clamped
origin it owns), each device sweeps its own band under `shard_map`
with a cross-device argmin merge after every pm iteration, and the
exact-metric merge/polish gathers run as masked LOCAL-shard lookups
merged by `pmin` (every flat A index has exactly one owner), so
per-device A residency — the lean bf16 feature table, N_A * 256 B ≈
4.3 GB at 4096^2, which since the round-4 HBM-streaming kernel binds
long before the kernel planes (~19 MB/1024^2-channel set) — drops to
1/n.  The sharded runner is BIT-IDENTICAL to the single-device lean
path at kappa=0 (tests/test_spatial.py
test_sharded_a_runner_bit_identical_to_single_device; kappa>0 trades
bit-identity for a marginally weaker cross-band coherence bias — see
sharded_a.py 'Equivalence'; the kernel-level band contract is pinned
separately by test_sharded_a_band_search_matches_sequential).  Composing it with
THIS runner's B' slabs — a ("bands", "slabs") 2-D mesh, for pairs
where both sides outgrow a chip — is implemented HERE (round-4):
`synthesize_spatial` detects the 2-D mesh and routes lean levels
through `_banded_lean_step_fn`, which runs the shared `lean_em_step`
under a shard_map with sharded_a's three band hooks while the slabs
axis keeps this runner's halo re-stitch.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig
from ..models.analogy import (
    _finalize,
    _level_state_glue,
    _prologue_fn,
    _save_level,
    assemble_features_lean,
    lean_em_step,
    plan_level,
    resume_prologue,
)
from ..ops.features import assemble_features
from .batch import (
    _batch_step_fn as _spatial_step_fn,
    _lean_step_fn as _spatial_lean_step_fn,
    _mesh_token,
)
from .mesh import batch_sharding, make_mesh, shard_map


def slab_halo(cfg: SynthConfig) -> int:
    """Rows of context on each side of a slab, derived from the config's
    window geometry (a fixed constant silently under-covers larger
    patches: at patch_size=11 the fine reach is 5 and a 4-row halo lets
    boundary features go wrong with exit code 0).

    The fine and coarse windows read independently, so the reach is the
    MAX of the fine window's patch_size//2 rows and the l+1 coarse
    window's coarse_patch_size//2 coarse rows (= 2*(coarse//2) fine
    rows, parity-aligned because slab cores are even-sized) — not their
    sum, which would double the boundary-row exchange for nothing.
    Rounded up to even so coarse slabs split at exactly half resolution
    (the coarse-side halo is halo//2)."""
    reach = max(cfg.patch_size // 2, 2 * (cfg.coarse_patch_size // 2))
    return reach + (reach % 2)


def _split_slabs(x: jnp.ndarray, n_slabs: int, halo: int) -> jnp.ndarray:
    """(H, ...) -> (n_slabs, H//n_slabs + 2*halo, ...) edge-clamped."""
    h = x.shape[0]
    hs = h // n_slabs
    pad = [(halo, halo)] + [(0, 0)] * (x.ndim - 1)
    xp = jnp.pad(x, pad, mode="edge")
    return jnp.stack([xp[i * hs : i * hs + hs + 2 * halo] for i in range(n_slabs)])


def _merge_cores(slabs: jnp.ndarray, halo: int) -> jnp.ndarray:
    """Inverse of `_split_slabs`: drop halos, concatenate cores."""
    core = slabs[:, halo : slabs.shape[1] - halo]
    return core.reshape(-1, *core.shape[2:])


@functools.lru_cache(maxsize=32)
def _reslab_fn(halo: int, n_slabs: int, n_arrays: int, mesh_key,
               axis: str = "batch"):
    """Jitted stitch-cores + re-split-with-fresh-halos over `n_arrays`
    slab-stacked arrays, slab-sharded in and out.

    Between EM iterations only the halo rows actually change hands; with
    input and output pinned to the slab sharding, XLA lowers the
    merge+split pair to the boundary-row exchanges between mesh neighbors
    instead of re-materializing the global arrays on the host every
    iteration (the module docstring's halo-exchange claim is made true
    here).  Array count is generic: the standard path re-halos
    (stacked-nnf, bp), the lean path (py, px, bp).  `axis` names the
    mesh axis the slab stack shards over ('slabs' when
    `synthesize_spatial` runs on the 2-D bands x slabs mesh).

    On 2-D meshes the merge+split CANNOT be left to GSPMD: on this jax
    (0.4.x) the SPMD partitioner materializes pad/concat of an array
    that is sharded along one mesh axis and replicated along the other
    as per-device dynamic-update-slice contributions summed by an
    all-reduce over ALL devices, double-counting the replicated-axis
    contributions once per band — the re-slabbed state comes back
    scaled by n_bands^2 (one doubling per stage; measured 4x on a
    (2, 2) mesh, 16x on (4, 2); regression-pinned by
    test_reslab_2d_mesh_bit_identical).  The 2-D path therefore runs
    the halo exchange MANUALLY under shard_map: each slab keeps its
    core and trades `halo` boundary rows with its mesh neighbors via
    two `ppermute`s per array, edge slabs re-clamping their outer halo
    (`jnp.pad` edge semantics).  The explicit permutes are also what
    makes the slabs axis exactly countable for the sentinel's comms
    ledger (parallel/comms.py `spatial_reslab_collectives`)."""
    from .batch import _MESHES

    mesh = _MESHES[mesh_key]
    shard = batch_sharding(mesh, axis)

    if len(mesh.axis_names) > 1:
        from jax.sharding import PartitionSpec as P

        perm_fwd = [(i, i + 1) for i in range(n_slabs - 1)]
        perm_bwd = [(i + 1, i) for i in range(n_slabs - 1)]

        def body(*slabs):
            from ..telemetry.metrics import (
                count_collectives,
                count_expected_collectives,
            )

            # EXPECTED side of the slabs-axis comms ledger, booked in
            # the same traced body as the observed permute sites so
            # both skip together on jit cache hits.
            count_expected_collectives(2 * n_arrays, axis)
            idx = jax.lax.axis_index(axis)
            outs = []
            for s in slabs:
                x = s[0]
                core = x[halo : x.shape[0] - halo]
                # OBSERVED: one collective-permute site per direction.
                count_collectives(1, axis, kind="collective_permute")
                from_prev = jax.lax.ppermute(
                    core[-halo:], axis, perm_fwd
                )
                count_collectives(1, axis, kind="collective_permute")
                from_next = jax.lax.ppermute(
                    core[:halo], axis, perm_bwd
                )
                top = jnp.where(
                    idx == 0,
                    jnp.repeat(core[:1], halo, axis=0),
                    from_prev,
                )
                bot = jnp.where(
                    idx == n_slabs - 1,
                    jnp.repeat(core[-1:], halo, axis=0),
                    from_next,
                )
                outs.append(
                    jnp.concatenate([top, core, bot], axis=0)[None]
                )
            return tuple(outs)

        S = P(axis)
        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(S,) * n_arrays,
                out_specs=(S,) * n_arrays,
                # Outputs are band-invariant (pure function of the
                # band-replicated slab state); no varying-mesh-axes
                # info crosses the boundary.
                check_vma=False,
            ),
            in_shardings=(shard,) * n_arrays,
            out_shardings=(shard,) * n_arrays,
        )

    def reslab(*slabs):
        return tuple(
            _split_slabs(_merge_cores(s, halo), n_slabs, halo)
            for s in slabs
        )

    return jax.jit(
        reslab,
        in_shardings=(shard,) * n_arrays,
        out_shardings=(shard,) * n_arrays,
    )


_BANDS_AXIS = "bands"
_SLABS_AXIS = "slabs"


@functools.lru_cache(maxsize=32)
def _banded_lean_step_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                         mesh_key, interpret: bool, polish_iters=None):
    """One lean EM iteration on the 2-D bands x slabs mesh: each device
    owns (A band, B' slab) and runs `lean_em_step` — the SAME body the
    single-device lean path, the 1-D spatial runner, and the sharded-A
    runner execute — with the three band hooks from
    parallel/sharded_a.py: its own band's kernel planes/bounds, the
    masked local-shard gather merged by `pmin` over the bands axis, and
    the cross-band argmin merge after every pm iteration.  The slabs
    axis stays independent (each slab column synthesizes its rows); the
    bands axis carries the A-side collectives.  Post-merge state is
    replicated across bands by construction (every band sees identical
    merged distances and the same slab key), so the slab-sharded
    out_specs are exact.
    """
    from .batch import _MESHES
    from .sharded_a import _band_merge, _sharded_dist
    from jax.sharding import PartitionSpec as P

    mesh = _MESHES[mesh_key]

    def call(f_a_tab, a_stacked, bounds_stacked, src_b_s, flt_s,
             src_b_c_s, flt_c_s, copy_a, py_s, px_s, keys):
        def body(f_a_shard, a_band, band, src_b, flt_b, src_b_c, flt_b_c,
                 copy_a, py, px, key):
            from ..telemetry.metrics import count_expected_collectives
            from .comms import sharded_a_allreduce_sites

            a_band, band = a_band[0], band[0]
            src_b, flt_b = src_b[0], flt_b[0]
            src_b_c, flt_b_c = src_b_c[0], flt_b_c[0]
            py, px, key = py[0], px[0], key[0]
            ha, wa = copy_a.shape[:2]
            row_lo_flat = band[0] * wa
            # EXPECTED side of the sentinel's comms ledger for this EM
            # step's bands-axis collectives, booked in the same traced
            # body as the observed sites (see parallel/sharded_a.py).
            count_expected_collectives(
                sharded_a_allreduce_sites(
                    cfg, ha, wa, per_em=True, polish_iters=polish_iters
                ),
                _BANDS_AXIS,
            )
            (py, px), dist, bp = lean_em_step(
                cfg, level, has_coarse, polish_iters,
                src_b, flt_b, src_b_c, flt_b_c,
                f_a_shard, copy_a, (py, px), key,
                (a_band,), interpret=interpret,
                dist_fn=lambda f_b_tab: functools.partial(
                    _sharded_dist, f_b_tab, f_a_shard, row_lo_flat
                ),
                bounds=(band,),
                sweep_merge=_band_merge,
            )
            return py[None], px[None], dist[None], bp[None]

        B, S = P(_BANDS_AXIS), P(_SLABS_AXIS)
        return shard_map(
            body,
            mesh=mesh,
            in_specs=(B, B, B, S, S, S, S, P(), S, S, S),
            out_specs=(S, S, S, S),
            # pallas_call outputs carry no varying-mesh-axes info.
            check_vma=False,
        )(f_a_tab, a_stacked, bounds_stacked, src_b_s, flt_s,
          src_b_c_s, flt_c_s, copy_a, py_s, px_s, keys)

    from jax.sharding import NamedSharding, PartitionSpec as P

    band = NamedSharding(mesh, P(_BANDS_AXIS))
    slab = NamedSharding(mesh, P(_SLABS_AXIS))
    repl = NamedSharding(mesh, P())
    # Pin every input's PHYSICAL sharding at the jit boundary.  On this
    # jax (0.4.x) an input whose layout GSPMD is left to derive can be
    # miscompiled where it crosses into the shard_map's manual region
    # when the specs leave a mesh axis unmentioned (see
    # sharded_a._band_assemble_fn for the measured double-count); with
    # committed shardings that match the in_specs the boundary is a
    # no-op and the hazard cannot arise.
    return jax.jit(
        call,
        in_shardings=(
            band, band, band, slab, slab, slab, slab, repl, slab, slab,
            slab,
        ),
    )


def synthesize_spatial(
    a,
    ap,
    b,
    cfg: Optional[SynthConfig] = None,
    mesh=None,
    progress=None,
    resume_from: Optional[str] = None,
    resume_strict: bool = False,
    mesh_plan: Optional[dict] = None,
):
    """B' for one (large) `b`, rows sharded over the mesh's batch axis.

    `b`'s height is padded (edge rows) to n_slabs * 2^(levels-1)
    granularity so every level splits into equal, parity-aligned slabs;
    the pad is cropped from the result.

    **2-D bands x slabs meshes** (axis names ("bands", "slabs"), e.g.
    `make_mesh(axis_names=("bands", "slabs"), shape=(2, 4))`): B' rows
    shard over the slabs axis as usual, and on lean levels the A-side
    lean table + kernel planes additionally shard into ownership bands
    over the bands axis (parallel/sharded_a.py's data path) — for style
    pairs AND targets that both outgrow one chip.  Per-device residency
    is then slab-share-of-B' + band-share-of-A.  With one band the 2-D
    path is bit-identical to the 1-D spatial runner; with several it
    keeps bit-identity at kappa=0 by the band-ownership contract
    (kappa>0: same accept family, marginally weaker cross-band
    coherence bias — sharded_a.py 'Equivalence').  Sub-lean levels keep
    the A side replicated (their tables are 4^-l of the finest's).

    `resume_from`: per-level checkpoint dir (cfg.save_level_artifacts of
    a prior run) — restarts from the finest completed level like
    create_image_analogy.  The fingerprint covers the *padded* B shape,
    so checkpoints only resume onto a mesh with the same padding grain.

    `mesh_plan`: the parallel/plan2d.py verdict dict (`MeshPlan
    .as_attrs()`) when the mesh shape was planned (or overridden) by
    the caller — recorded verbatim on the run plan.
    """
    import time

    from ..telemetry.spans import as_tracer

    tracer = as_tracer(progress)
    cfg = cfg or SynthConfig()
    mesh = mesh or make_mesh()
    token = _mesh_token(mesh)
    sub_mesh = sub_token = None
    if _BANDS_AXIS in mesh.axis_names:
        if mesh.axis_names != (_BANDS_AXIS, _SLABS_AXIS):
            raise ValueError(
                "2-D spatial mesh must have axis names "
                f"('{_BANDS_AXIS}', '{_SLABS_AXIS}'), got {mesh.axis_names}"
            )
        n_bands = int(mesh.shape[_BANDS_AXIS])
        slab_axis = _SLABS_AXIS
        n_slabs = int(mesh.shape[_SLABS_AXIS])
        # Non-banded levels (sub-lean, or lean with one band) run on a
        # 1-D SLABS SUBMESH — the first band row of devices.  Their
        # GSPMD-partitioned step fns are only proven on 1-D meshes: on
        # a 2-D mesh the partitioner's select-and-sum handling of
        # slabs-sharded / bands-replicated arrays double-counts the
        # replicated contributions (the same jax-0.4.x miscompile the
        # banded path routes around with explicit shardings and the
        # manual re-slab — see `_reslab_fn`).  Those levels are 4^-l
        # of the finest's work, so idling the other band rows costs
        # marginally while keeping every compiled program in its
        # test-pinned regime.
        from jax.sharding import Mesh

        sub_mesh = Mesh(mesh.devices[0, :], (_SLABS_AXIS,))
        sub_token = _mesh_token(sub_mesh)
    else:
        n_bands = 1
        slab_axis = mesh.axis_names[0]
        n_slabs = int(mesh.devices.size)
    halo = slab_halo(cfg)

    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    h0 = b.shape[0]

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    # Pad rows so each pyramid level splits evenly into even-sized cores.
    grain = n_slabs * (2 ** (levels - 1)) * 2
    pad_h = (-h0) % grain
    if pad_h:
        b = jnp.pad(
            b, [(0, pad_h)] + [(0, 0)] * (b.ndim - 1), mode="edge"
        )

    # The SAME compiled prologue the single-image driver uses: channel
    # resolve + remap + pyramids in one jit call — one dispatch, and
    # bit-identical leaves to create_image_analogy's (the parity tests
    # compare the two runners exactly; separate compilations of the
    # reduction-bearing prologue ops could legally round differently).
    # xfer injection point: the prologue dispatch is the run's
    # host->device transfer boundary (runtime/faults.py).
    from ..runtime.faults import fire as _fault_fire

    _fault_fire("xfer", 0)
    prologue_t0 = time.perf_counter()
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, pyr_raw_b, yiq_b
    ) = _prologue_fn(cfg, levels)(a, ap, b)
    # Shared drain + span (models/analogy.record_prologue) — every
    # runner's report carries the same prologue phase
    # (tools/check_report.py requires it).  Round 10: also declares
    # the run plan the live /progress ETA calibrates (the banded 2-D
    # runner's plan includes the comms-model collective term).
    from ..models.analogy import record_prologue

    record_prologue(
        tracer, pyr_raw_b, levels, prologue_t0, cfg=cfg,
        a_hw=a.shape[:2],
        runner="spatial-banded" if n_bands > 1 else "spatial",
        mesh_plan=mesh_plan,
    )

    key = jax.random.PRNGKey(cfg.seed)
    bp = flt_bp = nnf = None  # global (H_l, W[, C]) state per level

    start_level = levels - 1
    resumed = resume_prologue(
        resume_from, levels, cfg, b.shape, tracer, strict=resume_strict
    )
    if resumed is not None:
        start_level, nnf, bp, _aux = resumed
        flt_bp = bp
        if start_level < 0:
            return _finalize(bp, yiq_b, b, cfg)[:h0]

    for level in range(start_level, -1, -1):
        # level injection point + supervisor abort checkpoint
        # (runtime/faults.py).
        _fault_fire("level", level)
        level_t0 = time.perf_counter()
        f_a_src = pyr_src_a[level]
        h, w = pyr_src_b[level].shape[:2]
        ha, wa = f_a_src.shape[:2]
        has_coarse = level < levels - 1

        from ..models.analogy import _maybe_a_planes

        # Kernel eligibility is planned against the SLAB the vmapped step
        # will see (core + halos), not the global B'.  The kernel's
        # coordinates stay consistent on slabs because offsets are
        # relative (off = A_row - local_row, recomputed per EM call from
        # the global-coordinate NNF and the slab-local iota), so the
        # replicated A planes serve every slab unchanged; candidate
        # generation's global restarts subtract the local tile origin,
        # which lands them in the same relative frame.
        slab_shape = (h // n_slabs + 2 * halo, w)

        # Lean x spatial composition: levels whose GLOBAL row-major
        # feature tables would not fit one device's HBM run the lean
        # step per slab — plane-pair (py, px) field in slab form, bf16
        # chunk-assembled per-slab B tables, one replicated lean A
        # table — so the sharded runner reaches the sizes the
        # single-chip lean path handles, times the mesh (the round-2
        # runner stacked an (H, W, 2) field: 8 GB of lane pad at
        # 4096^2, exactly the wall it existed to pass).  Decision from
        # the shared planner: kernel eligibility is planned against the
        # SLAB the vmapped step will see, the byte estimate against the
        # global tables.
        plan = plan_level(
            cfg, level, f_a_src, pyr_flt_a[level], has_coarse, h, w,
            prev_nnf=nnf, eligible_shape=slab_shape, brute_lean=False,
        )
        lean = plan.lean
        # kernel injection point: the level's compiled work (assembly
        # + slab/band dispatch) starts past this line.
        _fault_fire("kernel", level)

        banded = lean and n_bands > 1
        a_stacked = bounds_stacked = None
        a_pad = 0
        if banded:
            # A rows that don't split evenly over the bands pad with
            # EDGE rows to band grain (round-17; replaces the hard
            # ValueError): the lean table and kernel planes are built
            # from the padded A so every band's shard is uniform (the
            # shard_map requirement), while the band BOUNDS stay
            # cropped to the real rows — no candidate is ever
            # generated or owned in the pad, so ownership semantics
            # and the bit-identity contract are unchanged.  With a
            # coarse level the grain doubles so the coarse pyramid
            # pads to exactly half the fine rows and splits on the
            # same band boundaries.
            a_grain = 2 * n_bands if has_coarse else n_bands
            a_pad = (-ha) % a_grain
            rows_pb = (ha + a_pad) // n_bands
            if (n_bands - 1) * rows_pb >= ha:
                raise ValueError(
                    f"2-D spatial level {level}: A rows ({ha}) leave "
                    f"band {n_bands - 1} of {n_bands} without a real "
                    f"row to own — use fewer bands"
                )
        # Banded levels use the full 2-D mesh; everything else runs on
        # the 1-D slabs submesh (or the 1-D mesh itself) — see the
        # sub_mesh comment above.
        lvl_mesh, lvl_token = mesh, token
        if sub_mesh is not None and not banded:
            lvl_mesh, lvl_token = sub_mesh, sub_token
        band_walls = None
        if lean:
            proj = None
            if banded:
                # Band-sharded A side (parallel/sharded_a.py data
                # path): the lean table's rows and the kernel planes
                # split into per-device ownership bands over the bands
                # axis, and the table is ASSEMBLED band-sharded too —
                # each band owner assembles its slice from a
                # halo-extended A-pyramid slab (sharded_a
                # _band_assemble_fn), so no device holds the full
                # table or its assembly temps.
                from jax.sharding import NamedSharding, PartitionSpec as P
                from ..kernels.patchmatch_tile import prepare_a_planes
                from ..models.analogy import _level_plan, _strip_noncompute
                from .sharded_a import (
                    _band_assemble_fn,
                    _band_assembly_aligned,
                )

                band_shard = NamedSharding(mesh, P(_BANDS_AXIS))
                # Band-grain edge padding of the A-side inputs (a_pad
                # rows on the fine arrays, half that on the coarse —
                # see the a_pad comment above).  The pad rows sit at
                # the END of the last band's shard, past its cropped
                # bounds, so they are assembled but never evaluated.
                def _pad_a_rows(x, n):
                    if not n:
                        return x
                    return jnp.pad(
                        x, [(0, n)] + [(0, 0)] * (x.ndim - 1),
                        mode="edge",
                    )

                ha_k = ha + a_pad
                src_a_k = _pad_a_rows(f_a_src, a_pad)
                flt_a_k = _pad_a_rows(pyr_flt_a[level], a_pad)
                src_c_k = flt_c_k = None
                hc_k = None
                if has_coarse:
                    hc = pyr_src_a[level + 1].shape[0]
                    hc_k = ha_k // 2
                    src_c_k = _pad_a_rows(
                        pyr_src_a[level + 1], hc_k - hc
                    )
                    flt_c_k = _pad_a_rows(
                        pyr_flt_a[level + 1], hc_k - hc
                    )
                if _band_assembly_aligned(ha_k, hc_k, n_bands,
                                          has_coarse):
                    coarse_args = (
                        (src_c_k, flt_c_k) if has_coarse else ()
                    )
                    f_a = _band_assemble_fn(
                        _strip_noncompute(cfg), token, has_coarse, n_bands
                    )(src_a_k, flt_a_k, *coarse_args)
                else:
                    f_a = jax.device_put(
                        assemble_features_lean(
                            src_a_k, flt_a_k, cfg, src_c_k, flt_c_k
                        ),
                        band_shard,
                    )
                chan_plan = _level_plan(
                    cfg, f_a_src, pyr_flt_a[level], has_coarse,
                    *slab_shape,
                )
                specs, use_coarse, _ = chan_plan
                bands_p = prepare_a_planes(
                    src_a_k,
                    flt_a_k,
                    src_c_k if use_coarse else None,
                    flt_c_k if use_coarse else None,
                    specs,
                    n_bands=n_bands,
                )
                a_stacked = jax.device_put(jnp.stack(bands_p), band_shard)
                # Bounds from the PADDED row grid, validity cropped to
                # the real rows (band_bounds' own convention when the
                # pad fits inside its ceil split).
                rows_pb = ha_k // n_bands
                bounds_stacked = jax.device_put(
                    jnp.stack([
                        jnp.asarray(
                            [i * rows_pb,
                             min(rows_pb, ha - i * rows_pb)],
                            jnp.int32,
                        )
                        for i in range(n_bands)
                    ]),
                    band_shard,
                )
                if tracer.enabled:
                    # Bands-axis straggler signal (round-17 mirror of
                    # the sharded-A runner's): the EM body's pmin/psum
                    # merges synchronize the bands every pm iteration,
                    # so post-merge skew is unobservable — the
                    # band-sharded ASSEMBLY, each band building its
                    # table slice independently, is where a slow band
                    # shows.  One readback barrier per band slice.
                    from ..models.analogy import shard_sync_walls

                    tab_rows = f_a.shape[0] // n_bands
                    band_walls = shard_sync_walls(
                        level_t0,
                        [
                            f_a[i * tab_rows:(i + 1) * tab_rows, :1]
                            for i in range(n_bands)
                        ],
                    )
            else:
                # 1-D lean: the A side is replicated (its single-chip
                # ceiling applies per device by design; the bands axis
                # is the escape hatch).
                f_a = assemble_features_lean(
                    f_a_src,
                    pyr_flt_a[level],
                    cfg,
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                )
        else:
            f_a = assemble_features(
                f_a_src,
                pyr_flt_a[level],
                cfg,
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
            )
            from ..ops.pca import fit_and_project

            f_a, proj = fit_and_project(f_a, cfg.pca_dims)

        # Banded levels build their per-band planes above (a_stacked) —
        # the full single-band plane set would re-materialize exactly
        # the multi-GB A-side resident that banding splits.
        a_planes = None if banded else _maybe_a_planes(
            cfg, pyr_src_a, pyr_flt_a, level, has_coarse, slab_shape
        )

        level_key = jax.random.fold_in(key, level)
        nnf, flt_bp, flt_bp_coarse_g = _level_state_glue(
            lean, plan.prev_kind, nnf, flt_bp, pyr_raw_b[level],
            h, w, ha, wa, level_key,
        )

        # Level-invariant slab views of the match-side images (the
        # coarse B' estimate is frozen for the whole level, so its slab
        # split is hoisted with them), placed on the mesh once per level.
        shard = batch_sharding(lvl_mesh, slab_axis)
        slab_src_b = jax.device_put(
            _split_slabs(pyr_src_b[level], n_slabs, halo), shard
        )
        slab_src_b_c = jax.device_put(
            _split_slabs(
                pyr_src_b[level + 1] if has_coarse else pyr_src_b[level],
                n_slabs,
                halo // 2 if has_coarse else halo,
            ),
            shard,
        )
        slab_flt_c = (
            jax.device_put(
                _split_slabs(flt_bp_coarse_g, n_slabs, halo // 2), shard
            )
            if has_coarse
            else None
        )

        if banded:
            from ..kernels import resolve_pallas
            from ..models.analogy import _strip_noncompute

            interpret = bool(resolve_pallas(cfg))

            def mk_step(p, _as=a_stacked, _bs=bounds_stacked):
                fn = _banded_lean_step_fn(
                    _strip_noncompute(cfg), level, has_coarse, token,
                    interpret, p,
                )

                def step(slab_src_b, slab_flt, slab_src_b_c, slab_flt_c,
                         f_a_, copy_a, slab_nnf, slab_keys, proj_,
                         a_planes_):
                    py_s, px_s, dist_s, bp_s = fn(
                        f_a_, _as, _bs, slab_src_b, slab_flt,
                        slab_src_b_c, slab_flt_c, copy_a,
                        slab_nnf[0], slab_nnf[1], slab_keys,
                    )
                    return (py_s, px_s), dist_s, bp_s

                return step
        else:
            mk_step = (  # noqa: E731
                (lambda p: _spatial_lean_step_fn(
                    cfg, level, has_coarse, lvl_token, polish_iters=p,
                    axis=slab_axis))
                if lean
                else (lambda p: _spatial_step_fn(
                    cfg, level, has_coarse, lvl_token, polish_iters=p,
                    axis=slab_axis))
            )
        step_final = mk_step(None)
        # Non-final EM iterations skip the gather-bound per-pixel polish
        # (config.py pm_polish_final_only), mirroring the single-image
        # and batch level functions.
        step_mid = (
            mk_step(0) if cfg.pm_polish_final_only else step_final
        )
        # One host-side slab placement per level; between EM iterations
        # the state stays in (sharded) slab form and is re-haloed by the
        # jitted _reslab, so per-iteration traffic is boundary rows only.
        if lean:
            slab_nnf = (
                jax.device_put(_split_slabs(nnf[0], n_slabs, halo), shard),
                jax.device_put(_split_slabs(nnf[1], n_slabs, halo), shard),
            )
        else:
            slab_nnf = jax.device_put(
                _split_slabs(nnf, n_slabs, halo), shard
            )
        slab_flt = jax.device_put(
            _split_slabs(flt_bp, n_slabs, halo), shard
        )
        nnf_s = dist_s = bp_s = None
        for em in range(cfg.em_iters):
            em_key = jax.random.fold_in(level_key, em)
            slab_keys = jax.random.split(em_key, n_slabs)
            args = (
                slab_src_b,
                slab_flt,
                slab_src_b_c,
                slab_flt_c if has_coarse else slab_flt,
                f_a,
                pyr_copy_a[level],
                slab_nnf,
                slab_keys,
                proj,
                a_planes,
            )
            step = (
                step_final if em == cfg.em_iters - 1 else step_mid
            )
            nnf_s, dist_s, bp_s = step(*args)
            if em < cfg.em_iters - 1:
                if lean:
                    py_s, px_s, slab_flt = _reslab_fn(
                        halo, n_slabs, 3, lvl_token, slab_axis
                    )(nnf_s[0], nnf_s[1], bp_s)
                    slab_nnf = (py_s, px_s)
                else:
                    slab_nnf, slab_flt = _reslab_fn(
                        halo, n_slabs, 2, lvl_token, slab_axis
                    )(nnf_s, bp_s)
        shard_walls = None
        if tracer.enabled:
            # Per-slab completion walls BEFORE the core merge touches
            # the stack (the straggler watch's raw signal: dist_s keeps
            # the leading slab axis, one readback barrier per slab
            # column) — the merged readback below then finds everything
            # already synced.
            from ..models.analogy import shard_sync_walls

            shard_walls = shard_sync_walls(
                level_t0, [dist_s[i] for i in range(n_slabs)]
            )
        if lean:
            nnf = (
                _merge_cores(nnf_s[0], halo),
                _merge_cores(nnf_s[1], halo),
            )
        else:
            nnf = _merge_cores(nnf_s, halo)
        dist = _merge_cores(dist_s, halo)
        bp = _merge_cores(bp_s, halo)
        flt_bp = bp

        if tracer.enabled:
            # Sync first (nnf_energy readback), then record the timed
            # `level` span whose emitted view is the legacy
            # `level_done` event — which now also carries wall_ms.
            from ..models.analogy import record_level_span

            record_level_span(
                tracer, cfg, level_t0, level, h, w, float(dist.mean()),
                spatial_slabs=n_slabs,
                shard_walls=shard_walls, shard_axis=slab_axis,
                extra_shard_walls=(
                    {_BANDS_AXIS: band_walls} if band_walls else None
                ),
            )
        if cfg.save_level_artifacts:
            nnf_save = nnf
            if isinstance(nnf, tuple):
                # Stack the lean plane pair on the HOST: checkpoints keep
                # the standard (H, W, 2) schema without materializing the
                # lane-padded stack on device (models/analogy.py does the
                # same).
                nnf_save = np.stack(
                    [np.asarray(nnf[0]), np.asarray(nnf[1])], axis=-1
                )
            _save_level(
                cfg.save_level_artifacts, level, nnf_save, dist, bp, cfg,
                b.shape,
            )

    out = _finalize(bp, yiq_b, b, cfg)
    return out[:h0]
