"""Device-mesh helpers (SURVEY.md §2 parallelism table).

All multi-chip behavior is expressed through a `jax.sharding.Mesh` + named
shardings; XLA inserts the ICI collectives.  The code degrades to a 1-chip
mesh on this box (v5e-1) and scales to v5e-8 unchanged — and runs on the
tests' 8 virtual CPU devices the same way [SURVEY.md §4 'multi-node
without a cluster'].
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


BATCH_AXIS = "batch"
SPACE_AXIS = "space"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across JAX versions.

    The public `jax.shard_map` (its replication check is the
    `check_vma` kwarg) landed after 0.4.x; on 0.4.x — this image ships
    0.4.37, where the bare attribute raises AttributeError — the same
    transform lives at `jax.experimental.shard_map.shard_map` with the
    check named `check_rep`.  Every runner routes through here so the
    sharded paths run on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (BATCH_AXIS,),
    shape: Optional[Tuple[int, ...]] = None,
) -> Mesh:
    """Mesh over the first `n_devices` devices (default: all).

    `shape` splits the devices over multiple named axes, e.g.
    shape=(2, 4), axis_names=("batch", "space") on 8 chips.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join a multi-host JAX cluster (SURVEY.md §2 comms-backend row).

    Thin wrapper over `jax.distributed.initialize`: after it,
    `jax.devices()` spans every host's chips, so `make_mesh()` /
    `make_hybrid_mesh()` and the existing pjit shardings scale to
    multi-host unchanged — XLA routes collectives over ICI within a
    slice and DCN across slices; there is no hand-written comms layer to
    swap.  Arguments default to the standard cluster-environment
    autodetection; when neither explicit arguments nor a recognizable
    cluster environment is present (a single dev box), the autodetection
    failure is treated as "not a cluster" and the call returns False
    without clustering.  Returns True when initialization happened.
    """
    if num_processes is not None and num_processes <= 1:
        return False
    import jax.distributed

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except (RuntimeError, ValueError) as e:
        if coordinator_address or num_processes or process_id:
            raise  # explicit cluster spec that failed: a real error
        import logging

        logging.getLogger("image_analogies_tpu").info(
            "no cluster environment detected (%s); running single-process",
            str(e).splitlines()[0][:120],
        )
        return False


def make_hybrid_mesh(
    dcn_axis: str = BATCH_AXIS,
    ici_axis: str = SPACE_AXIS,
) -> Mesh:
    """Mesh with the slower (cross-slice, DCN) axis outermost.

    The standard layout recipe: put the embarrassingly-parallel axis
    (frames) across slices where bandwidth is scarce, and the
    communication-heavy axis (spatial halos) inside a slice where
    collectives ride ICI.  Granularity is *slices*, not processes — a
    multi-host single-slice pod (e.g. v5e-16 with 4 hosts) is all-ICI
    and gets a flat mesh; only genuinely multi-slice topologies use the
    hybrid DCNxICI builder.
    """
    devices = jax.devices()
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
        from jax.experimental import mesh_utils

        per_slice = len(devices) // n_slices
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, per_slice),
            dcn_mesh_shape=(n_slices, 1),
        )
        return Mesh(arr, (dcn_axis, ici_axis))
    return make_mesh(
        axis_names=(dcn_axis, ici_axis), shape=(1, len(devices))
    )


def batch_sharding(mesh: Mesh, axis: str = BATCH_AXIS) -> NamedSharding:
    """Leading-axis sharding for per-frame arrays."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (the shared A / A' side)."""
    return NamedSharding(mesh, P())
