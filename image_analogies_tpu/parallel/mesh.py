"""Device-mesh helpers (SURVEY.md §2 parallelism table).

All multi-chip behavior is expressed through a `jax.sharding.Mesh` + named
shardings; XLA inserts the ICI collectives.  The code degrades to a 1-chip
mesh on this box (v5e-1) and scales to v5e-8 unchanged — and runs on the
tests' 8 virtual CPU devices the same way [SURVEY.md §4 'multi-node
without a cluster'].
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


BATCH_AXIS = "batch"
SPACE_AXIS = "space"


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (BATCH_AXIS,),
    shape: Optional[Tuple[int, ...]] = None,
) -> Mesh:
    """Mesh over the first `n_devices` devices (default: all).

    `shape` splits the devices over multiple named axes, e.g.
    shape=(2, 4), axis_names=("batch", "space") on 8 chips.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def batch_sharding(mesh: Mesh, axis: str = BATCH_AXIS) -> NamedSharding:
    """Leading-axis sharding for per-frame arrays."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (the shared A / A' side)."""
    return NamedSharding(mesh, P())
