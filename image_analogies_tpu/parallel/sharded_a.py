"""Band-sharded A-side synthesis: style pairs beyond one device's
feature-table budget (SURVEY.md §2 spatial-parallelism row's remaining
hard wall; round-3 VERDICT task 7).

The spatial runner (parallel/spatial.py) shards B' and replicates the A
side; its module docstring records the measured residency analysis —
since the round-4 HBM-streaming kernel, the binding A-side cost is the
lean bf16 FEATURE TABLE the exact-metric merge/polish gathers from
(N_A * 256 B ≈ 4.3 GB at 4096²), not the kernel planes.  This runner
shards THAT: A's rows split into `mesh`-many ownership bands, and each
device holds only

  - its band's slice of the (N_A, D) bf16 feature table, and
  - its band's kernel A-planes (`prepare_a_planes(n_bands=n)`),

so the A-side residency per device is 1/n of the single-chip cost and
the reachable style pair grows linearly with the mesh.

Data path per EM step (all inside one `shard_map` over the band axis):

1. **Kernel bulk search** — each device runs the tile kernel against
   ONLY its band (the ownership-band contract validated bit-identically
   against the sequential banded search in tests/test_sharded_a.py
   test_sharded_a_band_search_matches_sequential), and after every pm
   iteration the per-device fields argmin-merge across the axis
   (`pmin` on distance, ties to the lower band — order-equivalent to
   the sequential carry because accepts are strict improvements), so
   the next iteration's candidates sample from the GLOBAL best field.
2. **Exact-metric merge + polish** — every distance evaluation runs as
   a masked LOCAL gather (each flat A index has exactly one owning
   band; non-owners contribute +inf) merged by `pmin`, which is
   value-identical to the single-table gather.  The accept/tie logic
   runs replicated on the merged distances, so all devices carry the
   same field.

Equivalence: at kappa=0, sharded-lean levels are BIT-IDENTICAL to the
single-device lean path (same PRNG streams, same candidate order,
banded kernel == single-band kernel by the ownership contract,
masked-gather distances == table distances) — pinned by
tests/test_sharded_a.py.  At kappa>0 the kernel's accept is NOT a plain
min (an approximate candidate must clear `d_app * coh_factor <
d_coh`), so the cross-band raw-distance pmin is not order-equivalent
to the sequential carry: a band may accept an approximate candidate
that the sequential order would have rejected against another band's
coherent one.  The result is still a valid field of the same accept
family — strictly closer matches win, the coherence bias is just
marginally weaker across band boundaries — and the post-polish
Ashikhmin adoption pass (`coherence_sweeps_lean`, which runs on the
EXACT merged distances via the sharded dist_fn) applies the oracle's
kappa semantics identically.  Callers needing bit-level
reproducibility of a kappa>0 single-device run should use one device.

Levels below the lean/kernel threshold run the stock single-device
level function (`models/analogy._level_fn`) with the A side
replicated — those levels' A tables are 4^-l of the finest one's, so
replication there never binds.

Assembly is band-sharded too (round-5; removes the round-4 "v1 scope"
ceiling): each device assembles ITS band's slice of the (N_A, D) lean
table from a halo-extended row slab of the A pyramids
(`_band_assemble_fn` — `_split_slabs` provides the slab geometry the
spatial runner proves bit-exact; window reach is covered by
`slab_halo` rows, and edge clamping matches full assembly because
boundary slabs ARE the boundary).  Per-device peak during assembly is
one slab's table + temps (~1/n of the single-chip assembly), so the
reachable style pair is no longer bounded by one device's assembly
headroom.  Bit-identity with slicing the full table is pinned by
tests/test_sharded_a.py test_sharded_a_band_assembly_matches_full.
Only the kernel A-planes (raw image planes, ~MBs) are still prepared
globally before placement — they are not a memory-binding item.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import SynthConfig
from ..models.analogy import (
    _level_fn,
    _assemble_fa_fn,
    _finalize,
    _prologue_fn,
    assemble_features_lean,
    lean_em_step,
    plan_level,
    random_init_planes,
    upsample_nnf_planes,
)
from ..models.matcher import candidate_dist_lean
from ..ops.pyramid import upsample
from .mesh import make_mesh, shard_map

_AXIS = "bands"


def _band_merge(oy, ox, d):
    """Cross-band elementwise argmin of the blocked kernel state, ties
    to the lower band — the parallel form of the sequential banded
    carry.  Order-equivalent at kappa=0 (strict-improvement accepts);
    at kappa>0 the raw-distance pmin slightly weakens the cross-band
    coherence bias (module docstring, 'Equivalence')."""
    from ..telemetry.metrics import count_collectives

    # OBSERVED side of the sentinel's comms ledger: this site traces 4
    # all-reduces (2 pmin + 2 psum).  Trace-time count, like every
    # counter inside jitted code (telemetry/metrics.py caveat).
    count_collectives(4, _AXIS)
    i = jax.lax.axis_index(_AXIS)
    d_min = jax.lax.pmin(d, _AXIS)
    mine = jnp.where(d == d_min, i, jnp.iinfo(jnp.int32).max)
    winner = jax.lax.pmin(mine, _AXIS)
    sel = mine == winner
    oy_m = jax.lax.psum(jnp.where(sel, oy, 0), _AXIS)
    ox_m = jax.lax.psum(jnp.where(sel, ox, 0), _AXIS)
    return oy_m, ox_m, d_min


def _sharded_dist(f_b_tab, f_a_shard, row_lo_flat, idx):
    """Masked local-shard candidate distances merged by pmin: each flat
    A index has exactly one owning band, so the merge reproduces the
    single-table `candidate_dist_lean` value bit-for-bit."""
    from ..telemetry.metrics import count_collectives, get_registry

    # OBSERVED side of the sentinel's comms ledger: one pmin all-reduce
    # per distance-evaluation site (trace-time count).
    count_collectives(1, _AXIS)
    n_loc = f_a_shard.shape[0]
    # Per-device bytes the masked local gather moves for this candidate
    # batch (idx rows x one bf16 feature row each).  TRACE-TIME count
    # (telemetry/metrics.py JAX caveat): under jit this tallies bytes
    # per traced evaluation site, a static per-compilation figure — the
    # quantity the gather-traffic budget reasons about — not a runtime
    # execution count.
    get_registry().counter(
        "ia_sharded_gather_bytes_total",
        "bytes gathered per device by sharded-A candidate evaluations "
        "(trace-time static count)",
    ).inc(
        float(np.prod(idx.shape))
        * f_a_shard.shape[1] * f_a_shard.dtype.itemsize
    )
    loc = jnp.clip(idx - row_lo_flat, 0, n_loc - 1)
    d_loc = candidate_dist_lean(f_b_tab, f_a_shard, loc)
    owned = (idx >= row_lo_flat) & (idx < row_lo_flat + n_loc)
    return jax.lax.pmin(
        jnp.where(owned, d_loc, jnp.float32(jnp.inf)), _AXIS
    )


def _band_assembly_aligned(ha: int, hc, n_dev: int,
                           has_coarse: bool) -> bool:
    """Whether the band-sharded assembly's slab geometry is exact for
    these shapes.  Beyond ha % n_dev == 0, the COARSE pyramid slabs
    must land on the same band boundaries: rows-per-band even and the
    coarse height exactly ha/2 with n_dev dividing it — otherwise
    `_split_slabs` on the coarse side would offset every non-zero
    band's coarse rows (silently wrong coarse features, exit 0).
    Misaligned shapes fall back to global assembly + sharded
    placement."""
    if ha % n_dev:
        return False
    if not has_coarse:
        return True
    rows_pb = ha // n_dev
    return (
        rows_pb % 2 == 0
        and hc is not None
        and hc * 2 == ha
        and hc % n_dev == 0
    )


@functools.lru_cache(maxsize=32)
def _band_assemble_fn(cfg: SynthConfig, mesh_key, has_coarse: bool,
                      n_dev: int):
    """Band-sharded lean A-table assembly: each device assembles its
    own band's (rows/n * wa, D) slice from a halo-extended slab of the
    A pyramids, so no device ever holds the full table OR the full
    assembly temps (module docstring; the slab geometry is
    `_split_slabs`' — bit-exact per the spatial runner's halo contract,
    pinned by test_sharded_a_band_assembly_matches_full).

    The slab stacks are split EAGERLY and PLACED with an explicit
    (bands-sharded, otherwise-replicated) sharding before the jitted
    shard_map consumes them, and the jit pins matching `in_shardings`.
    Tracing `_split_slabs` into the same jit and letting GSPMD derive
    the manual-region boundary layout miscompiles on this jax (0.4.x)
    when the mesh has a second axis the specs leave unmentioned: GSPMD
    materializes the stacks as per-device dynamic-update-slice
    contributions summed by an all-reduce over ALL devices, which
    double-counts the slabs-replicated contributions — the assembled
    table comes back exactly n_slabs x the true values (root cause of
    the round-6 "2.5% of pixels diverge" 2-D measurement; regression-
    pinned by tests/test_sharded_a.py
    test_band_assembly_2d_mesh_matches_full)."""
    from jax.sharding import PartitionSpec as P

    from .batch import _MESHES
    from .spatial import _split_slabs, slab_halo

    mesh = _MESHES[mesh_key]
    halo = slab_halo(cfg)
    band_shard = NamedSharding(mesh, P(_AXIS))
    n_in = 4 if has_coarse else 2

    def body(*bslabs):
        parts = [s[0] for s in bslabs]
        s_src, s_flt = parts[0], parts[1]
        s_src_c = parts[2] if has_coarse else None
        s_flt_c = parts[3] if has_coarse else None
        rows_pb = s_src.shape[0] - 2 * halo
        wa = s_src.shape[1]
        tab = assemble_features_lean(
            s_src, s_flt, cfg, s_src_c, s_flt_c
        )
        d = tab.shape[1]
        core = tab.reshape(rows_pb + 2 * halo, wa, d)[
            halo : halo + rows_pb
        ]
        return core.reshape(rows_pb * wa, d)

    shmapped = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(_AXIS),) * n_in,
            out_specs=P(_AXIS),
            # assemble_features_lean's fori_loop body carries no
            # varying-mesh-axes info (same pattern as the level fns).
            check_vma=False,
        ),
        in_shardings=(band_shard,) * n_in,
    )

    def call(src_a, flt_a, src_c=None, flt_c=None):
        slabs = [
            jax.device_put(_split_slabs(src_a, n_dev, halo), band_shard),
            jax.device_put(_split_slabs(flt_a, n_dev, halo), band_shard),
        ]
        if has_coarse:
            slabs += [
                jax.device_put(
                    _split_slabs(src_c, n_dev, halo // 2), band_shard
                ),
                jax.device_put(
                    _split_slabs(flt_c, n_dev, halo // 2), band_shard
                ),
            ]
        return shmapped(*slabs)

    return call


@functools.lru_cache(maxsize=32)
def _sharded_level_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                      mesh_key, interpret: bool):
    """One sharded-lean pyramid level as ONE compiled shard_map call:
    all `cfg.em_iters` EM steps with the A table + kernel planes
    band-sharded.  The EM body is models/analogy.lean_em_step — the
    SAME function the single-device lean path runs (state glue, PRNG
    streams, and polish schedule mirror _level_fn_cached) — with the
    three sharded hooks passed through."""
    from .batch import _MESHES

    mesh = _MESHES[mesh_key]

    def run_level(f_a_tab, a_stacked, bounds_stacked, src_b_l, src_b_c,
                  raw_b_l, copy_a_l, p_py, p_px, prev_bp, level_key):
        def body(f_a_shard, a_band, band, src_b_l, src_b_c, raw_b_l,
                 copy_a_l, p_py, p_px, prev_bp, level_key):
            from ..telemetry.metrics import count_expected_collectives
            from .comms import sharded_a_allreduce_sites

            a_band, band = a_band[0], band[0]
            h, w = src_b_l.shape[:2]
            ha, wa = copy_a_l.shape[:2]
            row_lo_flat = band[0] * wa
            # EXPECTED side of the sentinel's comms ledger, booked at
            # trace time inside the same traced body that contains the
            # observed sites — the two series skip together on a jit
            # cache hit, so observed == expected holds per session.
            count_expected_collectives(
                sharded_a_allreduce_sites(cfg, ha, wa), _AXIS
            )

            if has_coarse:
                py, px = upsample_nnf_planes(p_py, p_px, (h, w), ha, wa)
                flt_bp_coarse = prev_bp
                flt_bp = upsample(prev_bp, (h, w))
            else:
                py, px = random_init_planes(level_key, h, w, ha, wa)
                flt_bp = raw_b_l
                flt_bp_coarse = flt_bp

            dist = None
            for em in range(cfg.em_iters):
                polish = (
                    cfg.pm_polish_iters
                    if (em == cfg.em_iters - 1
                        or not cfg.pm_polish_final_only)
                    else 0
                )
                (py, px), dist, bp = lean_em_step(
                    cfg, level, has_coarse, polish,
                    src_b_l,
                    flt_bp,
                    src_b_c if has_coarse else src_b_l,
                    flt_bp_coarse if has_coarse else flt_bp,
                    f_a_shard,
                    copy_a_l,
                    (py, px),
                    jax.random.fold_in(level_key, em),
                    (a_band,),
                    interpret=interpret,
                    dist_fn=lambda f_b_tab: functools.partial(
                        _sharded_dist, f_b_tab, f_a_shard, row_lo_flat
                    ),
                    bounds=(band,),
                    sweep_merge=_band_merge,
                )
                flt_bp = bp
            return py, px, dist, bp

        return shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(_AXIS), P(_AXIS), P(_AXIS),
                P(), P(), P(), P(), P(), P(), P(), P(),
            ),
            out_specs=P(),
            # pallas_call outputs carry no varying-mesh-axes info.
            check_vma=False,
        )(f_a_tab, a_stacked, bounds_stacked, src_b_l, src_b_c,
          raw_b_l, copy_a_l, p_py, p_px, prev_bp, level_key)

    return jax.jit(run_level)


def synthesize_sharded_a(
    a,
    ap,
    b,
    cfg: Optional[SynthConfig] = None,
    mesh=None,
    progress=None,
    resume_from: Optional[str] = None,
    resume_strict: bool = False,
):
    """B' for one (b) against a style pair whose A-side lean tables are
    BAND-SHARDED across the mesh — per-device A residency is 1/n of the
    single-chip lean path's, so the reachable style pair grows linearly
    with the mesh (module docstring: data path + equivalence).

    Sharded-lean levels are bit-identical to the single-device lean
    path at kappa=0 (kappa>0: same accept family, marginally weaker
    cross-band coherence bias — module docstring, 'Equivalence');
    sub-threshold levels run the stock replicated level function.
    Requires each sharded level's A rows to split evenly over the mesh
    (ha % n_devices == 0 — band planes must stack rectangularly).
    Warns if NO level engaged the sharded step (the flag's purpose
    unmet: every level fit under `cfg.feature_bytes_budget` or was not
    kernel-eligible).
    `progress` is an optional utils.progress.ProgressWriter (one timed
    `level_done` event per level, like the single driver).

    Checkpoint/resume: `cfg.save_level_artifacts` writes the standard
    per-level artifacts (lean plane pairs stacked host-side to the
    (H, W, 2) schema, like the other runners) and `resume_from`
    restarts from the finest completed level via the shared
    `resume_prologue`.
    """
    import time

    from ..kernels import resolve_pallas
    from ..kernels.patchmatch_tile import band_bounds, prepare_a_planes
    from ..models.analogy import (
        _level_plan,
        _save_level,
        _strip_noncompute,
        resume_prologue,
    )
    from .batch import _mesh_token

    from ..telemetry.spans import as_tracer

    tracer = as_tracer(progress)
    cfg = cfg or SynthConfig()
    mesh = mesh or make_mesh(axis_names=(_AXIS,))
    if mesh.axis_names != (_AXIS,):
        raise ValueError(
            f"sharded-A mesh must have a single '{_AXIS}' axis, got "
            f"{mesh.axis_names}"
        )
    n_dev = mesh.devices.size
    token = _mesh_token(mesh)

    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if a.shape != ap.shape:
        raise ValueError(f"A {a.shape} and A' {ap.shape} must match")

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    # xfer injection point: the prologue dispatch is the run's
    # host->device transfer boundary (runtime/faults.py).
    from ..runtime.faults import fire as _fault_fire

    _fault_fire("xfer", 0)
    prologue_t0 = time.perf_counter()
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, pyr_raw_b, yiq_b
    ) = _prologue_fn(cfg, levels)(a, ap, b)
    # Shared drain + span — uniform report phases across runners
    # (round 10: also declares the run plan — including the comms-model
    # collective term — for the live /progress ETA).
    from ..models.analogy import record_prologue

    record_prologue(
        tracer, pyr_raw_b, levels, prologue_t0, cfg=cfg,
        a_hw=a.shape[:2], runner="sharded_a",
    )

    key = jax.random.PRNGKey(cfg.seed)
    interpret = bool(resolve_pallas(cfg))
    shard = NamedSharding(mesh, P(_AXIS))

    bp = None
    nnf = None  # stacked array (replicated levels) or (py, px) planes
    n_sharded_levels = 0
    start_level = levels - 1
    resumed = resume_prologue(
        resume_from, levels, cfg, b.shape, tracer, strict=resume_strict
    )
    if resumed is not None:
        start_level, nnf, bp, _aux = resumed
        if start_level < 0:
            return _finalize(bp, yiq_b, b, cfg)
        # Resumed levels count as sharded coverage for the no-op warning
        # below only if they WOULD have sharded; simplest honest rule:
        # suppress the warning on resumed runs (the prior run warned).
        n_sharded_levels = levels - 1 - start_level
    for level in range(start_level, -1, -1):
        # level injection point + supervisor abort checkpoint
        # (runtime/faults.py).
        _fault_fire("level", level)
        level_t0 = time.perf_counter()
        shard_walls = None  # set on lean (band-sharded) levels only
        h, w = pyr_src_b[level].shape[:2]
        ha, wa = pyr_src_a[level].shape[:2]
        has_coarse = level < levels - 1
        level_key = jax.random.fold_in(key, level)

        # All dispatch decisions come from the shared planner
        # (models/analogy.plan_level); brute never takes the lean-brute
        # path here, so big-brute levels fall through to the stock
        # level function's unfuse rule.
        plan = plan_level(
            cfg, level, pyr_src_a[level], pyr_flt_a[level], has_coarse,
            h, w, prev_nnf=nnf, brute_lean=False,
        )
        lean = plan.lean
        # kernel injection point: the level's compiled work (band
        # assembly + sharded/stock level dispatch) starts past here.
        _fault_fire("kernel", level)
        if lean:
            if ha % n_dev:
                raise ValueError(
                    f"sharded-A level {level}: A rows ({ha}) must split "
                    f"evenly over {n_dev} devices"
                )
            chan_plan = _level_plan(
                cfg, pyr_src_a[level], pyr_flt_a[level], has_coarse, h, w
            )
            specs, use_coarse, _ = chan_plan
            # Band-sharded assembly: each device assembles its own
            # band's table slice from a halo-extended A-pyramid slab
            # (module docstring) — no device ever holds the full table
            # or the full assembly temps.  Shapes whose coarse slabs
            # would not land on band boundaries fall back to global
            # assembly + sharded placement (_band_assembly_aligned).
            hc = pyr_src_a[level + 1].shape[0] if has_coarse else None
            if _band_assembly_aligned(ha, hc, n_dev, has_coarse):
                coarse_args = (
                    (pyr_src_a[level + 1], pyr_flt_a[level + 1])
                    if has_coarse
                    else ()
                )
                f_a_tab = _band_assemble_fn(
                    _strip_noncompute(cfg), token, has_coarse, n_dev
                )(pyr_src_a[level], pyr_flt_a[level], *coarse_args)
            else:
                f_a_tab = jax.device_put(
                    assemble_features_lean(
                        pyr_src_a[level],
                        pyr_flt_a[level],
                        cfg,
                        pyr_src_a[level + 1] if has_coarse else None,
                        pyr_flt_a[level + 1] if has_coarse else None,
                    ),
                    shard,
                )
            if tracer.enabled:
                # Per-band completion walls of the band-sharded
                # ASSEMBLY (the straggler watch's per-band signal on
                # this runner): the EM body's pmin/psum merges
                # synchronize the bands every pm iteration, so
                # post-merge skew is unobservable by construction —
                # the assembly phase, each band building its own table
                # slice independently, is where a slow band shows.
                # Instrumented runs already pay per-level syncs (the
                # documented per-level-timing price); this adds the
                # per-band readbacks to the same barrier.
                from ..models.analogy import shard_sync_walls

                rows_pb = f_a_tab.shape[0] // n_dev
                shard_walls = shard_sync_walls(
                    level_t0,
                    [
                        f_a_tab[i * rows_pb:(i + 1) * rows_pb, :1]
                        for i in range(n_dev)
                    ],
                )
            bands = prepare_a_planes(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if use_coarse else None,
                pyr_flt_a[level + 1] if use_coarse else None,
                specs,
                n_bands=n_dev,
            )
            a_stacked = jax.device_put(jnp.stack(bands), shard)
            bounds_stacked = jax.device_put(
                jnp.stack(band_bounds(ha, n_dev)), shard
            )

            if nnf is None:
                p_py = p_px = jnp.zeros((8, 8), jnp.int32)  # unused
                prev_bp = pyr_raw_b[level]
            elif isinstance(nnf, tuple):
                p_py, p_px = nnf
                prev_bp = bp
            else:
                p_py, p_px = nnf[..., 0], nnf[..., 1]
                prev_bp = bp
            n_sharded_levels += 1
            run = _sharded_level_fn(
                _strip_noncompute(cfg), level, has_coarse, token,
                interpret,
            )
            py, px, dist, bp = run(
                f_a_tab, a_stacked, bounds_stacked,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else pyr_src_b[level],
                pyr_raw_b[level],
                pyr_copy_a[level],
                p_py, p_px,
                prev_bp,
                level_key,
            )
            nnf = (py, px)
        else:
            f_a_ext = proj_ext = None
            if plan.fa_external:
                f_a_ext, proj_ext = _assemble_fa_fn(cfg, has_coarse)(
                    pyr_src_a[level],
                    pyr_flt_a[level],
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                )
            run = _level_fn(
                cfg, level, has_coarse, False, plan.prev_kind,
                plan.fa_external, plan.fuse,
            )
            nnf, dist, bp = run(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else None,
                pyr_raw_b[level],
                pyr_copy_a[level],
                nnf,
                bp,
                level_key,
                f_a_ext,
                proj_ext,
            )

        if tracer.enabled:
            # Sync (the nnf_energy readback) BEFORE the wall is read,
            # then record a timed `level` span — the legacy
            # `level_done` event is the span's emitted view
            # (telemetry/spans.py).
            from ..models.analogy import record_level_span

            record_level_span(
                tracer, cfg, level_t0, level, h, w, float(dist.mean()),
                shard_walls=shard_walls, shard_axis=_AXIS,
                **({"shard_phase": "assemble"} if shard_walls else {}),
            )
        if cfg.save_level_artifacts:
            nnf_save = nnf
            if isinstance(nnf, tuple):
                # Stack the plane pair on the HOST: checkpoints keep the
                # standard (H, W, 2) schema without materializing the
                # lane-padded stack on device (models/analogy.py does
                # the same).
                nnf_save = np.stack(
                    [np.asarray(nnf[0]), np.asarray(nnf[1])], axis=-1
                )
            _save_level(
                cfg.save_level_artifacts, level, nnf_save, dist, bp, cfg,
                b.shape,
            )

    if not n_sharded_levels:
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "sharded-A run never engaged the sharded step: every level "
            "fit under feature_bytes_budget (%d bytes) or was not "
            "kernel-eligible, so the A side was REPLICATED on all %d "
            "devices — the synthesis is correct but nothing was "
            "sharded.  Lower cfg.feature_bytes_budget "
            "(--feature-bytes-budget) to engage A-side sharding.",
            cfg.feature_bytes_budget, n_dev,
        )
    return _finalize(bp, yiq_b, b, cfg)
