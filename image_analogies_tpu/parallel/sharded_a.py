"""Band-sharded A-side synthesis: style pairs beyond one device's
feature-table budget (SURVEY.md §2 spatial-parallelism row's remaining
hard wall; round-3 VERDICT task 7).

The spatial runner (parallel/spatial.py) shards B' and replicates the A
side; its module docstring records the measured residency analysis —
since the round-4 HBM-streaming kernel, the binding A-side cost is the
lean bf16 FEATURE TABLE the exact-metric merge/polish gathers from
(N_A * 256 B ≈ 4.3 GB at 4096²), not the kernel planes.  This runner
shards THAT: A's rows split into `mesh`-many ownership bands, and each
device holds only

  - its band's slice of the (N_A, D) bf16 feature table, and
  - its band's kernel A-planes (`prepare_a_planes(n_bands=n)`),

so the A-side residency per device is 1/n of the single-chip cost and
the reachable style pair grows linearly with the mesh.

Data path per EM step (all inside one `shard_map` over the band axis):

1. **Kernel bulk search** — each device runs the tile kernel against
   ONLY its band (the ownership-band contract validated bit-identically
   against the sequential banded search in tests/test_spatial.py
   test_sharded_a_band_search_matches_sequential), and after every pm
   iteration the per-device fields argmin-merge across the axis
   (`pmin` on distance, ties to the lower band — order-equivalent to
   the sequential carry because accepts are strict improvements), so
   the next iteration's candidates sample from the GLOBAL best field.
2. **Exact-metric merge + polish** — every distance evaluation runs as
   a masked LOCAL gather (each flat A index has exactly one owning
   band; non-owners contribute +inf) merged by `pmin`, which is
   value-identical to the single-table gather.  The accept/tie logic
   runs replicated on the merged distances, so all devices carry the
   same field.

Equivalence: at kappa=0, sharded-lean levels are BIT-IDENTICAL to the
single-device lean path (same PRNG streams, same candidate order,
banded kernel == single-band kernel by the ownership contract,
masked-gather distances == table distances) — pinned by
tests/test_spatial.py.  At kappa>0 the kernel's accept is NOT a plain
min (an approximate candidate must clear `d_app * coh_factor <
d_coh`), so the cross-band raw-distance pmin is not order-equivalent
to the sequential carry: a band may accept an approximate candidate
that the sequential order would have rejected against another band's
coherent one.  The result is still a valid field of the same accept
family — strictly closer matches win, the coherence bias is just
marginally weaker across band boundaries — and the post-polish
Ashikhmin adoption pass (`coherence_sweeps_lean`, which runs on the
EXACT merged distances via the sharded dist_fn) applies the oracle's
kappa semantics identically.  Callers needing bit-level
reproducibility of a kappa>0 single-device run should use one device.

Levels below the lean/kernel threshold run the stock single-device
level function (`models/analogy._level_fn`) with the A side
replicated — those levels' A tables are 4^-l of the finest one's, so
replication there never binds.

Production-hardening note (v1 scope): the full (N_A, D) table and the
kernel planes are ASSEMBLED unsharded (one jit) before being placed
band-sharded; assembling each band's slice directly on its owner
(windowed assembly needs halo rows of the A pyramids) is the remaining
step for an A side beyond one device's *assembly* headroom, which at
bf16 sits ~8x past the gather-table wall this runner removes.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import SynthConfig
from ..models.analogy import (
    _SAFE_EXEC_DIST_ELEMS,
    _feature_table_bytes,
    _kernel_eligible,
    _level_fn,
    _fa_external,
    _assemble_fa_fn,
    _finalize,
    _prologue_fn,
    assemble_features_lean,
    lean_em_step,
    random_init_planes,
    upsample_nnf_planes,
)
from ..models.matcher import candidate_dist_lean
from ..ops.pyramid import upsample
from .mesh import make_mesh

_AXIS = "bands"


def _band_merge(oy, ox, d):
    """Cross-band elementwise argmin of the blocked kernel state, ties
    to the lower band — the parallel form of the sequential banded
    carry.  Order-equivalent at kappa=0 (strict-improvement accepts);
    at kappa>0 the raw-distance pmin slightly weakens the cross-band
    coherence bias (module docstring, 'Equivalence')."""
    i = jax.lax.axis_index(_AXIS)
    d_min = jax.lax.pmin(d, _AXIS)
    mine = jnp.where(d == d_min, i, jnp.iinfo(jnp.int32).max)
    winner = jax.lax.pmin(mine, _AXIS)
    sel = mine == winner
    oy_m = jax.lax.psum(jnp.where(sel, oy, 0), _AXIS)
    ox_m = jax.lax.psum(jnp.where(sel, ox, 0), _AXIS)
    return oy_m, ox_m, d_min


def _sharded_dist(f_b_tab, f_a_shard, row_lo_flat, idx):
    """Masked local-shard candidate distances merged by pmin: each flat
    A index has exactly one owning band, so the merge reproduces the
    single-table `candidate_dist_lean` value bit-for-bit."""
    n_loc = f_a_shard.shape[0]
    loc = jnp.clip(idx - row_lo_flat, 0, n_loc - 1)
    d_loc = candidate_dist_lean(f_b_tab, f_a_shard, loc)
    owned = (idx >= row_lo_flat) & (idx < row_lo_flat + n_loc)
    return jax.lax.pmin(
        jnp.where(owned, d_loc, jnp.float32(jnp.inf)), _AXIS
    )


@functools.lru_cache(maxsize=32)
def _sharded_level_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                      mesh_key, interpret: bool):
    """One sharded-lean pyramid level as ONE compiled shard_map call:
    all `cfg.em_iters` EM steps with the A table + kernel planes
    band-sharded.  The EM body is models/analogy.lean_em_step — the
    SAME function the single-device lean path runs (state glue, PRNG
    streams, and polish schedule mirror _level_fn_cached) — with the
    three sharded hooks passed through."""
    from .batch import _MESHES

    mesh = _MESHES[mesh_key]

    def run_level(f_a_tab, a_stacked, bounds_stacked, src_b_l, src_b_c,
                  raw_b_l, copy_a_l, p_py, p_px, prev_bp, level_key):
        def body(f_a_shard, a_band, band, src_b_l, src_b_c, raw_b_l,
                 copy_a_l, p_py, p_px, prev_bp, level_key):
            a_band, band = a_band[0], band[0]
            h, w = src_b_l.shape[:2]
            ha, wa = copy_a_l.shape[:2]
            row_lo_flat = band[0] * wa

            if has_coarse:
                py, px = upsample_nnf_planes(p_py, p_px, (h, w), ha, wa)
                flt_bp_coarse = prev_bp
                flt_bp = upsample(prev_bp, (h, w))
            else:
                py, px = random_init_planes(level_key, h, w, ha, wa)
                flt_bp = raw_b_l
                flt_bp_coarse = flt_bp

            dist = None
            for em in range(cfg.em_iters):
                polish = (
                    cfg.pm_polish_iters
                    if (em == cfg.em_iters - 1
                        or not cfg.pm_polish_final_only)
                    else 0
                )
                (py, px), dist, bp = lean_em_step(
                    cfg, level, has_coarse, polish,
                    src_b_l,
                    flt_bp,
                    src_b_c if has_coarse else src_b_l,
                    flt_bp_coarse if has_coarse else flt_bp,
                    f_a_shard,
                    copy_a_l,
                    (py, px),
                    jax.random.fold_in(level_key, em),
                    (a_band,),
                    interpret=interpret,
                    dist_fn=lambda f_b_tab: functools.partial(
                        _sharded_dist, f_b_tab, f_a_shard, row_lo_flat
                    ),
                    bounds=(band,),
                    sweep_merge=_band_merge,
                )
                flt_bp = bp
            return py, px, dist, bp

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(_AXIS), P(_AXIS), P(_AXIS),
                P(), P(), P(), P(), P(), P(), P(), P(),
            ),
            out_specs=P(),
            # pallas_call outputs carry no varying-mesh-axes info.
            check_vma=False,
        )(f_a_tab, a_stacked, bounds_stacked, src_b_l, src_b_c,
          raw_b_l, copy_a_l, p_py, p_px, prev_bp, level_key)

    return jax.jit(run_level)


def synthesize_sharded_a(
    a,
    ap,
    b,
    cfg: Optional[SynthConfig] = None,
    mesh=None,
    progress=None,
    resume_from: Optional[str] = None,
):
    """B' for one (b) against a style pair whose A-side lean tables are
    BAND-SHARDED across the mesh — per-device A residency is 1/n of the
    single-chip lean path's, so the reachable style pair grows linearly
    with the mesh (module docstring: data path + equivalence).

    Sharded-lean levels are bit-identical to the single-device lean
    path at kappa=0 (kappa>0: same accept family, marginally weaker
    cross-band coherence bias — module docstring, 'Equivalence');
    sub-threshold levels run the stock replicated level function.
    Requires each sharded level's A rows to split evenly over the mesh
    (ha % n_devices == 0 — band planes must stack rectangularly).
    Warns if NO level engaged the sharded step (the flag's purpose
    unmet: every level fit under `cfg.feature_bytes_budget` or was not
    kernel-eligible).
    `progress` is an optional utils.progress.ProgressWriter (one timed
    `level_done` event per level, like the single driver).

    Checkpoint/resume: `cfg.save_level_artifacts` writes the standard
    per-level artifacts (lean plane pairs stacked host-side to the
    (H, W, 2) schema, like the other runners) and `resume_from`
    restarts from the finest completed level via the shared
    `resume_prologue`.
    """
    import time

    from ..kernels import resolve_pallas
    from ..kernels.patchmatch_tile import band_bounds, prepare_a_planes
    from ..models.analogy import (
        _level_plan,
        _save_level,
        _strip_noncompute,
        resume_prologue,
    )
    from .batch import _mesh_token

    cfg = cfg or SynthConfig()
    mesh = mesh or make_mesh(axis_names=(_AXIS,))
    if mesh.axis_names != (_AXIS,):
        raise ValueError(
            f"sharded-A mesh must have a single '{_AXIS}' axis, got "
            f"{mesh.axis_names}"
        )
    n_dev = mesh.devices.size
    token = _mesh_token(mesh)

    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if a.shape != ap.shape:
        raise ValueError(f"A {a.shape} and A' {ap.shape} must match")

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, pyr_raw_b, yiq_b
    ) = _prologue_fn(cfg, levels)(a, ap, b)

    key = jax.random.PRNGKey(cfg.seed)
    interpret = bool(resolve_pallas(cfg))
    shard = NamedSharding(mesh, P(_AXIS))

    bp = None
    nnf = None  # stacked array (replicated levels) or (py, px) planes
    n_sharded_levels = 0
    start_level = levels - 1
    resumed = resume_prologue(resume_from, levels, cfg, b.shape, progress)
    if resumed is not None:
        start_level, nnf, bp, _aux = resumed
        if start_level < 0:
            return _finalize(bp, yiq_b, b, cfg)
        # Resumed levels count as sharded coverage for the no-op warning
        # below only if they WOULD have sharded; simplest honest rule:
        # suppress the warning on resumed runs (the prior run warned).
        n_sharded_levels = levels - 1 - start_level
    for level in range(start_level, -1, -1):
        level_t0 = time.perf_counter()
        h, w = pyr_src_b[level].shape[:2]
        ha, wa = pyr_src_a[level].shape[:2]
        has_coarse = level < levels - 1
        level_key = jax.random.fold_in(key, level)

        # MAINTENANCE NOTE: this per-level glue (lean decision,
        # prev_kind, fa_ext, fuse) mirrors create_image_analogy's loop
        # (models/analogy.py) — a change there must be mirrored here;
        # the EM bodies themselves are shared (lean_em_step /
        # _level_fn), only the loop glue is duplicated.
        lean = (
            _kernel_eligible(
                cfg, pyr_src_a[level], pyr_flt_a[level], has_coarse, h, w
            )
            and _feature_table_bytes(h, w, ha, wa) > cfg.feature_bytes_budget
        )
        if lean and cfg.pca_dims:
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "level %d exceeds feature_bytes_budget: lean path "
                "matches in full-D bf16 space, pca_dims=%s is not "
                "applied at this level", level, cfg.pca_dims,
            )
        if lean:
            if ha % n_dev:
                raise ValueError(
                    f"sharded-A level {level}: A rows ({ha}) must split "
                    f"evenly over {n_dev} devices"
                )
            plan = _level_plan(
                cfg, pyr_src_a[level], pyr_flt_a[level], has_coarse, h, w
            )
            specs, use_coarse, _ = plan
            # Assemble the full table/planes once (see the module
            # docstring's v1 scope note), then place them band-sharded:
            # from here on each device touches only its shard.
            f_a_tab = jax.device_put(
                assemble_features_lean(
                    pyr_src_a[level],
                    pyr_flt_a[level],
                    cfg,
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                ),
                shard,
            )
            bands = prepare_a_planes(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if use_coarse else None,
                pyr_flt_a[level + 1] if use_coarse else None,
                specs,
                n_bands=n_dev,
            )
            a_stacked = jax.device_put(jnp.stack(bands), shard)
            bounds_stacked = jax.device_put(
                jnp.stack(band_bounds(ha, n_dev)), shard
            )

            if nnf is None:
                p_py = p_px = jnp.zeros((8, 8), jnp.int32)  # unused
                prev_bp = pyr_raw_b[level]
            elif isinstance(nnf, tuple):
                p_py, p_px = nnf
                prev_bp = bp
            else:
                p_py, p_px = nnf[..., 0], nnf[..., 1]
                prev_bp = bp
            n_sharded_levels += 1
            run = _sharded_level_fn(
                _strip_noncompute(cfg), level, has_coarse, token,
                interpret,
            )
            py, px, dist, bp = run(
                f_a_tab, a_stacked, bounds_stacked,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else pyr_src_b[level],
                pyr_raw_b[level],
                pyr_copy_a[level],
                p_py, p_px,
                prev_bp,
                level_key,
            )
            nnf = (py, px)
        else:
            prev_kind = (
                "none" if not has_coarse
                else ("planes" if isinstance(nnf, tuple) else "stacked")
            )
            fa_ext = _fa_external(ha, wa, False)
            f_a_ext = proj_ext = None
            if fa_ext:
                f_a_ext, proj_ext = _assemble_fa_fn(cfg, has_coarse)(
                    pyr_src_a[level],
                    pyr_flt_a[level],
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                )
            # Same oversized-brute unfuse rule as the single driver
            # (models/analogy._SAFE_EXEC_DIST_ELEMS).
            fuse = (
                cfg.matcher != "brute"
                or cfg.em_iters * (h * w) * (ha * wa)
                <= _SAFE_EXEC_DIST_ELEMS
            )
            run = _level_fn(
                cfg, level, has_coarse, False, prev_kind, fa_ext, fuse
            )
            nnf, dist, bp = run(
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else None,
                pyr_raw_b[level],
                pyr_copy_a[level],
                nnf,
                bp,
                level_key,
                f_a_ext,
                proj_ext,
            )

        if progress is not None:
            nnf_energy = float(dist.mean())
            progress.emit(
                "level_done",
                level=level,
                shape=[int(h), int(w)],
                wall_ms=round((time.perf_counter() - level_t0) * 1000, 3),
                nnf_energy=nnf_energy,
            )
        if cfg.save_level_artifacts:
            nnf_save = nnf
            if isinstance(nnf, tuple):
                # Stack the plane pair on the HOST: checkpoints keep the
                # standard (H, W, 2) schema without materializing the
                # lane-padded stack on device (models/analogy.py does
                # the same).
                nnf_save = np.stack(
                    [np.asarray(nnf[0]), np.asarray(nnf[1])], axis=-1
                )
            _save_level(
                cfg.save_level_artifacts, level, nnf_save, dist, bp, cfg,
                b.shape,
            )

    if not n_sharded_levels:
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "sharded-A run never engaged the sharded step: every level "
            "fit under feature_bytes_budget (%d bytes) or was not "
            "kernel-eligible, so the A side was REPLICATED on all %d "
            "devices — the synthesis is correct but nothing was "
            "sharded.  Lower cfg.feature_bytes_budget "
            "(--feature-bytes-budget) to engage A-side sharding.",
            cfg.feature_bytes_budget, n_dev,
        )
    return _finalize(bp, yiq_b, b, cfg)
