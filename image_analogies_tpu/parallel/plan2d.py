"""Mesh-shape planner for the 2-D bands x slabs spatial runner (r17).

`synthesize_spatial` accepts any (n_bands, n_slabs) factorization of
the device count, but the right split is a modeled trade, not a
default: more slabs cut each device's B'-share and candidate-DMA
traffic but shrink slab cores toward the kernel's LANE floor (a slab
under 128 rows silently falls back to the standard path and the whole
lean story is gone); more bands cut each device's A-side residency but
buy the bands-axis all-reduce schedule.  This module makes that trade
explicit: enumerate every factorization, price each with the SAME
analytic models the sentinel pins (parallel/comms.py collective
schedule, kernels.patchmatch_tile.candidate_dma_bytes_per_fetch), and
pick deterministically.

Decision rule (in order):

1. **Feasibility** — bands must divide the device count (by
   construction here), every band must own at least one real A row at
   every level it would run, a multi-band candidate must have at
   least one level where banding actually engages (a bands axis that
   never runs is pure device waste: those levels route to the 1-D
   slabs submesh), and modeled per-device peak residency must fit the
   HBM budget when one is given — residency is a CAPACITY constraint,
   not a cost addend, because traffic terms dwarf resident bytes and
   could never force bands on, yet splitting A once it outgrows a
   chip is the bands axis's whole reason to exist.
2. **Modeled bytes** — among the survivors, minimize per-device
   collective volume + candidate traffic (`score_bytes`), where a
   level whose slab geometry falls below the kernel floor is charged
   the STANDARD-path traffic penalty (`_DELEAN_PENALTY` x the lean
   per-candidate bytes): kernel coverage is priced by the work it
   covers, not counted per level — counting levels would let eight
   cheap de-slabbed coarse levels outvote one de-leaned finest level
   that carries almost all the pixels.

The chosen shape AND every rejected alternative (with its reason or
its losing score) are recorded on the run plan: the CLI threads the
planner's output through `synthesize_spatial(mesh_plan=...)` into the
`run_plan` prologue mark, so a flight dump shows why THIS mesh and
what it beat.  `--bands` / `--mesh-rows` remain the manual override —
an explicit value skips the planner entirely.

All prices are host-side integer arithmetic on shapes; the planner
never touches a device.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from ..config import SynthConfig

# Per-device slab-resident state arrays the residency model charges:
# src_b, flt_bp, coarse pair, py, px — boundary-halo'd f32 planes the
# level keeps live across EM iterations (spatial.py's slab views).
_N_SLAB_ARRAYS = 6
# Lean-table itemsize (bf16) — models/analogy.assemble_features_lean.
_TABLE_ITEMSIZE = 2
# Traffic multiplier for a level the kernel refuses (slab core under
# the LANE floor or A under the tile+halo floor): the standard path
# re-gathers full f32 patch windows per candidate with none of the
# packed-plane DMA coalescing, modeled as 4x the lean per-candidate
# moved bytes.  A modeled constant (like _N_SLAB_ARRAYS), not a
# measurement — its job is ordinal: de-leaning the finest level must
# cost more than any slab/band reshuffle could save.
_DELEAN_PENALTY = 4


@dataclasses.dataclass(frozen=True)
class MeshCandidate:
    """One (n_bands, n_slabs) factorization, priced."""

    n_bands: int
    n_slabs: int
    feasible: bool
    reason: str                 # infeasibility reason ("" if feasible)
    kernel_levels: int          # pyramid levels kernel-eligible at this split
    banded_levels: int          # levels where the bands axis engages
    comms_bytes: int            # modeled per-device collective payload, run
    residency_bytes: int        # modeled per-device peak residency
    dma_bytes: int              # modeled per-device candidate traffic, run
                                # (de-leaned levels carry _DELEAN_PENALTY)
    score_bytes: int            # comms + dma (lower wins; residency is
                                # a capacity constraint, not a cost)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Planner verdict: the chosen shape plus the full rejected field."""

    n_bands: int
    n_slabs: int
    chosen: MeshCandidate
    rejected: Tuple[MeshCandidate, ...]
    source: str = "planner"     # "planner" | "override"

    def as_attrs(self) -> dict:
        """Run-plan annotation payload (decision + rejected
        alternatives — the ISSUE's prologue-span requirement)."""
        return {
            "mesh_shape": [self.n_bands, self.n_slabs],
            "source": self.source,
            "chosen": self.chosen.as_dict(),
            "rejected": [c.as_dict() for c in self.rejected],
        }


def _factorizations(n_devices: int) -> List[Tuple[int, int]]:
    """All (bands, slabs) with bands * slabs == n_devices, bands
    ascending — the deterministic enumeration order ties break on."""
    return [
        (r, n_devices // r)
        for r in range(1, n_devices + 1)
        if n_devices % r == 0
    ]


def _level_shapes(a_shape, b_shape, cfg: SynthConfig, n_slabs: int):
    """(h, w, ha, wa, has_coarse) per level, finest first, with B rows
    padded to the runner's slab grain (synthesize_spatial's padding)."""
    levels = cfg.clamp_levels(tuple(a_shape), tuple(b_shape))
    h0, w0 = int(b_shape[0]), int(b_shape[1])
    ha0, wa0 = int(a_shape[0]), int(a_shape[1])
    grain = n_slabs * (2 ** (levels - 1)) * 2
    hb = h0 + ((-h0) % grain)
    out = []
    for lvl in range(levels):
        out.append((
            max(1, hb // 2 ** lvl),
            max(1, w0 // 2 ** lvl),
            max(1, ha0 // 2 ** lvl),
            max(1, wa0 // 2 ** lvl),
            lvl < levels - 1,
        ))
    return out


def _price(n_bands: int, n_slabs: int, a_shape, b_shape,
           cfg: SynthConfig,
           hbm_bytes: Optional[int] = None) -> MeshCandidate:
    from ..kernels.patchmatch_tile import (
        K_TOTAL,
        candidate_dma_bytes_per_fetch,
        plan_channels,
    )
    from .comms import (
        banded_spatial_level_collectives,
        sharded_a_band_merge_bytes,
    )
    from .spatial import slab_halo

    halo = slab_halo(cfg)
    # Channel planes per side, the level_eta_cost_units convention:
    # luminance synthesizes 1+1 planes, full color 3+3.
    n_src = n_flt = 1 if cfg.color_mode == "luminance" else 3
    kernel_levels = banded_levels = 0
    comms = residency = dma = 0
    for h, w, ha, wa, has_coarse in _level_shapes(
        a_shape, b_shape, cfg, n_slabs
    ):
        slab_h = h // n_slabs + 2 * halo
        plan = plan_channels(
            n_src, n_flt, cfg, has_coarse, slab_h, w, ha, wa,
        )
        eligible = plan is not None
        banded = eligible and n_bands > 1
        if eligible:
            kernel_levels += 1
        if banded:
            # Band ownership must survive the grain padding: a band
            # whose rows are ALL pad owns nothing and the runner
            # refuses (spatial.py's "use fewer bands" guard).
            a_grain = 2 * n_bands if has_coarse else n_bands
            ha_k = ha + ((-ha) % a_grain)
            rows_pb = ha_k // n_bands
            if (n_bands - 1) * rows_pb >= ha:
                return MeshCandidate(
                    n_bands, n_slabs, False,
                    f"band {n_bands - 1} of {n_bands} owns no real A "
                    f"row at level shape ha={ha}",
                    0, 0, 0, 0, 0, 0,
                )
            banded_levels += 1
        # Comms: the joint 2-D schedule, with a degenerate bands axis
        # when this level would not band (parallel/comms.py composes
        # exactly that way).
        sched = banded_spatial_level_collectives(
            cfg, ha, wa, h, w,
            (n_bands if banded else 1, n_slabs),
        )
        if n_slabs > 1:
            comms += sched["slabs"]["reslab_bytes"]
        if banded:
            merge = sharded_a_band_merge_bytes(cfg, slab_h, w)
            # 4 all-reduce legs per merge => bytes_per_merge / 4 is
            # the per-site plane payload.
            comms += (
                sched["bands"]["all_reduce_sites"]
                * merge["bytes_per_merge"] // 4
            )
        # Residency: slab-share-of-B' + (band-share when banded, full
        # when not) of the lean A table.  f32 slab planes; bf16 table.
        n_chan = (n_src + n_flt) * (2 if has_coarse else 1)
        slab_bytes = slab_h * w * 4 * _N_SLAB_ARRAYS
        table_bytes = ha * wa * n_chan * _TABLE_ITEMSIZE
        a_share = table_bytes // n_bands if banded else table_bytes
        # Kernel planes roughly double the A-side resident (planes +
        # table) — a modeled constant, not a measured one.
        residency = max(residency, slab_bytes + 2 * a_share)
        # Candidate traffic per device: every owned pixel fetches
        # K_TOTAL candidate windows per pm iteration per EM (the same
        # per-fetch byte model the DMA sentinel pins).  A de-leaned
        # level does the same candidate evaluation on the standard
        # path at _DELEAN_PENALTY x the lean bytes — this is where
        # kernel coverage enters the score, weighted by the pixels it
        # actually covers.
        moved, _useful = candidate_dma_bytes_per_fetch(n_chan, 8)
        per_cand = moved / 8.0
        if not eligible:
            per_cand *= _DELEAN_PENALTY
        dma += int(
            cfg.em_iters * cfg.pm_iters * K_TOTAL
            * (h * w / n_slabs) * per_cand
        )
    if n_bands > 1 and banded_levels == 0:
        return MeshCandidate(
            n_bands, n_slabs, False,
            "bands axis would never engage (no kernel-eligible level "
            "at this slab split) — pure device waste",
            kernel_levels, 0, 0, 0, 0, 0,
        )
    if hbm_bytes is not None and residency > hbm_bytes:
        # Residency is a CAPACITY constraint, not a cost addend:
        # traffic terms dwarf resident bytes, so folding residency
        # into the score could never force bands on — yet forcing
        # bands on when A outgrows a chip is the axis's whole reason
        # to exist.
        return MeshCandidate(
            n_bands, n_slabs, False,
            f"modeled per-device residency {residency} exceeds the "
            f"HBM budget {hbm_bytes}",
            kernel_levels, banded_levels, comms, residency, dma,
            comms + dma,
        )
    return MeshCandidate(
        n_bands, n_slabs, True, "", kernel_levels, banded_levels,
        comms, residency, dma, comms + dma,
    )


def plan_mesh_shape(n_devices: int, a_shape, b_shape,
                    cfg: Optional[SynthConfig] = None,
                    hbm_bytes: Optional[int] = None) -> MeshPlan:
    """Pick (n_bands, n_slabs) for `n_devices` and these shapes.

    `hbm_bytes` (optional) is the per-device HBM budget the residency
    model is held to — candidates whose modeled peak residency
    overflows it are infeasible, which is what forces bands on once A
    outgrows a chip.  Returns a `MeshPlan` whose `chosen`/`rejected`
    carry the full priced field; `as_attrs()` is the run-plan
    annotation payload.  Always succeeds: (1, n_devices) is feasible
    by construction absent an HBM cap (the 1-D runner's shape), and
    under a cap that nothing satisfies the minimum-residency candidate
    is chosen (the least-overflowing mesh, flagged by its reason)."""
    cfg = cfg or SynthConfig()
    cands = [
        _price(r, s, a_shape, b_shape, cfg, hbm_bytes)
        for r, s in _factorizations(int(n_devices))
    ]
    feasible = [c for c in cands if c.feasible]
    if feasible:
        # Feasibility -> modeled bytes (de-leaned levels already carry
        # their standard-path penalty inside dma_bytes); min() keeps
        # the FIRST minimum, and enumeration is bands-ascending, so
        # exact ties break toward fewer bands (the simpler mesh).
        best = min(feasible, key=lambda c: c.score_bytes)
    else:
        over = [c for c in cands if c.residency_bytes > 0]
        best = min(
            over or cands, key=lambda c: c.residency_bytes or 1 << 62
        )
    rejected = tuple(c for c in cands if c is not best)
    return MeshPlan(best.n_bands, best.n_slabs, best, rejected)


def override_plan(n_bands: int, n_slabs: int) -> MeshPlan:
    """A manual `--bands`/`--mesh-rows` choice, wrapped so the run
    plan records the override (and that nothing was rejected — the
    user decided)."""
    c = MeshCandidate(
        n_bands, n_slabs, True, "", -1, -1, 0, 0, 0, 0,
    )
    return MeshPlan(n_bands, n_slabs, c, (), source="override")
