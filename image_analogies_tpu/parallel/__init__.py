"""Multi-chip parallelism (SURVEY.md C15): mesh, batch, spatial."""
