"""Analytic ICI comms model for the four runners (VERDICT r5 task 6).

Multi-chip correctness is test-pinned (bit-identity on the 8-virtual-
device mesh), but nothing stated the per-EM-iteration collective
VOLUME as a function of (size, mesh) — the number that decides whether
the linear-scaling story survives on a real pod.  This module is that
statement, written as FUNCTIONS so a test can hold the compiled
artifacts to it: `tests/test_comms_model.py` lowers the actual sharded
level functions on the 8-virtual-device mesh and asserts the
collective-op counts in the emitted HLO match these formulas exactly.
ARCHITECTURE.md carries the prose form.

Conventions: counts are per TRACED level call (all EM iterations of
one level — the unit the runners compile); byte formulas give the
per-device payload of one collective (the ring/tree transfer
multiplier, 2(n-1)/n per all-reduce hop on a bidirectional ring, is a
topology property — multiply in when sizing a specific pod).

The four runners:

- **batch** (parallel/batch.py): pure data parallelism — frames shard
  over the mesh, the A side is replicated at placement time, and the
  per-EM step body contains ZERO collectives (asserted); the only
  cross-device traffic is the one-time input placement and the
  whole-stack luminance-remap stats in the prologue.

- **spatial** (parallel/spatial.py): per EM iteration (except the
  last of a level) the jitted re-slab exchanges slab BOUNDARY rows
  with mesh neighbors — collective-permutes, never all-gathers
  (asserted: the stitch/split pair must not re-materialize the global
  arrays).  `spatial_reslab_bytes` models the NECESSARY exchange (a
  lower bound): GSPMD's select-and-sum partitioning of the stitch
  additionally emits masked-combine all-reduces (observed on this
  toolchain, 2026-08-04) whose volume is partitioner-chosen — the
  test pins the permute/no-all-gather invariant and leaves the
  all-reduce mix to the compiler.

- **sharded-A** (parallel/sharded_a.py): the bands axis carries two
  collective families, counted by `sharded_a_allreduce_count`:
  the per-pm-iteration field merge (`_band_merge`: 2 pmin + 2 psum =
  4 all-reduces over the blocked state planes) and the masked-gather
  distance merge (`_sharded_dist`: 1 pmin over the (K, N) distance
  batch per evaluation site — entry, exact-metric merge, and every
  polish candidate).

- **2-D bands x slabs** (parallel/spatial.py `_banded_lean_step_fn`):
  the sharded-A terms on the bands axis (per single EM step —
  `sharded_a_allreduce_count` with em_iters=1 semantics via
  `per_em=True`) plus the spatial re-slab on the slabs axis; the two
  axes carry disjoint traffic.  On 2-D meshes the re-slab is the
  MANUAL ppermute halo exchange (round-17; `_reslab_fn`'s 2-D branch —
  GSPMD's select-and-sum stitch partitioning double-counts the
  bands-replicated contributions on this jax), so the slabs axis is
  exactly countable: `spatial_reslab_collectives` sites per re-slab,
  pinned against the compiled HLO, and
  `banded_spatial_level_collectives` composes the per-axis schedule
  for one whole lean level (the run-plan/prologue term and the
  sentinel ledger's expectation both draw from it).
"""

from __future__ import annotations

from typing import Dict

from ..config import SynthConfig


def batch_em_collectives() -> int:
    """Collective ops inside one batched EM step body: none — frames
    are independent and the A side is already resident everywhere."""
    return 0


def spatial_reslab_bytes(
    w: int, halo: int, n_arrays: int, itemsize: int = 4
) -> int:
    """Per-device payload of ONE re-slab (the between-EM-iteration
    stitch+re-split): each slab refreshes `halo` rows of context on
    each side from its two neighbors, for each of the `n_arrays`
    re-haloed state arrays (standard path: stacked nnf counts 2
    int32 planes + bp; lean path: py, px, bp) — boundary rows only,
    independent of slab height (the claim the collective-permute
    assertion pins)."""
    return 2 * halo * w * n_arrays * itemsize


def _polish_dist_calls(cfg: SynthConfig, ha: int, wa: int,
                      final: bool) -> int:
    """Distance-evaluation sites of one EM step's polish under the
    sequential cascade (the sharded runners' only polish — stream mode
    leaves custom dist_fns on the cascade): the entry re-evaluation
    plus 8 propagation + n_random probes per sweep; zero on non-final
    iterations under pm_polish_final_only."""
    from ..models.patchmatch import _polish_schedule_for

    override = None if (final or not cfg.pm_polish_final_only) else 0
    iters, n_random = _polish_schedule_for(cfg, ha, wa, override)
    if iters == 0:
        return 0
    return 1 + iters * (8 + n_random)


def sharded_a_allreduce_count(
    cfg: SynthConfig, ha: int, wa: int, *, per_em: bool = False
) -> int:
    """stablehlo.all_reduce ops traced into one band-sharded level
    call (`_sharded_level_fn`), or one EM step (`per_em=True` — the
    2-D runner's `_banded_lean_step_fn` unit).

    Per EM iteration:
      4 * pm_iters   `_band_merge` after every kernel sweep
                     (pmin dist + pmin winner + psum oy + psum ox)
      + 2            entry dist0 + exact-metric merge d_k
                     (1 `_sharded_dist` pmin each)
      + polish       `_polish_dist_calls` pmins
      + 8 if kappa>0 coherence adoption (2 sweeps x 4 neighbors) —
                     ONLY on EM iterations whose polish is engaged:
                     `tile_patchmatch_lean` returns before the
                     Ashikhmin pass when that EM's polish_iters is 0
                     (non-final iterations under pm_polish_final_only),
                     so a mid-EM contributes no coherence collectives.
                     (Round-9 fix — the model previously booked the 8
                     on every EM; the run sentinel's expected-vs-
                     observed ledger is what surfaced it.)
    """
    from ..models.patchmatch import _pm_iters_for

    pm_iters = _pm_iters_for(cfg, ha, wa)
    ems = 1 if per_em else cfg.em_iters
    total = 0
    for em in range(ems):
        final = per_em or em == cfg.em_iters - 1
        polish = _polish_dist_calls(cfg, ha, wa, final)
        total += 4 * pm_iters + 2
        total += polish
        if cfg.kappa > 0.0 and polish > 0:
            total += 2 * 4
    return total


def sharded_a_allreduce_sites(
    cfg: SynthConfig, ha: int, wa: int, *, per_em: bool = False,
    polish_iters=None,
) -> int:
    """Traced collective call SITES of one band-sharded level call (or
    one `_banded_lean_step_fn` EM step with that runner's explicit
    `polish_iters` override) — the unit a Python-side trace-time
    counter observes (telemetry/metrics.py's jit caveat), and the
    expected side of the run sentinel's comms assertion.

    Identical to `sharded_a_allreduce_count` except the polish term:
    the polish's sweep loop is a `jax.lax.scan` whose body traces
    ONCE, so an engaged polish contributes `1 + (8 + n_random)` sites
    regardless of its iteration count, where the runtime count is
    `1 + iters * (8 + n_random)`.  Every other term is a Python-level
    loop (pm iterations, coherence sweeps), where sites == runtime
    collectives.  The two formulas coincide at pm_polish_iters == 1 —
    which is why the HLO-count test and a site ledger can both be
    exact."""
    from ..models.patchmatch import _pm_iters_for, _polish_schedule_for

    pm_iters = _pm_iters_for(cfg, ha, wa)
    ems = 1 if per_em else cfg.em_iters
    total = 0
    for em in range(ems):
        if per_em:
            iters, n_random = _polish_schedule_for(
                cfg, ha, wa, polish_iters
            )
        else:
            final = em == cfg.em_iters - 1
            override = (
                None if (final or not cfg.pm_polish_final_only) else 0
            )
            iters, n_random = _polish_schedule_for(cfg, ha, wa, override)
        total += 4 * pm_iters + 2
        if iters > 0:
            total += 1 + 8 + n_random  # scan body: one trace per sweep set
            if cfg.kappa > 0.0:
                total += 2 * 4  # Ashikhmin pass, Python-unrolled
    return total


def spatial_reslab_collectives(n_arrays: int) -> int:
    """Collective-permute SITES traced into one 2-D re-slab call
    (`_reslab_fn`'s manual halo-exchange branch): each slab-stacked
    array trades `halo` boundary rows with both mesh neighbors — one
    `ppermute` site per direction per array.  Sites == compiled
    collective-permute ops (the exchange is Python-unrolled over
    arrays, no scan), which is what lets test_comms_model.py pin the
    count against the HLO exactly."""
    return 2 * n_arrays


def banded_spatial_level_collectives(
    cfg: SynthConfig, ha: int, wa: int, h: int, w: int,
    mesh_shape,
) -> Dict[str, Dict[str, int]]:
    """Joint 2-D comms schedule for ONE lean banded spatial level on a
    (n_bands, n_slabs) mesh: the per-axis collective counts and the
    slabs-axis payload bytes, composed from the two already-pinned 1-D
    models.  The two axes carry disjoint traffic:

    - **bands**: `sharded_a_allreduce_sites(per_em=True)` per EM
      iteration, with the polish schedule the spatial runner actually
      passes (`polish_iters=0` on non-final iterations under
      pm_polish_final_only) — the same expression
      `_banded_lean_step_fn` books as the sentinel ledger's expected
      side, so plan, ledger, and HLO pin cannot drift apart.
    - **slabs**: one manual re-slab between consecutive EM iterations
      (`em_iters - 1` per level), `spatial_reslab_collectives(3)`
      permute sites each (lean state: py, px, bp) moving
      `spatial_reslab_bytes` of boundary rows.

    With one band or one slab the corresponding axis entry is zero —
    the single-axis models apply directly (this function is the 2-D
    composition, not a replacement)."""
    n_bands, n_slabs = mesh_shape
    from .spatial import slab_halo

    halo = slab_halo(cfg)
    bands_sites = 0
    if n_bands > 1:
        for em in range(cfg.em_iters):
            final = em == cfg.em_iters - 1
            override = (
                None if (final or not cfg.pm_polish_final_only) else 0
            )
            bands_sites += sharded_a_allreduce_sites(
                cfg, ha, wa, per_em=True, polish_iters=override
            )
    # The manual ppermute re-slab runs whenever the MESH is 2-D (its
    # axis count, not the band count, selects `_reslab_fn`'s branch).
    n_reslabs = max(cfg.em_iters - 1, 0)
    permutes = n_reslabs * spatial_reslab_collectives(3)
    return {
        "bands": {"all_reduce_sites": bands_sites},
        "slabs": {
            "reslabs": n_reslabs,
            "collective_permutes": permutes,
            "reslab_bytes": n_reslabs * spatial_reslab_bytes(w, halo, 3),
        },
    }


def sharded_a_band_merge_bytes(
    cfg: SynthConfig, h: int, w: int
) -> Dict[str, int]:
    """Per-device payload of ONE `_band_merge` (4 all-reduces over the
    halo-blocked state planes).  Blocked planes are
    (n_ty*thp, n_tx*128); one f32/int32 plane each for the pmin-d,
    pmin-winner, psum-oy, psum-ox legs."""
    from ..kernels.patchmatch_tile import channel_specs, tile_geometry

    specs = channel_specs(1, 1, cfg, False)
    geom = tile_geometry(h, w, specs)
    elems = geom.n_ty * geom.thp * geom.n_tx * 128
    return {
        "elems_per_plane": elems,
        "bytes_per_merge": 4 * elems * 4,
    }
