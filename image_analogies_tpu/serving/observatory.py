"""Multi-replica scrape + aggregation — the fleet-facing half of the
round-19 observatory (in-daemon half: telemetry/timeseries.py +
telemetry/anomaly.py).

One daemon answers `/metrics.json`, `/slo` and `/obs/window` for
itself; a fleet of N replicas has N disjoint registries and NO
process that can answer "what is the fleet's p99" or "is the error
budget burning ACROSS replicas".  This module is that process:
`aggregate(targets)` scrapes every replica, merges the serialized
registries (sum counters, pool histogram cells bucket-by-bucket —
gauges are deliberately per-replica: summing queue depths across
replicas is meaningful, summing overhead fractions is not, so gauges
stay in the per-replica sections and never merge), grades the
round-15 `Objective`s over the POOLED duration family, and returns
the OBS record `tools/check_obs.py` validates.

The arithmetic contract (acceptance-tested end to end): fleet burn
rates are computed by `evaluate_slo` over the merged histogram cells
— POOLED, never averaged.  Averaging per-replica burn rates weights a
10-request replica equally with a 10000-request one; pooling the
buckets weights every request once.  Because bucket counts are
integers and the merge is plain addition, an independent re-merge of
the same per-replica payloads reproduces the fleet numbers BIT-EQUAL,
which is exactly what check_obs re-derives.

`ia-synth obs --targets host:p1,host:p2` drives `aggregate` +
`render_dashboard`; `tools/serve_load.py --obs-out` drives it against
two live in-process replicas under a load burst and measures the
observatory's request-path overhead into the committed artifact.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry.slo import (
    ROUTE_DURATION_METRIC,
    ROUTE_OBJECTIVES,
    REQUEST_DURATION_METRIC,
    evaluate_slo,
)

OBS_SCHEMA_VERSION = 1
OBS_ROUND = 19

# Families the OBS record keeps per replica: everything the fleet
# merge and the checker's re-derivation read, nothing else (a full
# registry dump per replica would swamp the artifact with engine
# counters that have per-replica meaning only).
KEEP_PREFIXES = ("ia_serve_", "ia_request_", "ia_slo_", "ia_anomaly_",
                 "ia_excache_", "ia_observatory_", "ia_route_")


def parse_targets(spec: str) -> List[str]:
    """"host:p1,host:p2" (or full http:// URLs) -> base URLs.

    Round 21: the spec may instead name the fleet router's replica-
    discovery file (written and kept current by `ia-synth route
    --discovery-out`) — either as a bare path that exists on disk or
    explicitly as `@PATH`.  Its `targets` list (replicas + the router
    itself) becomes the scrape set, so fleet scrapes track membership
    changes (adds, drains, rolling restarts) without a hand-maintained
    target list."""
    spec = str(spec)
    path = None
    if spec.startswith("@"):
        path = spec[1:]
    elif "," not in spec and os.path.isfile(spec):
        path = spec
    if path is not None:
        from .router import load_discovery

        try:
            doc = load_discovery(path)
        except (OSError, ValueError) as e:
            raise ValueError(f"discovery file {path}: {e}")
        targets = [str(t).rstrip("/") for t in doc.get("targets") or []]
        if not targets:
            raise ValueError(f"discovery file {path}: no targets")
        return targets
    out = []
    for part in spec.split(","):
        part = part.strip().rstrip("/")
        if not part:
            continue
        if not part.startswith(("http://", "https://")):
            part = f"http://{part}"
        out.append(part)
    if not out:
        raise ValueError(f"no targets in {spec!r}")
    return out


def _get_json(url: str, timeout: float) -> Any:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def scrape_replica(base_url: str, span_s: Optional[float] = None,
                   timeout: float = 10.0) -> Dict[str, Any]:
    """One replica's observatory surface: the JSON registry
    exposition, the SLO report (anomalies ride inside it when the
    replica's detector is on), and the windowed view.  A replica that
    answers /metrics.json but lacks /obs/window (an older daemon)
    still aggregates — `window` is None, stated per replica."""
    base_url = base_url.rstrip("/")
    rec: Dict[str, Any] = {"url": base_url, "error": None}
    try:
        metrics = _get_json(f"{base_url}/metrics.json", timeout)
        rec["metrics"] = {
            name: fam for name, fam in metrics.items()
            if name.startswith(KEEP_PREFIXES)
        }
        rec["slo"] = _get_json(f"{base_url}/slo", timeout)
        try:
            q = f"?span={span_s:g}" if span_s is not None else ""
            rec["window"] = _get_json(
                f"{base_url}/obs/window{q}", timeout
            )
        except (urllib.error.URLError, OSError, ValueError):
            rec["window"] = None
    except (urllib.error.URLError, OSError, ValueError) as e:
        rec["error"] = f"{type(e).__name__}: {e}"
        rec.setdefault("metrics", None)
        rec.setdefault("slo", None)
        rec.setdefault("window", None)
    return rec


def merge_registries(metrics_list: Sequence[Dict[str, Any]]
                     ) -> Dict[str, Any]:
    """Pool serialized registries (MetricsRegistry.to_dict form):
    counters sum per label set, histogram cells sum count/sum and
    bucket-by-bucket (replicas share bucket layouts per family — same
    binary — so bucket union is exact, and a label set present on one
    replica only carries through unchanged).  Gauges are SKIPPED:
    last-write-wins values have no fleet-sum semantics; read them in
    the per-replica sections."""
    merged: Dict[str, Any] = {}
    for metrics in metrics_list:
        for name, fam in (metrics or {}).items():
            kind = fam.get("kind")
            if kind == "gauge":
                continue
            values = fam.get("values") or {}
            tgt = merged.setdefault(name, {
                "kind": kind, "help": fam.get("help", ""), "values": {},
            })
            if tgt["kind"] != kind:
                raise ValueError(
                    f"metric {name!r}: kind mismatch across replicas "
                    f"({tgt['kind']} vs {kind})"
                )
            for label_str, cell in values.items():
                if kind == "counter":
                    tgt["values"][label_str] = (
                        tgt["values"].get(label_str, 0) + cell
                    )
                elif kind == "histogram":
                    cur = tgt["values"].get(label_str)
                    if cur is None:
                        tgt["values"][label_str] = {
                            "count": int(cell.get("count", 0)),
                            "sum": float(cell.get("sum", 0.0)),
                            "buckets": {
                                b: int(c) for b, c in
                                (cell.get("buckets") or {}).items()
                            },
                        }
                    else:
                        cur["count"] += int(cell.get("count", 0))
                        cur["sum"] += float(cell.get("sum", 0.0))
                        for b, c in (cell.get("buckets") or {}).items():
                            cur["buckets"][b] = (
                                cur["buckets"].get(b, 0) + int(c)
                            )
    return merged


def fleet_slo(merged: Dict[str, Any]) -> Dict[str, Any]:
    """The round-15 objectives graded over the POOLED duration family
    — the `Objective` semantics applied fleet-wide, so burn rates are
    request-weighted across replicas, never replica-averaged."""
    return evaluate_slo(merged)


def fleet_route_slo(merged: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Round 22: the router hop graded with the SAME engine over the
    pooled `ia_route_duration_ms` family — router and replica burn
    rates sit side by side in one report WITHOUT double-counting: a
    request contributes to `ia_request_duration_ms` on the replica
    that served it and to `ia_route_duration_ms` at the router that
    proxied it, and the two families are graded separately (router
    `unavailable`/`shed` outcomes are availability-excluded by the
    round-16 outcome taxonomy, same as on replicas).  None when no
    router was in the scrape set — absent, never imputed."""
    fam = merged.get(ROUTE_DURATION_METRIC)
    if not fam or not (fam.get("values") or {}):
        return None
    return evaluate_slo(merged, ROUTE_OBJECTIVES,
                        metric=ROUTE_DURATION_METRIC)


def aggregate(targets: Sequence[str], span_s: Optional[float] = None,
              timeout: float = 10.0) -> Dict[str, Any]:
    """Scrape every target and assemble the OBS record.

    Round 22 honesty rule: a target that is in the scrape set but
    unreachable mid-scrape DEGRADES the fleet verdict — its traffic is
    missing from the pooled families, so the fleet numbers are a
    floor, not the truth.  The record says so (`degraded` +
    `warnings`) instead of silently grading the survivors."""
    replicas = [scrape_replica(t, span_s, timeout) for t in targets]
    live = [r for r in replicas if r["error"] is None]
    unreachable = [r for r in replicas if r["error"] is not None]
    merged = merge_registries([r["metrics"] for r in live])
    fleet: Dict[str, Any] = {
        "replicas_total": len(replicas),
        "replicas_live": len(live),
        "degraded": bool(unreachable),
        "warnings": [
            f"target {r['url']} unreachable mid-scrape "
            f"({r['error']}); pooled numbers exclude its traffic"
            for r in unreachable
        ],
        "slo": fleet_slo(merged),
        "route_slo": fleet_route_slo(merged),
        "merged_metrics": merged,
        "anomalies_firing": sorted({
            f"{r['url']}:{w}"
            for r in live
            for w in ((r["slo"] or {}).get("anomalies") or {})
            .get("firing", [])
        }),
    }
    return {
        "schema_version": OBS_SCHEMA_VERSION,
        "kind": "obs",
        "round": OBS_ROUND,
        "targets": list(targets),
        "span_s": span_s,
        "replicas": replicas,
        "fleet": fleet,
    }


# ------------------------------------------------------------ rendering
def _fmt_ms(v) -> str:
    return "-" if v is None else f"{float(v):.1f}"


def render_dashboard(record: Dict[str, Any]) -> str:
    """Terminal dashboard over one OBS record: per-replica health
    lines, the pooled fleet objectives, and any firing anomaly."""
    lines: List[str] = []
    fleet = record.get("fleet") or {}
    lines.append(
        f"serving observatory — {fleet.get('replicas_live', 0)}/"
        f"{fleet.get('replicas_total', 0)} replicas live"
        + (f", window {record['span_s']:g}s"
           if record.get("span_s") else "")
    )
    lines.append("")
    lines.append(f"{'replica':<28} {'verdict':<9} {'p50ms':>8} "
                 f"{'p99ms':>8} {'req/s':>8} {'anomaly':<10}")
    for rep in record.get("replicas") or []:
        url = rep["url"]
        if rep.get("error"):
            lines.append(f"{url:<28} {'DOWN':<9} {'-':>8} {'-':>8} "
                         f"{'-':>8} {rep['error']}")
            continue
        slo = rep.get("slo") or {}
        lat = next(
            (o for o in slo.get("objectives", [])
             if o.get("kind") == "latency"), {},
        )
        window = rep.get("window") or {}
        rate = None
        cells = (window.get("histograms") or {}).get(
            REQUEST_DURATION_METRIC
        ) or {}
        if window.get("status") == "ok" and cells:
            rate = sum(
                c.get("rate_per_s") or 0.0 for c in cells.values()
            )
        anomalies = (slo.get("anomalies") or {})
        lines.append(
            f"{url:<28} {slo.get('verdict', '-'):<9} "
            f"{_fmt_ms(lat.get('observed_p50_ms')):>8} "
            f"{_fmt_ms(lat.get('observed_p99_ms')):>8} "
            f"{(f'{rate:.2f}' if rate is not None else '-'):>8} "
            f"{anomalies.get('verdict', '-'):<10}"
        )
    lines.append("")
    lines.append("fleet objectives (pooled, request-weighted):")
    for o in (fleet.get("slo") or {}).get("objectives", []):
        burn = o.get("burn_rate")
        lines.append(
            f"  {o['name']:<24} {o['status']:<10} "
            f"burn={'-' if burn is None else f'{burn:.4f}'} "
            f"bad={o.get('bad_count', 0)}/{o.get('denominator', 0)}"
            + (f" p99={_fmt_ms(o.get('observed_p99_ms'))}ms"
               if o.get("kind") == "latency" else "")
        )
    route = fleet.get("route_slo")
    if route:
        lines.append("")
        lines.append("router hop objectives (pooled):")
        for o in route.get("objectives", []):
            burn = o.get("burn_rate")
            lines.append(
                f"  {o['name']:<24} {o['status']:<10} "
                f"burn={'-' if burn is None else f'{burn:.4f}'} "
                f"bad={o.get('bad_count', 0)}/{o.get('denominator', 0)}"
                + (f" p99={_fmt_ms(o.get('observed_p99_ms'))}ms"
                   if o.get("kind") == "latency" else "")
            )
    firing = fleet.get("anomalies_firing") or []
    lines.append("")
    lines.append(
        "anomalies firing: " + (", ".join(firing) if firing else "none")
    )
    for warn in fleet.get("warnings") or []:
        lines.append(f"WARNING (fleet degraded): {warn}")
    return "\n".join(lines) + "\n"


def write_obs(record: Dict[str, Any], path: str) -> None:
    from ..utils.io import atomic_write_json

    atomic_write_json(path, record)
