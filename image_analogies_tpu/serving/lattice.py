"""Shape-lattice admission for the serving daemon (round 20).

`exec_key` binds the exact padded frame shape (serving/excache.py), so
real traffic with arbitrary image sizes fragments the executable cache
into unbounded cardinality and every never-seen size pays a
multi-second XLA compile.  This module bounds the key space by the
LATTICE instead of by traffic: incoming frames are canonicalized onto
a small geometric grid of bucket shapes — edge-padded up to the
smallest bucket that contains them at ingest, cropped back to the
client's shape at demux (the batch runner's mesh-padding trim idiom
from round 12, applied per request) — so every client size inside the
lattice's bounds lands on one of `len(rungs)^2 x len(channels)`
executables, all of which warmup precompiles before the port announce.

Geometry: one rung ladder shared by both axes.  Rungs start at
`min_side` and grow by `growth` (ceil), with the top rung clamped to
exactly `max_side`; `bucket_for(h, w)` rounds each axis up to its
smallest rung independently, so a 100x30 frame pays a 100-class rung
on H and a 30-class rung on W rather than a square superset.

Bypass rule (stated, not hidden): a frame with EITHER axis above the
top rung leaves the lattice entirely and takes the round-13 exact-key
path — an honest cache miss with its own compile, booked under the
`path="bypass"` admission counter, never a silent crop or a refused
request.  Frames below `min_side` (down to 1x1) pad UP to the bottom
rung: the lattice's floor is also the daemon's degenerate-frame
armor.  Session traffic (video) bypasses the lattice by design — a
stream's carried NNF state is sized to its true frame shape and its
executables are keyed at the batch-1 grain.

Semantics contract (the honest version): synthesis is
shape-dependent — PatchMatch propagation is global and the PRNG
streams are shape-keyed — so for an off-bucket frame the engine runs
on the PADDED canvas and the client receives the crop of that padded
synthesis.  That output is bit-identical to what the unbucketed
daemon would serve for the same frame edge-padded client-side
(`crop(serve(pad(F))) == lattice(F)`, the check_lattice.py sentinel),
and deterministic/replay-safe (journal replay re-buckets the raw
manifest under the same lattice config and reproduces the bytes) —
but it is NOT the pixel-exact answer of an exact-shape run.  Frames
exactly ON a bucket shape are untouched and bit-identical to the
lattice-off path.

Bucket choice is a priced trade, not a default: coarser growth means
fewer executables (less warmup compile, smaller cache residency) but
more pad waste on every request; finer growth inverts it.
`plan_lattice` makes the trade a planner-style recorded decision
(parallel/plan2d.py's idiom): enumerate candidate growth factors,
price each as `n_buckets x compile-unit + expected-waste x waste
penalty`, choose deterministically, and record the chosen candidate
plus every rejected alternative so `/serving` and the LATTICE
artifact show why THIS grid and what it beat.  An explicit
`--lattice MIN:MAX:GROWTH` skips the planner (source="override",
nothing rejected — the operator decided).

All arithmetic is host-side integers on shapes; the lattice never
touches a device.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

# The warmup manifest's own floor (excache.load_warmup_manifest):
# a lattice rung below it could not be precompiled through the
# manifest path, so the lattice refuses to exist there.
MIN_RUNG = 8

# Growth factors the planner prices when --lattice gives no explicit
# one.  Deterministic enumeration order; ties break toward the FIRST
# (coarsest) candidate.
PLAN_GROWTHS = (2.0, 1.5, 1.3, 1.2)

# Score model constants (plan2d's _DELEAN_PENALTY discipline: modeled,
# not measured — their job is ordinal).  Each bucket is one warmup
# compile + one resident executable set: 1 unit.  Expected pad waste
# multiplies EVERY request's compute for the lattice's whole lifetime,
# so a unit of waste fraction is priced at many compile-units.
_COMPILE_UNIT = 1.0
_WASTE_PENALTY = 40.0


@dataclasses.dataclass(frozen=True)
class LatticeConfig:
    """The lattice's declared bounds.  `growth` None means the planner
    picks from PLAN_GROWTHS; an explicit value is an override."""

    min_side: int = 32
    max_side: int = 512
    growth: Optional[float] = None
    channels: Tuple[int, ...] = (3,)

    def __post_init__(self):
        if self.min_side < MIN_RUNG:
            raise ValueError(
                f"lattice min_side {self.min_side} < {MIN_RUNG} (the "
                "warmup manifest's shape floor)"
            )
        if self.max_side < self.min_side:
            raise ValueError(
                f"lattice max_side {self.max_side} < min_side "
                f"{self.min_side}"
            )
        if self.growth is not None and not 1.0 < self.growth <= 8.0:
            raise ValueError(
                f"lattice growth {self.growth} not in (1.0, 8.0]"
            )
        if not self.channels or any(
            c not in (1, 3) for c in self.channels
        ):
            raise ValueError(
                f"lattice channels {self.channels!r} must be a "
                "non-empty subset of (1, 3)"
            )


def parse_lattice_spec(spec: Optional[str]) -> Optional[LatticeConfig]:
    """`--lattice` value -> LatticeConfig (None = lattice off).

    Accepted forms:
      off | none | (empty)   lattice disabled
      on | default           default bounds, planner-chosen growth
      MIN:MAX                explicit bounds, planner-chosen growth
      MIN:MAX:GROWTH         fully explicit (planner skipped)
    """
    if spec is None:
        return None
    s = spec.strip().lower()
    if s in ("", "off", "none", "0", "false"):
        return None
    if s in ("on", "default", "auto"):
        return LatticeConfig()
    parts = s.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"--lattice {spec!r}: expected off|on|MIN:MAX|"
            "MIN:MAX:GROWTH"
        )
    try:
        min_side, max_side = int(parts[0]), int(parts[1])
        growth = float(parts[2]) if len(parts) == 3 else None
    except ValueError:
        raise ValueError(
            f"--lattice {spec!r}: MIN/MAX must be integers, GROWTH a "
            "float"
        ) from None
    return LatticeConfig(
        min_side=min_side, max_side=max_side, growth=growth
    )


def _rungs(min_side: int, max_side: int,
           growth: float) -> Tuple[int, ...]:
    """The geometric ladder, bottom rung `min_side`, each subsequent
    rung ceil(prev x growth) (at least +1 so the ladder always
    climbs), top rung clamped to exactly `max_side`."""
    out = [int(min_side)]
    r = int(min_side)
    while r < max_side:
        r = max(r + 1, int(math.ceil(r * growth)))
        out.append(min(r, int(max_side)))
    return tuple(dict.fromkeys(out))


class ShapeLattice:
    """The admission grid: a resolved rung ladder + bucket lookup."""

    def __init__(self, config: LatticeConfig,
                 growth: Optional[float] = None):
        g = growth if growth is not None else config.growth
        if g is None:
            raise ValueError(
                "ShapeLattice needs a resolved growth (run "
                "plan_lattice, or give LatticeConfig an explicit one)"
            )
        self.config = config
        self.growth = float(g)
        self.rungs: Tuple[int, ...] = _rungs(
            config.min_side, config.max_side, self.growth
        )

    @property
    def top(self) -> int:
        return self.rungs[-1]

    @property
    def size(self) -> int:
        """The exec-key cardinality bound the lattice guarantees for
        in-bounds sessionless traffic."""
        return len(self.rungs) ** 2 * len(self.config.channels)

    def bucket_for(self, h: int, w: int) -> Optional[Tuple[int, int]]:
        """Smallest (bh, bw) rung pair containing (h, w), each axis
        independently; None when either axis exceeds the top rung
        (the bypass verdict — exact-key path)."""
        if h > self.top or w > self.top:
            return None
        bh = next(r for r in self.rungs if r >= h)
        bw = next(r for r in self.rungs if r >= w)
        return bh, bw

    @staticmethod
    def waste_frac(h: int, w: int, bh: int, bw: int) -> float:
        """Fraction of the bucket canvas that is pad, for this frame:
        the per-request price of admission."""
        return 1.0 - (h * w) / float(bh * bw)

    def shapes(self) -> List[Dict[str, int]]:
        """Every bucket as a warmup-manifest entry — the full grid
        (rungs^2 per channel count), which IS the set warmup
        precompiles so a fresh replica is warm for all of them before
        the port announce."""
        return [
            {"height": bh, "width": bw, "channels": c}
            for c in self.config.channels
            for bh in self.rungs
            for bw in self.rungs
        ]

    def snapshot(self) -> Dict[str, Any]:
        return {
            "min_side": self.config.min_side,
            "max_side": self.config.max_side,
            "growth": self.growth,
            "rungs": list(self.rungs),
            "buckets": self.size,
            "channels": list(self.config.channels),
        }


@dataclasses.dataclass(frozen=True)
class LatticeCandidate:
    """One growth factor, priced."""

    growth: float
    rungs: Tuple[int, ...]
    buckets: int               # executables the grid costs (per full grid)
    worst_waste_frac: float    # worst in-bounds single-request pad waste
    expected_waste_frac: float  # uniform-size-mix expected pad waste
    score: float               # buckets x compile + waste x penalty (lower wins)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["rungs"] = list(self.rungs)
        return d


@dataclasses.dataclass(frozen=True)
class LatticePlan:
    """Planner verdict: chosen grid + the full rejected field (the
    plan2d recorded-decision idiom, applied to bucket geometry)."""

    lattice: ShapeLattice
    chosen: LatticeCandidate
    rejected: Tuple[LatticeCandidate, ...]
    source: str = "planner"    # "planner" | "override"

    def as_dict(self) -> dict:
        return {
            "source": self.source,
            "lattice": self.lattice.snapshot(),
            "chosen": self.chosen.as_dict(),
            "rejected": [c.as_dict() for c in self.rejected],
            "score_model": {
                "compile_unit": _COMPILE_UNIT,
                "waste_penalty": _WASTE_PENALTY,
            },
        }


def _price(config: LatticeConfig, growth: float) -> LatticeCandidate:
    rungs = _rungs(config.min_side, config.max_side, growth)
    buckets = len(rungs) ** 2 * len(config.channels)
    # Per-axis fill for a frame landing in (r_{k-1}, r_k]: h/r_k.
    # Worst case h = r_{k-1}+1; uniform-mix expectation is the mean of
    # the gap, (r_{k-1}+1+r_k)/2 / r_k.  The below-min region (frames
    # under the bottom rung) is excluded — its waste is set by
    # min_side, identical across growth candidates, so it cannot order
    # them.  A single-rung ladder has no inter-rung gap: fill 1.0.
    worst_fill = 1.0
    mean_fill = 1.0
    if len(rungs) > 1:
        worst_fill = min(
            (lo + 1) / float(hi)
            for lo, hi in zip(rungs, rungs[1:])
        )
        mean_fill = min(
            (lo + 1 + hi) / (2.0 * hi)
            for lo, hi in zip(rungs, rungs[1:])
        )
    worst_waste = 1.0 - worst_fill ** 2
    expected_waste = 1.0 - mean_fill ** 2
    score = buckets * _COMPILE_UNIT + expected_waste * _WASTE_PENALTY
    return LatticeCandidate(
        growth=growth, rungs=rungs, buckets=buckets,
        worst_waste_frac=round(worst_waste, 4),
        expected_waste_frac=round(expected_waste, 4),
        score=round(score, 3),
    )


def plan_lattice(config: LatticeConfig) -> LatticePlan:
    """Resolve a LatticeConfig into a priced, recorded grid choice.

    An explicit `growth` is an override: priced (so the artifact still
    shows its waste/bucket numbers) but never second-guessed, with
    nothing rejected.  Otherwise every PLAN_GROWTHS candidate is
    priced and the lowest score wins (first minimum — enumeration is
    coarsest-first, so exact ties break toward fewer executables)."""
    if config.growth is not None:
        chosen = _price(config, config.growth)
        return LatticePlan(
            ShapeLattice(config), chosen, (), source="override"
        )
    cands = [_price(config, g) for g in PLAN_GROWTHS]
    best = min(cands, key=lambda c: c.score)
    rejected = tuple(c for c in cands if c is not best)
    return LatticePlan(
        ShapeLattice(config, growth=best.growth), best, rejected
    )
