"""Compiled-executable cache — the serving tier's accounting layer
over the engine's jit caches (round 13 tentpole, with
serving/queueing.py and serving/daemon.py).

The engine already caches compiled executables process-wide
(`parallel/batch._batch_prologue_fn_cached` / `_batch_level_fn_cached`
and friends are `functools.lru_cache`s keyed on (cfg, level, mesh)),
so a repeat-shape dispatch skips the ~140 ms prologue compile
automatically.  What serving needs on top is the part functools cannot
give:

  - an ADMISSION-VISIBLE key: one record per (pyramid shape, config
    fingerprint, matcher, compression mode) so the daemon can answer
    "will this request compile or reuse?" BEFORE dispatching, label
    the request's span `cache-hit` vs `compiled`, and expose
    hit/miss/evict counters a scraper can watch;
  - a WARMUP path: a manifest of expected shapes compiled at daemon
    start, so the first paying request of each shape is a hit;
  - honest EVICTION: `functools.lru_cache` offers no per-key eviction,
    so capacity eviction here is EPOCH-grained — evicting one entry
    calls `kernels.patchmatch_tile.clear_compiled_level_caches()`
    (the mode-flip setters' invalidation hook, which drops every
    cached level/prologue/step function across all four runners) and
    demotes every other resident entry to cold.  The next use of a
    demoted key is counted (and priced) as a miss.  Capacity should
    therefore be sized so eviction is rare (default 8 resident
    shapes); the counters make an undersized cache visible as an
    eviction rate, not a silent recompile storm.

The cache key deliberately matches the jit keys' own identity: the
config fingerprint hashes `models.analogy._strip_noncompute(cfg)` (the
same stripping the jit caches apply, so two configs differing only in
`save_level_artifacts` share one executable AND one cache entry), and
the compression mode captures the process-wide kernel knobs
(`IA_CAND_DTYPE` / `IA_CAND_PRUNE` / packed layout) that shape traced
graphs without living in the config.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

WARMUP_SCHEMA_VERSION = 1

ExecKey = Tuple[tuple, str, str, str]


def config_fingerprint(cfg) -> str:
    """Stable digest of the COMPUTE-shaping config fields — the same
    identity the jit caches key on (`_strip_noncompute` removes the
    host-side checkpoint path), so the serving cache can never split or
    alias entries the engine's own caches share."""
    import dataclasses

    from ..models.analogy import _strip_noncompute

    return hashlib.sha1(
        repr(dataclasses.astuple(_strip_noncompute(cfg))).encode()
    ).hexdigest()[:12]


def compression_mode() -> str:
    """The process-wide kernel-compression knobs as one label: these
    are module globals, not config fields (the `_POLISH_MODE`
    rationale), but they shape every traced graph — a mode flip (a
    supervisor ladder step, a `set_cand_compression` call) must change
    the executable identity."""
    from ..kernels.patchmatch_tile import (
        resolve_cand_dtype,
        resolve_packed,
        resolve_prune,
    )

    prune = resolve_prune()
    return "|".join((
        resolve_cand_dtype(),
        "full" if prune is None else f"prune{prune[0]}:{prune[1]}",
        "packed" if resolve_packed() else "unpacked",
    ))


def exec_key(b_shape, cfg, batch_size: int = 1) -> ExecKey:
    """The executable identity of one dispatch: (stacked pyramid-input
    shape, config fingerprint, matcher, compression mode).  The
    leading `batch_size` is part of the shape because the batch
    runner's vmapped executables are shape-specialized over the frame
    axis — which is why the daemon pads every dispatch to one static
    batch grain (serving/daemon.py)."""
    return (
        (int(batch_size),) + tuple(int(d) for d in b_shape),
        config_fingerprint(cfg),
        cfg.matcher,
        compression_mode(),
    )


def key_str(key: ExecKey) -> str:
    shape, fp, matcher, comp = key
    return f"{'x'.join(map(str, shape))}/{matcher}/{comp}/{fp}"


class _Entry:
    __slots__ = ("key", "warm", "hits", "compiles", "last_used_t",
                 "compile_ms", "last_request_id")

    def __init__(self, key: ExecKey):
        self.key = key
        self.warm = False
        self.hits = 0
        self.compiles = 0
        self.last_used_t = time.monotonic()
        self.compile_ms: Optional[float] = None
        # Last request to look this entry up (round 15 tracing) — the
        # /serving snapshot's breadcrumb from a cache line back to a
        # concrete request id the access log / trace CLI can expand.
        self.last_request_id: Optional[str] = None


class ExecutableCache:
    """LRU accounting cache over the engine's compiled executables.

    `lookup(key)` returns "hit" (resident and warm) or "miss" (new, or
    demoted to cold by an epoch eviction), admitting/evicting as
    needed and booking `ia_serve_excache_{hits,misses,evictions}_total`
    (hits/misses carry a {kind} label so warmup traffic never inflates
    the client ledger the sentinel's serving check prices)."""

    def __init__(self, capacity: int = 8, registry=None):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1 ({capacity})")
        self.capacity = int(capacity)
        self._registry = registry
        self._entries: "OrderedDict[ExecKey, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.evictions = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..telemetry.metrics import get_registry

        return get_registry()

    def _count(self, which: str, kind: str) -> None:
        self._reg().counter(
            f"ia_serve_excache_{which}_total",
            f"serving executable-cache {which} by request kind "
            "(client vs warmup)",
        ).inc(labels={"kind": kind})

    def lookup(self, key: ExecKey, kind: str = "client",
               request_id: Optional[str] = None) -> str:
        """Admit `key`, return "hit" or "miss", and book the counters.

        A miss either admits a new entry (evicting the LRU entry at
        capacity — an EPOCH eviction, see the module docstring) or
        re-warms a demoted one.  The caller dispatches either way; the
        engine's jit caches do the actual reuse/compile.  `request_id`
        (round 15) stamps the entry with the looking-up request."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.last_used_t = time.monotonic()
                if request_id is not None:
                    entry.last_request_id = request_id
                if entry.warm:
                    entry.hits += 1
                    self._count("hits", kind)
                    return "hit"
                # Demoted by an epoch eviction: the engine caches were
                # cleared, so this use recompiles — an honest miss.
                entry.warm = True
                entry.compiles += 1
                self._count("misses", kind)
                return "miss"
            entry = _Entry(key)
            entry.warm = True
            entry.compiles = 1
            if request_id is not None:
                entry.last_request_id = request_id
            self._entries[key] = entry
            if len(self._entries) > self.capacity:
                self._evict_lru()
            self._count("misses", kind)
            return "miss"

    def _evict_lru(self) -> None:
        """Capacity eviction (caller holds the lock): drop the LRU
        entry, clear the engine's compiled-function caches, and demote
        every remaining entry to cold — selective per-key eviction is
        impossible over `functools.lru_cache`, so eviction is honest
        at epoch granularity rather than fictitious at key
        granularity."""
        evicted_key, _ = self._entries.popitem(last=False)
        self.evictions += 1
        self._reg().counter(
            "ia_serve_excache_evictions_total",
            "serving executable-cache capacity evictions (epoch-"
            "grained: one eviction clears the engine's jit caches and "
            "demotes every resident entry to cold)",
        ).inc()
        from ..kernels.patchmatch_tile import clear_compiled_level_caches

        clear_compiled_level_caches()
        for entry in self._entries.values():
            entry.warm = False
        import logging

        logging.getLogger("image_analogies_tpu").info(
            "serving excache: evicted %s (epoch eviction: %d resident "
            "entries demoted to cold)",
            key_str(evicted_key), len(self._entries),
        )

    def force_epoch_eviction(self) -> int:
        """Forced epoch eviction — the `serve_evict` chaos point
        (round 16): clear the engine's compiled-function caches and
        demote every resident entry to cold, exactly the aftermath of
        a capacity eviction but without dropping any accounting entry.
        The next lookup of each key is an honest miss.  Returns how
        many entries were demoted."""
        with self._lock:
            from ..kernels.patchmatch_tile import (
                clear_compiled_level_caches,
            )

            clear_compiled_level_caches()
            demoted = 0
            for entry in self._entries.values():
                if entry.warm:
                    entry.warm = False
                    demoted += 1
            self.evictions += 1
            self._reg().counter(
                "ia_serve_excache_evictions_total",
                "serving executable-cache capacity evictions (epoch-"
                "grained: one eviction clears the engine's jit caches "
                "and demotes every resident entry to cold)",
            ).inc()
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "serving excache: FORCED epoch eviction (%d resident "
                "entries demoted to cold)", demoted,
            )
            return demoted

    def note_compile_ms(self, key: ExecKey, wall_ms: float) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.compile_ms = round(float(wall_ms), 3)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "resident": len(self._entries),
                "evictions": self.evictions,
                "entries": [
                    {
                        "key": key_str(e.key),
                        "warm": e.warm,
                        "hits": e.hits,
                        "compiles": e.compiles,
                        "compile_ms": e.compile_ms,
                        "last_request_id": e.last_request_id,
                    }
                    for e in self._entries.values()
                ],
            }


# ---------------------------------------------------------------- warmup
def load_warmup_manifest(path: str) -> List[Dict[str, Any]]:
    """Parse a warmup manifest: {"schema_version": 1, "kind":
    "serve_warmup", "entries": [{"height": H, "width": W,
    "channels": C}, ...]} — the shapes the operator expects traffic
    at, compiled at daemon start so the first client request of each
    shape is a hit.  Malformed manifests raise ValueError at startup
    (a typo'd manifest must fail the daemon's launch, not silently
    leave it cold)."""
    with open(path) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) or manifest.get(
        "schema_version"
    ) != WARMUP_SCHEMA_VERSION:
        raise ValueError(
            f"warmup manifest {path}: schema_version "
            f"{manifest.get('schema_version') if isinstance(manifest, dict) else None!r}"
            f" != {WARMUP_SCHEMA_VERSION}"
        )
    if manifest.get("kind") != "serve_warmup":
        raise ValueError(
            f"warmup manifest {path}: kind "
            f"{manifest.get('kind')!r} != 'serve_warmup'"
        )
    entries = manifest.get("entries")
    if not isinstance(entries, list) or not entries:
        raise ValueError(f"warmup manifest {path}: empty 'entries'")
    out = []
    for i, e in enumerate(entries):
        try:
            h, w = int(e["height"]), int(e["width"])
            c = int(e.get("channels", 3))
        except (TypeError, KeyError, ValueError):
            raise ValueError(
                f"warmup manifest {path}: entries[{i}] needs integer "
                "height/width (+ optional channels)"
            ) from None
        if h < 8 or w < 8 or c not in (1, 3):
            raise ValueError(
                f"warmup manifest {path}: entries[{i}] shape "
                f"{h}x{w}x{c} out of range (min 8x8, channels 1|3)"
            )
        out.append({"height": h, "width": w, "channels": c})
    return out


OBSERVED_WARMUP_FILE = "warmup.observed.json"
OBSERVED_WARMUP_KIND = "serve_warmup_observed"


def save_observed_warmup(path: str, shapes) -> None:
    """Persist the runtime-observed working set (round 16 satellite:
    warmup-manifest drift).  `shapes` is an LRU-ordered iterable of
    (height, width, channels) actually served by this process; the
    successor merges them into its warmup so restarts pre-compile the
    REAL traffic mix, not just the hand-declared manifest.  Atomic
    write (tmp + replace): a crash mid-write leaves the previous
    generation readable."""
    import os

    doc = {
        "schema_version": WARMUP_SCHEMA_VERSION,
        "kind": OBSERVED_WARMUP_KIND,
        "entries": [
            {"height": int(h), "width": int(w), "channels": int(c)}
            for (h, w, c) in shapes
        ],
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_observed_warmup(path: str) -> List[Dict[str, Any]]:
    """Best-effort read of `save_observed_warmup` output: a missing,
    corrupt, or wrong-kind file yields [] — the observed set is an
    optimization, and unlike the operator's manifest it must never
    fail a takeover.  Entries that fail the manifest's own shape
    bounds are skipped individually."""
    import os

    if not os.path.exists(path):
        return []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict) \
            or doc.get("kind") != OBSERVED_WARMUP_KIND:
        return []
    out = []
    for e in doc.get("entries") or []:
        try:
            h, w = int(e["height"]), int(e["width"])
            c = int(e.get("channels", 3))
        except (TypeError, KeyError, ValueError):
            continue
        if h < 8 or w < 8 or c not in (1, 3):
            continue
        out.append({"height": h, "width": w, "channels": c})
    return out


def merge_warmup_entries(*entry_lists) -> List[Dict[str, Any]]:
    """Concatenate warmup entry lists (manifest first, then observed)
    deduplicated by (height, width, channels), order-preserving —
    `run_warmup` dedupes by executable key anyway, this keeps the
    startup report readable."""
    seen = set()
    out = []
    for entries in entry_lists:
        for e in entries or []:
            ident = (e["height"], e["width"], e.get("channels", 3))
            if ident in seen:
                continue
            seen.add(ident)
            out.append(dict(e))
    return out


def run_warmup(entries: List[Dict[str, Any]],
               dispatch_fn: Callable[[tuple], Any],
               cache: "ExecutableCache", key_fn) -> List[Dict[str, Any]]:
    """Drive each manifest entry's shape through the daemon's dispatch
    path (a synthetic zero image; `dispatch_fn` performs the cache
    lookup itself, exactly as a client dispatch would, with
    kind="warmup" so warmup traffic stays out of the client ledger).
    Entries are deduplicated by executable key so a manifest that
    repeats a shape never books a warmup "hit" (the sentinel's
    `cache hits <= requests` ledger is a claim about CLIENT traffic).
    Returns per-entry {key, wall_ms} records."""
    done = set()
    report = []
    for e in entries:
        shape = (e["height"], e["width"], e["channels"])
        key = key_fn(shape)
        if key in done:
            continue
        done.add(key)
        t0 = time.perf_counter()
        dispatch_fn(shape)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        cache.note_compile_ms(key, wall_ms)
        report.append({"key": key_str(key), "wall_ms": round(wall_ms, 1)})
    return report
