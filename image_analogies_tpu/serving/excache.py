"""Compiled-executable cache — the serving tier's accounting layer
over the engine's jit caches (round 13 tentpole, with
serving/queueing.py and serving/daemon.py).

The engine already caches compiled executables process-wide
(`parallel/batch._batch_prologue_fn_cached` / `_batch_level_fn_cached`
and friends are `functools.lru_cache`s keyed on (cfg, level, mesh)),
so a repeat-shape dispatch skips the ~140 ms prologue compile
automatically.  What serving needs on top is the part functools cannot
give:

  - an ADMISSION-VISIBLE key: one record per (pyramid shape, config
    fingerprint, matcher, compression mode) so the daemon can answer
    "will this request compile or reuse?" BEFORE dispatching, label
    the request's span `cache-hit` vs `compiled`, and expose
    hit/miss/evict counters a scraper can watch;
  - a WARMUP path: a manifest of expected shapes compiled at daemon
    start, so the first paying request of each shape is a hit;
  - honest EVICTION: `functools.lru_cache` offers no per-key eviction,
    so capacity eviction here is EPOCH-grained — evicting one entry
    calls `kernels.patchmatch_tile.clear_compiled_level_caches()`
    (the mode-flip setters' invalidation hook, which drops every
    cached level/prologue/step function across all four runners) and
    demotes every other resident entry to cold.  The next use of a
    demoted key is counted (and priced) as a miss.  Capacity should
    therefore be sized so eviction is rare (default 8 resident
    shapes); the counters make an undersized cache visible as an
    eviction rate, not a silent recompile storm.

The cache key deliberately matches the jit keys' own identity: the
config fingerprint hashes `models.analogy._strip_noncompute(cfg)` (the
same stripping the jit caches apply, so two configs differing only in
`save_level_artifacts` share one executable AND one cache entry), and
the compression mode captures the process-wide kernel knobs
(`IA_CAND_DTYPE` / `IA_CAND_PRUNE` / packed layout) that shape traced
graphs without living in the config.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

WARMUP_SCHEMA_VERSION = 1

ExecKey = Tuple[tuple, str, str, str]


def config_fingerprint(cfg) -> str:
    """Stable digest of the COMPUTE-shaping config fields — the same
    identity the jit caches key on (`_strip_noncompute` removes the
    host-side checkpoint path), so the serving cache can never split or
    alias entries the engine's own caches share."""
    import dataclasses

    from ..models.analogy import _strip_noncompute

    return hashlib.sha1(
        repr(dataclasses.astuple(_strip_noncompute(cfg))).encode()
    ).hexdigest()[:12]


def compression_mode() -> str:
    """The process-wide kernel-compression knobs as one label: these
    are module globals, not config fields (the `_POLISH_MODE`
    rationale), but they shape every traced graph — a mode flip (a
    supervisor ladder step, a `set_cand_compression` call) must change
    the executable identity."""
    from ..kernels.patchmatch_tile import (
        resolve_cand_dtype,
        resolve_packed,
        resolve_prune,
    )

    prune = resolve_prune()
    return "|".join((
        resolve_cand_dtype(),
        "full" if prune is None else f"prune{prune[0]}:{prune[1]}",
        "packed" if resolve_packed() else "unpacked",
    ))


def exec_key(b_shape, cfg, batch_size: int = 1) -> ExecKey:
    """The executable identity of one dispatch: (stacked pyramid-input
    shape, config fingerprint, matcher, compression mode).  The
    leading `batch_size` is part of the shape because the batch
    runner's vmapped executables are shape-specialized over the frame
    axis — which is why the daemon pads every dispatch to one static
    batch grain (serving/daemon.py)."""
    return (
        (int(batch_size),) + tuple(int(d) for d in b_shape),
        config_fingerprint(cfg),
        cfg.matcher,
        compression_mode(),
    )


def key_str(key: ExecKey) -> str:
    shape, fp, matcher, comp = key
    return f"{'x'.join(map(str, shape))}/{matcher}/{comp}/{fp}"


class _Entry:
    __slots__ = ("key", "warm", "hits", "compiles", "last_used_t",
                 "compile_ms", "last_request_id")

    def __init__(self, key: ExecKey):
        self.key = key
        self.warm = False
        self.hits = 0
        self.compiles = 0
        self.last_used_t = time.monotonic()
        self.compile_ms: Optional[float] = None
        # Last request to look this entry up (round 15 tracing) — the
        # /serving snapshot's breadcrumb from a cache line back to a
        # concrete request id the access log / trace CLI can expand.
        self.last_request_id: Optional[str] = None


class ExecutableCache:
    """LRU accounting cache over the engine's compiled executables.

    `lookup(key)` returns "hit" (resident and warm) or "miss" (new, or
    demoted to cold by an epoch eviction), admitting/evicting as
    needed and booking `ia_serve_excache_{hits,misses,evictions}_total`
    (hits/misses carry a {kind} label so warmup traffic never inflates
    the client ledger the sentinel's serving check prices)."""

    def __init__(self, capacity: int = 8, registry=None):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1 ({capacity})")
        self.capacity = int(capacity)
        self._registry = registry
        self._entries: "OrderedDict[ExecKey, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.evictions = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..telemetry.metrics import get_registry

        return get_registry()

    def _count(self, which: str, kind: str) -> None:
        self._reg().counter(
            f"ia_serve_excache_{which}_total",
            f"serving executable-cache {which} by request kind "
            "(client vs warmup)",
        ).inc(labels={"kind": kind})

    def lookup(self, key: ExecKey, kind: str = "client",
               request_id: Optional[str] = None) -> str:
        """Admit `key`, return "hit" or "miss", and book the counters.

        A miss either admits a new entry (evicting the LRU entry at
        capacity — an EPOCH eviction, see the module docstring) or
        re-warms a demoted one.  The caller dispatches either way; the
        engine's jit caches do the actual reuse/compile.  `request_id`
        (round 15) stamps the entry with the looking-up request."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.last_used_t = time.monotonic()
                if request_id is not None:
                    entry.last_request_id = request_id
                if entry.warm:
                    entry.hits += 1
                    self._count("hits", kind)
                    return "hit"
                # Demoted by an epoch eviction: the engine caches were
                # cleared, so this use recompiles — an honest miss.
                entry.warm = True
                entry.compiles += 1
                self._count("misses", kind)
                return "miss"
            entry = _Entry(key)
            entry.warm = True
            entry.compiles = 1
            if request_id is not None:
                entry.last_request_id = request_id
            self._entries[key] = entry
            if len(self._entries) > self.capacity:
                self._evict_lru()
            self._count("misses", kind)
            return "miss"

    def _evict_lru(self) -> None:
        """Capacity eviction (caller holds the lock): drop the LRU
        entry, clear the engine's compiled-function caches, and demote
        every remaining entry to cold — selective per-key eviction is
        impossible over `functools.lru_cache`, so eviction is honest
        at epoch granularity rather than fictitious at key
        granularity."""
        evicted_key, _ = self._entries.popitem(last=False)
        self.evictions += 1
        self._reg().counter(
            "ia_serve_excache_evictions_total",
            "serving executable-cache capacity evictions (epoch-"
            "grained: one eviction clears the engine's jit caches and "
            "demotes every resident entry to cold)",
        ).inc()
        from ..kernels.patchmatch_tile import clear_compiled_level_caches

        clear_compiled_level_caches()
        for entry in self._entries.values():
            entry.warm = False
        import logging

        logging.getLogger("image_analogies_tpu").info(
            "serving excache: evicted %s (epoch eviction: %d resident "
            "entries demoted to cold)",
            key_str(evicted_key), len(self._entries),
        )

    def force_epoch_eviction(self) -> int:
        """Forced epoch eviction — the `serve_evict` chaos point
        (round 16): clear the engine's compiled-function caches and
        demote every resident entry to cold, exactly the aftermath of
        a capacity eviction but without dropping any accounting entry.
        The next lookup of each key is an honest miss.  Returns how
        many entries were demoted."""
        with self._lock:
            from ..kernels.patchmatch_tile import (
                clear_compiled_level_caches,
            )

            clear_compiled_level_caches()
            demoted = 0
            for entry in self._entries.values():
                if entry.warm:
                    entry.warm = False
                    demoted += 1
            self.evictions += 1
            self._reg().counter(
                "ia_serve_excache_evictions_total",
                "serving executable-cache capacity evictions (epoch-"
                "grained: one eviction clears the engine's jit caches "
                "and demotes every resident entry to cold)",
            ).inc()
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "serving excache: FORCED epoch eviction (%d resident "
                "entries demoted to cold)", demoted,
            )
            return demoted

    def note_compile_ms(self, key: ExecKey, wall_ms: float) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.compile_ms = round(float(wall_ms), 3)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "resident": len(self._entries),
                "evictions": self.evictions,
                "entries": [
                    {
                        "key": key_str(e.key),
                        "warm": e.warm,
                        "hits": e.hits,
                        "compiles": e.compiles,
                        "compile_ms": e.compile_ms,
                        "last_request_id": e.last_request_id,
                    }
                    for e in self._entries.values()
                ],
            }


# ---------------------------------------------------------------- warmup
def load_warmup_manifest(path: str) -> List[Dict[str, Any]]:
    """Parse a warmup manifest: {"schema_version": 1, "kind":
    "serve_warmup", "entries": [{"height": H, "width": W,
    "channels": C}, ...]} — the shapes the operator expects traffic
    at, compiled at daemon start so the first client request of each
    shape is a hit.  Malformed manifests raise ValueError at startup
    (a typo'd manifest must fail the daemon's launch, not silently
    leave it cold)."""
    with open(path) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) or manifest.get(
        "schema_version"
    ) != WARMUP_SCHEMA_VERSION:
        raise ValueError(
            f"warmup manifest {path}: schema_version "
            f"{manifest.get('schema_version') if isinstance(manifest, dict) else None!r}"
            f" != {WARMUP_SCHEMA_VERSION}"
        )
    if manifest.get("kind") != "serve_warmup":
        raise ValueError(
            f"warmup manifest {path}: kind "
            f"{manifest.get('kind')!r} != 'serve_warmup'"
        )
    entries = manifest.get("entries")
    if not isinstance(entries, list) or not entries:
        raise ValueError(f"warmup manifest {path}: empty 'entries'")
    out = []
    for i, e in enumerate(entries):
        try:
            h, w = int(e["height"]), int(e["width"])
            c = int(e.get("channels", 3))
        except (TypeError, KeyError, ValueError):
            raise ValueError(
                f"warmup manifest {path}: entries[{i}] needs integer "
                "height/width (+ optional channels)"
            ) from None
        if h < 8 or w < 8 or c not in (1, 3):
            raise ValueError(
                f"warmup manifest {path}: entries[{i}] shape "
                f"{h}x{w}x{c} out of range (min 8x8, channels 1|3)"
            )
        out.append({"height": h, "width": w, "channels": c})
    return out


OBSERVED_WARMUP_FILE = "warmup.observed.json"
OBSERVED_WARMUP_KIND = "serve_warmup_observed"


def save_observed_warmup(path: str, shapes, merge: bool = False) -> None:
    """Persist the runtime-observed working set (round 16 satellite:
    warmup-manifest drift).  `shapes` is an LRU-ordered iterable of
    (height, width, channels) actually served by this process; the
    successor merges them into its warmup so restarts pre-compile the
    REAL traffic mix, not just the hand-declared manifest.  Atomic
    write (tmp + replace): a crash mid-write leaves the previous
    generation readable.

    `merge=True` is the round-21 shared-warm-tier mode: the file lives
    under a fleet-shared warm dir, so N replicas write it — each
    writer UNIONS its shapes into whatever is already on disk instead
    of overwriting (last-writer-wins would shrink the fleet's observed
    set to one replica's traffic slice).  The read-union-replace race
    between two simultaneous drains can drop at most one writer's
    fresh shapes for one generation; the loser re-merges them on its
    next sighting, so the union converges."""
    import os

    entries = [
        {"height": int(h), "width": int(w), "channels": int(c)}
        for (h, w, c) in shapes
    ]
    if merge:
        entries = merge_warmup_entries(load_observed_warmup(path),
                                       entries)
    doc = {
        "schema_version": WARMUP_SCHEMA_VERSION,
        "kind": OBSERVED_WARMUP_KIND,
        "entries": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_observed_warmup(path: str) -> List[Dict[str, Any]]:
    """Best-effort read of `save_observed_warmup` output: a missing,
    corrupt, or wrong-kind file yields [] — the observed set is an
    optimization, and unlike the operator's manifest it must never
    fail a takeover.  Entries that fail the manifest's own shape
    bounds are skipped individually."""
    import os

    if not os.path.exists(path):
        return []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict) \
            or doc.get("kind") != OBSERVED_WARMUP_KIND:
        return []
    out = []
    for e in doc.get("entries") or []:
        try:
            h, w = int(e["height"]), int(e["width"])
            c = int(e.get("channels", 3))
        except (TypeError, KeyError, ValueError):
            continue
        if h < 8 or w < 8 or c not in (1, 3):
            continue
        out.append({"height": h, "width": w, "channels": c})
    return out


def merge_warmup_entries(*entry_lists) -> List[Dict[str, Any]]:
    """Concatenate warmup entry lists (manifest first, then observed)
    deduplicated by (height, width, channels), order-preserving —
    `run_warmup` dedupes by executable key anyway, this keeps the
    startup report readable."""
    seen = set()
    out = []
    for entries in entry_lists:
        for e in entries or []:
            ident = (e["height"], e["width"], e.get("channels", 3))
            if ident in seen:
                continue
            seen.add(ident)
            out.append(dict(e))
    return out


def run_warmup(entries: List[Dict[str, Any]],
               dispatch_fn: Callable[[tuple], Any],
               cache: "ExecutableCache", key_fn,
               max_workers: int = 4,
               tracer=None) -> List[Dict[str, Any]]:
    """Drive each manifest entry's shape through the daemon's dispatch
    path (a synthetic zero image; `dispatch_fn` performs the cache
    lookup itself, exactly as a client dispatch would, with
    kind="warmup" so warmup traffic stays out of the client ledger).
    Entries are deduplicated by executable key so a manifest that
    repeats a shape never books a warmup "hit" (the sentinel's
    `cache hits <= requests` ledger is a claim about CLIENT traffic).

    Round 18: distinct shapes compile CONCURRENTLY on a small thread
    pool (`max_workers`, clamped to the shape count; <= 1 keeps the
    old sequential path) — shape compiles are independent jit traces,
    so the port-announce delay is the SLOWEST shape's compile, not the
    sum.  When `tracer` is a live Tracer, one `warmup` span tree is
    attached carrying a child span per shape with its compile wall —
    the per-shape attribution an operator reads instead of one opaque
    startup stall.  Returns per-entry {key, wall_ms} records in
    manifest order."""
    work = []
    seen = set()
    for e in entries:
        shape = (e["height"], e["width"], e["channels"])
        key = key_fn(shape)
        if key in seen:
            continue
        seen.add(key)
        work.append((key, shape))
    t_start = time.perf_counter()
    spans: List[tuple] = []

    def one(key, shape):
        t0 = time.perf_counter()
        dispatch_fn(shape)
        t1 = time.perf_counter()
        wall_ms = (t1 - t0) * 1000.0
        cache.note_compile_ms(key, wall_ms)
        spans.append((key, t0, t1))
        return {"key": key_str(key), "wall_ms": round(wall_ms, 1)}

    if len(work) <= 1 or max_workers <= 1:
        report = [one(key, shape) for key, shape in work]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(int(max_workers), len(work)),
            thread_name_prefix="ia-serve-warmup",
        ) as pool:
            futures = [pool.submit(one, key, shape)
                       for key, shape in work]
            # In submission order (manifest order) — a failed shape
            # raises here exactly as the sequential loop did.
            report = [f.result() for f in futures]
    if tracer is not None and getattr(tracer, "enabled", False) \
            and work:
        from ..telemetry.spans import span_at

        t_end = time.perf_counter()
        root = span_at(
            "warmup", t_start, t_end,
            shapes=len(work),
            workers=min(int(max_workers), len(work)),
        )
        for key, t0, t1 in sorted(spans, key=lambda s: s[1]):
            root.children.append(span_at(
                "warmup_shape", t0, t1, key=key_str(key),
                compile_ms=round((t1 - t0) * 1000.0, 1),
            ))
        tracer.attach_tree(root)
    return report


# ------------------------------------------------------------ disk tier
# Round 18 tentpole: a persistent executable store under
# <state_dir>/excache/.  The in-memory ExecutableCache above stays the
# accounting layer ("will this dispatch compile or reuse?"); the disk
# tier makes the answer survive the process.  Architecture:
#
#   - The engine's jit factories expose a persist hook
#     (parallel/batch.set_persist_hook).  On the COLD path the hook
#     owns compilation: it AOT-lowers the jit function
#     (`lower(*args).compile()`), serializes the executable
#     (jax.experimental.serialize_executable — the AOT API that
#     survives jax 0.4.37), writes one checksummed blob file, and
#     calls the compiled object — one compile total, because jit's
#     internal executable cache is NOT reused by AOT lowering.
#   - On restore the blob is deserialized and matched AT CALL TIME by
#     (role, ident, argument signature): ident is the stripped-config
#     lru key (stable across processes — dataclass repr of compute
#     fields only) plus the process-wide compression mode, the
#     signature is the argument pytree structure + leaf shapes/dtypes.
#     No tracing happens on a restored path.
#   - Entries are keyed by exec_key x a BACKEND FINGERPRINT (jax/
#     jaxlib versions, platform, device kind + count, XLA env seams):
#     any mismatch is an honest miss — recompile + overwrite, never a
#     wrong answer.  Corrupt or torn blob files are skipped with a
#     counted error (`ia_excache_disk_errors_total`), journal-style.
#   - `index.json` maps exec_key -> its blob set (sealed only after a
#     successful dispatch), giving the daemon an admission-visible
#     "disk" verdict and the warm-set shapes a restart restores before
#     the port is announced.
DISK_SCHEMA_VERSION = 1
_BLOB_MAGIC = b"IAXC1\n"
_INDEX_FILE = "index.json"


def backend_fingerprint() -> Dict[str, Any]:
    """The environment a serialized executable is only valid in: jax
    wire format + compiler version + device topology + the env seams
    that change generated code without appearing in any config field.
    (The kernel-compression mode is already inside `exec_key` /
    the hook ident, so it is deliberately absent here.)"""
    import os

    import jax
    import jaxlib

    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform if devs else "none",
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
    }


def _digest(s: str) -> str:
    return hashlib.sha1(s.encode()).hexdigest()[:16]


def _arg_signature(args) -> tuple:
    """Stable cross-process identity of a call's arguments: the pytree
    structure plus each leaf's (shape, dtype) — exactly what shape-
    specializes a jit trace.  Python-scalar leaves (the luma-bucket
    stats tuple) are identified by type, not value: they trace as
    dynamic scalars, so one executable serves every value."""
    import jax

    leaves, tree = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(int(d) for d in leaf.shape),
                        str(leaf.dtype)))
        else:
            sig.append(("py", type(leaf).__name__))
    return (repr(tree), tuple(sig))


_BYPASS_LOCK = threading.Lock()
_BYPASS_DEPTH = 0
_BYPASS_SAVED: tuple = ()


@contextlib.contextmanager
def _jax_cache_bypass():
    """Disable jax's persistent compilation cache around one AOT
    compile.  Serializing an executable that was itself LOADED from
    jax's cache produces a blob whose deserialize later fails with XLA
    "Symbols not found" — the object code is not self-contained — so a
    persisted blob must always come from a fresh XLA compile (the AOT
    store IS the persistence layer for hook-covered functions; losing
    the jax-cache write for them costs nothing).  The config knob
    can't express this: `is_cache_used`/`_cache` are memoized once per
    process, so the only off switch after first use is the module
    state itself.  Swapping `_cache = None` makes both the read and
    write paths report "disabled" (`_initialize_cache` is memoized via
    `_cache_initialized`, which we force True so a first-ever compile
    landing inside the window can't lazily resurrect it).  The swap is
    process-global, not thread-local — a concurrent eager-op compile
    on another thread just skips one jax-cache write, which is a
    missed optimization, never a correctness problem — and the depth
    counter keeps parallel warmup compiles from restoring early."""
    global _BYPASS_DEPTH, _BYPASS_SAVED
    try:
        from jax._src import compilation_cache as jax_cc
    except Exception:  # noqa: BLE001 - private API probe
        yield
        return
    with _BYPASS_LOCK:
        if _BYPASS_DEPTH == 0:
            _BYPASS_SAVED = (
                getattr(jax_cc, "_cache", None),
                getattr(jax_cc, "_cache_initialized", True),
            )
            jax_cc._cache = None
            jax_cc._cache_initialized = True
        _BYPASS_DEPTH += 1
    try:
        yield
    finally:
        with _BYPASS_LOCK:
            _BYPASS_DEPTH -= 1
            if _BYPASS_DEPTH == 0:
                jax_cc._cache = _BYPASS_SAVED[0]
                jax_cc._cache_initialized = _BYPASS_SAVED[1]
                _BYPASS_SAVED = ()


def _jax_cache_bypass_available() -> bool:
    try:
        from jax._src import compilation_cache as jax_cc

        return hasattr(jax_cc, "_cache") and hasattr(
            jax_cc, "_cache_initialized"
        )
    except Exception:  # noqa: BLE001 - private API probe
        return False


class DiskExecCache:
    """Persistent disk tier for the serving executable cache.

    Store layout under `root` (= <state_dir>/excache/):

        index.json            {schema_version, fingerprint, entries:
                               {key_str: {shape, warmup_shape, blobs}}}
        blobs/<role>-<ident>-<sig>.jexec
                              MAGIC + sha256(payload) + payload, where
                              payload pickles {fingerprint, role,
                              ident, sig, blob, in_tree, out_tree}

    Honesty rules: a fingerprint mismatch drops the whole index (miss,
    recompile, overwrite); a corrupt/torn/missing blob is skipped with
    `ia_excache_disk_errors_total` and degrades its entry to a miss; a
    restored executable that rejects its arguments (pre-execution
    shape/sharding check) falls back to the jit path with a counted
    error.  Never a wrong answer.

    Threading: the loaded-executable table and index are lock-guarded;
    the per-dispatch blob-recording context is THREAD-LOCAL — the
    daemon opens it INSIDE the supervised attempt closure (which runs
    on the supervisor's worker thread, where the engine actually calls
    the hook), so the parallel warmup pool and the pipelined
    dispatcher each seal only their own dispatch's blobs."""

    def __init__(self, root: str, registry=None):
        import os

        self.root = str(root)
        self.blob_dir = os.path.join(self.root, "blobs")
        os.makedirs(self.blob_dir, exist_ok=True)
        self._registry = registry
        self._fp = backend_fingerprint()
        self._lock = threading.RLock()
        # (role, ident_digest, sig_digest) -> loaded/compiled callable
        self._loaded: Dict[tuple, Any] = {}
        # key_str(exec_key) -> {"shape", "warmup_shape", "blobs"}
        self._entries: Dict[str, Dict[str, Any]] = {}
        # Keys THIS process deliberately dropped (dead blobs found by
        # probe/restore): the shared-dir index merge must not
        # resurrect them from a sibling's older index generation.
        self._dropped: set = set()
        self._ctx = threading.local()
        self.errors = 0
        self.stored = 0
        self.restore_ms: Optional[float] = None
        self._owns_jax_cache = False
        self._saved_jax_knobs: Optional[tuple] = None
        # serialize/deserialize availability probed once; a platform
        # without the AOT API degrades to a no-op tier (all misses),
        # never a crash.
        try:
            from jax.experimental.serialize_executable import (  # noqa: F401
                deserialize_and_load,
                serialize,
            )

            self.enabled = True
        except Exception:  # noqa: BLE001 - optional capability
            self.enabled = False
        if self.enabled:
            self._enable_jax_cache()
        self._load_index()

    def _enable_jax_cache(self) -> None:
        """Point jax's own persistent compilation cache under the same
        root.  The AOT tier above covers the hook-wrapped level/prologue
        executables; this covers the long tail of tiny ops the engine
        dispatches eagerly around them (colorspace einsum, rng seeding,
        padding slices) — each only ~15-25 ms to compile, but there are
        a dozen of them on a restart's first request and together they
        dominate the residual cold start once the big executables come
        from disk.  Thresholds drop to zero because that long tail is
        exactly the sub-second population jax's defaults skip.  A jax
        without the knobs, or one the user already pointed elsewhere,
        is left alone.

        Enabled ONLY when the per-compile bypass is available too
        (`_jax_cache_bypass`): the hook's AOT compiles must never read
        this cache, or the serialized blobs come out non-self-contained
        (see the bypass docstring) — no bypass, no jax cache."""
        import os

        import jax

        if not _jax_cache_bypass_available():
            return
        try:
            if jax.config.jax_compilation_cache_dir is not None:
                return
            self._saved_jax_knobs = (
                jax.config.jax_persistent_cache_min_compile_time_secs,
                jax.config.jax_persistent_cache_min_entry_size_bytes,
            )
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(self.root, "jaxcache"),
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1
            )
            self._owns_jax_cache = True
        except Exception:  # noqa: BLE001 - optional capability
            pass

    def release_jax_cache(self) -> None:
        """Undo `_enable_jax_cache` when the owning daemon stops.  The
        knob is process-global and jax memoizes the cache object at
        first compile, so without this the jax cache — and its
        per-compile key-hash + serialize-and-write overhead — outlives
        the daemon and taxes every later compile in the process
        (long-lived test runners feel this as minutes).  Restores the
        config to its pre-enable state and `reset_cache()`s jax's
        memos; a successor daemon on the same state dir simply
        re-enables and re-initializes against the same directory."""
        global _BYPASS_SAVED
        if not self._owns_jax_cache:
            return
        self._owns_jax_cache = False
        try:
            import jax
            from jax._src import compilation_cache as jax_cc

            jax.config.update("jax_compilation_cache_dir", None)
            if self._saved_jax_knobs is not None:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    self._saved_jax_knobs[0],
                )
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes",
                    self._saved_jax_knobs[1],
                )
            with _BYPASS_LOCK:
                jax_cc.reset_cache()
                if _BYPASS_DEPTH > 0:
                    # A hook compile is mid-bypass: make its exit
                    # restore the reset state, not the pre-reset
                    # cache object we just tore down.
                    _BYPASS_SAVED = (None, False)
        except Exception:  # noqa: BLE001 - optional capability
            pass

    # ---------------------------------------------------- metrics
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..telemetry.metrics import get_registry

        return get_registry()

    def _count(self, which: str, kind: Optional[str] = None) -> None:
        c = self._reg().counter(
            f"ia_excache_disk_{which}_total",
            f"serving disk executable-cache {which}"
            + (" by request kind" if kind is not None else
               " (corrupt/torn blob files, serialize/store failures "
               "— skipped journal-style, never raised)"),
        )
        c.inc(labels={"kind": kind} if kind is not None else None)

    def _error(self, why: str) -> None:
        self.errors += 1
        self._count("errors")
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "disk excache: %s (honest miss)", why
        )

    # ------------------------------------------------------ index
    def _index_path(self) -> str:
        import os

        return os.path.join(self.root, _INDEX_FILE)

    def _load_index(self) -> None:
        import os

        path = self._index_path()
        if not os.path.exists(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            self._error(f"unreadable index {path}")
            return
        if not isinstance(doc, dict) or doc.get(
            "schema_version"
        ) != DISK_SCHEMA_VERSION:
            self._error(f"index {path}: wrong schema")
            return
        if doc.get("fingerprint") != self._fp:
            # Not corruption: a different backend's executables are
            # simply not ours to run.  The entries die; blob files are
            # overwritten as this process re-seals.
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "disk excache: backend fingerprint changed "
                "(%s -> %s); persisted executables invalidated",
                doc.get("fingerprint"), self._fp,
            )
            return
        entries = doc.get("entries")
        if isinstance(entries, dict):
            for kstr, e in entries.items():
                if (isinstance(e, dict)
                        and isinstance(e.get("blobs"), list)):
                    self._entries[str(kstr)] = {
                        "shape": e.get("shape"),
                        "warmup_shape": e.get("warmup_shape"),
                        "blobs": [str(b) for b in e["blobs"]],
                    }

    def _write_index(self) -> None:
        """Whole-index write, MERGED with whatever a sibling process
        already put on disk (round 21 shared warm tier: N replicas
        root their DiskExecCache at one `--warm-dir`, so last-writer-
        wins would silently discard every other replica's sealed
        entries).  Same-fingerprint on-disk entries this process
        neither holds nor deliberately dropped carry through; a key
        dropped here as dead stays dropped (a sibling that re-seals it
        writes it back).  The read-merge-replace race between two
        simultaneous seals can lose one writer's newest entry for one
        generation — its next seal or index write restores it, so the
        union converges."""
        import os

        entries: Dict[str, Dict[str, Any]] = {}
        path = self._index_path()
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            if (isinstance(doc, dict)
                    and doc.get("schema_version") == DISK_SCHEMA_VERSION
                    and doc.get("fingerprint") == self._fp
                    and isinstance(doc.get("entries"), dict)):
                for kstr, e in doc["entries"].items():
                    if str(kstr) in self._dropped:
                        continue
                    if (isinstance(e, dict)
                            and isinstance(e.get("blobs"), list)):
                        entries[str(kstr)] = e
        except (OSError, ValueError):
            pass
        entries.update(self._entries)
        doc = {
            "schema_version": DISK_SCHEMA_VERSION,
            "fingerprint": self._fp,
            "entries": entries,
        }
        tmp = self._index_path() + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, self._index_path())
        except OSError as e:
            self._error(f"index write failed: {e}")

    # ------------------------------------------------------ blobs
    def _blob_name(self, tkey: tuple) -> str:
        role, ident_d, sig_d = tkey
        return f"{role}-{ident_d}-{sig_d}.jexec"

    def _blob_path(self, name: str) -> str:
        import os

        return os.path.join(self.blob_dir, os.path.basename(name))

    def _write_blob(self, tkey: tuple, role: str, ident_r: str,
                    sig: tuple, compiled) -> Optional[str]:
        """Serialize + atomically write one executable; returns the
        blob name, or None (counted) on failure."""
        import os
        import pickle

        from jax.experimental.serialize_executable import serialize

        try:
            blob, in_tree, out_tree = serialize(compiled)
            payload = pickle.dumps({
                "fingerprint": self._fp,
                "role": role,
                "ident": ident_r,
                "sig": sig,
                "blob": blob,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
        except Exception as e:  # noqa: BLE001 - persistence best-effort
            self._error(f"serialize failed for {role}: {e}")
            return None
        name = self._blob_name(tkey)
        path = self._blob_path(name)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                fh.write(_BLOB_MAGIC)
                fh.write(hashlib.sha256(payload).digest())
                fh.write(payload)
            os.replace(tmp, path)
        except OSError as e:
            self._error(f"blob write failed for {name}: {e}")
            return None
        self.stored += 1
        return name

    def _read_blob(self, name: str, expected: bool = True):
        """Deserialize one blob file into a callable, or None with a
        counted error on ANY corruption (bad magic, checksum mismatch,
        truncation, unpicklable payload, fingerprint drift).  A
        MISSING file is an error only when `expected` (the index or a
        sealed entry named it); the hook's own cold-path peek passes
        expected=False — an executable that was never persisted is
        the normal compile path, not a store fault."""
        import os
        import pickle

        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        path = self._blob_path(name)
        if not os.path.exists(path):
            if expected:
                self._error(f"blob {name} missing")
            return None
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
            if not raw.startswith(_BLOB_MAGIC):
                raise ValueError("bad magic")
            digest = raw[len(_BLOB_MAGIC):len(_BLOB_MAGIC) + 32]
            payload = raw[len(_BLOB_MAGIC) + 32:]
            if hashlib.sha256(payload).digest() != digest:
                raise ValueError("checksum mismatch (torn write?)")
            doc = pickle.loads(payload)
            if doc.get("fingerprint") != self._fp:
                raise ValueError("backend fingerprint mismatch")
            fn = deserialize_and_load(
                doc["blob"], doc["in_tree"], doc["out_tree"]
            )
            tkey = (doc["role"], _digest(doc["ident"]),
                    _digest(repr(tuple(doc["sig"]))))
            return tkey, fn
        except Exception as e:  # noqa: BLE001 - corrupt file, skip
            self._error(f"blob {name} unreadable: {e}")
            return None

    # --------------------------------------------- the persist hook
    def clear_loaded(self) -> None:
        """Epoch eviction (parallel/batch.clear_persist_loaded): drop
        every loaded/compiled executable, keep the disk files — the
        next use of each key restores from disk."""
        with self._lock:
            self._loaded.clear()

    def call(self, role: str, ident: tuple, jit_fn, args):
        """The hook body (parallel/batch._PersistWrap): loaded table
        -> disk blob -> AOT compile + store -> plain jit fallback.
        Persistence failures degrade to the jit path; they never
        change an answer."""
        if not self.enabled:
            return jit_fn(*args)
        ident_r = repr(ident) + "|" + compression_mode()
        sig = _arg_signature(args)
        tkey = (role, _digest(ident_r), _digest(repr(sig)))
        recording = getattr(self._ctx, "blobs", None)
        with self._lock:
            fn = self._loaded.get(tkey)
        if fn is None:
            hit = self._read_blob(self._blob_name(tkey),
                                  expected=False)
            if hit is not None:
                _, fn = hit
                with self._lock:
                    self._loaded[tkey] = fn
        if fn is not None:
            if recording is not None:
                recording.add(self._blob_name(tkey))
            try:
                return fn(*args)
            except (TypeError, ValueError) as e:
                # Pre-execution argument/sharding rejection on a
                # restored executable — an honest miss, not a wrong
                # answer (the check fires before any compute).
                self._error(
                    f"restored executable rejected args ({role}): {e}"
                )
                with self._lock:
                    self._loaded.pop(tkey, None)
        try:
            with _jax_cache_bypass():
                compiled = jit_fn.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 - AOT path best-effort
            self._error(f"AOT compile failed for {role}: {e}")
            return jit_fn(*args)
        name = self._write_blob(tkey, role, ident_r, sig, compiled)
        if name is not None and recording is not None:
            recording.add(name)
        with self._lock:
            self._loaded[tkey] = compiled
        return compiled(*args)

    # ------------------------------------------- dispatch bracketing
    def begin_recording(self) -> None:
        """Open THIS THREAD's blob-recording window: every blob the
        hook serves or seals from this thread until `end_recording`
        belongs to the current dispatch.  The daemon calls it at the
        top of the supervised attempt closure — the closure runs on
        the supervisor's worker thread, which is where the engine's
        jit factories actually invoke the hook."""
        self._ctx.blobs = set()

    def end_recording(self) -> set:
        """Close this thread's recording window, returning the blob
        names it captured (a retried attempt's captures are unioned by
        the caller)."""
        blobs = getattr(self._ctx, "blobs", None)
        self._ctx.blobs = None
        return blobs if blobs is not None else set()

    def seal(self, key: ExecKey, warmup_shape, blobs) -> None:
        """Seal one exec_key's entry (key -> the blob set its
        dispatch touched) into the index.  The daemon calls this only
        after a SUCCESSFUL dispatch — a half-compiled crashed dispatch
        can never claim a warm restart it cannot deliver.  An empty
        blob set (hook disabled, or every persist attempt failed)
        seals nothing.  `warmup_shape` is the client-visible (H, W, C)
        the restart warmup replays."""
        if not blobs:
            return
        kstr = key_str(key)
        entry = {
            "shape": [int(d) for d in key[0]],
            "warmup_shape": (
                [int(d) for d in warmup_shape]
                if warmup_shape is not None else None
            ),
            "blobs": sorted(blobs),
        }
        with self._lock:
            if self._entries.get(kstr) == entry:
                return
            self._entries[kstr] = entry
            self._dropped.discard(kstr)
            self._write_index()

    # -------------------------------------------------- verdict/restore
    def probe(self, key: ExecKey, kind: str = "client") -> str:
        """The admission-visible disk verdict for one exec_key the in-
        memory cache just missed: "disk" when a sealed entry's blobs
        are all loadable (loading them NOW, so the dispatch that
        follows runs restored executables without tracing), else
        "miss".  Books `ia_excache_disk_{hits,misses}_total{kind}` —
        exactly one of the two per in-memory miss, which is the
        sentinel reconciliation (disk hits + disk misses == in-memory
        misses)."""
        kstr = key_str(key)
        with self._lock:
            entry = self._entries.get(kstr)
        if entry is not None and self.enabled:
            ok = True
            for name in entry["blobs"]:
                with self._lock:
                    # Already resident (restored at start, or a prior
                    # probe): nothing to load.
                    if any(self._blob_name(t) == name
                           for t in self._loaded):
                        continue
                hit = self._read_blob(name)
                if hit is None:
                    ok = False
                    break
                tkey, fn = hit
                with self._lock:
                    self._loaded[tkey] = fn
            if ok:
                self._count("hits", kind)
                return "disk"
            # A sealed entry that cannot restore is dead weight —
            # drop it so the NEXT probe is a clean miss, and let this
            # dispatch recompile + re-seal.
            with self._lock:
                self._entries.pop(kstr, None)
                self._dropped.add(kstr)
                self._write_index()
        self._count("misses", kind)
        return "miss"

    def restore_warm_set(self) -> List[Dict[str, Any]]:
        """Daemon-start restore (before the port is announced): load
        every sealed entry's blobs into the table, dropping entries
        that no longer restore (counted errors).  Returns per-entry
        {key, blobs, wall_ms} and records the total wall on
        `ia_excache_disk_restore_ms`."""
        report = []
        t_all = time.perf_counter()
        with self._lock:
            items = list(self._entries.items())
        for kstr, entry in items:
            if not self.enabled:
                break
            t0 = time.perf_counter()
            ok = True
            for name in entry["blobs"]:
                hit = self._read_blob(name)
                if hit is None:
                    ok = False
                    break
                tkey, fn = hit
                with self._lock:
                    self._loaded[tkey] = fn
            if not ok:
                with self._lock:
                    self._entries.pop(kstr, None)
                    self._dropped.add(kstr)
                    self._write_index()
                continue
            report.append({
                "key": kstr,
                "blobs": len(entry["blobs"]),
                "wall_ms": round(
                    (time.perf_counter() - t0) * 1000.0, 1
                ),
            })
        self.restore_ms = round(
            (time.perf_counter() - t_all) * 1000.0, 1
        )
        self._reg().gauge(
            "ia_excache_disk_restore_ms",
            "wall of the last daemon-start disk executable restore "
            "(deserialize every sealed entry, before port announce)",
        ).set(self.restore_ms)
        return report

    def warmup_shapes(self) -> List[Dict[str, Any]]:
        """Sealed entries' client-visible shapes as warmup manifest
        entries — the restart warmup replays the persisted working
        set even when the operator's manifest is empty or stale."""
        out = []
        with self._lock:
            for entry in self._entries.values():
                ws = entry.get("warmup_shape")
                if isinstance(ws, list) and len(ws) == 3:
                    out.append({
                        "height": int(ws[0]), "width": int(ws[1]),
                        "channels": int(ws[2]),
                    })
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "root": self.root,
                "enabled": self.enabled,
                "entries": len(self._entries),
                "loaded": len(self._loaded),
                "stored": self.stored,
                "errors": self.errors,
                "restore_ms": self.restore_ms,
                "fingerprint": dict(self._fp),
            }
