"""Durable request journal for the serving daemon (round 16 tentpole).

The daemon is a single in-memory process: before this module, a crash
lost every queued/admitted request with nothing on disk to say they
ever existed.  The journal closes that window with a write-ahead
ledger: every ADMITTED request is appended to
``<state-dir>/journal.jsonl`` BEFORE the client's ack/response path
runs, and marked on completion — so the set of acknowledged-but-
unfinished requests is always recoverable from disk, and
``ia-synth serve --takeover <state-dir>`` replays exactly that set
through the successor's normal queue (bit-identical responses via the
per-request PRNG / ``_b_stats`` isolation contract; see
serving/daemon.py).

Record grammar (one JSON object per line):

  {"kind": "req",  "request_id": ..., "ts": ..., "manifest": {...}}
  {"kind": "mark", "request_id": ..., "outcome": "done" | "replayed"
                                                 | "cancelled"}

``manifest`` is the client's parsed request body (shape/dtype/
image_b64/session_id/...), complete enough for
``daemon._frame_from_manifest`` to reconstruct an identical
``ServeRequest`` on replay.  A ``mark`` retires one ``req``:

  - ``done``       — response written by the process that admitted it;
  - ``replayed``   — completed by a successor after takeover;
  - ``cancelled``  — retired without synthesis (client socket gone,
                     deadline already blown).

The ledger invariant the ``check_serving_recovery`` sentinel grades:

  appended == done + replayed + cancelled + pending,   pending >= 0

published as the ``ia_serve_journal_{appended,done,replayed,
cancelled,pending}`` gauges on every append/mark.

Durability mechanics are accesslog.py's, deliberately: one ``os.write``
per line on an O_APPEND descriptor under a lock, size-capped rotation
to ``<path>.1`` with pending-entry compaction (every still-pending
``req`` is re-written into the fresh generation, so no number of
rotations can hide an unretired request from replay; readers walk
``.1`` then live), OSError counted on ``.errors``
rather than raised (a full disk degrades durability accounting, not
availability — the ``serve_diskfull`` fault point exercises exactly
this arm).  A crash mid-write loses at most the torn final line;
``read_entries`` skips it and every completed line still replays.

The pid lockfile (``<state-dir>/daemon.lock``) serializes takeover:
acquiring while the named pid is still alive is refused, a stale pid
is reaped.  One state dir == at most one daemon.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .accesslog import read_entries

JOURNAL_FILE = "journal.jsonl"
LOCK_FILE = "daemon.lock"
DEFAULT_MAX_BYTES = 8 * 1024 * 1024

MARK_OUTCOMES = ("done", "replayed", "cancelled")


def journal_path(state_dir: str) -> str:
    return os.path.join(state_dir, JOURNAL_FILE)


class RequestJournal:
    """Write-ahead request ledger with size-capped rotation.

    Opening scans whatever already exists at `path` (both rotation
    generations, torn-line tolerant) and rebuilds the ledger — the
    successor's view of its predecessor's unfinished work.
    """

    def __init__(self, path: str, max_bytes: int = DEFAULT_MAX_BYTES,
                 registry=None):
        if max_bytes < 1024:
            raise ValueError(f"max_bytes too small ({max_bytes})")
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.registry = registry
        self.errors = 0
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._size = 0
        # rid -> "req" record for appended-but-unmarked requests, in
        # append order (dict preserves insertion order == replay order).
        self._pending: Dict[str, Dict[str, Any]] = {}
        self.appended = 0
        self.marked: Dict[str, int] = {o: 0 for o in MARK_OUTCOMES}
        self._scan()
        self._publish()

    # -- recovery scan --------------------------------------------------

    def _scan(self) -> None:
        """Rebuild the ledger from disk: count every readable ``req``,
        retire the ones a ``mark`` names.  Marks for requests that
        rotated out of both generations are orphans and ignored — the
        ledger only ever books work it can still see."""
        for rec in read_entries(self.path):
            kind = rec.get("kind")
            rid = rec.get("request_id")
            if not isinstance(rid, str):
                continue
            if kind == "req" and isinstance(rec.get("manifest"), dict):
                if rid not in self._pending:
                    self.appended += 1
                self._pending[rid] = rec
            elif kind == "mark":
                outcome = rec.get("outcome")
                if outcome in MARK_OUTCOMES and rid in self._pending:
                    del self._pending[rid]
                    self.marked[outcome] += 1

    # -- write path -----------------------------------------------------

    def _open(self) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._size = os.fstat(self._fd).st_size

    def _write(self, record: Dict[str, Any]) -> bool:
        """One line, one os.write; rotate first when it would overflow.
        OSError is counted, never raised (accesslog contract)."""
        from ..runtime.faults import fire as _fault_fire

        line = (json.dumps(record, sort_keys=True,
                           separators=(",", ":")) + "\n").encode()
        with self._lock:
            try:
                # serve_diskfull: simulate the write failing at the
                # syscall boundary — the counted-not-raised arm.
                if _fault_fire("serve_diskfull", self._fire_seq()) \
                        == "fail":
                    raise OSError("injected serve_diskfull")
                if self._fd is None:
                    self._open()
                if self._size + len(line) > self.max_bytes and self._size:
                    os.close(self._fd)
                    os.replace(self.path, self.path + ".1")
                    self._fd = None
                    self._open()
                    # Compact: re-write every still-pending entry into
                    # the fresh generation, so a pending request can
                    # never rotate out of the replay set no matter how
                    # many rotations pass (the live file may
                    # transiently exceed max_bytes when the pending
                    # backlog itself is that large).
                    for prec in self._pending.values():
                        pline = (json.dumps(
                            prec, sort_keys=True,
                            separators=(",", ":"),
                        ) + "\n").encode()
                        os.write(self._fd, pline)
                        self._size += len(pline)
                os.write(self._fd, line)
                self._size += len(line)
                return True
            except OSError:
                self.errors += 1
                return False

    def _fire_seq(self) -> int:
        # Per-journal write ordinal: the fault-plan key for
        # serve_diskfull ("fail write N counting from 0").
        seq = self.appended + sum(self.marked.values())
        return seq

    def append(self, request_id: str,
               manifest: Dict[str, Any]) -> bool:
        """Journal one admitted request BEFORE its ack path.  Returns
        whether the line hit disk (False == durability degraded, the
        request still serves)."""
        rec = {
            "kind": "req",
            "request_id": str(request_id),
            "ts": round(time.time(), 6),
            "manifest": manifest,
        }
        ok = self._write(rec)
        with self._lock:
            self.appended += 1
            self._pending[str(request_id)] = rec
        self._publish()
        return ok

    def mark(self, request_id: str, outcome: str = "done") -> bool:
        """Retire one journaled request.  Idempotent per rid: only the
        first mark books (duplicate response paths must not unbalance
        the ledger)."""
        if outcome not in MARK_OUTCOMES:
            raise ValueError(
                f"journal outcome {outcome!r} not in {MARK_OUTCOMES}"
            )
        rid = str(request_id)
        with self._lock:
            if rid not in self._pending:
                return False
            del self._pending[rid]
            self.marked[outcome] += 1
        self._write({"kind": "mark", "request_id": rid,
                     "outcome": outcome})
        self._publish()
        return True

    def compact(self) -> int:
        """Rewrite the journal down to its still-pending entries (one
        atomic generation: tmp + replace, then the `.1` rotation file
        is dropped — its retired history is now redundant).  The drain
        path runs this AFTER the session snapshot lands: the pending
        set a takeover successor replays must never be the freshest
        thing on disk while the session snapshot the router was told
        exists is still unwritten, so ordering is sessions first,
        compaction last (a SIGKILL between the two loses only the
        compaction, which the rotation path redoes for free).  Returns
        the number of pending entries kept; OSError is counted, never
        raised (the live journal stays as it was)."""
        with self._lock:
            try:
                tmp = self.path + ".tmp"
                size = 0
                with open(tmp, "wb") as fh:
                    for prec in self._pending.values():
                        pline = (json.dumps(
                            prec, sort_keys=True,
                            separators=(",", ":"),
                        ) + "\n").encode()
                        fh.write(pline)
                        size += len(pline)
                if self._fd is not None:
                    os.close(self._fd)
                    self._fd = None
                os.replace(tmp, self.path)
                self._size = size
                try:
                    os.unlink(self.path + ".1")
                except FileNotFoundError:
                    pass
                return len(self._pending)
            except OSError:
                self.errors += 1
                return 0

    # -- read side ------------------------------------------------------

    def pending_entries(self) -> List[Dict[str, Any]]:
        """Appended-but-unretired ``req`` records, oldest first — the
        takeover replay set."""
        with self._lock:
            return list(self._pending.values())

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {"appended": self.appended,
                   "pending": len(self._pending),
                   "errors": self.errors}
            out.update(self.marked)
        return out

    def _publish(self) -> None:
        reg = self.registry
        if reg is None:
            return
        g = reg.gauge(
            "ia_serve_journal",
            "request-journal ledger (appended == done + replayed + "
            "cancelled + pending)",
        )
        for field, value in self.counts().items():
            if field == "errors":
                continue
            g.set(float(value), labels={"field": field})
        # errors are monotone on self — publish as gauge for dumps.
        reg.gauge(
            "ia_serve_journal_errors",
            "journal write errors counted-not-raised",
        ).set(float(self.errors))

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


# -- state-dir pid lock ------------------------------------------------

def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def acquire_lock(state_dir: str, pid: Optional[int] = None) -> str:
    """Claim `state_dir` for this process.  Refuses (RuntimeError) when
    the lockfile names a pid that is still alive — the double-takeover
    guard — and silently reaps a stale lock (dead pid, unreadable
    file).  Returns the lockfile path."""
    os.makedirs(state_dir, exist_ok=True)
    path = os.path.join(state_dir, LOCK_FILE)
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                holder = int(fh.read().strip() or "0")
        except (OSError, ValueError):
            holder = 0
        if holder and holder != os.getpid() and _pid_alive(holder):
            raise RuntimeError(
                f"state dir {state_dir!r} is locked by live pid "
                f"{holder} ({path}); refusing takeover"
            )
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(str(pid if pid is not None else os.getpid()))
    os.replace(tmp, path)
    return path


def release_lock(state_dir: str) -> None:
    """Drop the lock if THIS process holds it (a successor's lock is
    never clobbered by a predecessor's late exit)."""
    path = os.path.join(state_dir, LOCK_FILE)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            holder = int(fh.read().strip() or "0")
    except (OSError, ValueError):
        return
    if holder == os.getpid():
        try:
            os.unlink(path)
        except OSError:
            pass
