"""The synthesis daemon — synthesis-as-a-service over the existing
runners (round 13 tentpole; serving/excache.py holds the compiled-
executable cache, serving/queueing.py the batching/admission policy,
and `ia-synth serve` in cli.py the front door).

One long-lived process, one style pair: the daemon loads (A, A') at
startup (matching the batch runner's shared-style contract) and serves
`POST /synthesize` requests carrying a B image each, on the SAME HTTP
server the per-run exporter uses (`telemetry/live.py`, generalized
this round to take injected routes and a health callback) — so
`/metrics`, `/healthz`, and the `live.json` rendezvous file work
identically for a daemon and a run.

Request lifecycle (the span names, in order):

    queued      handler thread validated + enqueued the request
    admitted    dispatcher popped it into a batch
    cache-hit | compiled
                the executable cache's verdict for the dispatch
    executed    the batch dispatch returned
    demuxed     this request's output row was fanned back out

Isolation contract — a request's output NEVER depends on its
co-tenants.  Two constructions enforce it:

  - PRNG: every dispatch passes `frame_indices=[0]*grain` to
    `synthesize_batch`, so each frame gets the key stream of a solo
    single-frame run regardless of batch position.
  - Luminance statistics: the batch runner normalizes style luminance
    over the whole stack, which would leak co-tenant statistics into
    every output.  The daemon instead computes each request's (mu,
    sigma) at admission, quantizes both to 1/32 buckets, makes the
    bucket part of the batching-compatibility key, and passes the
    BUCKET CENTER as the dispatch's canonical stats — so a request's
    remap depends only on its own bucket, not on who shared its
    batch.  (The quantization perturbs the remap by at most half a
    bucket — the price of batchability, stated here rather than
    hidden.)

Static batch grain: every dispatch is padded (last frame repeated) to
exactly `max_batch` frames, because the batch runner's executables are
shape-specialized over the frame axis — variable batch sizes would
give each occupancy level its own compile and make the executable
cache's "repeat shape = hit" claim false.  The ballast rows are
trimmed before demux; the waste is bounded by (max_batch - 1) frames
per dispatch and shrinks to zero at full occupancy.

Failure containment: each dispatch runs under
`runtime/supervisor.supervise` with `tracer=None` (exception-retry
only — the watchdog's deadline model is calibrated for full runs, not
sub-second serving dispatches) and `ladder=[]` (NO degradation ladder:
every rung flips process-wide kernel modes, which would silently
change co-tenant and future-request outputs).  A give-up maps to HTTP
500 for that batch's requests; the daemon keeps serving.

Session affinity (round 14, video/): a request may carry a
`session_id`, declaring itself the next frame of a video.  The id
joins the batching-compatibility key — a session's frames NEVER
coalesce with strangers (and sessionless traffic, whose compat gains
only a constant None element, batches exactly as before) — and the
dispatcher routes session batches through a per-session
`video.VideoStream` held in an LRU table (`max_sessions`), so
consecutive frames warm-start from the session's carried NNF state
and pay the delta-sized schedule instead of the full cold pyramid.
Deliberate contract changes inside a session: output DEPENDS on
session history (that is the point), the remap statistics freeze on
the session's OPENING frame's luma bucket (a stream must remap every
frame against one style normalization or the style itself flickers),
and a failed dispatch fails its requests AND resets the session to
cold (the supervisor's retry ladder is calibrated for stateless
dispatches; replaying a half-stepped stream would double-book its
ledger).  Session dispatches still consult the executable cache
(keyed at the stream's own batch-1 grain) so the serving sentinel's
`hits + misses == dispatches` ledger stays exact.
"""

from __future__ import annotations

import base64
import json
import os
import re
import shutil
import tempfile
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .accesslog import AccessLog
from .excache import ExecutableCache, exec_key, key_str, run_warmup
from .queueing import (
    AdmissionController,
    BatchingPolicy,
    RequestQueue,
    ServeRequest,
    demux,
)

# Luminance-stats quantization grain (buckets of 1/32 in both mu and
# sigma): fine enough that the canonical-stats remap is visually
# indistinguishable from exact stats, coarse enough that same-source
# request streams actually coalesce.
LUMA_BUCKET = 32.0

REQUEST_TIMEOUT_S = 600.0

# Client-supplied X-Request-Id values must be short and safe (they land
# in logs, span attrs, and metrics labels verbatim); anything else is
# ignored and a server id generated instead.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def _request_id_from_headers(headers) -> str:
    """The request's id: the client's `X-Request-Id` when present and
    well-formed (so a caller can correlate daemon telemetry with its
    own), else a fresh server-generated one."""
    if headers:
        for k, v in headers.items():
            if str(k).lower() == "x-request-id" \
                    and isinstance(v, str) and _REQUEST_ID_RE.match(v):
                return v
    return uuid.uuid4().hex[:12]


def _phase_attribution(req: ServeRequest,
                       total_ms: float) -> Dict[str, float]:
    """queue/compile/execute/demux millis from the request's lifecycle
    events plus its dispatch's prologue wall — the critical-path split
    the access log carries and `ia-synth trace` renders.

    Definitions (all relative offsets from enqueue, so they tile):
      queue_ms   = enqueue -> admitted
      compile_ms = the dispatch's prologue wall (0 when none carried),
                   clamped into the execution window
      execute_ms = cache-verdict -> executed, minus compile_ms
      demux_ms   = executed -> the response (demux + settle + handler
                   wakeup — everything after the engine returned)
    The parts deliberately sum to total_ms minus only the sub-ms
    admitted -> cache-verdict preamble, which is what lets the trace
    CLI assert its 5%% reconstruction bound."""
    t = {ev["name"]: ev["t_ms"] for ev in req.spans}
    out: Dict[str, float] = {}
    if "admitted" in t:
        out["queue_ms"] = round(t["admitted"], 3)
    verdict = t.get("cache-hit", t.get("compiled"))
    executed = t.get("executed")
    if executed is not None and verdict is not None:
        window = max(0.0, executed - verdict)
        c = min(float(req.compile_ms or 0.0), window)
        out["compile_ms"] = round(c, 3)
        out["execute_ms"] = round(window - c, 3)
        out["demux_ms"] = round(max(0.0, total_ms - executed), 3)
    return out


def _luma_bucket(frame: np.ndarray) -> Optional[Tuple[float, float]]:
    """(mu, sigma) of the frame's luminance, quantized to LUMA_BUCKET
    bucket CENTERS — the canonical statistics this request will be
    remapped under (and batched by)."""
    if frame.ndim == 3 and frame.shape[2] == 3:
        y = (
            0.299 * frame[..., 0] + 0.587 * frame[..., 1]
            + 0.114 * frame[..., 2]
        )
    else:
        y = frame[..., 0] if frame.ndim == 3 else frame
    mu, sigma = float(np.mean(y)), float(np.std(y))
    return (
        (np.floor(mu * LUMA_BUCKET) + 0.5) / LUMA_BUCKET,
        (np.floor(sigma * LUMA_BUCKET) + 0.5) / LUMA_BUCKET,
    )


class SynthDaemon:
    """The daemon: queue + dispatcher + executable cache + HTTP front
    end, all instrumented into one injected registry.

    `start()` binds the (generalized) live-telemetry server with the
    serving routes mounted, runs the warmup manifest, and starts the
    dispatcher thread; `stop()` drains.  The caller owns process-level
    wiring (installing the registry as process default so engine
    counters land in it, flight-recorder signal hooks, live.json
    announcement) — cli.cmd_serve is the reference harness."""

    def __init__(
        self,
        a,
        ap,
        cfg,
        *,
        registry,
        tracer=None,
        mesh=None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: Optional[int] = None,
        max_wait_ms: float = 25.0,
        max_queue_depth: int = 32,
        cache_capacity: int = 8,
        max_retries: int = 1,
        max_sessions: int = 16,
        flight=None,
        work_dir: Optional[str] = None,
        observability: bool = True,
        access_log_path: Optional[str] = None,
        slo_window_s: float = 300.0,
    ):
        from ..parallel.batch import make_mesh
        from ..telemetry.slo import SloEngine

        self.a = np.asarray(a, np.float32)
        self.ap = np.asarray(ap, np.float32)
        self.cfg = cfg
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self.mesh = mesh or make_mesh()
        if max_batch is None:
            max_batch = max(1, int(self.mesh.devices.size))
        self.policy = BatchingPolicy(
            max_batch=max_batch, max_wait_ms=max_wait_ms
        )
        self.admission = AdmissionController(
            max_depth=max_queue_depth, registry=registry
        )
        self.cache = ExecutableCache(
            capacity=cache_capacity, registry=registry
        )
        self.queue = RequestQueue()
        self.max_retries = int(max_retries)
        if max_sessions < 1:
            raise ValueError(
                f"max_sessions must be >= 1 ({max_sessions})"
            )
        self.max_sessions = int(max_sessions)
        # session_id -> video.VideoStream, LRU-evicted at capacity.
        # Touched only by the dispatcher thread (routes read len()).
        self._sessions: "OrderedDict[str, Any]" = OrderedDict()
        self.host = host
        self._requested_port = port
        self.live = None  # LiveTelemetryServer after start()
        self._work_dir = work_dir
        self._own_work_dir = work_dir is None
        self._inflight = 0
        self._stop = threading.Event()
        self._dispatcher: Optional[threading.Thread] = None
        # Round 15 observability: per-request span trees + run-subtree
        # tracer + structured access log, all gated on ONE switch so
        # the overhead-pin harness can run a bit-identical bare arm.
        # (The request-duration histogram and request ids stay on
        # either way — they ARE the response contract.)
        self.observability = bool(observability)
        self._access_log_path = access_log_path
        self.access: Optional[AccessLog] = None
        self.slo = SloEngine(registry, window_s=slo_window_s)
        self._init_metrics()

    # ------------------------------------------------------- metrics
    def _init_metrics(self) -> None:
        r = self.registry
        self._c_requests = r.counter(
            "ia_serve_requests_total",
            "well-formed synthesis requests received (before the "
            "admission decision; booked first so admitted + shed can "
            "never outrun it)",
        )
        self._c_admitted = r.counter(
            "ia_serve_admitted_total", "requests admitted to the queue"
        )
        self._c_shed = r.counter(
            "ia_serve_shed_total",
            "requests shed with 429 + Retry-After (admission control)",
        )
        self._c_completed = r.counter(
            "ia_serve_completed_total", "requests answered 200"
        )
        self._c_failed = r.counter(
            "ia_serve_failed_total",
            "admitted requests answered 5xx (supervisor give-up or "
            "dispatch error)",
        )
        self._c_dispatches = r.counter(
            "ia_serve_dispatches_total",
            "batch dispatches onto the engine, by kind "
            "(client/warmup); every dispatch consults the executable "
            "cache exactly once",
        )
        self._g_depth = r.gauge(
            "ia_serve_queue_depth", "requests waiting in the queue"
        )
        self._g_inflight = r.gauge(
            "ia_serve_inflight",
            "requests inside the currently-executing dispatch",
        )
        self._h_latency = r.histogram(
            "ia_serve_request_ms",
            "serving request latency by lifecycle phase (ms): queued "
            "= enqueue->admitted, service = admitted->done, total = "
            "enqueue->done",
        )
        from ..telemetry.slo import (
            REQUEST_DURATION_BUCKETS,
            REQUEST_DURATION_METRIC,
        )

        # The SLO engine's raw material: one observation per request
        # at response time, labelled with outcome and cache verdict —
        # explicit buckets chosen so every latency objective threshold
        # is an exact bound (telemetry/slo.py).
        self._h_duration = r.histogram(
            REQUEST_DURATION_METRIC,
            "end-to-end request latency (ms) by route/outcome/cache — "
            "the raw family the SLO objectives are evaluated from",
            buckets=REQUEST_DURATION_BUCKETS,
        )
        self._g_depth.set(0)
        self._g_inflight.set(0)

    # ------------------------------------------------------ lifecycle
    def start(self) -> "SynthDaemon":
        from ..telemetry.live import LiveTelemetryServer
        from ..telemetry.spans import as_tracer

        if self.tracer is None:
            self.tracer = as_tracer(None)
        if self._own_work_dir:
            self._work_dir = tempfile.mkdtemp(prefix="ia-serve-")
        if self.observability:
            self.access = AccessLog(
                self._access_log_path
                or os.path.join(self._work_dir, "access.jsonl")
            )
        self.live = LiveTelemetryServer(
            self.tracer,
            self.registry,
            port=self._requested_port,
            host=self.host,
            flight=self.flight,
            health_cb=self.health,
            routes={
                ("POST", "/synthesize"): self._route_synthesize,
                ("GET", "/serving"): self._route_serving,
                ("GET", "/slo"): self._route_slo,
            },
        ).start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="ia-serve-dispatch",
            daemon=True,
        )
        self._dispatcher.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for req in self.queue.drain():
            req.status = "failed"
            req.error = "daemon shutting down"
            self._c_failed.inc()
            req.done.set()
        self._g_depth.set(0)
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=30.0)
            self._dispatcher = None
        if self.live is not None:
            self.live.stop()
            self.live = None
        if self.access is not None:
            self.access.close()
            self.access = None
        if self._own_work_dir and self._work_dir:
            shutil.rmtree(self._work_dir, ignore_errors=True)

    @property
    def url(self) -> str:
        return self.live.url

    # -------------------------------------------------------- warmup
    def warmup(self, entries: List[Dict[str, Any]]) -> List[Dict]:
        """Compile the manifest's shapes through the real dispatch
        path BEFORE announcing the endpoint (cli.cmd_serve orders it
        so): rendezvous implies warm."""

        def dispatch(shape):
            frame = np.zeros(shape, np.float32)
            req = self._make_request(frame)
            self._execute([req], kind="warmup")
            if req.status != "ok":
                raise RuntimeError(
                    f"warmup dispatch failed for shape {shape}: "
                    f"{req.error}"
                )

        return run_warmup(
            entries, dispatch, self.cache,
            lambda shape: exec_key(shape, self.cfg, self.policy.max_batch),
        )

    # ------------------------------------------------------- serving
    def _make_request(self, frame: np.ndarray,
                      session: Optional[str] = None,
                      req_id: Optional[str] = None) -> ServeRequest:
        # Session dispatches run one frame at a time through the
        # stream's own solo-mesh executables, so their cache identity
        # is the batch-1 grain, not the daemon's padding grain.
        grain = 1 if session is not None else self.policy.max_batch
        key = exec_key(frame.shape, self.cfg, grain)
        bucket = None
        if self.cfg.color_mode == "luminance" and \
                self.cfg.luminance_remap:
            bucket = _luma_bucket(frame)
        kwargs = {"req_id": req_id} if req_id else {}
        return ServeRequest(
            frame=frame, key=key, compat=key + (bucket, session),
            b_stats=bucket, session=session, **kwargs,
        )

    def _route_synthesize(self, body: Optional[bytes], headers=None):
        """POST /synthesize handler (runs on an HTTP handler thread):
        assign/accept the request id -> validate -> admit-or-shed ->
        enqueue -> block on completion.  Every exit echoes
        `request_id` in the body (the machine-parseable error
        contract), books the `ia_request_duration_ms` cell for its
        outcome, and appends the structured access-log line."""
        rid = _request_id_from_headers(headers)
        t_in = time.monotonic()
        bytes_in = len(body) if body else 0
        try:
            manifest = _parse_manifest(body)
            frame = _frame_from_manifest(manifest)
            session = _session_from_manifest(manifest)
        except ValueError as e:
            payload = _json_bytes({
                "status": "rejected", "error": str(e),
                "request_id": rid,
            })
            self._book_response(
                rid, None, "rejected", 400,
                (time.monotonic() - t_in) * 1000.0, bytes_in,
                len(payload),
            )
            return 400, payload, "application/json"
        req = self._make_request(frame, session, req_id=rid)
        req.span("queued")
        # Requests books FIRST (the serving sentinel check's ordering
        # contract), then exactly one of admitted/shed.
        self._c_requests.inc()
        ok, retry_after = self.admission.admit(
            len(self.queue), self._inflight
        )
        if not ok:
            self._c_shed.inc()
            payload = _json_bytes({
                "status": "shed",
                "error": "shed by admission control (queue at "
                         "capacity); retry after retry_after_s",
                "request_id": rid,
                "retry_after_s": retry_after,
            })
            self._book_response(
                rid, req, "shed", 429,
                (time.monotonic() - t_in) * 1000.0, bytes_in,
                len(payload),
            )
            return (
                429, payload, "application/json",
                {"Retry-After": str(int(np.ceil(retry_after)))},
            )
        self._c_admitted.inc()
        self.queue.put(req)
        self._g_depth.set(len(self.queue))
        if not req.done.wait(REQUEST_TIMEOUT_S):
            # The client gives up, but the request is still queued or
            # in flight: the DISPATCHER still owns its ledger entry
            # and will book completed/failed when it settles — booking
            # failed here too would double-count the admission ledger
            # the serving sentinel check balances.
            req.error = "request timed out in the daemon"
            payload = _json_bytes({
                "status": "failed", "request_id": rid,
                "error": req.error,
            })
            self._book_response(
                rid, req, "timeout", 504,
                (time.monotonic() - req.enqueue_t) * 1000.0, bytes_in,
                len(payload),
            )
            return 504, payload, "application/json"
        total_ms = (time.monotonic() - req.enqueue_t) * 1000.0
        self._h_latency.observe(total_ms, labels={"phase": "total"})
        if req.status != "ok":
            payload = _json_bytes({
                "status": "failed", "request_id": rid,
                "error": req.error, "spans": req.spans,
            })
            self._book_response(
                rid, req, "failed", 500, total_ms, bytes_in,
                len(payload),
            )
            return 500, payload, "application/json"
        out = np.asarray(req.result, np.float32)
        payload = _json_bytes({
            "status": "ok",
            "request_id": rid,
            "cache": req.cache,
            "batch_size": req.batch_size,
            "wall_ms": round(total_ms, 3),
            "spans": req.spans,
            "shape": list(out.shape),
            "dtype": "float32",
            "image_b64": base64.b64encode(
                np.ascontiguousarray(out).tobytes()
            ).decode(),
        })
        self._book_response(
            rid, req, "ok", 200, total_ms, bytes_in, len(payload)
        )
        return 200, payload, "application/json"

    def _book_response(self, rid: str, req: Optional[ServeRequest],
                       outcome: str, code: int, total_ms: float,
                       bytes_in: int, bytes_out: int) -> None:
        """Response-time bookkeeping, one call per exit path: the
        request-duration observation (always — it is the SLO engine's
        raw material) and the access-log line (observability only)."""
        cache = req.cache if req is not None and req.cache else "none"
        self._h_duration.observe(total_ms, labels={
            "route": "/synthesize", "outcome": outcome, "cache": cache,
        })
        if self.access is None:
            return
        entry: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "request_id": rid,
            "route": "/synthesize",
            "outcome": outcome,
            "http_status": code,
            "total_ms": round(total_ms, 3),
            "bytes_in": bytes_in,
            "bytes_out": bytes_out,
        }
        if req is not None:
            entry["t0"] = round(req.t0, 6)
            entry["session_id"] = req.session
            entry["exec_key"] = key_str(req.key)
            entry["cache"] = req.cache
            entry["batch_size"] = req.batch_size
            entry.update(_phase_attribution(req, total_ms))
        self.access.log(entry)

    def _route_slo(self, _body):
        """GET /slo: grade the declarative objectives over the sliding
        window and publish the burn-rate gauges — evaluation happens
        HERE (pull), never on the request hot path."""
        return 200, _json_bytes(self.slo.evaluate()), "application/json"

    def _route_serving(self, _body):
        """GET /serving: the operator's one-look snapshot — queue /
        in-flight occupancy, cache residency, and the SLO quantiles."""
        snap = {
            "queue_depth": len(self.queue),
            "inflight": self._inflight,
            "policy": {
                "max_batch": self.policy.max_batch,
                "max_wait_ms": self.policy.max_wait_ms,
                "max_queue_depth": self.admission.max_depth,
                "effective_queue_depth": self.admission.effective_depth(),
            },
            "cache": self.cache.snapshot(),
            "sessions": {
                "active": len(self._sessions),
                "max": self.max_sessions,
                "frames": {
                    sid: stream.t
                    for sid, stream in self._sessions.items()
                },
            },
            "slo_ms": {
                phase: {
                    "p50": self._h_latency.quantile(
                        0.5, labels={"phase": phase}
                    ),
                    "p99": self._h_latency.quantile(
                        0.99, labels={"phase": phase}
                    ),
                }
                for phase in ("queued", "service", "total")
            },
        }
        return 200, _json_bytes(snap), "application/json"

    def health(self) -> Dict[str, Any]:
        """/healthz callback: the full sentinel evaluation (which now
        includes the serving ledger check) against the daemon's
        registry."""
        from ..telemetry.sentinel import evaluate_health

        return evaluate_health(
            metrics=self.registry.to_dict(), context="serving"
        )

    # ---------------------------------------------------- dispatcher
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.next_batch(self.policy, timeout=0.25)
            if batch is None:
                continue
            self._g_depth.set(len(self.queue))
            try:
                self._execute(batch, kind="client")
            except BaseException as e:  # noqa: BLE001 - daemon survives
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "serving dispatch error"
                )
                for req in batch:
                    if not req.done.is_set():
                        req.status = "failed"
                        req.error = f"{type(e).__name__}: {e}"
                        self._c_failed.inc()
                        req.done.set()

    def _admit_batch(self, batch: List[ServeRequest],
                     kind: str) -> float:
        """Shared dispatch preamble: admission spans/latency, the
        in-flight gauges, the dispatch counter, and the executable-
        cache verdict (booked exactly once per dispatch — the serving
        sentinel's `hits + misses == dispatches` contract).  Returns
        the admission timestamp."""
        admit_t = time.monotonic()
        for req in batch:
            req.span("admitted")
            req.batch_size = len(batch)
            self._h_latency.observe(
                (admit_t - req.enqueue_t) * 1000.0,
                labels={"phase": "queued"},
            )
        self._inflight = len(batch)
        self._g_inflight.set(len(batch))
        self._c_dispatches.inc(labels={"kind": kind})
        cache_status = self.cache.lookup(
            batch[0].key, kind=kind, request_id=batch[0].req_id
        )
        span_name = "cache-hit" if cache_status == "hit" else "compiled"
        for req in batch:
            req.cache = cache_status
            req.span(span_name)
        return admit_t

    def _settle_batch(self, batch: List[ServeRequest],
                      admit_t: float, run_roots=(),
                      compile_ms: Optional[float] = None) -> None:
        """Shared dispatch epilogue: per-request span trees grafted
        onto the daemon tracer, service latency, done events, and the
        in-flight gauges back to idle.  `compile_ms` (the dispatch's
        prologue wall) is stamped on every co-tenant BEFORE `done`
        fires, so the handler thread's access-log line sees it."""
        for req in batch:
            req.compile_ms = compile_ms
        if self.observability:
            try:
                self._attach_request_trees(batch, run_roots)
            except Exception:  # noqa: BLE001 - never fail the dispatch
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "per-request span tree construction failed"
                )
        service_ms = (time.monotonic() - admit_t) * 1000.0
        for req in batch:
            self._h_latency.observe(
                service_ms, labels={"phase": "service"}
            )
            req.done.set()
        self._inflight = 0
        self._g_inflight.set(0)

    def _attach_request_trees(self, batch: List[ServeRequest],
                              run_roots) -> None:
        """Convert each request's lifecycle events into ONE real span
        tree — `serve_request` root spanning enqueue -> settle, one
        child interval per lifecycle event (each reaching to the next
        event), the dispatch's run->level subtree grafted under the
        batch LEAD's root (once, not per co-tenant; co-tenants carry a
        `run_in` pointer) — and graft it onto the daemon tracer, where
        the flight recorder, /progress, and check_report already look.
        Runs on the dispatcher thread only, after the dispatch, so the
        tracer's span stack is untouched (module docstring of
        serving/queueing.py: why lifecycle events can't be live
        spans)."""
        if self.tracer is None or not self.tracer.enabled:
            return
        from ..telemetry.spans import span_at

        settle_perf = time.perf_counter()
        lead = batch[0]
        for req in batch:
            base = req.enqueue_perf
            rel_end = (settle_perf - base) * 1000.0
            events = [(ev["name"], float(ev["t_ms"]))
                      for ev in req.spans]
            root = span_at(
                "serve_request", base, settle_perf,
                request_id=req.req_id, session=req.session,
                cache=req.cache, batch_size=req.batch_size,
                outcome=req.status,
            )
            for i, (name, t_ms) in enumerate(events):
                nxt = (events[i + 1][1] if i + 1 < len(events)
                       else rel_end)
                root.children.append(span_at(
                    name, base + t_ms / 1000.0,
                    base + max(t_ms, nxt) / 1000.0,
                ))
            if req is lead and run_roots:
                root.children.extend(run_roots)
                root.attrs["run_attached"] = len(run_roots)
            elif run_roots:
                root.attrs["run_in"] = lead.req_id
            self.tracer.attach_tree(root)

    def _execute(self, batch: List[ServeRequest],
                 kind: str = "client") -> None:
        """One dispatch: cache verdict -> pad to the static grain ->
        supervised `synthesize_batch` -> demux -> settle requests.
        Session batches (compat pins them to one session id) detour
        through the per-session warm-start stream instead."""
        import dataclasses

        from ..parallel.batch import synthesize_batch
        from ..runtime.supervisor import SupervisorGaveUp, supervise

        if batch[0].session is not None:
            self._execute_session(batch, kind=kind)
            return

        grain = self.policy.max_batch
        admit_t = self._admit_batch(batch, kind)

        frames = np.stack([r.frame for r in batch])
        if frames.shape[0] < grain:
            frames = np.concatenate(
                [frames]
                + [frames[-1:]] * (grain - frames.shape[0]), axis=0
            )
        b_stats = batch[0].b_stats
        ckpt_dir = tempfile.mkdtemp(
            prefix="dispatch-", dir=self._work_dir
        )
        cfg = dataclasses.replace(
            self.cfg, save_level_artifacts=ckpt_dir
        )
        # Per-dispatch run tracer (observability on): the batch
        # runner's run->level->em_iter tree, grafted under the batch
        # lead's serve_request root at settle.  Instrumentation only —
        # `synthesize_batch` reads the tracer, never branches numerics
        # on it (the solo-dispatch bit-identity test pins this) — and
        # LEAN: the runner keeps the span tree but skips its optional
        # per-level device readbacks (energy means, shard-sync walls),
        # so request tracing adds no device syncs to the hot path.
        run_tracer = None
        if self.observability and self.tracer is not None \
                and self.tracer.enabled:
            from ..telemetry.spans import Tracer

            run_tracer = Tracer(lean=True)

        def attempt(resume_from):
            return synthesize_batch(
                self.a, self.ap, frames, cfg, self.mesh,
                progress=run_tracer,
                resume_from=resume_from,
                frame_indices=[0] * grain,
                _b_stats=b_stats,
            )

        try:
            out = supervise(
                attempt,
                ckpt_dir=ckpt_dir,
                tracer=None,
                registry=self.registry,
                max_retries=self.max_retries,
                ladder=[],
                backoff_s=0.05,
                max_backoff_s=1.0,
            )
            out = np.asarray(out, np.float32)
            for req in batch:
                req.span("executed")
            demux(batch, out[: len(batch)])
            for req in batch:
                if kind == "client":
                    self._c_completed.inc()
        except SupervisorGaveUp as e:
            for req in batch:
                req.status = "failed"
                req.error = f"supervisor gave up: {e}"
                if kind == "client":
                    self._c_failed.inc()
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
            run_roots, compile_ms = (), None
            if run_tracer is not None:
                run_roots = tuple(run_tracer.roots)
                walls = [
                    sp.wall_ms for sp in run_tracer.find("prologue")
                    if sp.wall_ms is not None
                ]
                if walls:
                    compile_ms = round(sum(walls), 3)
            self._settle_batch(
                batch, admit_t, run_roots=run_roots,
                compile_ms=compile_ms,
            )

    # ---------------------------------------------- session dispatch
    def _session_stream(self, sid: str, proto: ServeRequest):
        """The session's VideoStream, created on first use (remap
        stats pinned to the opening frame's luma bucket) and LRU-
        evicted at `max_sessions` — an evicted session's next frame
        simply opens a new stream and runs cold."""
        stream = self._sessions.get(sid)
        if stream is not None:
            self._sessions.move_to_end(sid)
            return stream
        import dataclasses

        from ..video.sequence import VideoStream

        cfg = dataclasses.replace(self.cfg, save_level_artifacts=None)
        stream = VideoStream(
            self.a, self.ap, cfg=cfg, b_stats=proto.b_stats,
            registry=self.registry,
        )
        self._sessions[sid] = stream
        while len(self._sessions) > self.max_sessions:
            evicted, _ = self._sessions.popitem(last=False)
            import logging

            logging.getLogger("image_analogies_tpu").info(
                "serving session %s evicted (LRU, %d resident)",
                evicted, len(self._sessions),
            )
        return stream

    def _execute_session(self, batch: List[ServeRequest],
                         kind: str = "client") -> None:
        """One session dispatch: the batch (all one session, by
        compat) steps through the session's warm-start stream in
        arrival order.  No supervisor: a failed step leaves the
        stream's carried state unsettled, so the dispatch fails its
        requests and RESETS the session — the next frame opens a
        fresh stream and runs cold (module docstring)."""
        sid = batch[0].session
        admit_t = self._admit_batch(batch, kind)
        try:
            stream = self._session_stream(sid, batch[0])
            outs = []
            for req in batch:
                outs.append(np.asarray(
                    stream.step(req.frame, request_id=req.req_id),
                    np.float32,
                ))
            for req in batch:
                req.span("executed")
            demux(batch, outs)
            for req in batch:
                if kind == "client":
                    self._c_completed.inc()
        except BaseException as e:  # noqa: BLE001 - daemon survives
            import logging

            logging.getLogger("image_analogies_tpu").exception(
                "serving session %s dispatch error (session reset)", sid
            )
            self._sessions.pop(sid, None)
            for req in batch:
                if not req.done.is_set():
                    req.status = "failed"
                    req.error = f"{type(e).__name__}: {e}"
                    if kind == "client":
                        self._c_failed.inc()
        finally:
            self._settle_batch(batch, admit_t)


# ------------------------------------------------------------- payloads
def _json_bytes(obj) -> bytes:
    return (json.dumps(obj) + "\n").encode()


def _decode_request(body: Optional[bytes]) -> np.ndarray:
    """Parse a /synthesize payload into one float32 (H, W, C) frame.

    Wire format: JSON {"image_b64": base64 of the raw pixel buffer,
    "shape": [H, W, C], "dtype": "float32"|"uint8", optional
    "session_id": str} — raw buffers rather than PNG so the daemon has
    zero image-codec dependencies on the hot path (uint8 payloads are
    scaled to [0, 1]).  Raises ValueError (-> HTTP 400) on any
    malformation.  (The route handler parses the manifest once and
    pulls frame + session separately; this wrapper is the frame-only
    convenience the tests and warmup path use.)"""
    return _frame_from_manifest(_parse_manifest(body))


def _parse_manifest(body: Optional[bytes]) -> dict:
    if not body:
        raise ValueError("empty request body")
    try:
        manifest = json.loads(body.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"request body is not JSON: {e}") from None
    if not isinstance(manifest, dict):
        raise ValueError("request body is not a JSON object")
    return manifest


def _session_from_manifest(manifest: dict) -> Optional[str]:
    """The optional session-affinity id: a non-empty string of at most
    64 characters (the compat key embeds it verbatim; the bound keeps
    a hostile client from inflating queue snapshots and logs)."""
    sid = manifest.get("session_id")
    if sid is None:
        return None
    if not isinstance(sid, str) or not 1 <= len(sid) <= 64:
        raise ValueError(
            "session_id must be a non-empty string of <= 64 characters"
        )
    return sid


def _frame_from_manifest(manifest: dict) -> np.ndarray:
    shape = manifest.get("shape")
    if (
        not isinstance(shape, list) or len(shape) != 3
        or not all(isinstance(d, int) and d >= 1 for d in shape)
        or shape[2] not in (1, 3)
    ):
        raise ValueError(
            f"shape {shape!r} is not [H, W, C] with C in (1, 3)"
        )
    dtype = manifest.get("dtype", "float32")
    if dtype not in ("float32", "uint8"):
        raise ValueError(f"dtype {dtype!r} not in ('float32', 'uint8')")
    b64 = manifest.get("image_b64")
    if not isinstance(b64, str):
        raise ValueError("image_b64 missing")
    try:
        raw = base64.b64decode(b64, validate=True)
    except Exception as e:  # noqa: BLE001 - malformed base64
        raise ValueError(f"image_b64 does not decode: {e}") from None
    want = shape[0] * shape[1] * shape[2] * (4 if dtype == "float32"
                                             else 1)
    if len(raw) != want:
        raise ValueError(
            f"payload is {len(raw)} bytes; shape {shape} x {dtype} "
            f"needs {want}"
        )
    frame = np.frombuffer(raw, np.float32 if dtype == "float32"
                          else np.uint8).reshape(shape)
    if dtype == "uint8":
        frame = frame.astype(np.float32) / 255.0
    else:
        frame = frame.astype(np.float32)
    if shape[2] == 1:
        frame = frame[..., 0]
    return frame
