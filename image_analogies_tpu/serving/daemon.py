"""The synthesis daemon — synthesis-as-a-service over the existing
runners (round 13 tentpole; serving/excache.py holds the compiled-
executable cache, serving/queueing.py the batching/admission policy,
and `ia-synth serve` in cli.py the front door).

One long-lived process, one style pair: the daemon loads (A, A') at
startup (matching the batch runner's shared-style contract) and serves
`POST /synthesize` requests carrying a B image each, on the SAME HTTP
server the per-run exporter uses (`telemetry/live.py`, generalized
this round to take injected routes and a health callback) — so
`/metrics`, `/healthz`, and the `live.json` rendezvous file work
identically for a daemon and a run.

Request lifecycle (the span names, in order):

    queued      handler thread validated + enqueued the request
    admitted    dispatcher popped it into a batch
    cache-hit | compiled
                the executable cache's verdict for the dispatch
    executed    the batch dispatch returned
    demuxed     this request's output row was fanned back out

Isolation contract — a request's output NEVER depends on its
co-tenants.  Two constructions enforce it:

  - PRNG: every dispatch passes `frame_indices=[0]*grain` to
    `synthesize_batch`, so each frame gets the key stream of a solo
    single-frame run regardless of batch position.
  - Luminance statistics: the batch runner normalizes style luminance
    over the whole stack, which would leak co-tenant statistics into
    every output.  The daemon instead computes each request's (mu,
    sigma) at admission, quantizes both to 1/32 buckets, makes the
    bucket part of the batching-compatibility key, and passes the
    BUCKET CENTER as the dispatch's canonical stats — so a request's
    remap depends only on its own bucket, not on who shared its
    batch.  (The quantization perturbs the remap by at most half a
    bucket — the price of batchability, stated here rather than
    hidden.)

Static batch grain: every dispatch is padded (last frame repeated) to
exactly `max_batch` frames, because the batch runner's executables are
shape-specialized over the frame axis — variable batch sizes would
give each occupancy level its own compile and make the executable
cache's "repeat shape = hit" claim false.  The ballast rows are
trimmed before demux; the waste is bounded by (max_batch - 1) frames
per dispatch and shrinks to zero at full occupancy.

Failure containment: each dispatch runs under
`runtime/supervisor.supervise` with `tracer=None` (exception-retry
only — the watchdog's deadline model is calibrated for full runs, not
sub-second serving dispatches) and `ladder=[]` (NO degradation ladder:
every rung flips process-wide kernel modes, which would silently
change co-tenant and future-request outputs).  A give-up maps to HTTP
500 for that batch's requests; the daemon keeps serving.

Session affinity (round 14, video/): a request may carry a
`session_id`, declaring itself the next frame of a video.  The id
joins the batching-compatibility key — a session's frames NEVER
coalesce with strangers (and sessionless traffic, whose compat gains
only a constant None element, batches exactly as before) — and the
dispatcher routes session batches through a per-session
`video.VideoStream` held in an LRU table (`max_sessions`), so
consecutive frames warm-start from the session's carried NNF state
and pay the delta-sized schedule instead of the full cold pyramid.
Deliberate contract changes inside a session: output DEPENDS on
session history (that is the point), the remap statistics freeze on
the session's OPENING frame's luma bucket (a stream must remap every
frame against one style normalization or the style itself flickers),
and a failed dispatch fails its requests AND resets the session to
cold (the supervisor's retry ladder is calibrated for stateless
dispatches; replaying a half-stepped stream would double-book its
ledger).  Session dispatches still consult the executable cache
(keyed at the stream's own batch-1 grain) so the serving sentinel's
`hits + misses == dispatches` ledger stays exact.

Crash resilience (round 16, serving/journal.py): when the daemon is
given a `state_dir` it appends every ADMITTED request to a durable
journal BEFORE acknowledging it, marks the entry retired when the
response is written (`done`), when the client vanished (`cancelled`),
or when a successor re-executed it (`replayed`), and on `--takeover`
replays every un-retired entry through the normal queue — the
isolation contract above (solo PRNG streams + bucket-center luma
stats) is exactly what makes the replayed output bit-identical to the
answer the dead daemon would have produced.  The same state dir
carries the hot-restart hand-off: a graceful drain (SIGTERM or
`POST /drain`) 503s new work, lets in-flight batches and their
response writes finish under a deadline, then snapshots the runtime-
observed warm shapes (`warmup.observed.json`) and every resident
session's carried NNF state for the successor.  A `daemon.lock` file
naming the holder pid makes double-takeover a refused startup, not a
split-brain journal.

Persistent executables + pipelined dispatch (round 18): a state dir
also carries `excache/` — the DISK tier of the executable cache
(serving/excache.DiskExecCache).  The daemon installs the tier as the
engine's process-wide persist hook at start, restores every sealed
executable set from disk BEFORE the port is announced, and on an
in-memory cache miss probes the disk tier for an admission-visible
third verdict: `disk` (span `disk-restored`) — the request runs a
deserialized executable with no jit trace, which is what makes a
restart's first request ~restore-priced instead of compile-priced.
Separately, the dispatcher is split into dispatch and completion
stages over a bounded in-flight window (`pipeline_window`, default 2):
the dispatcher thread pops, admits, and executes batch t+1 while the
completer thread demuxes/settles batch t — host-side response work
overlaps device execution.  Admission control, drain, the journal,
and the gauges all read the lock-guarded in-flight count, so every
round-16 ledger claim holds with the window open; responses stay
bit-identical to solo dispatch because the split moves WHERE settle
runs, never what the engine computes.

Shape-lattice admission (round 20, serving/lattice.py): with
`lattice=` set, sessionless frames are edge-padded up to the smallest
lattice bucket containing them at `_make_request` — BEFORE the
executable key and the luminance bucket are computed, so the key, the
compat identity, the dispatch, and the disk-tier seal all see the
bucket shape — and demux crops each request's output row back to its
true (H, W).  exec_key cardinality is thereby bounded by the lattice
(`lattice.size` executables, all precompiled by warmup before the
port announce) instead of by traffic; frames larger than the top rung
bypass to the round-13 exact-key path with an honest miss, and
session traffic never buckets (a stream's NNF state is sized to its
real frames).  The `ia_lattice_admissions_total{path=...}` counter
and `ia_lattice_bucket_waste_frac` gauge price the trade live, and
`ia_serve_shape_cardinality` splits into `view="raw"` /
`view="bucketed"` cells (the unlabeled cell follows the bucketed
series — what the anomaly watch grades).
"""

from __future__ import annotations

import base64
import json
import os
import queue as stdqueue
import re
import shutil
import tempfile
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .accesslog import AccessLog, find_request
from .excache import (
    OBSERVED_WARMUP_FILE,
    ExecutableCache,
    exec_key,
    key_str,
    run_warmup,
    save_observed_warmup,
)
from .journal import (
    RequestJournal,
    acquire_lock,
    journal_path,
    release_lock,
)
from .queueing import (
    AdmissionController,
    BatchingPolicy,
    RequestQueue,
    ServeRequest,
    demux,
)

# Luminance-stats quantization grain (buckets of 1/32 in both mu and
# sigma): fine enough that the canonical-stats remap is visually
# indistinguishable from exact stats, coarse enough that same-source
# request streams actually coalesce.
LUMA_BUCKET = 32.0

REQUEST_TIMEOUT_S = 600.0

# Client-supplied X-Request-Id values must be short and safe (they land
# in logs, span attrs, and metrics labels verbatim); anything else is
# ignored and a server id generated instead.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def _request_id_from_headers(headers) -> str:
    """The request's id: the client's `X-Request-Id` when present and
    well-formed (so a caller can correlate daemon telemetry with its
    own), else a fresh server-generated one."""
    if headers:
        for k, v in headers.items():
            if str(k).lower() == "x-request-id" \
                    and isinstance(v, str) and _REQUEST_ID_RE.match(v):
                return v
    return uuid.uuid4().hex[:12]


# X-Trace-Hop is a small decimal (the router sends 1; a deeper proxy
# chain counts up).  Anything else is replaced with 1, never rejected.
_TRACE_HOP_RE = re.compile(r"^\d{1,3}$")


def _trace_context_from_headers(headers) -> Dict[str, Any]:
    """Inbound distributed-trace context (round 22): `X-Parent-Span`
    (the upstream hop's span id — the fleet router's, normally) and
    `X-Trace-Hop`.  Returns {} when neither header is present (direct
    untraced traffic pays nothing).  Malformed or oversized values are
    REPLACED with generated/clamped ones and echoed back — the
    round-15 `X-Request-Id` policy: a hostile value must not poison
    logs/span attrs, and a request must never be rejected over
    telemetry decoration."""
    parent = hop = None
    for k, v in (headers or {}).items():
        lk = str(k).lower()
        if lk == "x-parent-span" and isinstance(v, str):
            parent = v
        elif lk == "x-trace-hop" and isinstance(v, str):
            hop = v
    if parent is None and hop is None:
        return {}
    if parent is None or not _REQUEST_ID_RE.match(parent):
        parent = uuid.uuid4().hex[:12]
    hop_n = int(hop) if (hop is not None
                         and _TRACE_HOP_RE.match(hop)) else 1
    return {"parent_span": parent, "hop": hop_n}


def _phase_attribution(req: ServeRequest,
                       total_ms: float) -> Dict[str, float]:
    """queue/compile/execute/demux millis from the request's lifecycle
    events plus its dispatch's prologue wall — the critical-path split
    the access log carries and `ia-synth trace` renders.

    Definitions (all relative offsets from enqueue, so they tile):
      queue_ms   = enqueue -> admitted
      compile_ms = the dispatch's prologue wall (0 when none carried),
                   clamped into the execution window
      restore_ms = the same prologue wall when the cache verdict was
                   `disk` — a deserialize-and-run, NOT a jit compile;
                   booked under its own name (round-18 bugfix) so the
                   SLO histograms and the trace waterfall never blend
                   the restore population into the compile population
                   (compile_ms is 0 on a disk-restored dispatch)
      execute_ms = cache-verdict -> executed, minus compile/restore
      demux_ms   = executed -> the response (demux + settle + handler
                   wakeup — everything after the engine returned)
    The parts deliberately sum to total_ms minus only the sub-ms
    admitted -> cache-verdict preamble, which is what lets the trace
    CLI assert its 5%% reconstruction bound."""
    t = {ev["name"]: ev["t_ms"] for ev in req.spans}
    out: Dict[str, float] = {}
    if "admitted" in t:
        out["queue_ms"] = round(t["admitted"], 3)
    verdict = t.get(
        "cache-hit", t.get("disk-restored", t.get("compiled"))
    )
    executed = t.get("executed")
    if executed is not None and verdict is not None:
        window = max(0.0, executed - verdict)
        c = min(float(req.compile_ms or 0.0), window)
        if "disk-restored" in t:
            out["compile_ms"] = 0.0
            out["restore_ms"] = round(c, 3)
        else:
            out["compile_ms"] = round(c, 3)
        out["execute_ms"] = round(window - c, 3)
        out["demux_ms"] = round(max(0.0, total_ms - executed), 3)
    return out


def _luma_bucket(frame: np.ndarray) -> Optional[Tuple[float, float]]:
    """(mu, sigma) of the frame's luminance, quantized to LUMA_BUCKET
    bucket CENTERS — the canonical statistics this request will be
    remapped under (and batched by)."""
    if frame.ndim == 3 and frame.shape[2] == 3:
        y = (
            0.299 * frame[..., 0] + 0.587 * frame[..., 1]
            + 0.114 * frame[..., 2]
        )
    else:
        y = frame[..., 0] if frame.ndim == 3 else frame
    mu, sigma = float(np.mean(y)), float(np.std(y))
    return (
        (np.floor(mu * LUMA_BUCKET) + 0.5) / LUMA_BUCKET,
        (np.floor(sigma * LUMA_BUCKET) + 0.5) / LUMA_BUCKET,
    )


class SynthDaemon:
    """The daemon: queue + dispatcher + executable cache + HTTP front
    end, all instrumented into one injected registry.

    `start()` binds the (generalized) live-telemetry server with the
    serving routes mounted, runs the warmup manifest, and starts the
    dispatcher thread; `stop()` drains.  The caller owns process-level
    wiring (installing the registry as process default so engine
    counters land in it, flight-recorder signal hooks, live.json
    announcement) — cli.cmd_serve is the reference harness."""

    def __init__(
        self,
        a,
        ap,
        cfg,
        *,
        registry,
        tracer=None,
        mesh=None,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: Optional[int] = None,
        max_wait_ms: float = 25.0,
        max_queue_depth: int = 32,
        cache_capacity: int = 8,
        max_retries: int = 1,
        max_sessions: int = 16,
        flight=None,
        work_dir: Optional[str] = None,
        observability: bool = True,
        access_log_path: Optional[str] = None,
        slo_window_s: float = 300.0,
        state_dir: Optional[str] = None,
        warm_dir: Optional[str] = None,
        drain_deadline_s: float = 30.0,
        dispatch_deadline_s: Optional[float] = None,
        pipeline_window: int = 2,
        warmup_workers: int = 4,
        obs_interval_s: float = 5.0,
        obs_capacity: int = 120,
        anomaly_config=None,
        lattice=None,
        archive_dir: Optional[str] = None,
        archive_interval_s: float = 30.0,
        incident_min_interval_s: float = 60.0,
    ):
        from ..parallel.batch import make_mesh
        from ..telemetry.anomaly import AnomalyDetector
        from ..telemetry.slo import SloEngine
        from ..telemetry.timeseries import TimeSeriesRing

        self.a = np.asarray(a, np.float32)
        self.ap = np.asarray(ap, np.float32)
        self.cfg = cfg
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self.mesh = mesh or make_mesh()
        if max_batch is None:
            max_batch = max(1, int(self.mesh.devices.size))
        self.policy = BatchingPolicy(
            max_batch=max_batch, max_wait_ms=max_wait_ms
        )
        self.admission = AdmissionController(
            max_depth=max_queue_depth, registry=registry
        )
        # Round 20 shape lattice: `lattice` may be a LatticePlan (the
        # CLI's planner output), a LatticeConfig (planned here), or
        # None (off).  Resolved before the executable cache and
        # _init_metrics: the LRU capacity must hold the WHOLE bucket
        # grid — a capacity under the grid makes warmup evict its own
        # work (thrash), silently voiding the warm-before-announce
        # contract — and the lattice metric family registers exactly
        # when the lattice exists.
        self.lattice = None
        self.lattice_plan = None
        if lattice is not None:
            from .lattice import LatticeConfig, plan_lattice

            if isinstance(lattice, LatticeConfig):
                lattice = plan_lattice(lattice)
            self.lattice_plan = lattice
            self.lattice = lattice.lattice
            # Grid + headroom so a trickle of bypass (over-top) keys
            # cannot evict the warm lattice either.
            cache_capacity = max(
                cache_capacity, self.lattice.size + 2
            )
        self.cache = ExecutableCache(
            capacity=cache_capacity, registry=registry
        )
        self.queue = RequestQueue()
        self.max_retries = int(max_retries)
        if max_sessions < 1:
            raise ValueError(
                f"max_sessions must be >= 1 ({max_sessions})"
            )
        self.max_sessions = int(max_sessions)
        # session_id -> video.VideoStream, LRU-evicted at capacity.
        # Touched only by the dispatcher thread (routes read len()).
        self._sessions: "OrderedDict[str, Any]" = OrderedDict()
        self.host = host
        self._requested_port = port
        self.live = None  # LiveTelemetryServer after start()
        self._work_dir = work_dir
        self._own_work_dir = work_dir is None
        # True in-flight REQUEST count, summed across every dispatched-
        # but-unsettled batch (the pipelined dispatcher can hold up to
        # `pipeline_window` of them): admission control, drain, and the
        # inflight gauge all read it, so their round-16 claims survive
        # the window opening past 1.  Lock-guarded because admit runs
        # on the dispatcher thread and settle on the completer.
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._stop = threading.Event()
        self._dispatcher: Optional[threading.Thread] = None
        # Round 18 pipelined dispatch: the dispatcher acquires one
        # window slot per batch, runs the engine, and hands the batch's
        # settle closure (demux + counters + done) to the completer
        # thread, which releases the slot — so host-side response work
        # of batch t overlaps device execution of batch t+1, bounded
        # at `pipeline_window` unsettled batches.  Window 1 degrades
        # to the round-13 serial loop (settle still runs on the
        # completer, but the single slot serializes dispatches).
        if pipeline_window < 1:
            raise ValueError(
                f"pipeline_window must be >= 1 ({pipeline_window})"
            )
        self.pipeline_window = int(pipeline_window)
        self.warmup_workers = int(warmup_workers)
        self._window = threading.BoundedSemaphore(self.pipeline_window)
        self._settle_q: "stdqueue.Queue" = stdqueue.Queue()
        self._completer: Optional[threading.Thread] = None
        self._pipeline_busy = 0
        # Round 18 disk tier (DiskExecCache when state_dir is set).
        self.disk = None
        # Round 15 observability: per-request span trees + run-subtree
        # tracer + structured access log, all gated on ONE switch so
        # the overhead-pin harness can run a bit-identical bare arm.
        # (The request-duration histogram and request ids stay on
        # either way — they ARE the response contract.)
        self.observability = bool(observability)
        self._access_log_path = access_log_path
        self.access: Optional[AccessLog] = None
        self.slo = SloEngine(registry, window_s=slo_window_s)
        # Round 16 resilience state (all inert when state_dir is None
        # except drain, which still quiesces and exits cleanly).
        self.state_dir = state_dir
        # Round 21 shared warm tier: the fleet-shared directory N
        # replicas root their disk executable cache and observed-
        # warmup file under (journal/lock/sessions stay per-replica in
        # state_dir).  None = warm state lives in state_dir, the
        # single-daemon rounds 16-20 layout unchanged.
        self.warm_dir = warm_dir
        self.journal: Optional[RequestJournal] = None
        self.drain_deadline_s = float(drain_deadline_s)
        self.dispatch_deadline_s = dispatch_deadline_s
        self._draining = threading.Event()
        self.drained = threading.Event()
        # Handler threads currently building/writing a response for an
        # ADMITTED request — drain waits for this to hit zero so
        # in-flight responses complete before the process exits (the
        # round-12 SIGTERM handler used to cut them mid-write).
        self._outstanding = 0
        self._outstanding_lock = threading.Lock()
        # Running mean of per-request pad waste over lattice-admitted
        # (non-bypass) traffic — handler threads book it, so guarded.
        self._lattice_lock = threading.Lock()
        self._lattice_waste_sum = 0.0
        self._lattice_waste_n = 0
        # Runtime-observed frame shapes, LRU order — the drift fix for
        # hand-authored warmup manifests: snapshotted to
        # warmup.observed.json and merged into the successor's warmup.
        # With the lattice on this set holds BUCKET shapes (what the
        # successor must actually precompile — the raw-shape long tail
        # would re-fragment its warmup); the raw client shapes are
        # tracked separately for the `view="raw"` cardinality cell.
        self._observed_shapes: "OrderedDict[Tuple[int, ...], None]" = \
            OrderedDict()
        self._observed_raw_shapes: \
            "OrderedDict[Tuple[int, ...], None]" = OrderedDict()
        # Round 19 observatory: windowed time-series ring + live
        # anomaly watches, sampled on one daemon thread.  Interval <= 0
        # disables the whole plane (the overhead-pin harness's bare
        # arm); evaluation never runs on the request hot path.
        self.obs: Optional[TimeSeriesRing] = None
        self.anomaly: Optional[AnomalyDetector] = None
        if obs_interval_s > 0:
            self.obs = TimeSeriesRing(
                registry, interval_s=obs_interval_s,
                capacity=obs_capacity,
            )
            self.anomaly = AnomalyDetector(
                self.obs, registry, config=anomaly_config,
                max_queue_depth=max_queue_depth,
            )
        # Round 23 durable telemetry archive + black box (both built
        # in start(): reload must happen before the first anomaly
        # evaluation, and the routes close over the live objects).
        # Interval <= 0 keeps the archive open (boot/drain records,
        # incidents) but skips the periodic snapshot cadence.
        self.archive_dir = archive_dir
        self.archive = None
        self.incidents = None
        self._archive_interval_s = float(archive_interval_s)
        self._incident_min_interval_s = float(incident_min_interval_s)
        self._archive_last_t = -float("inf")
        self._dispatch_seq = 0  # client-dispatch ordinal (fault keys)
        # request_id -> {"sha256", "shape"} for replayed requests; the
        # chaos harness reads it from GET /journal to assert replay
        # bit-identity against the original acked responses.
        self._replayed: Dict[str, Dict[str, Any]] = {}
        self._init_metrics()

    # ------------------------------------------------------- metrics
    def _init_metrics(self) -> None:
        r = self.registry
        self._c_requests = r.counter(
            "ia_serve_requests_total",
            "well-formed synthesis requests received (before the "
            "admission decision; booked first so admitted + shed can "
            "never outrun it)",
        )
        self._c_admitted = r.counter(
            "ia_serve_admitted_total", "requests admitted to the queue"
        )
        self._c_shed = r.counter(
            "ia_serve_shed_total",
            "requests shed with 429 + Retry-After (admission control)",
        )
        self._c_completed = r.counter(
            "ia_serve_completed_total", "requests answered 200"
        )
        self._c_failed = r.counter(
            "ia_serve_failed_total",
            "admitted requests answered 5xx (supervisor give-up or "
            "dispatch error)",
        )
        self._c_cancelled = r.counter(
            "ia_serve_cancelled_total",
            "admitted requests retired before dispatch (client socket "
            "gone or client deadline already blown in the queue) — a "
            "ledger outcome, not an availability failure",
        )
        self._c_dispatches = r.counter(
            "ia_serve_dispatches_total",
            "batch dispatches onto the engine, by kind "
            "(client/warmup); every dispatch consults the executable "
            "cache exactly once",
        )
        self._g_depth = r.gauge(
            "ia_serve_queue_depth", "requests waiting in the queue"
        )
        self._g_inflight = r.gauge(
            "ia_serve_inflight",
            "requests inside dispatched-but-unsettled batches (summed "
            "across the pipeline window)",
        )
        self._g_pipeline = r.gauge(
            "ia_serve_pipeline_inflight_batches",
            "dispatched-but-unsettled batches (pipelined-dispatch "
            "window occupancy; bounded by pipeline_window)",
        )
        self._h_latency = r.histogram(
            "ia_serve_request_ms",
            "serving request latency by lifecycle phase (ms): queued "
            "= enqueue->admitted, service = admitted->done, total = "
            "enqueue->done",
        )
        from ..telemetry.slo import (
            REQUEST_DURATION_BUCKETS,
            REQUEST_DURATION_METRIC,
        )

        # The SLO engine's raw material: one observation per request
        # at response time, labelled with outcome and cache verdict —
        # explicit buckets chosen so every latency objective threshold
        # is an exact bound (telemetry/slo.py).
        self._h_duration = r.histogram(
            REQUEST_DURATION_METRIC,
            "end-to-end request latency (ms) by route/outcome/cache — "
            "the raw family the SLO objectives are evaluated from",
            buckets=REQUEST_DURATION_BUCKETS,
        )
        self._g_shape_card = r.gauge(
            "ia_serve_shape_cardinality",
            "distinct client frame shapes observed (LRU-bounded at "
            "32), split into view=raw (as sent) and view=bucketed "
            "(post-lattice) cells; the unlabeled cell follows the "
            "bucketed series — the anomaly detector's shape-growth "
            "watch input (raw == bucketed when the lattice is off)",
        )
        self._g_depth.set(0)
        self._g_inflight.set(0)
        self._g_pipeline.set(0)
        self._g_shape_card.set(0)
        self._g_shape_card.set(0, labels={"view": "raw"})
        self._g_shape_card.set(0, labels={"view": "bucketed"})
        if self.lattice is not None:
            self._c_lattice = r.counter(
                "ia_lattice_admissions_total",
                "sessionless admissions through the shape lattice by "
                "path: bucketed (padded up to a bucket), exact "
                "(already on a bucket shape), bypass (over the top "
                "rung — exact-key path, honest miss)",
            )
            self._g_lattice_waste = r.gauge(
                "ia_lattice_bucket_waste_frac",
                "running mean fraction of the bucket canvas that is "
                "pad, over lattice-admitted requests (the per-request "
                "compute price of bounded exec-key cardinality)",
            )
            self._g_lattice_buckets = r.gauge(
                "ia_lattice_buckets",
                "exec-key cardinality bound the lattice guarantees "
                "for in-bounds sessionless traffic (rungs^2 x "
                "channels)",
            )
            self._g_lattice_waste.set(0.0)
            self._g_lattice_buckets.set(self.lattice.size)

    # ------------------------------------------------------ lifecycle
    def start(self) -> "SynthDaemon":
        from ..telemetry.live import LiveTelemetryServer
        from ..telemetry.spans import as_tracer

        if self.tracer is None:
            self.tracer = as_tracer(None)
        if self._own_work_dir:
            self._work_dir = tempfile.mkdtemp(prefix="ia-serve-")
        if self.state_dir is not None:
            # Lock FIRST (refuses when another live daemon holds the
            # dir — the double-takeover guard), then open the journal,
            # which scans surviving entries into the pending ledger.
            os.makedirs(self.state_dir, exist_ok=True)
            acquire_lock(self.state_dir)
            self.journal = RequestJournal(
                journal_path(self.state_dir), registry=self.registry
            )
        if self._warm_root is not None:
            # Disk executable tier: restore the persisted warm set
            # BEFORE the dispatcher exists (and hence before cmd_serve
            # can announce the endpoint) — rendezvous implies the
            # sealed executables are already resident — then install
            # the tier as the engine's process-wide persist hook so
            # this daemon's dispatches read/write it.  With --warm-dir
            # the root is the FLEET-shared dir: every replica restores
            # the union of what any replica sealed (index writes
            # merge, never clobber), which is what makes a freshly
            # spawned replica's first request land near the fleet's
            # warm p99 instead of the cold-compile wall.
            from ..parallel import batch as _pbatch

            from .excache import DiskExecCache

            self.disk = DiskExecCache(
                os.path.join(self._warm_root, "excache"),
                registry=self.registry,
            )
            restored = self.disk.restore_warm_set()
            if restored:
                import logging

                logging.getLogger("image_analogies_tpu").info(
                    "disk excache: restored %d executable set(s) "
                    "in %.1f ms", len(restored), self.disk.restore_ms,
                )
            _pbatch.set_persist_hook(self.disk)
        if self.observability:
            self.access = AccessLog(
                self._access_log_path
                or os.path.join(self._work_dir, "access.jsonl")
            )
        if self.archive_dir is not None:
            # Durable telemetry archive (round 23): reload BEFORE the
            # sampler starts — the first anomaly evaluation of this
            # boot must already grade against the pre-restart baseline
            # and the ring generation must already sit past every
            # archived window's stamp.
            from ..telemetry.archive import (
                IncidentStore,
                TelemetryArchive,
            )

            self.archive = TelemetryArchive(
                self.archive_dir, registry=self.registry
            )
            self.incidents = IncidentStore(
                self.archive_dir, registry=self.registry,
                min_interval_s=self._incident_min_interval_s,
            )
            resumed = self.archive.resumed
            if (self.obs is not None
                    and resumed.get("generation") is not None):
                self.obs.seed_generation(
                    int(resumed["generation"]) + 1
                )
            if (self.anomaly is not None
                    and resumed.get("baseline_p99_ms") is not None
                    and self.anomaly.config.baseline_p99_ms is None):
                # The operator gave no --baseline: the archived one
                # (what the PREVIOUS boot graded against) carries
                # over, so the latency watch never cold-starts to
                # no_data across a restart.  An explicit --baseline
                # always wins.
                import dataclasses as _dc

                self.anomaly.config = _dc.replace(
                    self.anomaly.config,
                    baseline_p99_ms=float(resumed["baseline_p99_ms"]),
                )
        self.live = LiveTelemetryServer(
            self.tracer,
            self.registry,
            port=self._requested_port,
            host=self.host,
            flight=self.flight,
            health_cb=self.health,
            routes={
                ("POST", "/synthesize"): self._route_synthesize,
                ("GET", "/serving"): self._route_serving,
                ("GET", "/slo"): self._route_slo,
                ("GET", "/journal"): self._route_journal,
                ("GET", "/obs/window"): self._route_obs_window,
                ("GET", "/request"): self._route_request,
                ("POST", "/drain"): self._route_drain,
                ("POST", "/sessions/adopt"): self._route_sessions_adopt,
                ("GET", "/incidents"): self._route_incidents,
                ("GET", "/archive"): self._route_archive,
            },
        ).start()
        if self.obs is not None:
            # Anomaly evaluation rides the sampler tick (never the
            # request path): each tick snapshots the registry, then
            # grades the watches so /healthz and the status gauges are
            # at most one interval stale.  With the archive on, the
            # same tick also persists the periodic snapshot and runs
            # the black-box trigger check (`_obs_tick`).
            self.obs.start_sampler(on_tick=self._obs_tick)
        self._completer = threading.Thread(
            target=self._completer_loop, name="ia-serve-complete",
            daemon=True,
        )
        self._completer.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="ia-serve-dispatch",
            daemon=True,
        )
        self._dispatcher.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.obs is not None:
            self.obs.stop_sampler()
        for req in self.queue.drain():
            req.status = "failed"
            if self._draining.is_set() and self.journal is not None \
                    and not req.replay:
                # Drain deadline expired with this request still
                # queued: its journal entry stays PENDING so the
                # takeover successor replays it (the 500 below tells
                # the live client; a vanished client's answer arrives
                # via the successor's /journal replay record).
                req.error = ("daemon drained before dispatch; "
                             "journaled for takeover replay")
                req.journal_keep = True
            else:
                req.error = "daemon shutting down"
            self._c_failed.inc()
            req.done.set()
        self._g_depth.set(0)
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=30.0)
            self._dispatcher = None
        if self._completer is not None:
            # Sentinel AFTER the dispatcher joined: every settle
            # closure it enqueued is already in the queue, so FIFO
            # order settles them all before the completer exits.
            self._settle_q.put(None)
            self._completer.join(timeout=30.0)
            self._completer = None
        if self.disk is not None:
            # Uninstall only OUR hook: a successor daemon (takeover
            # chaos overlaps lifetimes briefly) may have already
            # installed its own tier.
            from ..parallel import batch as _pbatch

            if _pbatch.get_persist_hook() is self.disk:
                _pbatch.set_persist_hook(None)
            self.disk.release_jax_cache()
        if self.live is not None:
            self.live.stop()
            self.live = None
        if self.access is not None:
            self.access.close()
            self.access = None
        if self.archive is not None:
            self.archive.close()
            self.archive = None
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        if self.state_dir is not None:
            release_lock(self.state_dir)
        if self._own_work_dir and self._work_dir:
            shutil.rmtree(self._work_dir, ignore_errors=True)

    @property
    def url(self) -> str:
        return self.live.url

    # -------------------------------------------------------- warmup
    def warmup(self, entries: List[Dict[str, Any]]) -> List[Dict]:
        """Compile the manifest's shapes through the real dispatch
        path BEFORE announcing the endpoint (cli.cmd_serve orders it
        so): rendezvous implies warm.  With a warm root (state dir, or
        the fleet-shared --warm-dir), the hand-authored manifest is
        merged with the RUNTIME-OBSERVED shapes (warmup.observed.json
        — under a shared warm dir that file is the UNION every replica
        merged in, so a fresh replica precompiles the whole fleet's
        observed buckets before its port announce) — the fix for
        manifest drift, where the shapes clients actually send stopped
        matching the shapes the manifest author guessed — plus the
        disk tier's sealed shapes, so a restart re-warms its persisted
        working set (cheap: those dispatches restore, they don't
        compile).  Round
        18: distinct shapes warm concurrently on `warmup_workers`
        threads, with per-shape compile walls on the warmup span tree
        (run_warmup's docstring).  Round 20: with the lattice on, the
        FULL bucket grid joins the entry list — warm-before-announce
        now covers every shape in-bounds traffic can possibly key —
        and both the dedup key and the dispatch run through the same
        bucketing `_make_request` applies, so an off-bucket manifest
        entry warms its bucket exactly once instead of compiling a
        raw shape no client dispatch will ever key."""
        from .excache import merge_warmup_entries

        if self._warm_root is not None:
            from .excache import load_observed_warmup

            entries = merge_warmup_entries(
                entries,
                load_observed_warmup(self.observed_warmup_path),
                self.disk.warmup_shapes() if self.disk is not None
                else [],
            )
        if self.lattice is not None:
            entries = merge_warmup_entries(
                entries, self.lattice.shapes()
            )

        def dispatch(shape):
            frame = np.zeros(shape, np.float32)
            req = self._make_request(frame)
            self._execute([req], kind="warmup")
            if req.status != "ok":
                raise RuntimeError(
                    f"warmup dispatch failed for shape {shape}: "
                    f"{req.error}"
                )

        def key_fn(shape):
            return exec_key(
                self._lattice_shape(shape), self.cfg,
                self.policy.max_batch,
            )

        return run_warmup(
            entries, dispatch, self.cache, key_fn,
            max_workers=self.warmup_workers,
            tracer=self.tracer if self.observability else None,
        )

    # ------------------------------------------------------- serving
    def _lattice_shape(self, shape) -> tuple:
        """A shape tuple as the lattice would admit it: (H, W[, C])
        with the leading two axes rounded up to their bucket, raw when
        the lattice is off or the shape bypasses (over the top rung).
        The warmup dedup key and the dispatch path must agree on
        exactly this mapping."""
        if self.lattice is None:
            return tuple(shape)
        b = self.lattice.bucket_for(int(shape[0]), int(shape[1]))
        if b is None:
            return tuple(shape)
        return b + tuple(shape[2:])

    def _make_request(self, frame: np.ndarray,
                      session: Optional[str] = None,
                      req_id: Optional[str] = None) -> ServeRequest:
        # Session dispatches run one frame at a time through the
        # stream's own solo-mesh executables, so their cache identity
        # is the batch-1 grain, not the daemon's padding grain.
        grain = 1 if session is not None else self.policy.max_batch
        crop = None
        # Lattice admission (round 20), sessionless only: pad BEFORE
        # the executable key and the luma bucket are computed, so the
        # whole downstream pipeline — compat identity, dispatch stack,
        # disk-tier seal — sees the bucket shape and nothing else.
        # (A video session's NNF state is sized to its true frames;
        # bucketing it would warm-start from misaligned state.)
        if self.lattice is not None and session is None:
            h, w = int(frame.shape[0]), int(frame.shape[1])
            b = self.lattice.bucket_for(h, w)
            if b is None:
                path = "bypass"
            elif b == (h, w):
                path = "exact"
            else:
                pad = [(0, b[0] - h), (0, b[1] - w)]
                if frame.ndim == 3:
                    pad.append((0, 0))
                frame = np.pad(frame, pad, mode="edge")
                crop = (h, w)
                path = "bucketed"
            # Client + replay traffic only (warmup's synthetic
            # dispatches carry no req_id and are not admissions).
            if req_id:
                self._c_lattice.inc(labels={"path": path})
                if b is not None:
                    waste = self.lattice.waste_frac(h, w, b[0], b[1])
                    with self._lattice_lock:
                        self._lattice_waste_sum += waste
                        self._lattice_waste_n += 1
                        self._g_lattice_waste.set(round(
                            self._lattice_waste_sum
                            / self._lattice_waste_n, 6,
                        ))
        key = exec_key(frame.shape, self.cfg, grain)
        bucket = None
        if self.cfg.color_mode == "luminance" and \
                self.cfg.luminance_remap:
            bucket = _luma_bucket(frame)
        kwargs = {"req_id": req_id} if req_id else {}
        return ServeRequest(
            frame=frame, key=key, compat=key + (bucket, session),
            b_stats=bucket, session=session, crop=crop, **kwargs,
        )

    def _route_synthesize(self, body: Optional[bytes], headers=None,
                          ctx=None):
        """POST /synthesize handler (runs on an HTTP handler thread):
        assign/accept the request id -> validate -> admit-or-shed ->
        journal -> enqueue -> block on completion.  Every exit echoes
        `request_id` in the body (the machine-parseable error
        contract), books the `ia_request_duration_ms` cell for its
        outcome, and appends the structured access-log line.

        Round 22: inbound trace context (`X-Parent-Span`/
        `X-Trace-Hop`, forwarded by the fleet router) is validated
        here — malformed values replaced, never rejected — echoed on
        EVERY exit body alongside `request_id`, and recorded on the
        request's `serve_request` span root so the router's
        `route_request` tree and this one join by id."""
        rid = _request_id_from_headers(headers)
        tctx = _trace_context_from_headers(headers)
        t_in = time.monotonic()
        bytes_in = len(body) if body else 0
        try:
            manifest = _parse_manifest(body)
            frame = _frame_from_manifest(manifest)
            session = _session_from_manifest(manifest)
            deadline_ms = _deadline_from_manifest(manifest)
        except ValueError as e:
            payload = _json_bytes({
                "status": "rejected", "error": str(e),
                "request_id": rid, **tctx,
            })
            self._book_response(
                rid, None, "rejected", 400,
                (time.monotonic() - t_in) * 1000.0, bytes_in,
                len(payload), trace=tctx,
            )
            return 400, payload, "application/json"
        if self._draining.is_set():
            # Refused BEFORE the requests counter: the admission
            # ledger (requests == admitted + shed) covers only
            # requests the daemon actually triaged.  `unavailable` is
            # excluded from the SLO availability denominator exactly
            # like shed — a planned drain must not burn error budget.
            retry = max(1.0, round(self.drain_deadline_s, 1))
            payload = _json_bytes({
                "status": "unavailable",
                "error": "daemon is draining; retry against the "
                         "successor",
                "request_id": rid,
                "retry_after_s": retry,
                **tctx,
            })
            self._book_response(
                rid, None, "unavailable", 503,
                (time.monotonic() - t_in) * 1000.0, bytes_in,
                len(payload), trace=tctx,
            )
            return (
                503, payload, "application/json",
                {"Retry-After": str(int(np.ceil(retry)))},
            )
        req = self._make_request(frame, session, req_id=rid)
        if tctx:
            req.trace_parent = tctx.get("parent_span")
            req.trace_hop = tctx.get("hop")
        if deadline_ms is not None:
            req.deadline_t = t_in + deadline_ms / 1000.0
        if ctx is not None:
            req.alive = ctx.get("alive")
        req.span("queued")
        # Requests books FIRST (the serving sentinel check's ordering
        # contract), then exactly one of admitted/shed.
        self._c_requests.inc()
        ok, retry_after = self.admission.admit(
            len(self.queue), self._inflight
        )
        shed_error = ("shed by admission control (queue at "
                      "capacity); retry after retry_after_s")
        if ok and not self.admission.deadline_permits(
                req.deadline_t, len(self.queue), self._inflight):
            # Deadline pricing: admitting work whose deadline the
            # queue-depth x p50-service estimate already blows just
            # burns a dispatch on an answer nobody is waiting for.
            ok = False
            shed_error = ("shed at admission: client deadline "
                          "cannot be met at current queue depth")
        if not ok:
            self._c_shed.inc()
            payload = _json_bytes({
                "status": "shed",
                "error": shed_error,
                "request_id": rid,
                "retry_after_s": retry_after,
                **tctx,
            })
            self._book_response(
                rid, req, "shed", 429,
                (time.monotonic() - t_in) * 1000.0, bytes_in,
                len(payload), trace=tctx,
            )
            return (
                429, payload, "application/json",
                {"Retry-After": str(int(np.ceil(retry_after)))},
            )
        self._c_admitted.inc()
        self._note_observed_shape(manifest)
        if self.journal is not None:
            self.journal.append(rid, manifest)
            from ..runtime import faults

            # serve_crash: the chaos harness's hard-kill window —
            # the request is durably journaled but NOT yet enqueued
            # or acknowledged; a takeover must replay it.  Keyed by
            # the journal append ordinal.
            if faults.fire(
                "serve_crash", self.journal.appended - 1
            ) == "fail":
                os._exit(137)
        with self._outstanding_lock:
            self._outstanding += 1
        try:
            return self._await_response(
                rid, req, t_in, bytes_in, tctx
            )
        finally:
            with self._outstanding_lock:
                self._outstanding -= 1

    def _await_response(self, rid: str, req: ServeRequest,
                        t_in: float, bytes_in: int,
                        tctx: Optional[Dict[str, Any]] = None):
        """The admitted request's wait-and-respond tail, under the
        drain machinery's outstanding-responses counter (graceful
        drain waits for this to return before snapshotting state and
        exiting — an in-flight response is never cut mid-write)."""
        tctx = tctx or {}
        self.queue.put(req)
        self._g_depth.set(len(self.queue))
        if not req.done.wait(REQUEST_TIMEOUT_S):
            # The client gives up, but the request is still queued or
            # in flight: the DISPATCHER still owns its ledger entry
            # and will book completed/failed when it settles — booking
            # failed here too would double-count the admission ledger
            # the serving sentinel check balances.
            req.error = "request timed out in the daemon"
            payload = _json_bytes({
                "status": "failed", "request_id": rid,
                "error": req.error, **tctx,
            })
            self._book_response(
                rid, req, "timeout", 504,
                (time.monotonic() - req.enqueue_t) * 1000.0, bytes_in,
                len(payload), trace=tctx,
            )
            return 504, payload, "application/json"
        total_ms = (time.monotonic() - req.enqueue_t) * 1000.0
        self._h_latency.observe(total_ms, labels={"phase": "total"})
        if req.status == "cancelled":
            # Retired before dispatch (socket gone / deadline blown in
            # queue).  499 after nginx's "client closed request"; the
            # body exists for the rare still-listening client.
            payload = _json_bytes({
                "status": "cancelled", "request_id": rid,
                "error": req.error, **tctx,
            })
            self._book_response(
                rid, req, "cancelled", 499, total_ms, bytes_in,
                len(payload), trace=tctx,
            )
            return 499, payload, "application/json"
        if req.status != "ok":
            payload = _json_bytes({
                "status": "failed", "request_id": rid,
                "error": req.error, "spans": req.spans, **tctx,
            })
            self._book_response(
                rid, req, "failed", 500, total_ms, bytes_in,
                len(payload), trace=tctx,
            )
            return 500, payload, "application/json"
        out = np.asarray(req.result, np.float32)
        payload = _json_bytes({
            "status": "ok",
            "request_id": rid,
            **tctx,
            "cache": req.cache,
            "batch_size": req.batch_size,
            "wall_ms": round(total_ms, 3),
            "spans": req.spans,
            "shape": list(out.shape),
            "dtype": "float32",
            "image_b64": base64.b64encode(
                np.ascontiguousarray(out).tobytes()
            ).decode(),
        })
        self._book_response(
            rid, req, "ok", 200, total_ms, bytes_in, len(payload),
            trace=tctx,
        )
        return 200, payload, "application/json"

    def _book_response(self, rid: str, req: Optional[ServeRequest],
                       outcome: str, code: int, total_ms: float,
                       bytes_in: int, bytes_out: int,
                       trace: Optional[Dict[str, Any]] = None) -> None:
        """Response-time bookkeeping, one call per exit path: the
        request-duration observation (always — it is the SLO engine's
        raw material) and the access-log line (observability only).
        Also the journal's `done` mark — a response write IS what
        retires a journal entry (cancellation marks happen at the
        dispatcher, and drain-stranded requests skip the mark via
        `journal_keep` so the successor still replays them)."""
        if (
            self.journal is not None and req is not None
            and not req.replay
            and outcome in ("ok", "failed", "timeout")
            and not getattr(req, "journal_keep", False)
        ):
            self.journal.mark(rid, "done")
        cache = req.cache if req is not None and req.cache else "none"
        # The request id rides as the bucket's exemplar (round 19):
        # a latency-spike bucket in the exposition names the exact
        # request to `ia-synth trace`.
        self._h_duration.observe(total_ms, labels={
            "route": "/synthesize", "outcome": outcome, "cache": cache,
        }, exemplar=rid)
        if self.access is None:
            return
        entry: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "request_id": rid,
            "route": "/synthesize",
            "outcome": outcome,
            "http_status": code,
            "total_ms": round(total_ms, 3),
            "bytes_in": bytes_in,
            "bytes_out": bytes_out,
        }
        if trace:
            entry["parent_span"] = trace.get("parent_span")
            entry["hop"] = trace.get("hop")
        if req is not None:
            entry["t0"] = round(req.t0, 6)
            entry["session_id"] = req.session
            entry["exec_key"] = key_str(req.key)
            entry["cache"] = req.cache
            entry["batch_size"] = req.batch_size
            entry.update(_phase_attribution(req, total_ms))
        self.access.log(entry)

    def _route_slo(self, _body):
        """GET /slo: grade the declarative objectives over the sliding
        window and publish the burn-rate gauges — evaluation happens
        HERE (pull), never on the request hot path.  With the round-19
        observatory on, the live anomaly report rides along under
        `anomalies` so one scrape answers both "is the budget burning"
        and "is something anomalous right now"."""
        report = self.slo.evaluate()
        if self.anomaly is not None:
            report["anomalies"] = self.anomaly.evaluate()
        return 200, _json_bytes(report), "application/json"

    def _route_obs_window(self, _body, _headers, ctx):
        """GET /obs/window?span=S: the time-series ring's windowed
        view (rates + windowed quantiles) over the last S seconds
        (omitted = the whole ring)."""
        if self.obs is None:
            return 404, _json_bytes({
                "error": "observatory disabled (obs_interval_s <= 0)",
            }), "application/json"
        raw = (ctx.get("query") or {}).get("span")
        span = None
        if raw not in (None, ""):
            try:
                span = float(raw)
                if span <= 0:
                    raise ValueError
            except ValueError:
                return 400, _json_bytes({
                    "error": f"span must be a positive number "
                             f"of seconds, got {raw!r}",
                }), "application/json"
        return 200, _json_bytes(self.obs.window(span)), \
            "application/json"

    # ------------------------------------ archive + black box (r23)
    def _obs_tick(self) -> None:
        """The sampler tick's full round-23 itinerary, in order: grade
        the anomaly watches (round 19, unchanged), persist the
        periodic archive snapshot when the cadence says so, then run
        the black-box trigger check.  Never the request hot path —
        and never lets an archive failure take the sampler down (the
        archive itself counts-not-raises; this guard covers the
        bundle assembly)."""
        report = (self.anomaly.evaluate()
                  if self.anomaly is not None else None)
        if self.archive is None:
            return
        try:
            now = time.monotonic()
            if (self._archive_interval_s > 0
                    and now - self._archive_last_t
                    >= self._archive_interval_s):
                self._archive_last_t = now
                self._archive_snapshot(anomaly_report=report)
            self._maybe_capture_incident(report)
        except Exception:  # noqa: BLE001 - observer never kills
            import logging

            logging.getLogger("image_analogies_tpu").exception(
                "telemetry archive tick failed"
            )

    def _archive_snapshot(self, anomaly_report=None,
                          final: bool = False) -> bool:
        """One durable snapshot record: the obs window view (with its
        generation stamp), the graded SLO report, the anomaly report +
        the ACTIVE latency baseline (what a successor must resume
        against), and the lattice/shape-cardinality state."""
        if self.archive is None:
            return False
        if anomaly_report is None and self.anomaly is not None:
            anomaly_report = self.anomaly.evaluate()
        return self.archive.append("snapshot", {
            "final": bool(final),
            "obs_window": (self.obs.window()
                           if self.obs is not None else None),
            "obs_generation": (self.obs.generation
                               if self.obs is not None else None),
            "slo": self.slo.evaluate(),
            "anomaly": anomaly_report,
            "anomaly_baseline_p99_ms": (
                self.anomaly.config.baseline_p99_ms
                if self.anomaly is not None else None
            ),
            "lattice": self._lattice_snapshot(),
            "shape_cardinality": {
                "raw": len(self._observed_raw_shapes),
                "bucketed": len(self._observed_shapes),
            },
        })

    def _maybe_capture_incident(self, anomaly_report=None) -> \
            Optional[str]:
        """The black-box trigger: an SLO objective in fast_burn/
        exhausted, or a firing anomaly watch, captures ONE bundle
        (the store rate-limits per trigger kind, so a burn episode
        that stays hot across many ticks still yields one crime
        scene).  Captures are also noted in the archive stream, so
        `ia-synth history` shows incidents inline with the restarts
        they explain."""
        if self.incidents is None:
            return None
        slo_report = self.slo.evaluate()
        burning = [
            o for o in slo_report.get("objectives", [])
            if o.get("status") in ("fast_burn", "exhausted")
        ]
        firing = list((anomaly_report or {}).get("firing") or [])
        if not burning and not firing:
            return None
        trigger = {
            "kind": "slo_burn" if burning else "anomaly",
            "objectives": [
                {"name": o.get("name"), "status": o.get("status"),
                 "burn_rate": o.get("burn_rate")}
                for o in burning
            ],
            "watches": firing,
        }
        inc_id = self.incidents.capture(
            trigger, self._incident_bundle(slo_report, anomaly_report)
        )
        if inc_id is not None and self.archive is not None:
            self.archive.append("incident", {
                "id": inc_id, "trigger": trigger,
            })
        return inc_id

    def _incident_bundle(self, slo_report,
                         anomaly_report) -> Dict[str, Any]:
        """A self-contained crime scene: everything the `ia-synth
        incident <id>` renderer and a post-mortem need WITHOUT the
        daemon still being alive."""
        tail: List[Dict[str, Any]] = []
        if self.access is not None:
            from collections import deque as _deque

            from .accesslog import read_entries as _read_entries

            # Bounded tail across every rotation generation — the
            # round-23 accesslog shift chain is what lets this reach
            # back past one rotation.
            tail = list(_deque(
                _read_entries(self.access.path), maxlen=100
            ))
        return {
            "flight": (self.flight.to_dict(reason="incident")
                       if self.flight is not None else None),
            "access_tail": tail,
            "obs_window": (self.obs.window()
                           if self.obs is not None else None),
            "slo": slo_report,
            "anomaly": anomaly_report,
            "serving": {
                "queue_depth": len(self.queue),
                "inflight": self._inflight,
                "draining": self._draining.is_set(),
                "cache": self.cache.snapshot(),
                "lattice": self._lattice_snapshot(),
            },
            "fingerprint": self._fingerprint(),
        }

    def _fingerprint(self) -> Dict[str, Any]:
        """Config + backend identity for the bundle: enough to answer
        "was the incident daemon running the config I think it was"."""
        import dataclasses as _dc

        backend = None
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - identity is best-effort
            pass
        return {
            "pid": os.getpid(),
            "boot_id": (self.archive.boot_id
                        if self.archive is not None else None),
            "backend": backend,
            "devices": int(self.mesh.devices.size),
            "config": (_dc.asdict(self.cfg)
                       if _dc.is_dataclass(self.cfg)
                       else str(self.cfg)),
            "policy": {
                "max_batch": self.policy.max_batch,
                "max_wait_ms": self.policy.max_wait_ms,
                "max_queue_depth": self.admission.max_depth,
                "pipeline_window": self.pipeline_window,
            },
            "state_dir": self.state_dir,
            "archive_dir": self.archive_dir,
        }

    def _route_incidents(self, _body, _headers, ctx):
        """GET /incidents: the black-box index; `?id=` returns one
        full bundle.  404 (not empty-list) when the archive plane is
        off — absence of the FEATURE and absence of incidents must be
        distinguishable to the router's fan-out."""
        from ..telemetry.archive import list_incidents, load_incident

        if self.incidents is None:
            return 404, _json_bytes({
                "error": "incident capture disabled "
                         "(no --archive-dir)",
            }), "application/json"
        inc_id = (ctx.get("query") or {}).get("id") if ctx else None
        if inc_id:
            doc = load_incident(self.archive_dir, inc_id)
            if doc is None:
                return 404, _json_bytes({
                    "error": f"incident {inc_id!r} not found",
                    "id": inc_id,
                }), "application/json"
            return 200, _json_bytes(doc), "application/json"
        return 200, _json_bytes({
            "archive_dir": self.archive_dir,
            "incidents": list_incidents(self.archive_dir),
            **self.incidents.stats(),
        }), "application/json"

    def _route_archive(self, _body):
        """GET /archive: live archive stats + what reload resumed —
        the chaos harness asserts torn-tail tolerance and baseline
        continuity from exactly this snapshot."""
        if self.archive is None:
            return 404, _json_bytes({
                "error": "telemetry archive disabled "
                         "(no --archive-dir)",
            }), "application/json"
        snap = self.archive.stats()
        snap["incidents"] = (self.incidents.stats()
                             if self.incidents is not None else None)
        snap["anomaly_baseline_p99_ms"] = (
            self.anomaly.config.baseline_p99_ms
            if self.anomaly is not None else None
        )
        snap["obs_generation"] = (self.obs.generation
                                  if self.obs is not None else None)
        return 200, _json_bytes(snap), "application/json"

    def _route_request(self, _body, _headers, ctx):
        """GET /request?id=<request_id>: one request's access-log
        record + its flight-recorder events, live over HTTP — the
        `ia-synth trace <id> --url` backend (post-mortem trace reads
        artifacts; this answers while the daemon still runs).  404
        with a JSON error on an unknown id."""
        rid = (ctx.get("query") or {}).get("id")
        if not rid:
            return 400, _json_bytes({
                "error": "missing required query parameter: id",
            }), "application/json"
        entry = None
        if self.access is not None:
            entry = find_request(self.access.path, rid)
        if entry is None:
            return 404, _json_bytes({
                "error": f"request id {rid!r} not found"
                + ("" if self.access is not None
                   else " (access log disabled)"),
                "request_id": rid,
            }), "application/json"
        events = []
        if self.flight is not None:
            from ..telemetry.flight import tree_events

            # Whole-tree events (round 22): the serve_request root
            # plus its lifecycle/run children, so the fleet waterfall
            # can nest the replica's inner spans inside the router's
            # proxy window without a second scrape.
            events = tree_events(self.flight.to_dict(), rid)
        return 200, _json_bytes({
            "request": entry, "flight_events": events,
        }), "application/json"

    def _route_serving(self, _body):
        """GET /serving: the operator's one-look snapshot — queue /
        in-flight occupancy, cache residency, and the SLO quantiles."""
        snap = {
            "queue_depth": len(self.queue),
            "inflight": self._inflight,
            # Round 21: the router's poller routes on queue_depth +
            # inflight and needs the drain state + state_dir (the
            # migration source) without a second scrape.
            "draining": self._draining.is_set(),
            "state_dir": self.state_dir,
            "warm_dir": self.warm_dir,
            "policy": {
                "max_batch": self.policy.max_batch,
                "max_wait_ms": self.policy.max_wait_ms,
                "max_queue_depth": self.admission.max_depth,
                "effective_queue_depth": self.admission.effective_depth(),
            },
            "pipeline": {
                "window": self.pipeline_window,
                "inflight_batches": self._pipeline_busy,
            },
            "cache": self.cache.snapshot(),
            "disk_cache": (self.disk.snapshot()
                           if self.disk is not None else None),
            "lattice": self._lattice_snapshot(),
            "sessions": {
                "active": len(self._sessions),
                "max": self.max_sessions,
                "frames": {
                    sid: stream.t
                    for sid, stream in self._sessions.items()
                },
            },
            "slo_ms": {
                phase: {
                    "p50": self._h_latency.quantile(
                        0.5, labels={"phase": phase}
                    ),
                    "p99": self._h_latency.quantile(
                        0.99, labels={"phase": phase}
                    ),
                }
                for phase in ("queued", "service", "total")
            },
        }
        return 200, _json_bytes(snap), "application/json"

    def _lattice_snapshot(self) -> Optional[Dict[str, Any]]:
        """The /serving lattice section: grid geometry + the decision
        provenance + the live waste/cardinality numbers (None with the
        lattice off)."""
        if self.lattice is None:
            return None
        with self._lattice_lock:
            waste_n = self._lattice_waste_n
            mean_waste = (self._lattice_waste_sum / waste_n
                          if waste_n else 0.0)
        snap = dict(self.lattice.snapshot())
        snap.update({
            "source": (self.lattice_plan.source
                       if self.lattice_plan is not None
                       else "direct"),
            "mean_bucket_waste_frac": round(mean_waste, 6),
            "admissions": waste_n,
            "shape_cardinality": {
                "raw": len(self._observed_raw_shapes),
                "bucketed": len(self._observed_shapes),
            },
        })
        return snap

    def _route_journal(self, _body):
        """GET /journal: the durability ledger — journal counts, the
        drain state machine's position, and the replay record (rid ->
        output sha256) a takeover successor accumulates.  The chaos
        harness asserts zero acked loss and replay bit-identity from
        exactly this snapshot."""
        snap = {
            "ledger": (self.journal.counts()
                       if self.journal is not None else None),
            "state_dir": self.state_dir,
            "draining": self._draining.is_set(),
            "drained": self.drained.is_set(),
            "replayed": dict(self._replayed),
        }
        return 200, _json_bytes(snap), "application/json"

    def _route_drain(self, _body):
        """POST /drain: flip to draining (idempotent) and return
        immediately — 202, the drain worker finishes asynchronously.
        New requests now get 503 + Retry-After; `drained` flips once
        in-flight work and response writes are settled and the
        hand-off state is on disk."""
        already = self._draining.is_set()
        self.begin_drain(reason="drain")
        payload = {
            "status": "draining",
            "already_draining": already,
            "queue_depth": len(self.queue),
            "inflight": self._inflight,
            "drain_deadline_s": self.drain_deadline_s,
        }
        return 202, _json_bytes(payload), "application/json"

    # ------------------------------------------------ drain machinery
    def begin_drain(self, reason: str = "drain") -> None:
        """Enter draining (idempotent): refuse new work, let queued +
        in-flight requests and their response writes finish under
        `drain_deadline_s`, snapshot hand-off state, set `drained`.
        The caller (cli.cmd_serve's SIGTERM handler / main loop)
        decides when to actually exit."""
        if self._draining.is_set():
            return
        self._draining.set()
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "serving drain started (reason=%s, queue=%d, inflight=%d)",
            reason, len(self.queue), self._inflight,
        )
        t = threading.Thread(
            target=self._drain_worker, name="ia-serve-drain",
            daemon=True,
        )
        t.start()

    def _drain_worker(self) -> None:
        deadline = time.monotonic() + self.drain_deadline_s
        while time.monotonic() < deadline:
            with self._outstanding_lock:
                outstanding = self._outstanding
            if (len(self.queue) == 0 and self._inflight == 0
                    and outstanding == 0):
                break
            time.sleep(0.02)
        # A breath for the HTTP server threads to push the last
        # payloads through their sockets (handlers return bytes; the
        # server writes them just after).
        time.sleep(0.1)
        try:
            self._drain_snapshot()
        except Exception:  # noqa: BLE001 - drain must terminate
            import logging

            logging.getLogger("image_analogies_tpu").exception(
                "drain snapshot failed (continuing to exit)"
            )
        if self.archive is not None:
            # Final archive record BEFORE the flight flush: the
            # successor's reload reads baselines/generation from the
            # freshest possible window, and a SIGKILL past this point
            # loses nothing the archive promised to keep.
            try:
                self._archive_snapshot(final=True)
            except Exception:  # noqa: BLE001 - drain must terminate
                pass
        if self.flight is not None:
            try:
                # Sticky "drain" label: distinguishes a graceful
                # hand-off dump from the round-12 sigterm dump.
                self.flight.flush(reason="drain")
            except Exception:  # noqa: BLE001
                pass
        self.drained.set()

    def _drain_snapshot(self) -> None:
        """Persist the hand-off state a takeover successor restores:
        every resident session's carried NNF/B' state (session ids are
        hashed into dir names — they are client-chosen strings, not
        safe path components), the runtime-observed warm shapes, and
        finally the journal's pending-only compaction.  ORDER IS THE
        ROUND-21 DRAIN CONTRACT: the router is told "drained" only
        after this whole function, but a SIGKILL can land anywhere
        inside it — sessions.json must hit disk BEFORE the journal
        compaction runs, because the compaction is the one destructive
        step (it discards retired history); sessions-first means a
        mid-drain kill leaves either the old journal intact (replay
        works, snapshot maybe stale) or the full snapshot plus a
        compacted journal — never a compacted journal with the session
        snapshot the router was promised still unwritten."""
        if self.state_dir is None:
            if self.warm_dir is not None:
                self._save_observed_shapes()
            return
        import hashlib

        index: Dict[str, str] = {}
        for sid, stream in self._sessions.items():
            dirname = hashlib.sha1(sid.encode()).hexdigest()[:16]
            sdir = os.path.join(self.state_dir, "sessions", dirname)
            try:
                stream.save_state(sdir)
                index[sid] = dirname
            except Exception:  # noqa: BLE001 - skip broken streams
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "session %s snapshot failed (skipped)", sid
                )
        tmp = os.path.join(self.state_dir, "sessions.json.tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"schema_version": 1, "sessions": index}, fh)
        os.replace(tmp, os.path.join(self.state_dir, "sessions.json"))
        self._save_observed_shapes()
        if self.journal is not None:
            self.journal.compact()

    # --------------------------------------------- takeover machinery
    @property
    def _warm_root(self) -> Optional[str]:
        """Directory the warm tier (disk excache + observed warmup)
        lives under: the fleet-shared --warm-dir when set, else the
        replica's own state dir."""
        return self.warm_dir if self.warm_dir is not None \
            else self.state_dir

    @property
    def observed_warmup_path(self) -> Optional[str]:
        root = self._warm_root
        if root is None:
            return None
        return os.path.join(root, OBSERVED_WARMUP_FILE)

    @staticmethod
    def _lru_note(lru: "OrderedDict", key, cap: int = 32) -> bool:
        """Insert/refresh `key` in an LRU set bounded at `cap`;
        True when the key was fresh."""
        fresh = key not in lru
        lru[key] = None
        lru.move_to_end(key)
        while len(lru) > cap:
            lru.popitem(last=False)
        return fresh

    def _note_observed_shape(self, manifest: Dict[str, Any]) -> None:
        """LRU-track the (H, W, C) shapes real clients send; persisted
        on first sighting and at drain so the successor's warmup
        compiles what traffic actually needs, not what the manifest
        author guessed.  With the lattice on, the PERSISTED set holds
        bucket shapes (what a successor must actually precompile —
        persisting the raw long tail would re-fragment its warmup into
        exactly the cardinality the lattice exists to bound) while the
        raw client shapes feed the `view="raw"` cardinality cell."""
        shape = manifest.get("shape")
        if not (isinstance(shape, list) and len(shape) == 3):
            return
        raw = tuple(int(d) for d in shape)
        key = raw
        if self.lattice is not None:
            self._lru_note(self._observed_raw_shapes, raw)
            b = self.lattice.bucket_for(raw[0], raw[1])
            if b is not None:
                key = b + raw[2:]
        fresh = self._lru_note(self._observed_shapes, key)
        # Cardinality gauges for the anomaly shape-growth watch: every
        # distinct shape is a compile, so a climbing gauge is compile
        # budget walking out the door.  The unlabeled cell follows the
        # bucketed series (== raw when the lattice is off) — the
        # series the watch grades, so the lattice's collapse doesn't
        # mask genuine raw-traffic drift (which keeps its own cell).
        bucketed = len(self._observed_shapes)
        raw_card = (len(self._observed_raw_shapes)
                    if self.lattice is not None else bucketed)
        self._g_shape_card.set(bucketed)
        self._g_shape_card.set(raw_card, labels={"view": "raw"})
        self._g_shape_card.set(bucketed,
                               labels={"view": "bucketed"})
        if fresh and self._warm_root is not None:
            try:
                self._save_observed_shapes()
            except OSError:
                pass

    def _save_observed_shapes(self) -> None:
        if self._warm_root is None or not self._observed_shapes:
            return
        # Under a fleet-shared warm dir each replica UNIONS its shapes
        # into the file (overwrite would shrink the fleet's observed
        # set to the last drainer's traffic slice — round 21 satellite).
        save_observed_warmup(
            self.observed_warmup_path, list(self._observed_shapes),
            merge=self.warm_dir is not None,
        )

    def restore_sessions(self) -> int:
        """Takeover: re-open every session stream the predecessor
        snapshotted at drain.  Best-effort — a session that fails to
        restore simply runs its next frame cold."""
        if self.state_dir is None:
            return 0
        import dataclasses

        from ..video.sequence import VideoStream

        idx_path = os.path.join(self.state_dir, "sessions.json")
        try:
            with open(idx_path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return 0
        sessions = doc.get("sessions")
        if not isinstance(sessions, dict):
            return 0
        cfg = dataclasses.replace(self.cfg, save_level_artifacts=None)
        n = 0
        for sid, dirname in sessions.items():
            if not (isinstance(sid, str) and isinstance(dirname, str)):
                continue
            sdir = os.path.join(self.state_dir, "sessions",
                                os.path.basename(dirname))
            stream = VideoStream(
                self.a, self.ap, cfg=cfg, registry=self.registry
            )
            if stream.restore_state(sdir):
                self._sessions[sid] = stream
                n += 1
        return n

    def adopt_sessions(self, source_state_dir: str,
                       only: Optional[List[str]] = None) -> List[str]:
        """Round 21 cross-replica session migration: restore session
        streams from ANOTHER replica's drain snapshot (the router
        calls POST /sessions/adopt when it drains a replica, handing
        that replica's pinned sessions to survivors over the shared
        filesystem).  `only` limits adoption to the named session ids;
        None adopts the whole snapshot.  Best-effort per session —
        one that fails to restore simply runs its next frame cold on
        whichever replica it lands.  Returns the adopted ids.

        Runs on an HTTP handler thread while the dispatcher owns
        `_sessions`: plain dict insertion is safe under the GIL, and
        the router's migration protocol routes an adopted session's
        next frame here only AFTER this call returns, so the
        dispatcher never races the restore of a stream it is using."""
        import dataclasses

        from ..telemetry.spans import span_at
        from ..video.sequence import VideoStream

        p_adopt0 = time.perf_counter()
        idx_path = os.path.join(source_state_dir, "sessions.json")
        try:
            with open(idx_path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return []
        sessions = doc.get("sessions")
        if not isinstance(sessions, dict):
            return []
        wanted = None if only is None else {str(s) for s in only}
        cfg = dataclasses.replace(self.cfg, save_level_artifacts=None)
        adopted: List[str] = []
        restores = []  # (sid, restored, p_start, p_end) for the span
        for sid, dirname in sessions.items():
            if not (isinstance(sid, str) and isinstance(dirname, str)):
                continue
            if wanted is not None and sid not in wanted:
                continue
            sdir = os.path.join(source_state_dir, "sessions",
                                os.path.basename(dirname))
            p_s0 = time.perf_counter()
            stream = VideoStream(
                self.a, self.ap, cfg=cfg, registry=self.registry
            )
            restored = stream.restore_state(sdir)
            restores.append((sid, restored, p_s0,
                             time.perf_counter()))
            if restored:
                self._sessions[sid] = stream
                self._sessions.move_to_end(sid)
                adopted.append(sid)
        while len(self._sessions) > self.max_sessions:
            self._sessions.popitem(last=False)
        p_adopt1 = time.perf_counter()
        if adopted:
            self.registry.counter(
                "ia_serve_sessions_adopted_total",
                "session streams adopted from another replica's drain "
                "snapshot (round 21 fleet migration)",
            ).inc(len(adopted))
        self.registry.histogram(
            "ia_serve_adopt_ms",
            "wall of one /sessions/adopt restore (round 22 migration "
            "visibility: the replica half of a drain migration)",
        ).observe((p_adopt1 - p_adopt0) * 1000.0)
        if self.tracer.enabled:
            # Migration visibility (round 22): the adopt is a real
            # span tree — one session_restore child per stream — so a
            # repinned session's first frame can point at the restore
            # cost instead of an anonymous stall.
            root = span_at(
                "sessions_adopt", p_adopt0, p_adopt1,
                source=source_state_dir, sessions=len(adopted),
            )
            for sid, restored, a, b in restores:
                root.children.append(span_at(
                    "session_restore", a, b, session=sid,
                    restored=restored,
                ))
            self.tracer.attach_tree(root)
        return adopted

    def _route_sessions_adopt(self, body: Optional[bytes]):
        """POST /sessions/adopt {"state_dir": DIR, "sessions": [...]}:
        the router-facing migration endpoint (adopt_sessions above).
        Refused while draining — a draining replica is shedding
        sessions, not collecting them."""
        try:
            doc = json.loads((body or b"{}").decode("utf-8"))
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            src = doc.get("state_dir")
            if not isinstance(src, str) or not src:
                raise ValueError("state_dir (source replica's state "
                                 "dir) is required")
            only = doc.get("sessions")
            if only is not None and not (
                isinstance(only, list)
                and all(isinstance(s, str) for s in only)
            ):
                raise ValueError("sessions must be a list of strings")
        except (ValueError, UnicodeDecodeError) as e:
            return 400, _json_bytes(
                {"status": "rejected", "error": str(e)}
            ), "application/json"
        if self._draining.is_set():
            return 503, _json_bytes({
                "status": "unavailable",
                "error": "daemon is draining; adopt elsewhere",
            }), "application/json"
        adopted = self.adopt_sessions(src, only=only)
        return 200, _json_bytes({
            "status": "ok",
            "adopted": adopted,
            "sessions_active": len(self._sessions),
        }), "application/json"

    def replay_journal(self) -> int:
        """Takeover: push every journal-pending request back through
        the NORMAL queue (replay flag set — no client is waiting; the
        settle path marks them `replayed` and records the output hash
        for the bit-identity audit).  An entry whose manifest no
        longer reconstructs is retired `cancelled` rather than left to
        wedge the ledger forever."""
        if self.journal is None:
            return 0
        n = 0
        for rec in self.journal.pending_entries():
            rid = rec.get("request_id", "")
            try:
                manifest = rec["manifest"]
                frame = _frame_from_manifest(manifest)
                session = _session_from_manifest(manifest)
            except (ValueError, KeyError, TypeError):
                self.journal.mark(rid, "cancelled")
                self._c_cancelled.inc()
                continue
            req = self._make_request(frame, session, req_id=rid)
            req.replay = True
            req.span("queued")
            # Replays walk the whole admission ledger (requests ->
            # admitted -> completed/failed) so every serving-sentinel
            # invariant holds on the successor's registry too.
            self._c_requests.inc()
            self._c_admitted.inc()
            self.queue.put(req)
            n += 1
        self._g_depth.set(len(self.queue))
        if n:
            import logging

            logging.getLogger("image_analogies_tpu").warning(
                "takeover: replaying %d journaled request(s)", n
            )
        return n

    def health(self) -> Dict[str, Any]:
        """/healthz callback: the full sentinel evaluation (which now
        includes the serving ledger check) against the daemon's
        registry."""
        from ..telemetry.sentinel import evaluate_health

        return evaluate_health(
            metrics=self.registry.to_dict(), context="serving"
        )

    # ---------------------------------------------------- dispatcher
    def _note_pipeline(self, delta: int) -> None:
        with self._inflight_lock:
            self._pipeline_busy = max(0, self._pipeline_busy + delta)
            self._g_pipeline.set(self._pipeline_busy)

    def _completer_loop(self) -> None:
        """Completion stage of the pipelined dispatcher: run each
        batch's settle closure (demux -> counters -> done events) and
        only then release its window slot.  A settle that dies still
        releases the slot and fails its undone requests — a wedged
        completer must degrade to failed requests, never to a daemon
        whose window never reopens."""
        while True:
            item = self._settle_q.get()
            if item is None:
                return
            settle, batch = item
            try:
                settle()
            except BaseException as e:  # noqa: BLE001 - daemon survives
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "serving settle error"
                )
                for req in batch:
                    if not req.done.is_set():
                        req.status = "failed"
                        req.error = f"{type(e).__name__}: {e}"
                        self._c_failed.inc()
                        req.done.set()
            finally:
                self._note_pipeline(-1)
                self._window.release()

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.next_batch(self.policy, timeout=0.25)
            if batch is None:
                continue
            self._g_depth.set(len(self.queue))
            batch = self._filter_batch(batch)
            if not batch:
                continue
            # One window slot per dispatched batch; timed re-checks so
            # stop() can't be wedged behind a full window.
            acquired = False
            while not self._stop.is_set():
                if self._window.acquire(timeout=0.25):
                    acquired = True
                    break
            if not acquired:
                for req in batch:
                    req.status = "failed"
                    req.error = "daemon shutting down"
                    self._c_failed.inc()
                    req.done.set()
                continue
            self._note_pipeline(+1)
            deferred: List[Any] = []
            try:
                self._dispatch_guarded(batch, defer=deferred.append)
            except BaseException as e:  # noqa: BLE001 - daemon survives
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "serving dispatch error"
                )
                for req in batch:
                    if not req.done.is_set():
                        req.status = "failed"
                        req.error = f"{type(e).__name__}: {e}"
                        self._c_failed.inc()
                        req.done.set()
            finally:
                if deferred:
                    # Engine work is done; settle (demux + response
                    # fields + done) happens on the completer while
                    # this thread pops the next batch.
                    self._settle_q.put((deferred[0], batch))
                else:
                    # Settle already ran inline (session batch, or an
                    # exception path that must not race the guard
                    # above): the slot frees immediately.
                    self._note_pipeline(-1)
                    self._window.release()

    def _dispatch_guarded(self, batch: List[ServeRequest],
                          defer=None) -> None:
        """Client dispatch under the round-16 guards: the serve_hang /
        serve_evict fault points (keyed by client-dispatch ordinal)
        and, when `dispatch_deadline_s` is set, a DispatchDeadline
        whose abort token is installed on THIS thread — so a wedged
        dispatch (the injected hang, or an engine stall at a `level`
        fire point) unwinds as LevelAborted instead of freezing the
        dispatcher forever."""
        from ..runtime import faults
        from ..runtime.supervisor import DispatchDeadline

        seq = self._dispatch_seq
        self._dispatch_seq += 1
        dd = None
        if self.dispatch_deadline_s:
            dd = DispatchDeadline(self.dispatch_deadline_s).arm()
            faults.set_abort_token(dd.token)
        try:
            faults.fire("serve_hang", seq)
            if faults.fire("serve_evict", seq) == "fail":
                # Forced cache-epoch eviction: the next lookup is an
                # honest miss + recompile, not a wrong answer.
                self.cache.force_epoch_eviction()
            self._execute(batch, kind="client", defer=defer)
        finally:
            if dd is not None:
                dd.cancel()
                faults.set_abort_token(None)

    def _filter_batch(
        self, batch: List[ServeRequest]
    ) -> List[ServeRequest]:
        """Last call before the engine burns a dispatch: drop popped
        requests whose client socket is already gone or whose client
        deadline expired while queued.  Replays are exempt (their
        client is the journal).  Runs on the dispatcher thread, so the
        cancel path owns the ledger entry exactly like settle does."""
        now = time.monotonic()
        keep: List[ServeRequest] = []
        for req in batch:
            if req.replay:
                keep.append(req)
                continue
            if req.alive is not None:
                try:
                    alive = bool(req.alive())
                except Exception:  # noqa: BLE001 - probe never fatal
                    alive = True
                if not alive:
                    self._cancel_request(
                        req, "client disconnected before dispatch"
                    )
                    continue
            if req.deadline_t is not None and now > req.deadline_t:
                self._cancel_request(
                    req, "client deadline expired in queue"
                )
                continue
            keep.append(req)
        return keep

    def _cancel_request(self, req: ServeRequest, why: str) -> None:
        req.status = "cancelled"
        req.error = why
        self._c_cancelled.inc()
        if self.journal is not None:
            self.journal.mark(req.req_id, "cancelled")
        req.done.set()

    def _admit_batch(self, batch: List[ServeRequest],
                     kind: str) -> float:
        """Shared dispatch preamble: admission spans/latency, the
        in-flight gauges, the dispatch counter, and the executable-
        cache verdict (booked exactly once per dispatch — the serving
        sentinel's `hits + misses == dispatches` contract).  When the
        disk tier exists, every in-memory MISS is resolved one level
        further down — `probe` books exactly one of disk-hit/disk-miss
        (the sentinel's new `disk hits + disk misses == misses`
        reconciliation) and a disk hit upgrades the verdict to the
        three-valued `disk` (span `disk-restored`): the dispatch runs
        deserialized executables, no jit trace.  Returns the admission
        timestamp."""
        admit_t = time.monotonic()
        for req in batch:
            req.span("admitted")
            req.batch_size = len(batch)
            self._h_latency.observe(
                (admit_t - req.enqueue_t) * 1000.0,
                labels={"phase": "queued"},
            )
        with self._inflight_lock:
            self._inflight += len(batch)
            self._g_inflight.set(self._inflight)
        self._c_dispatches.inc(labels={"kind": kind})
        cache_status = self.cache.lookup(
            batch[0].key, kind=kind, request_id=batch[0].req_id
        )
        if cache_status == "miss" and self.disk is not None:
            cache_status = self.disk.probe(batch[0].key, kind=kind)
        span_name = {
            "hit": "cache-hit", "disk": "disk-restored",
        }.get(cache_status, "compiled")
        for req in batch:
            req.cache = cache_status
            req.span(span_name)
        return admit_t

    def _settle_batch(self, batch: List[ServeRequest],
                      admit_t: float, run_roots=(),
                      compile_ms: Optional[float] = None) -> None:
        """Shared dispatch epilogue: per-request span trees grafted
        onto the daemon tracer, service latency, done events, and the
        in-flight gauges back to idle.  `compile_ms` (the dispatch's
        prologue wall) is stamped on every co-tenant BEFORE `done`
        fires, so the handler thread's access-log line sees it."""
        for req in batch:
            req.compile_ms = compile_ms
        if self.observability:
            try:
                self._attach_request_trees(batch, run_roots)
            except Exception:  # noqa: BLE001 - never fail the dispatch
                import logging

                logging.getLogger("image_analogies_tpu").exception(
                    "per-request span tree construction failed"
                )
        service_ms = (time.monotonic() - admit_t) * 1000.0
        for req in batch:
            self._h_latency.observe(
                service_ms, labels={"phase": "service"}
            )
            if req.replay:
                self._settle_replay(req)
            req.done.set()
        with self._inflight_lock:
            self._inflight = max(0, self._inflight - len(batch))
            self._g_inflight.set(self._inflight)

    def _settle_replay(self, req: ServeRequest) -> None:
        """A replayed request has no handler thread: the dispatcher
        retires its journal entry here.  Success marks `replayed` and
        records the output sha256 (the chaos harness's bit-identity
        evidence); failure leaves the entry PENDING so the next
        takeover tries again (at-least-once until a response exists
        somewhere)."""
        if req.status != "ok" or req.result is None:
            return
        import hashlib

        out = np.ascontiguousarray(np.asarray(req.result, np.float32))
        self._replayed[req.req_id] = {
            "sha256": hashlib.sha256(out.tobytes()).hexdigest(),
            "shape": list(out.shape),
        }
        if self.journal is not None:
            self.journal.mark(req.req_id, "replayed")

    def _attach_request_trees(self, batch: List[ServeRequest],
                              run_roots) -> None:
        """Convert each request's lifecycle events into ONE real span
        tree — `serve_request` root spanning enqueue -> settle, one
        child interval per lifecycle event (each reaching to the next
        event), the dispatch's run->level subtree grafted under the
        batch LEAD's root (once, not per co-tenant; co-tenants carry a
        `run_in` pointer) — and graft it onto the daemon tracer, where
        the flight recorder, /progress, and check_report already look.
        Runs on the dispatcher thread only, after the dispatch, so the
        tracer's span stack is untouched (module docstring of
        serving/queueing.py: why lifecycle events can't be live
        spans)."""
        if self.tracer is None or not self.tracer.enabled:
            return
        from ..telemetry.spans import span_at

        settle_perf = time.perf_counter()
        lead = batch[0]
        for req in batch:
            base = req.enqueue_perf
            rel_end = (settle_perf - base) * 1000.0
            events = [(ev["name"], float(ev["t_ms"]))
                      for ev in req.spans]
            root = span_at(
                "serve_request", base, settle_perf,
                request_id=req.req_id, session=req.session,
                cache=req.cache, batch_size=req.batch_size,
                outcome=req.status,
            )
            if req.trace_parent is not None:
                # Round-22 join key: the upstream (router) span id —
                # the fleet waterfall matches this against the
                # route_request tree's span_id.
                root.attrs["parent_span"] = req.trace_parent
                root.attrs["hop"] = req.trace_hop
            for i, (name, t_ms) in enumerate(events):
                nxt = (events[i + 1][1] if i + 1 < len(events)
                       else rel_end)
                root.children.append(span_at(
                    name, base + t_ms / 1000.0,
                    base + max(t_ms, nxt) / 1000.0,
                ))
            if req is lead and run_roots:
                root.children.extend(run_roots)
                root.attrs["run_attached"] = len(run_roots)
            elif run_roots:
                root.attrs["run_in"] = lead.req_id
            self.tracer.attach_tree(root)

    def _execute(self, batch: List[ServeRequest],
                 kind: str = "client", defer=None) -> None:
        """One dispatch: cache verdict -> pad to the static grain ->
        supervised `synthesize_batch` -> demux -> settle requests.
        Session batches (compat pins them to one session id) detour
        through the per-session warm-start stream instead.

        Pipelining seam (round 18): when `defer` is given (the client
        dispatcher), the settle tail — demux, outcome counters, done
        events — is packaged as a closure and handed over instead of
        run inline, so it executes on the completer thread while this
        thread starts the next batch.  The split is PLACEMENT only:
        the engine call, the device sync (`np.asarray`), and the
        `executed` timestamp all stay here, and every exception path
        settles inline before propagating — the dispatch-loop guard's
        "fail the undone" sweep never races a deferred settle."""
        import dataclasses

        from ..parallel.batch import synthesize_batch
        from ..runtime.supervisor import SupervisorGaveUp, supervise

        if batch[0].session is not None:
            self._execute_session(batch, kind=kind)
            return

        grain = self.policy.max_batch
        admit_t = self._admit_batch(batch, kind)
        ckpt_dir = None
        run_tracer = None
        out = None
        ok = False
        gaveup = None

        def settle():
            try:
                if ok:
                    demux(batch, out[: len(batch)])
                    for req in batch:
                        if kind == "client":
                            self._c_completed.inc()
                elif gaveup is not None:
                    for req in batch:
                        req.status = "failed"
                        req.error = f"supervisor gave up: {gaveup}"
                        if kind == "client":
                            self._c_failed.inc()
            finally:
                if ckpt_dir is not None:
                    shutil.rmtree(ckpt_dir, ignore_errors=True)
                run_roots, compile_ms = (), None
                if run_tracer is not None:
                    run_roots = tuple(run_tracer.roots)
                    walls = [
                        sp.wall_ms
                        for sp in run_tracer.find("prologue")
                        if sp.wall_ms is not None
                    ]
                    if walls:
                        compile_ms = round(sum(walls), 3)
                self._settle_batch(
                    batch, admit_t, run_roots=run_roots,
                    compile_ms=compile_ms,
                )

        try:
            frames = np.stack([r.frame for r in batch])
            if frames.shape[0] < grain:
                frames = np.concatenate(
                    [frames]
                    + [frames[-1:]] * (grain - frames.shape[0]), axis=0
                )
            b_stats = batch[0].b_stats
            ckpt_dir = tempfile.mkdtemp(
                prefix="dispatch-", dir=self._work_dir
            )
            cfg = dataclasses.replace(
                self.cfg, save_level_artifacts=ckpt_dir
            )
            # Per-dispatch run tracer (observability on): the batch
            # runner's run->level->em_iter tree, grafted under the
            # batch lead's serve_request root at settle.
            # Instrumentation only — `synthesize_batch` reads the
            # tracer, never branches numerics on it (the solo-dispatch
            # bit-identity test pins this) — and LEAN: the runner
            # keeps the span tree but skips its optional per-level
            # device readbacks (energy means, shard-sync walls), so
            # request tracing adds no device syncs to the hot path.
            if self.observability and self.tracer is not None \
                    and self.tracer.enabled:
                from ..telemetry.spans import Tracer

                run_tracer = Tracer(lean=True)

            # Disk-tier recording: the window opens INSIDE the attempt
            # closure because supervise runs attempts on its worker
            # threads, and the recording context is thread-local to
            # wherever the engine invokes the persist hook.  Retried
            # attempts union their captures; the entry seals only
            # after the dispatch succeeds.
            disk = self.disk
            recorded: set = set()

            def attempt(resume_from):
                if disk is not None:
                    disk.begin_recording()
                try:
                    return synthesize_batch(
                        self.a, self.ap, frames, cfg, self.mesh,
                        progress=run_tracer,
                        resume_from=resume_from,
                        frame_indices=[0] * grain,
                        _b_stats=b_stats,
                    )
                finally:
                    if disk is not None:
                        recorded.update(disk.end_recording())

            try:
                out = supervise(
                    attempt,
                    ckpt_dir=ckpt_dir,
                    tracer=None,
                    registry=self.registry,
                    max_retries=self.max_retries,
                    ladder=[],
                    backoff_s=0.05,
                    max_backoff_s=1.0,
                )
                out = np.asarray(out, np.float32)
                for req in batch:
                    req.span("executed")
                ok = True
            except SupervisorGaveUp as e:
                gaveup = e
            if ok and disk is not None:
                fs = batch[0].frame.shape
                disk.seal(
                    batch[0].key,
                    fs if len(fs) == 3 else fs + (1,),
                    recorded,
                )
        except BaseException:
            settle()
            raise
        if defer is not None:
            defer(settle)
        else:
            settle()

    # ---------------------------------------------- session dispatch
    def _session_stream(self, sid: str, proto: ServeRequest):
        """The session's VideoStream, created on first use (remap
        stats pinned to the opening frame's luma bucket) and LRU-
        evicted at `max_sessions` — an evicted session's next frame
        simply opens a new stream and runs cold."""
        stream = self._sessions.get(sid)
        if stream is not None:
            self._sessions.move_to_end(sid)
            return stream
        import dataclasses

        from ..video.sequence import VideoStream

        cfg = dataclasses.replace(self.cfg, save_level_artifacts=None)
        stream = VideoStream(
            self.a, self.ap, cfg=cfg, b_stats=proto.b_stats,
            registry=self.registry,
        )
        self._sessions[sid] = stream
        while len(self._sessions) > self.max_sessions:
            evicted, _ = self._sessions.popitem(last=False)
            import logging

            logging.getLogger("image_analogies_tpu").info(
                "serving session %s evicted (LRU, %d resident)",
                evicted, len(self._sessions),
            )
        return stream

    def _execute_session(self, batch: List[ServeRequest],
                         kind: str = "client") -> None:
        """One session dispatch: the batch (all one session, by
        compat) steps through the session's warm-start stream in
        arrival order.  No supervisor: a failed step leaves the
        stream's carried state unsettled, so the dispatch fails its
        requests and RESETS the session — the next frame opens a
        fresh stream and runs cold (module docstring)."""
        sid = batch[0].session
        admit_t = self._admit_batch(batch, kind)
        try:
            stream = self._session_stream(sid, batch[0])
            outs = []
            for req in batch:
                outs.append(np.asarray(
                    stream.step(req.frame, request_id=req.req_id),
                    np.float32,
                ))
            for req in batch:
                req.span("executed")
            demux(batch, outs)
            for req in batch:
                if kind == "client":
                    self._c_completed.inc()
        except BaseException as e:  # noqa: BLE001 - daemon survives
            import logging

            logging.getLogger("image_analogies_tpu").exception(
                "serving session %s dispatch error (session reset)", sid
            )
            self._sessions.pop(sid, None)
            for req in batch:
                if not req.done.is_set():
                    req.status = "failed"
                    req.error = f"{type(e).__name__}: {e}"
                    if kind == "client":
                        self._c_failed.inc()
        finally:
            self._settle_batch(batch, admit_t)


# ------------------------------------------------------------- payloads
def _json_bytes(obj) -> bytes:
    return (json.dumps(obj) + "\n").encode()


def _decode_request(body: Optional[bytes]) -> np.ndarray:
    """Parse a /synthesize payload into one float32 (H, W, C) frame.

    Wire format: JSON {"image_b64": base64 of the raw pixel buffer,
    "shape": [H, W, C], "dtype": "float32"|"uint8", optional
    "session_id": str} — raw buffers rather than PNG so the daemon has
    zero image-codec dependencies on the hot path (uint8 payloads are
    scaled to [0, 1]).  Raises ValueError (-> HTTP 400) on any
    malformation.  (The route handler parses the manifest once and
    pulls frame + session separately; this wrapper is the frame-only
    convenience the tests and warmup path use.)"""
    return _frame_from_manifest(_parse_manifest(body))


def _parse_manifest(body: Optional[bytes]) -> dict:
    if not body:
        raise ValueError("empty request body")
    try:
        manifest = json.loads(body.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"request body is not JSON: {e}") from None
    if not isinstance(manifest, dict):
        raise ValueError("request body is not a JSON object")
    return manifest


def _session_from_manifest(manifest: dict) -> Optional[str]:
    """The optional session-affinity id: a non-empty string of at most
    64 characters (the compat key embeds it verbatim; the bound keeps
    a hostile client from inflating queue snapshots and logs)."""
    sid = manifest.get("session_id")
    if sid is None:
        return None
    if not isinstance(sid, str) or not 1 <= len(sid) <= 64:
        raise ValueError(
            "session_id must be a non-empty string of <= 64 characters"
        )
    return sid


def _deadline_from_manifest(manifest: dict) -> Optional[float]:
    """The optional client deadline budget (`deadline_ms`): how long
    the client will wait for its answer, measured from receipt.  A
    finite positive number of milliseconds (bounded at an hour — a
    'deadline' past REQUEST_TIMEOUT_S is a typo, not a budget)."""
    ms = manifest.get("deadline_ms")
    if ms is None:
        return None
    if isinstance(ms, bool) or not isinstance(ms, (int, float)) \
            or not np.isfinite(ms) or not 0 < ms <= 3_600_000:
        raise ValueError(
            f"deadline_ms {ms!r} is not a positive number of "
            f"milliseconds (<= 3600000)"
        )
    return float(ms)


def _frame_from_manifest(manifest: dict) -> np.ndarray:
    shape = manifest.get("shape")
    if (
        not isinstance(shape, list) or len(shape) != 3
        or not all(isinstance(d, int) and d >= 1 for d in shape)
        or shape[2] not in (1, 3)
    ):
        raise ValueError(
            f"shape {shape!r} is not [H, W, C] with C in (1, 3)"
        )
    dtype = manifest.get("dtype", "float32")
    if dtype not in ("float32", "uint8"):
        raise ValueError(f"dtype {dtype!r} not in ('float32', 'uint8')")
    b64 = manifest.get("image_b64")
    if not isinstance(b64, str):
        raise ValueError("image_b64 missing")
    try:
        raw = base64.b64decode(b64, validate=True)
    except Exception as e:  # noqa: BLE001 - malformed base64
        raise ValueError(f"image_b64 does not decode: {e}") from None
    want = shape[0] * shape[1] * shape[2] * (4 if dtype == "float32"
                                             else 1)
    if len(raw) != want:
        raise ValueError(
            f"payload is {len(raw)} bytes; shape {shape} x {dtype} "
            f"needs {want}"
        )
    frame = np.frombuffer(raw, np.float32 if dtype == "float32"
                          else np.uint8).reshape(shape)
    if dtype == "uint8":
        frame = frame.astype(np.float32) / 255.0
    else:
        frame = frame.astype(np.float32)
    if shape[2] == 1:
        frame = frame[..., 0]
    return frame
