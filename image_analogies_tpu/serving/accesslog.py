"""Structured access log for the serving daemon (round 15, with the
request-scoped tracing in serving/daemon.py).

One JSONL line per finished request — every outcome, including the
ones that never reached the queue (400 rejected) or never left it
(429 shed, 504 timeout) — carrying the request id, session, executable
key + cache verdict (`hit` | `disk` | `miss`: `disk` marks a dispatch
served by a DESERIALIZED executable from the persistent tier, booked
as `restore_ms` rather than `compile_ms` in the phase attribution),
the phase attribution (queue/compile/restore/execute/demux
milliseconds), and byte counts.  This is the flat, grep-able
counterpart to the per-request span tree: the span tree answers "what
happened inside THIS request", the access log answers "which requests
should I look at".

Durability contract:

  - **Atomic append.**  Each line is ONE `os.write` on an O_APPEND
    file descriptor; POSIX appends of this size are not interleaved
    across writers, so concurrent handler threads never shear a line.
    A lock serializes writers anyway (rotation needs it), making the
    syscall-level guarantee a backstop, not the mechanism.
  - **Size-capped rotation.**  When the live file would exceed
    `max_bytes` the writer renames it to `<path>.1` (clobbering the
    previous rotation — one generation of history, bounded disk) and
    reopens.  Readers (`read_entries`, the `ia-synth trace` CLI) walk
    `.1` then the live file, oldest first.
  - **Never the hot path's problem.**  `log()` swallows OSError after
    recording it on `self.errors` — a full disk degrades observability,
    not availability.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

DEFAULT_MAX_BYTES = 4 * 1024 * 1024


class AccessLog:
    """Append-only JSONL writer with size-capped rotation."""

    def __init__(self, path: str,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        if max_bytes < 1024:
            raise ValueError(f"max_bytes too small ({max_bytes})")
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.errors = 0
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._size = 0

    def _open(self) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._size = os.fstat(self._fd).st_size

    def log(self, entry: Dict[str, Any]) -> None:
        """Serialize and append one record; rotates first when the
        line would push the live file past `max_bytes`."""
        line = (json.dumps(entry, sort_keys=True,
                           separators=(",", ":")) + "\n").encode()
        with self._lock:
            try:
                if self._fd is None:
                    self._open()
                if self._size + len(line) > self.max_bytes and self._size:
                    os.close(self._fd)
                    os.replace(self.path, self.path + ".1")
                    self._fd = None
                    self._open()
                os.write(self._fd, line)
                self._size += len(line)
            except OSError:
                self.errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


def read_entries(path: str) -> Iterator[Dict[str, Any]]:
    """Yield access-log records oldest-first across the rotation
    (`<path>.1` then `<path>`), skipping unparseable lines (a crash
    mid-write loses at most the final line; everything readable still
    reads)."""
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def find_request(path: str, request_id: str
                 ) -> Optional[Dict[str, Any]]:
    """The LAST record for `request_id` (retries/duplicates: latest
    wins), or None when the id never hit this log."""
    found = None
    for rec in read_entries(path):
        if rec.get("request_id") == request_id:
            found = rec
    return found


# Lifecycle order of every phase any access record can carry.  A
# REPLICA record (serving/daemon.py) carries the queue..demux subset;
# a ROUTER record (serving/router.py) carries pick/proxy/respond.  The
# two sets are disjoint per record, so one ordered tuple serves both
# readers — and the fleet waterfall (serving/fleettrace.py) relies on
# that shared order when it nests a replica's phases inside the
# router's proxy window.
PHASE_ORDER = (
    "pick_ms", "queue_ms", "compile_ms", "restore_ms",
    "execute_ms", "demux_ms", "proxy_ms", "respond_ms",
)


def phase_fields(rec: Dict[str, Any]) -> List[tuple]:
    """(phase, millis) pairs present in one record, in lifecycle
    order — shared by the trace CLI and tools/serve_load.py so the
    committed critical path and the printed waterfall agree."""
    out = []
    for phase in PHASE_ORDER:
        v = rec.get(phase)
        if isinstance(v, (int, float)):
            out.append((phase[:-3], float(v)))
    return out
