"""Structured access log for the serving daemon (round 15, with the
request-scoped tracing in serving/daemon.py).

One JSONL line per finished request — every outcome, including the
ones that never reached the queue (400 rejected) or never left it
(429 shed, 504 timeout) — carrying the request id, session, executable
key + cache verdict (`hit` | `disk` | `miss`: `disk` marks a dispatch
served by a DESERIALIZED executable from the persistent tier, booked
as `restore_ms` rather than `compile_ms` in the phase attribution),
the phase attribution (queue/compile/restore/execute/demux
milliseconds), and byte counts.  This is the flat, grep-able
counterpart to the per-request span tree: the span tree answers "what
happened inside THIS request", the access log answers "which requests
should I look at".

Durability contract:

  - **Atomic append.**  Each line is ONE `os.write` on an O_APPEND
    file descriptor; POSIX appends of this size are not interleaved
    across writers, so concurrent handler threads never shear a line.
    A lock serializes writers anyway (rotation needs it), making the
    syscall-level guarantee a backstop, not the mechanism.
  - **Size-capped rotation.**  When the live file would exceed
    `max_bytes` the writer seals it through a numbered shift chain —
    `.{N-1}→.N … .1→.2`, then live→`.1`, each step one atomic
    `os.replace`, the oldest generation dropping off the end — keeping
    `generations` (default 4) files of history so an incident
    bundle's access-log tail (round 23, telemetry/archive.py) can
    reach back past one rotation.  Readers (`read_entries`, the
    `ia-synth trace` CLI) walk `.N … .1` then the live file, oldest
    first.
  - **Never the hot path's problem.**  `log()` swallows OSError after
    recording it on `self.errors` — a full disk degrades observability,
    not availability.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

DEFAULT_MAX_BYTES = 4 * 1024 * 1024
DEFAULT_GENERATIONS = 4


class AccessLog:
    """Append-only JSONL writer with size-capped rotation across
    `generations` numbered history files."""

    def __init__(self, path: str,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 generations: int = DEFAULT_GENERATIONS):
        if max_bytes < 1024:
            raise ValueError(f"max_bytes too small ({max_bytes})")
        if generations < 1:
            raise ValueError(
                f"generations must be >= 1 ({generations})"
            )
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.generations = int(generations)
        self.errors = 0
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._size = 0

    def _open(self) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._size = os.fstat(self._fd).st_size

    def log(self, entry: Dict[str, Any]) -> None:
        """Serialize and append one record; rotates first when the
        line would push the live file past `max_bytes`."""
        line = (json.dumps(entry, sort_keys=True,
                           separators=(",", ":")) + "\n").encode()
        with self._lock:
            try:
                if self._fd is None:
                    self._open()
                if self._size + len(line) > self.max_bytes and self._size:
                    os.close(self._fd)
                    self._fd = None
                    # Shift chain, oldest first: .{N-1}→.N … .1→.2,
                    # live→.1.  Each step is one atomic os.replace, so
                    # a crash mid-shift leaves every line readable in
                    # SOME generation (possibly duplicated by number,
                    # never lost or torn).
                    for i in range(self.generations - 1, 0, -1):
                        src = f"{self.path}.{i}"
                        if os.path.exists(src):
                            os.replace(src, f"{self.path}.{i + 1}")
                    os.replace(self.path, self.path + ".1")
                    self._open()
                os.write(self._fd, line)
                self._size += len(line)
            except OSError:
                self.errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


def read_entries(path: str) -> Iterator[Dict[str, Any]]:
    """Yield access-log records oldest-first across every rotation
    generation (`<path>.N … <path>.1` then `<path>`), skipping
    unparseable lines (a crash mid-write loses at most the final
    line; everything readable still reads).  The shift chain keeps
    numbered generations contiguous from 1, so the scan stops at the
    first gap — single-`.1` writers (the round-16 journal) read
    exactly as before."""
    gens = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        gens.append(f"{path}.{i}")
        i += 1
    for p in list(reversed(gens)) + [path]:
        if not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def find_request(path: str, request_id: str
                 ) -> Optional[Dict[str, Any]]:
    """The LAST record for `request_id` (retries/duplicates: latest
    wins), or None when the id never hit this log."""
    found = None
    for rec in read_entries(path):
        if rec.get("request_id") == request_id:
            found = rec
    return found


# Lifecycle order of every phase any access record can carry.  A
# REPLICA record (serving/daemon.py) carries the queue..demux subset;
# a ROUTER record (serving/router.py) carries pick/proxy/respond.  The
# two sets are disjoint per record, so one ordered tuple serves both
# readers — and the fleet waterfall (serving/fleettrace.py) relies on
# that shared order when it nests a replica's phases inside the
# router's proxy window.
PHASE_ORDER = (
    "pick_ms", "queue_ms", "compile_ms", "restore_ms",
    "execute_ms", "demux_ms", "proxy_ms", "respond_ms",
)


def phase_fields(rec: Dict[str, Any]) -> List[tuple]:
    """(phase, millis) pairs present in one record, in lifecycle
    order — shared by the trace CLI and tools/serve_load.py so the
    committed critical path and the printed waterfall agree."""
    out = []
    for phase in PHASE_ORDER:
        v = rec.get(phase)
        if isinstance(v, (int, float)):
            out.append((phase[:-3], float(v)))
    return out
