"""Request queue, continuous batching, and admission control for the
serving daemon (round 13 tentpole, with serving/excache.py and
serving/daemon.py).

Three pure-testable policy pieces, kept free of HTTP and engine
imports so tests/test_serving.py can unit-test them with stub
requests:

  - `coalesce(entries, now, policy)` — the continuous-batching
    decision: queued requests whose COMPAT KEY matches the head's
    (executable key + luminance-stats bucket, serving/daemon.py)
    coalesce into one `parallel/batch` dispatch.  The batch flushes
    when it reaches `max_batch` or when the HEAD request has waited
    `max_wait_ms` — head-of-line age, not batch age, so a lone
    request's latency is bounded by max_wait regardless of arrival
    pattern.  Incompatible requests behind the head stay queued for a
    later batch (no reordering within a compat key: FIFO per key).
  - `AdmissionController` — the backpressure decision: a request is
    shed (HTTP 429 + Retry-After) when queue depth reaches
    `max_depth`; the threshold HALVES while the backend is degraded
    (the existing straggler gauge `ia_shard_imbalance_ratio` over the
    sentinel's IMBALANCE_RATIO_MAX, or the supervisor's degradation
    counter moving), so a struggling backend sheds load before it
    wedges rather than after.  Retry-After is estimated from observed
    service latency x backlog, clamped to [1, 60] s.
  - `demux(batch, stacked)` — the per-request result fan-out: row i of
    the dispatched stack belongs to batch[i] by construction (the
    daemon stacks frames in batch order), so demux is positional and
    its ordering is pinned by unit test, not convention.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


@dataclass
class ServeRequest:
    """One in-flight synthesis request (daemon-internal).

    `compat` is the batching identity: the executable key PLUS the
    luminance-stats bucket PLUS the session id (None for sessionless
    traffic) — two requests coalesce only if they share a compiled
    executable AND the same canonical remap statistics AND the same
    session, so a sessionless request's output never depends on its
    co-tenants (the batch-composition-independence contract,
    serving/daemon.py) and a session's frames never share a dispatch
    with strangers."""

    frame: Any  # np.ndarray (H, W, C) float32
    key: tuple  # executable key (serving/excache.exec_key)
    compat: tuple  # key + (luminance bucket, session id)
    b_stats: Optional[Tuple[float, float]]  # canonical bucket stats
    session: Optional[str] = None  # session-affinity id (daemon)
    req_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    # Inbound distributed-trace context (round 22): the upstream span
    # id (the router's `X-Parent-Span`) and hop count, validated at
    # ingest; None for untraced/direct traffic.  Recorded on the
    # serve_request root's attrs so the router and replica trees join.
    trace_parent: Optional[str] = None
    trace_hop: Optional[int] = None
    enqueue_t: float = field(default_factory=time.monotonic)
    # Absolute anchors for the SAME instant `enqueue_t` names: `t0` is
    # wall-clock epoch seconds (so post-mortem dumps from DIFFERENT
    # requests — whose monotonic zeroes are all their own enqueue — can
    # be ordered against each other), `enqueue_perf` is the
    # perf_counter reading the Tracer's span clock uses (so the
    # request's lifecycle events can be replayed as real spans on the
    # daemon tracer's timeline).  The relative `t_ms` span fields stay.
    t0: float = field(default_factory=time.time)
    enqueue_perf: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event,
                                  repr=False)
    # Round-16 resilience fields.  `deadline_t` is the client deadline
    # as a monotonic instant (None: no deadline); the dispatcher drops
    # a queued request whose deadline already passed instead of paying
    # a dispatch it cannot use.  `alive` is a zero-arg socket-liveness
    # probe bound to the client connection (None: unknown — treat as
    # alive); the dispatcher cancels queued requests whose probe says
    # the client hung up.  `replay` marks a request reconstructed from
    # the journal during takeover (no waiting client; the journal mark
    # is the response).  `manifest` keeps the parsed request body for
    # journaling at admission.
    deadline_t: Optional[float] = None
    alive: Any = None  # Optional[Callable[[], bool]]
    replay: bool = False
    manifest: Optional[Dict[str, Any]] = field(default=None, repr=False)
    # Round 20 shape lattice: when admission padded `frame` up to a
    # lattice bucket, `crop` is the client's true (H, W) — demux slices
    # the output row back down to it before the response is encoded.
    # None: the frame rode its exact shape (lattice off, on-bucket, or
    # bypass).
    crop: Optional[Tuple[int, int]] = None
    # Filled by the dispatcher before `done` is set:
    result: Any = None  # np.ndarray output frame on success
    error: Optional[str] = None  # failure detail (maps to 5xx)
    status: str = "queued"  # queued|ok|failed|cancelled
    cache: Optional[str] = None  # hit|miss for this request's dispatch
    batch_size: int = 0  # real (unpadded) co-tenant count
    # Prologue wall of this request's dispatch (ms) — the compile-phase
    # attribution the access log splits out of the execution window;
    # None when the dispatch carried no run tracer (sessions, disabled
    # observability).
    compile_ms: Optional[float] = None
    spans: List[Dict[str, Any]] = field(default_factory=list)

    def span(self, name: str) -> None:
        """Append a lifecycle span event (queued -> admitted ->
        compiled|cache-hit -> executed -> demuxed), timestamped
        relative to enqueue — plain dicts, not Tracer spans, because
        requests overlap arbitrarily across threads while the Tracer's
        span stack is strictly nested.  (The daemon converts them into
        a real per-request span tree at settle time, on the dispatcher
        thread, where no stack discipline is violated.)  `t_abs` is the
        wall-clock instant (`t0` + the relative offset)."""
        t_ms = round((time.monotonic() - self.enqueue_t) * 1000.0, 3)
        self.spans.append({
            "name": name,
            "t_ms": t_ms,
            "t_abs": round(self.t0 + t_ms / 1000.0, 6),
        })


@dataclass(frozen=True)
class BatchingPolicy:
    """max_batch: dispatch grain (and padding target — every dispatch
    is padded to exactly this many frames so the executable cache sees
    ONE batch shape per request shape).  max_wait_ms: the longest the
    queue head may age before a partial batch flushes."""

    max_batch: int = 4
    max_wait_ms: float = 25.0

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 ({self.max_batch})")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0 ({self.max_wait_ms})"
            )


def coalesce(entries: Sequence[ServeRequest], now: float,
             policy: BatchingPolicy) -> Optional[List[ServeRequest]]:
    """The batching decision over a snapshot of the queue (oldest
    first): return the head-compatible batch to dispatch now, or None
    (keep waiting — more compatible requests may arrive before the
    head ages out).  Pure: no locking, no popping; the caller removes
    the returned requests under its own lock."""
    if not entries:
        return None
    head = entries[0]
    batch = [r for r in entries if r.compat == head.compat]
    batch = batch[: policy.max_batch]
    if len(batch) >= policy.max_batch:
        return batch
    if (now - head.enqueue_t) * 1000.0 >= policy.max_wait_ms:
        return batch
    return None


def head_deadline(entries: Sequence[ServeRequest],
                  policy: BatchingPolicy) -> Optional[float]:
    """monotonic time at which the head's max-wait expires (the
    dispatcher's sleep bound), or None for an empty queue."""
    if not entries:
        return None
    return entries[0].enqueue_t + policy.max_wait_ms / 1000.0


class RequestQueue:
    """Thread-safe FIFO between HTTP handler threads (producers) and
    the dispatcher thread (consumer), with a condition variable so the
    dispatcher sleeps exactly until new work or the head's max-wait
    deadline."""

    def __init__(self):
        self._q: "deque[ServeRequest]" = deque()
        self._cond = threading.Condition()

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    def put(self, req: ServeRequest) -> None:
        with self._cond:
            self._q.append(req)
            self._cond.notify_all()

    def next_batch(self, policy: BatchingPolicy,
                   timeout: float = 0.5) -> Optional[List[ServeRequest]]:
        """Block (up to `timeout`) for the next dispatchable batch,
        removing it from the queue.  Returns None on timeout with no
        flushable batch — the dispatcher loops so shutdown checks run
        at least every `timeout` seconds."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                now = time.monotonic()
                batch = coalesce(list(self._q), now, policy)
                if batch is not None:
                    ids = {id(r) for r in batch}
                    kept = [r for r in self._q if id(r) not in ids]
                    self._q.clear()
                    self._q.extend(kept)
                    return batch
                if now >= deadline:
                    return None
                head_dl = head_deadline(list(self._q), policy)
                wait_until = deadline if head_dl is None else min(
                    deadline, head_dl
                )
                self._cond.wait(max(0.001, wait_until - now))

    def drain(self) -> List[ServeRequest]:
        """Remove and return everything queued (shutdown path: the
        daemon fails the leftovers as 'shutting down' rather than
        leaving their handler threads blocked forever)."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            return out


class AdmissionController:
    """Shed-or-admit, consulted by handler threads BEFORE enqueueing.

    The effective depth limit is `max_depth`, halved while
    `backend_degraded()` — wired to the same gauges the sentinel
    grades (`ia_shard_imbalance_ratio` against IMBALANCE_RATIO_MAX,
    plus any supervisor degradation bookings), so backpressure
    tightens the moment the backend starts limping, not when the
    queue finally overflows."""

    def __init__(self, max_depth: int = 32, registry=None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1 ({max_depth})")
        self.max_depth = int(max_depth)
        self._registry = registry

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..telemetry.metrics import get_registry

        return get_registry()

    def backend_degraded(self) -> bool:
        from ..telemetry.sentinel import IMBALANCE_RATIO_MAX

        snap = self._reg().to_dict()
        for v in snap.get("ia_shard_imbalance_ratio", {}).get(
            "values", {}
        ).values():
            if isinstance(v, (int, float)) and v > IMBALANCE_RATIO_MAX:
                return True
        degr = snap.get("ia_degradations_total", {}).get("values", {})
        return any(v for v in degr.values())

    def effective_depth(self) -> int:
        if self.backend_degraded():
            return max(1, self.max_depth // 2)
        return self.max_depth

    def admit(self, queue_depth: int,
              inflight: int) -> Tuple[bool, Optional[float]]:
        """(True, None) to admit, (False, retry_after_s) to shed.
        In-flight work counts against the limit too: a full batch
        mid-execution is backlog the client will wait behind."""
        limit = self.effective_depth()
        if queue_depth + inflight < limit:
            return True, None
        return False, self.retry_after(queue_depth + inflight)

    def retry_after(self, backlog: int) -> float:
        """Seconds the shed client should wait: observed p50 service
        latency x backlog ahead of it (the closed-loop drain time),
        clamped to [1, 60] — an estimate, deliberately coarse."""
        est = self.service_p50_s() * max(1, backlog)
        return round(min(60.0, max(1.0, est)), 1)

    def service_p50_s(self) -> float:
        """Observed p50 service-phase latency in seconds (0.0 before
        any request completed — cold daemons price deadlines at
        queue-wait only)."""
        p50 = self._reg().histogram(
            "ia_serve_request_ms",
            "serving request latency by lifecycle phase (ms)",
        ).quantile(0.5, labels={"phase": "service"})
        p50_ms = float(p50) if isinstance(p50, (int, float)) else 0.0
        return p50_ms / 1000.0

    def deadline_permits(self, deadline_t: Optional[float],
                         queue_depth: int, inflight: int,
                         now: Optional[float] = None) -> bool:
        """The hedged-shedding decision (round 16): would this request
        plausibly finish before its client deadline?  Prices the work
        AHEAD of it — (backlog + itself) x p50 service — against the
        time remaining; a request that cannot make it is shed at
        admission instead of wasting a dispatch the client will never
        read.  No deadline, or no latency history yet, admits."""
        if deadline_t is None:
            return True
        if now is None:
            now = time.monotonic()
        remaining = deadline_t - now
        if remaining <= 0.0:
            return False
        p50 = self.service_p50_s()
        if p50 <= 0.0:
            return True
        est = p50 * (queue_depth + inflight + 1)
        return est <= remaining


def demux(batch: Sequence[ServeRequest], stacked) -> None:
    """Fan the dispatched stack's rows back out to their requests:
    row i -> batch[i], by construction of the dispatch (the daemon
    stacks `[r.frame for r in batch]` in batch order and the runner
    preserves frame order through padding/trim).  A request admitted
    through the shape lattice gets its row cropped back to the
    client's true (H, W) here — per request, because co-tenants
    sharing a bucket may carry different raw shapes.  Marks each
    request ok; the caller sets `done` after response fields are
    final."""
    if len(stacked) < len(batch):
        raise ValueError(
            f"demux: {len(stacked)} output rows for {len(batch)} "
            "requests"
        )
    for i, req in enumerate(batch):
        row = stacked[i]
        if req.crop is not None:
            row = row[: req.crop[0], : req.crop[1]]
        req.result = row
        req.status = "ok"
        req.span("demuxed")
