"""Fleet trace fabric — the cross-process half of request tracing
(round 22 tentpole; the single-process half is round 15's span trees +
access log).

A routed request is a two-process story: the router's `route_request`
tree (received -> pick -> proxy_attempt per try -> respond) and one
replica's `serve_request` tree (queue -> compile|restore -> execute ->
demux).  This module joins them into ONE waterfall:

  - **Context propagation.**  The router forwards `X-Request-Id` (the
    join key), `X-Parent-Span` (its own per-request span id) and
    `X-Trace-Hop` downstream; the replica records them on its access
    entry and its `serve_request` root.  Header grammar: ids match
    `^[A-Za-z0-9._-]{1,64}$` (the round-15 request-id grammar), hops
    are 1-3 decimal digits.  Malformed values are REPLACED with
    generated ones, never rejected (`valid_token`/`parse_hop` are the
    shared validators both processes use).

  - **Join algorithm.**  Pull the router's access record + tree
    events and each replica's via `GET /request?id=` (the discovery
    file names every surface), then match: a replica record joins when
    its `parent_span` equals the router record's `span_id`, or —
    fallback for direct/untraced hops — when its `request_id` matches.

  - **Clock model.**  Each process stamps an ABSOLUTE wall anchor
    (`t0`, epoch seconds) next to its own monotonic walls.  Walls are
    never mixed across processes: replica phases nest inside the
    router's final proxy attempt using the replica's OWN relative
    offsets, so per-phase sums always stay within each process's own
    total.  The wall anchors are used only to bound clock skew:
    causality says the replica's handling happened inside the
    router's request window, so any excursion of
    [D.t0, D.t0 + D.total] outside [R.t0, R.t0 + R.total] is a LOWER
    bound on the clock offset — reported as `skew_bound_ms`, never
    corrected for (no imputation).

  - **Honest attribution.**  Named spans attribute: the router's
    pick/respond walls, every non-final proxy attempt's full wall
    (the retry cost IS a named span), and — inside the final attempt —
    the joined replica's phase sum, clipped to the attempt wall.  The
    remainder is `unattributed_ms` (network + HTTP framing + replica
    preamble): reported as a gap, never spread over neighbors.
    `critical_path_coverage` = attributed / router-observed total; the
    round-22 acceptance bar holds it >= 0.95 on the committed
    artifact (tools/check_fleet_trace.py).
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from .accesslog import phase_fields

FLEET_TRACE_SCHEMA_VERSION = 1

# Shared trace-token grammar: X-Request-Id AND X-Parent-Span values.
_TOKEN_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
_HOP_RE = re.compile(r"^\d{1,3}$")


def valid_token(v: Any) -> bool:
    """True when `v` is a well-formed trace token (request id or span
    id) safe for logs, span attrs, and metric exemplars verbatim."""
    return isinstance(v, str) and bool(_TOKEN_RE.match(v))


def parse_hop(v: Optional[str]) -> Optional[int]:
    """The validated hop count, or None when absent/malformed (a
    malformed hop is treated as absent — replaced, never rejected)."""
    if isinstance(v, str) and _HOP_RE.match(v):
        return int(v)
    return None


def _get_json(url: str, timeout: float) -> Any:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_fleet_trace(discovery: Dict[str, Any], request_id: str,
                      timeout: float = 10.0) -> Dict[str, Any]:
    """Walk one discovery doc (serving/router.py `discovery()` /
    `load_discovery`) and pull every process's view of `request_id`:
    the router's `GET /request?id=` plus each replica's.  A 404 means
    "this process never saw the request" (normal: only one replica
    serves it) and an unreachable process is recorded under `errors`
    — fetched best-effort, joined honestly."""
    out: Dict[str, Any] = {
        "request_id": request_id, "router": None, "replicas": [],
        "errors": [],
    }
    router_url = discovery.get("router")
    if isinstance(router_url, str) and router_url:
        try:
            out["router"] = _get_json(
                router_url.rstrip("/")
                + f"/request?id={request_id}", timeout,
            )
        except urllib.error.HTTPError as e:
            if e.code != 404:
                out["errors"].append(f"router: HTTP {e.code}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            out["errors"].append(f"router: {type(e).__name__}")
    for rep in discovery.get("replicas") or []:
        if not isinstance(rep, dict):
            continue
        url = rep.get("url")
        name = rep.get("name")
        if not isinstance(url, str) or not url:
            continue
        try:
            doc = _get_json(
                url.rstrip("/") + f"/request?id={request_id}", timeout,
            )
        except urllib.error.HTTPError as e:
            if e.code != 404:
                out["errors"].append(f"{name or url}: HTTP {e.code}")
            continue
        except (urllib.error.URLError, OSError, ValueError) as e:
            out["errors"].append(f"{name or url}: {type(e).__name__}")
            continue
        out["replicas"].append({"name": name, "url": url, "doc": doc})
    return out


def _num(v: Any) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def join_fleet_trace(router_rec: Optional[Dict[str, Any]],
                     replica_recs: List[Dict[str, Any]],
                     request_id: str,
                     router_events: Optional[List[Dict]] = None,
                     replica_events: Optional[Dict[str, List[Dict]]]
                     = None) -> Dict[str, Any]:
    """Join one router access record with the replica access records
    that claim the same request — PURE function over plain records
    (the clock-skew tests feed it synthetic processes), fetch lives in
    `fetch_fleet_trace`.  Returns the joined fleet-trace record
    (schema above: skew bound, attribution, waterfall rows)."""
    joined: Dict[str, Any] = {
        "schema_version": FLEET_TRACE_SCHEMA_VERSION,
        "kind": "fleet_trace",
        "request_id": request_id,
        "router": router_rec,
        "replicas": [],
        "rows": [],
        "skew_bound_ms": None,
        "anchor_delta_ms": None,
        "attributed_ms": None,
        "unattributed_ms": None,
        "retry_ms": 0.0,
        "retries": 0,
        "critical_path_coverage": None,
        "notes": [],
    }
    if router_events:
        joined["router_events"] = router_events
    if replica_events:
        joined["replica_events"] = replica_events
    if router_rec is None:
        joined["notes"].append(
            "no router record: request was not routed (or the router "
            "log rotated past it)"
        )
        for rec in replica_recs:
            joined["replicas"].append({"record": rec, "joined": False})
        return joined
    span_id = router_rec.get("span_id")
    r_t0 = _num(router_rec.get("t0"))
    r_total = _num(router_rec.get("total_ms")) or 0.0
    pick_ms = _num(router_rec.get("pick_ms")) or 0.0
    respond_ms = _num(router_rec.get("respond_ms")) or 0.0
    attempts = [a for a in (router_rec.get("attempts") or [])
                if isinstance(a, dict)]
    joined["retries"] = int(router_rec.get("retries") or 0)

    # -- join -----------------------------------------------------
    final_rep: Optional[Dict[str, Any]] = None
    for rec in replica_recs:
        is_join = (
            (span_id is not None
             and rec.get("parent_span") == span_id)
            or rec.get("request_id") == request_id
        )
        entry = {
            "record": rec, "joined": bool(is_join),
            "anchor_delta_ms": (
                round((_num(rec.get("t0")) - r_t0) * 1000.0, 3)
                if is_join and r_t0 is not None
                and _num(rec.get("t0")) is not None else None
            ),
        }
        joined["replicas"].append(entry)
        if is_join and final_rep is None:
            final_rep = rec

    # -- skew bound (wall anchors only; never corrected for) ------
    skew = 0.0
    if final_rep is not None and r_t0 is not None:
        d_t0 = _num(final_rep.get("t0"))
        d_total = _num(final_rep.get("total_ms")) or 0.0
        if d_t0 is not None:
            early = (r_t0 - d_t0) * 1000.0
            late = ((d_t0 + d_total / 1000.0)
                    - (r_t0 + r_total / 1000.0)) * 1000.0
            skew = max(0.0, early, late)
            joined["anchor_delta_ms"] = round((d_t0 - r_t0) * 1000.0,
                                              3)
    joined["skew_bound_ms"] = round(skew, 3)
    if skew > 0:
        joined["notes"].append(
            f"clock skew >= {skew:.1f} ms between router and replica "
            "wall anchors (replica window escapes the router window); "
            "rows are nested by each process's OWN offsets, not "
            "shifted"
        )

    # -- attribution + rows ---------------------------------------
    rows: List[Dict[str, Any]] = []
    off = 0.0
    attributed = 0.0
    rows.append({"process": "router", "phase": "pick",
                 "offset_ms": round(off, 3),
                 "wall_ms": round(pick_ms, 3)})
    attributed += pick_ms
    off += pick_ms
    retry_ms = 0.0
    for i, att in enumerate(attempts):
        wall = _num(att.get("wall_ms")) or 0.0
        last = i == len(attempts) - 1
        label = (f"proxy_attempt[{att.get('outcome')}"
                 f"->{att.get('replica')}]")
        rows.append({"process": "router", "phase": label,
                     "offset_ms": round(off, 3),
                     "wall_ms": round(wall, 3)})
        if not last:
            # A retried attempt's whole wall is named work: the
            # proxy_attempt span with its retry_reason IS the
            # attribution.
            retry_ms += wall
            attributed += wall
        elif final_rep is not None:
            # Nest the replica's phases inside the final attempt using
            # the REPLICA's own relative offsets — no cross-clock math.
            phases = phase_fields(final_rep)
            p_sum = sum(w for _, w in phases)
            inner = min(p_sum, wall)
            attributed += inner
            if p_sum > wall:
                joined["notes"].append(
                    f"replica phase sum {p_sum:.1f} ms exceeds the "
                    f"router's attempt wall {wall:.1f} ms (clock "
                    "granularity/skew); clipped in the coverage "
                    "arithmetic, replica rows untouched"
                )
            p_off = off
            # The replica record doesn't know its fleet name; the
            # router's record does (the chosen replica of the final
            # attempt).
            proc = str(att.get("replica")
                       or router_rec.get("replica") or "replica")
            for pname, wallp in phases:
                rows.append({"process": proc, "phase": pname,
                             "offset_ms": round(p_off, 3),
                             "wall_ms": round(wallp, 3)})
                p_off += wallp
        else:
            joined["notes"].append(
                "no replica record joined: the proxy window is "
                "unattributed below the router's own spans"
            )
        off += wall
    joined["retry_ms"] = round(retry_ms, 3)
    rows.append({"process": "router", "phase": "respond",
                 "offset_ms": round(off, 3),
                 "wall_ms": round(respond_ms, 3)})
    attributed += respond_ms
    joined["rows"] = rows
    attributed = min(attributed, r_total)
    joined["attributed_ms"] = round(attributed, 3)
    joined["unattributed_ms"] = round(max(0.0, r_total - attributed),
                                      3)
    joined["critical_path_coverage"] = (
        round(attributed / r_total, 4) if r_total > 0 else None
    )
    return joined


def render_fleet_waterfall(joined: Dict[str, Any],
                           width: int = 40) -> str:
    """The one-command waterfall `ia-synth trace <id> --fleet` prints:
    every row offset/wall as bars on the router's request timeline,
    the skew bound, and the honest unattributed gap."""
    out: List[str] = []
    rid = joined.get("request_id")
    router = joined.get("router") or {}
    total = _num(router.get("total_ms")) or 0.0
    out.append(f"fleet trace {rid}")
    out.append(
        f"  router: outcome={router.get('outcome')} "
        f"replica={router.get('replica')} "
        f"total={total:.1f} ms retries={joined.get('retries')}"
    )
    scale = (width / total) if total > 0 else 0.0
    for row in joined.get("rows") or []:
        offset = _num(row.get("offset_ms")) or 0.0
        wall = _num(row.get("wall_ms")) or 0.0
        lead = int(offset * scale)
        bar = max(1, int(wall * scale)) if wall > 0 else 0
        out.append(
            f"  {row.get('process', ''):>8s} "
            f"{row.get('phase', ''):<28s} "
            f"{' ' * lead}{'#' * bar}  {wall:9.1f} ms"
        )
    gap = joined.get("unattributed_ms")
    cov = joined.get("critical_path_coverage")
    if gap is not None:
        out.append(
            f"  unattributed gap {gap:.1f} ms "
            f"(coverage {cov if cov is not None else 'n/a'})"
        )
    skew = joined.get("skew_bound_ms")
    if skew is not None:
        out.append(f"  clock skew bound {skew:.1f} ms")
    for note in joined.get("notes") or []:
        out.append(f"  note: {note}")
    return "\n".join(out)
