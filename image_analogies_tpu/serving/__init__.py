"""Synthesis-as-a-service (round 13): a request-queue daemon over the
existing runners, with a compiled-executable cache, continuous
batching, and admission control.  `ia-synth serve` is the front door;
serving/daemon.py documents the architecture."""

from .daemon import SynthDaemon
from .excache import (
    ExecutableCache,
    compression_mode,
    config_fingerprint,
    exec_key,
    load_warmup_manifest,
    run_warmup,
)
from .queueing import (
    AdmissionController,
    BatchingPolicy,
    RequestQueue,
    ServeRequest,
    coalesce,
    demux,
)

__all__ = [
    "SynthDaemon",
    "ExecutableCache",
    "compression_mode",
    "config_fingerprint",
    "exec_key",
    "load_warmup_manifest",
    "run_warmup",
    "AdmissionController",
    "BatchingPolicy",
    "RequestQueue",
    "ServeRequest",
    "coalesce",
    "demux",
]
