"""Fleet router — the routing half of fleet-scale serving (round 21
tentpole; the observation half is serving/observatory.py).

One `SynthDaemon` replica is one process with one queue; the router is
the lightweight front tier that makes N of them behave like one
service: `POST /synthesize` spreads across replicas by least
outstanding work (the router's own in-flight count per replica PLUS
the queue_depth + inflight each replica reports on `/serving`, scraped
by a background poller), while a request carrying `session_id` sticks
to the replica holding that session's warm-start stream — spreading a
video session across replicas would re-pay a cold frame per hop, so
affinity is correctness-adjacent, not a nicety.

The router holds NO synthesis state and imports NO JAX: it is cheap
enough to run in-process next to anything (the CLI's `ia-synth route`,
the load harness, a test).  All durable state lives in the replicas:

  - requests are journaled AT THE REPLICA after admission, so a proxy
    retry after a CONNECTION failure is safe (the request either never
    reached admission, or it is journaled and a takeover will replay
    it — outputs are bit-identical either way, by the round-16
    isolation contract);
  - sessions migrate THROUGH THE FILESYSTEM: `drain_replica` drains
    the victim (its drain snapshot writes sessions BEFORE the journal
    compaction — the round-21 ordering fix), then tells a survivor to
    `POST /sessions/adopt` from the victim's state dir, then re-pins
    the affinity table.  The router only ever coordinates; it never
    carries NNF state over HTTP.

Telemetry flows through the standard registry (`ia_route_*` families,
kept by the observatory's scrape filter) and the router answers
`/metrics.json` + `/slo` like any replica, so `ia-synth obs` pointed
at the discovery file grades router and replicas in one sweep.  The
discovery file (`--discovery-out`) is rewritten atomically on every
membership/drain change: `{"targets": [...]}` is exactly what
`ia-synth obs --targets <file>` consumes (round 21 satellite).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

ROUTER_SCHEMA_VERSION = 1
DISCOVERY_KIND = "fleet_discovery"

# Outcome vocabulary for ia_route_duration_ms — aligned with the
# replica's ia_request_duration_ms outcomes so telemetry/slo.py's
# admitted/bad split applies unchanged: `unavailable`/`shed`/
# `cancelled`/`rejected` are availability-EXCLUDED (round-16
# semantics: the backend never owed those requests a response), while
# `failed`/`timeout` burn budget and `ok` earns it.
_OUTCOME_BY_CODE = {
    200: "ok", 400: "rejected", 429: "shed", 499: "cancelled",
    503: "unavailable", 504: "timeout",
}


def _outcome_for_code(code: int) -> str:
    return _OUTCOME_BY_CODE.get(code, "failed")


def _header(headers, name: str) -> Optional[str]:
    """Case-insensitive header lookup over whatever mapping the HTTP
    layer handed us."""
    want = name.lower()
    for k, v in (headers or {}).items():
        if str(k).lower() == want and isinstance(v, str):
            return v
    return None

# One proxy hop is bounded by the replica's own behavior (admission
# sheds, dispatch deadlines); the router just needs to outlast a cold
# compile on the slowest replica.
DEFAULT_PROXY_TIMEOUT_S = 600.0


class ReplicaHandle:
    """Router-side view of one replica: identity + the poller's last
    scrape + the router's own outstanding-proxy count."""

    def __init__(self, name: str, url: str,
                 state_dir: Optional[str] = None):
        self.name = name
        self.url = url.rstrip("/")
        self.state_dir = state_dir
        self.alive = False
        self.draining = False
        self.queue_depth = 0
        self.inflight = 0
        self.outstanding = 0  # router-local proxies in flight
        self.poll_ms: Optional[float] = None
        self.proxied = 0
        self.errors = 0

    def score(self) -> int:
        """Least-outstanding-requests with queue-depth awareness: the
        router's own unreturned proxies (instant) plus the replica's
        last-reported backlog (poll-interval stale; the local count
        covers the staleness window)."""
        return self.outstanding + self.queue_depth + self.inflight

    def snapshot(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "url": self.url,
            "state_dir": self.state_dir,
            "alive": self.alive,
            "draining": self.draining,
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
            "outstanding": self.outstanding,
            "poll_ms": self.poll_ms,
            "proxied": self.proxied,
            "errors": self.errors,
        }


def _http_json(url: str, timeout: float, *, method: str = "GET",
               body: Optional[bytes] = None) -> Any:
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _session_from_body(body: Optional[bytes]) -> Optional[str]:
    """The request's session_id, parsed leniently: routing must never
    reject what the replica would accept — a malformed body routes
    anywhere and gets the replica's own 400."""
    if not body:
        return None
    try:
        manifest = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    sid = manifest.get("session_id") if isinstance(manifest, dict) \
        else None
    return sid if isinstance(sid, str) and sid else None


class FleetRouter:
    """The front tier.  `start()` binds the HTTP endpoint (a
    LiveTelemetryServer, same surface as every daemon) and the poller;
    `add_replica` / `remove_replica` / `drain_replica` manage
    membership.  Thread-safety: membership + affinity live behind one
    lock; proxying happens OUTSIDE it (only the pick and the
    bookkeeping lock)."""

    def __init__(self, registry, *, tracer=None, host: str = "127.0.0.1",
                 port: int = 0, poll_interval_s: float = 0.5,
                 scrape_timeout_s: float = 5.0,
                 proxy_timeout_s: float = DEFAULT_PROXY_TIMEOUT_S,
                 discovery_path: Optional[str] = None,
                 flight=None, access_log_path: Optional[str] = None):
        from ..telemetry.spans import as_tracer

        self.registry = registry
        self.tracer = as_tracer(tracer)
        self.host = host
        self._requested_port = port
        self.poll_interval_s = float(poll_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.discovery_path = discovery_path
        self.flight = flight
        self.live = None
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaHandle] = {}
        self._affinity: Dict[str, str] = {}  # session_id -> replica
        self._seq = 0
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        # Plain counters mirrored into the registry: /fleet reads them
        # without walking serialized metric families.
        self.affinity_counts = {"hit": 0, "new": 0, "repin": 0}
        self.proxied = 0
        self.proxy_errors = 0
        self.retries = 0
        self.migrations = 0
        r = registry
        self._c_requests = r.counter(
            "ia_route_requests_total",
            "requests proxied through the fleet router, by replica "
            "and outcome",
        )
        self._c_affinity = r.counter(
            "ia_route_affinity_total",
            "session-affinity routing decisions (hit: pinned replica "
            "served; new: first sighting pinned; repin: pin moved off "
            "a draining/dead replica)",
        )
        self._c_migrations = r.counter(
            "ia_route_migrations_total",
            "session streams migrated between replicas at drain",
        )
        self._g_outstanding = r.gauge(
            "ia_route_outstanding",
            "router-local in-flight proxies per replica",
        )
        self._g_up = r.gauge(
            "ia_route_replica_up",
            "replica reachability from the router's poller (1 up, "
            "0 down)",
        )
        self._g_draining = r.gauge(
            "ia_route_replica_draining",
            "replica drain state as the router sees it (1 draining)",
        )
        self._h_proxy = r.histogram(
            "ia_route_proxy_ms",
            "router proxy wall per request (pick + replica round "
            "trip), by outcome",
        )
        from ..telemetry.slo import (
            REQUEST_DURATION_BUCKETS,
            ROUTE_DURATION_METRIC,
        )

        # Router-observed end-to-end latency — same bucket ladder and
        # outcome vocabulary as the replica family, so the existing
        # SloEngine grades the router hop with unchanged budget
        # arithmetic (round-22 satellite: router requests no longer
        # vanish from SLO math).
        self._h_duration = r.histogram(
            ROUTE_DURATION_METRIC,
            "router-observed request latency (ms) by outcome/replica "
            "— the raw family the router SLO objectives grade",
            buckets=REQUEST_DURATION_BUCKETS,
        )
        self._c_retries = r.counter(
            "ia_route_retries_total",
            "proxy attempts re-routed to another replica, by reason "
            "(conn_error: connection-level failure; draining: replica "
            "refused before admission)",
        )
        self._c_unrouted = r.counter(
            "ia_route_unrouted_total",
            "requests the router could not place on any live "
            "non-draining replica (503 + Retry-After)",
        )
        self._h_migration = r.histogram(
            "ia_route_migration_ms",
            "drain-time session migration wall (drain signal -> "
            "sessions adopted + re-pinned) per drain_replica call",
        )
        # Router-side JSONL access log (round-22 tentpole): same
        # durability contract as the replica's (serving/accesslog.py),
        # one line per routed request with per-phase walls and the
        # chosen replica.  Off (None) unless a path is given — the
        # hot path stays allocation-free when untraced.
        from .accesslog import AccessLog

        self.access = (
            AccessLog(access_log_path) if access_log_path else None
        )
        self._slo_engine = None

    # ------------------------------------------------------ lifecycle
    def start(self) -> "FleetRouter":
        from ..telemetry.live import LiveTelemetryServer

        self.live = LiveTelemetryServer(
            self.tracer,
            self.registry,
            port=self._requested_port,
            host=self.host,
            flight=self.flight,
            health_cb=self.health,
            routes={
                ("POST", "/synthesize"): self._route_synthesize,
                ("GET", "/fleet"): self._route_fleet,
                ("GET", "/replicas"): self._route_replicas,
                ("GET", "/slo"): self._route_slo,
                ("GET", "/request"): self._route_request,
                ("GET", "/incidents"): self._route_incidents,
                ("POST", "/replicas/add"): self._route_add,
                ("POST", "/replicas/remove"): self._route_remove,
                ("POST", "/drain_replica"): self._route_drain_replica,
            },
        ).start()
        self._poller = threading.Thread(
            target=self._poll_loop, name="ia-route-poll", daemon=True
        )
        self._poller.start()
        self._write_discovery()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=10.0)
            self._poller = None
        if self.live is not None:
            self.live.stop()
            self.live = None
        if self.access is not None:
            self.access.close()

    @property
    def url(self) -> str:
        return self.live.url

    def health(self) -> Dict[str, Any]:
        with self._lock:
            live = sum(1 for h in self._replicas.values() if h.alive)
            total = len(self._replicas)
        return {
            "verdict": "ok" if live else "violated",
            "context": "router",
            "replicas_live": live,
            "replicas_total": total,
        }

    # ----------------------------------------------------- membership
    def add_replica(self, url: str, name: Optional[str] = None,
                    state_dir: Optional[str] = None) -> ReplicaHandle:
        """Register one replica.  Its state_dir (the migration source/
        sink) comes from the caller or from the replica's own /serving
        snapshot on the first successful poll."""
        url = url.rstrip("/")
        with self._lock:
            for h in self._replicas.values():
                if h.url == url:
                    return h
            if name is None:
                name = f"r{self._seq}"
                self._seq += 1
            if name in self._replicas:
                raise ValueError(f"replica name {name!r} already "
                                 "registered")
            handle = ReplicaHandle(name, url, state_dir)
            self._replicas[name] = handle
        self._poll_one(handle)
        self._write_discovery()
        return handle

    def remove_replica(self, name: str) -> bool:
        with self._lock:
            handle = self._replicas.pop(name, None)
            if handle is None:
                return False
            for sid in [s for s, rep in self._affinity.items()
                        if rep == name]:
                del self._affinity[sid]
        self._write_discovery()
        return True

    def replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [h.snapshot() for h in self._replicas.values()]

    # -------------------------------------------------------- polling
    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            with self._lock:
                handles = list(self._replicas.values())
            for h in handles:
                self._poll_one(h)

    def _poll_one(self, h: ReplicaHandle) -> None:
        t0 = time.monotonic()
        try:
            snap = _http_json(h.url + "/serving",
                              self.scrape_timeout_s)
            h.queue_depth = int(snap.get("queue_depth") or 0)
            h.inflight = int(snap.get("inflight") or 0)
            h.draining = bool(snap.get("draining"))
            if h.state_dir is None:
                sd = snap.get("state_dir")
                if isinstance(sd, str) and sd:
                    h.state_dir = sd
            h.alive = True
            h.poll_ms = round((time.monotonic() - t0) * 1000.0, 2)
        except (urllib.error.URLError, OSError, ValueError):
            h.alive = False
        self._g_up.set(1.0 if h.alive else 0.0,
                       labels={"replica": h.name})
        self._g_draining.set(1.0 if h.draining else 0.0,
                             labels={"replica": h.name})

    # -------------------------------------------------------- routing
    def _pick(self, session: Optional[str],
              exclude: Optional[str] = None):
        """One routing decision under the lock: affinity first (a live
        non-draining pinned replica is a `hit`), else least score.
        Returns (handle, affinity_result|None, considered) where
        `considered` lists every candidate's outstanding score at
        decision time (the `pick` span's attrs — round-22 trace
        fabric); books the outstanding increment the caller must pair
        with `_settle`."""
        with self._lock:
            result = None
            handle = None
            considered: List[Dict[str, Any]] = []
            if session is not None:
                pinned = self._affinity.get(session)
                if pinned is not None:
                    h = self._replicas.get(pinned)
                    if (h is not None and h.alive and not h.draining
                            and h.name != exclude):
                        handle, result = h, "hit"
                        considered = [{"replica": h.name,
                                       "score": h.score(),
                                       "pinned": True}]
            if handle is None:
                candidates = [
                    h for h in self._replicas.values()
                    if h.alive and not h.draining and h.name != exclude
                ]
                considered = [{"replica": h.name, "score": h.score()}
                              for h in candidates]
                if not candidates:
                    return None, None, considered
                handle = min(
                    candidates, key=lambda h: (h.score(), h.name)
                )
                if session is not None:
                    result = ("repin" if session in self._affinity
                              else "new")
                    self._affinity[session] = handle.name
            if result is not None:
                self.affinity_counts[result] += 1
                self._c_affinity.inc(labels={"result": result})
            handle.outstanding += 1
            self._g_outstanding.set(
                float(handle.outstanding),
                labels={"replica": handle.name},
            )
            return handle, result, considered

    def _settle(self, handle: ReplicaHandle, ok: bool) -> None:
        with self._lock:
            handle.outstanding = max(0, handle.outstanding - 1)
            self._g_outstanding.set(
                float(handle.outstanding),
                labels={"replica": handle.name},
            )
            if ok:
                handle.proxied += 1
                self.proxied += 1
            else:
                handle.errors += 1
                self.proxy_errors += 1

    def _route_synthesize(self, body: Optional[bytes], headers=None):
        """Proxy one /synthesize.  Connection-level failures mark the
        replica down and retry ONCE elsewhere (safe: admission
        journals before ack, and replayed outputs are bit-identical);
        HTTP-level replies (200/400/429/503) pass through — except a
        draining 503, which re-routes once because the poller simply
        hasn't caught the drain yet.

        Round-22 trace fabric: every request gets a validated (or
        generated — malformed values replaced, never rejected, the
        round-15 id policy) `X-Request-Id`, the router's own span id is
        forwarded downstream as `X-Parent-Span` with an incremented
        `X-Trace-Hop`, and — when the router is traced — the whole hop
        is reconstructed as a `route_request` span tree (received ->
        pick -> proxy_attempt per try -> respond) plus one access-log
        line with per-phase walls and the chosen replica, joinable
        with the replica's `serve_request` tree by request id."""
        from ..telemetry.spans import new_span_id, span_at
        from .fleettrace import parse_hop, valid_token

        p_recv = time.perf_counter()
        t0_wall = time.time()
        session = _session_from_body(body)
        raw_rid = _header(headers, "x-request-id")
        rid = (raw_rid if raw_rid is not None and valid_token(raw_rid)
               else new_span_id())
        raw_parent = _header(headers, "x-parent-span")
        client_parent = (
            raw_parent
            if raw_parent is None or valid_token(raw_parent)
            else new_span_id()
        )
        hop_in = parse_hop(_header(headers, "x-trace-hop"))
        hop_out = (hop_in if hop_in is not None else 0) + 1
        span_id = new_span_id()
        traced = self.tracer.enabled or self.access is not None
        bytes_in = len(body or b"")
        children: List[Tuple[str, float, float, Dict[str, Any]]] = []
        attempts: List[Dict[str, Any]] = []
        retries = 0
        pick_ms = 0.0
        proxy_ms = 0.0
        p_received_end = time.perf_counter()
        if traced:
            children.append(("received", p_recv, p_received_end, {}))

        def finish(code, payload, ctype, extra_headers, outcome,
                   replica, proxy_outcome):
            p_end = time.perf_counter()
            total_ms = (p_end - p_recv) * 1000.0
            self._h_proxy.observe(total_ms,
                                  labels={"outcome": proxy_outcome})
            self._h_duration.observe(total_ms, labels={
                "outcome": outcome, "replica": replica or "none",
            }, exemplar=rid)
            out_headers = {
                "X-Request-Id": rid,
                "X-Parent-Span": span_id,
                "X-Trace-Hop": str(hop_out),
            }
            out_headers.update(extra_headers or {})
            if traced:
                p_last = children[-1][2] if children else p_recv
                children.append(("respond", p_last, p_end, {}))
                root_attrs: Dict[str, Any] = {
                    "request_id": rid, "span_id": span_id,
                    "outcome": outcome, "http_status": code,
                    "replica": replica, "attempts": len(attempts),
                    "retries": retries, "hop": hop_in or 0,
                }
                if session is not None:
                    root_attrs["session"] = session
                if client_parent is not None:
                    root_attrs["parent_span"] = client_parent
                root = span_at("route_request", p_recv, p_end,
                               **root_attrs)
                for name, a, b, attrs in children:
                    root.children.append(
                        span_at(name, a, b, request_id=rid, **attrs)
                    )
                self.tracer.attach_tree(root)
                if self.access is not None:
                    entry: Dict[str, Any] = {
                        "ts": root.ts, "t0": round(t0_wall, 6),
                        "kind": "router", "route": "/synthesize",
                        "request_id": rid, "span_id": span_id,
                        "hop": hop_in or 0,
                        "session_id": session, "outcome": outcome,
                        "http_status": code, "replica": replica,
                        "attempts": attempts, "retries": retries,
                        "total_ms": round(total_ms, 3),
                        "pick_ms": round(pick_ms, 3),
                        "proxy_ms": round(proxy_ms, 3),
                        "respond_ms": round(
                            (p_end - p_last) * 1000.0, 3),
                        "bytes_in": bytes_in,
                        "bytes_out": len(payload or b""),
                    }
                    if client_parent is not None:
                        entry["parent_span"] = client_parent
                    self.access.log(entry)
            return (code, payload, ctype, out_headers)

        exclude = None
        for attempt in (0, 1):
            p_pick0 = time.perf_counter()
            handle, aff, considered = self._pick(session,
                                                 exclude=exclude)
            p_pick1 = time.perf_counter()
            pick_ms += (p_pick1 - p_pick0) * 1000.0
            if traced:
                pick_attrs: Dict[str, Any] = {
                    "replica": handle.name if handle else None,
                    "considered": considered,
                }
                if aff is not None:
                    pick_attrs["affinity"] = aff
                children.append(("pick", p_pick0, p_pick1, pick_attrs))
            if handle is None:
                self._c_unrouted.inc()
                payload = json.dumps({
                    "status": "unavailable",
                    "error": "no live non-draining replica",
                    "request_id": rid,
                }).encode()
                return finish(503, payload, "application/json",
                              {"Retry-After": "1"}, "unavailable",
                              None, "unrouted")
            hdrs = {
                "Content-Type": "application/json",
                "X-Request-Id": rid,
                "X-Parent-Span": span_id,
                "X-Trace-Hop": str(hop_out),
            }
            req = urllib.request.Request(
                handle.url + "/synthesize", data=body or b"{}",
                method="POST", headers=hdrs,
            )
            code = None
            p_send = time.perf_counter()
            try:
                with urllib.request.urlopen(
                    req, timeout=self.proxy_timeout_s
                ) as resp:
                    code, payload = resp.status, resp.read()
                    resp_headers = dict(resp.headers)
            except urllib.error.HTTPError as e:
                code, payload = e.code, e.read()
                resp_headers = dict(e.headers)
            except (urllib.error.URLError, OSError):
                # Connection refused/reset: the replica is gone (or
                # going).  Mark it down so the next pick skips it and
                # retry the request elsewhere once.
                p_fail = time.perf_counter()
                wall = (p_fail - p_send) * 1000.0
                proxy_ms += wall
                self._settle(handle, ok=False)
                with self._lock:
                    handle.alive = False
                self._g_up.set(0.0, labels={"replica": handle.name})
                self._c_requests.inc(labels={
                    "replica": handle.name, "outcome": "conn_error",
                })
                retrying = attempt == 0
                attempts.append({
                    "replica": handle.name, "outcome": "conn_error",
                    "wall_ms": round(wall, 3),
                    "retry_reason": "conn_error" if retrying else None,
                })
                if traced:
                    children.append(("proxy_attempt", p_send, p_fail, {
                        "replica": handle.name, "outcome": "conn_error",
                        "retry_reason": (
                            "conn_error" if retrying else None),
                    }))
                if retrying:
                    with self._lock:
                        self.retries += 1
                    retries += 1
                    self._c_retries.inc(
                        labels={"reason": "conn_error"})
                    exclude = handle.name
                    continue
                payload = json.dumps({
                    "status": "unavailable",
                    "error": "replica connection failed twice",
                    "request_id": rid,
                }).encode()
                return finish(502, payload, "application/json", {},
                              "failed", handle.name, "conn_error")
            p_resp = time.perf_counter()
            wall = (p_resp - p_send) * 1000.0
            proxy_ms += wall
            draining_503 = False
            if code == 503 and attempt == 0:
                try:
                    draining_503 = json.loads(
                        payload.decode("utf-8")
                    ).get("status") == "unavailable"
                except (ValueError, UnicodeDecodeError):
                    draining_503 = False
            if draining_503:
                # The replica started draining between polls: it
                # refused BEFORE admission (no journal entry), so a
                # re-route duplicates nothing.
                self._settle(handle, ok=False)
                with self._lock:
                    handle.draining = True
                    self.retries += 1
                retries += 1
                self._c_retries.inc(labels={"reason": "draining"})
                self._g_draining.set(
                    1.0, labels={"replica": handle.name}
                )
                self._c_requests.inc(labels={
                    "replica": handle.name, "outcome": "draining",
                })
                attempts.append({
                    "replica": handle.name, "outcome": "draining",
                    "wall_ms": round(wall, 3),
                    "retry_reason": "draining",
                })
                if traced:
                    children.append(("proxy_attempt", p_send, p_resp, {
                        "replica": handle.name, "outcome": "draining",
                        "retry_reason": "draining",
                    }))
                exclude = handle.name
                continue
            self._settle(handle, ok=code == 200)
            self._c_requests.inc(labels={
                "replica": handle.name, "outcome": str(code),
            })
            attempts.append({
                "replica": handle.name, "outcome": str(code),
                "wall_ms": round(wall, 3),
            })
            if traced:
                children.append(("proxy_attempt", p_send, p_resp, {
                    "replica": handle.name, "outcome": str(code),
                }))
            out_headers = {"X-Routed-To": handle.name}
            if "Retry-After" in resp_headers:
                out_headers["Retry-After"] = resp_headers["Retry-After"]
            return finish(code, payload, "application/json",
                          out_headers, _outcome_for_code(code),
                          handle.name,
                          "ok" if code == 200 else "error")
        raise AssertionError("unreachable")

    # ------------------------------------------------- drain/migrate
    def drain_replica(self, name: str, wait_s: float = 120.0
                      ) -> Dict[str, Any]:
        """Rolling-restart primitive: stop routing to `name`, POST its
        /drain, wait for `drained` (the drain snapshot — sessions
        BEFORE journal compaction — is on disk once that flips), then
        hand its pinned sessions to the least-loaded survivor via
        /sessions/adopt and re-pin them.  Synchronous; returns the
        migration report.  The caller owns the process afterwards
        (kill, takeover, re-add).

        Round-22 migration visibility: the whole drain is one
        `drain_migration` span tree (drain_wait -> sessions_adopt ->
        repin) attached to the router tracer, and the drain-to-adopted
        wall lands in `ia_route_migration_ms` — so a repinned
        session's first frame shows its true cost attribution in the
        fleet waterfall instead of an anonymous stall."""
        from ..telemetry.spans import span_at

        p_drain0 = time.perf_counter()
        mig_children: List[Any] = []
        with self._lock:
            handle = self._replicas.get(name)
            if handle is None:
                raise KeyError(f"unknown replica {name!r}")
            handle.draining = True
            pinned = [s for s, rep in self._affinity.items()
                      if rep == name]
        self._g_draining.set(1.0, labels={"replica": name})
        self._write_discovery()
        report: Dict[str, Any] = {
            "replica": name, "state_dir": handle.state_dir,
            "sessions_pinned": list(pinned), "drained": False,
            "sessions_migrated": [], "migrated_to": None,
        }
        try:
            _http_json(handle.url + "/drain", self.scrape_timeout_s,
                       method="POST", body=b"{}")
        except (urllib.error.URLError, OSError, ValueError):
            # Already dead: its sessions still migrate below if a
            # snapshot exists on disk (e.g. a previous drain).
            pass
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            try:
                snap = _http_json(handle.url + "/journal",
                                  self.scrape_timeout_s)
                if snap.get("drained"):
                    report["drained"] = True
                    break
            except (urllib.error.URLError, OSError, ValueError):
                # Process exited after drain: snapshot is on disk.
                report["drained"] = True
                break
            time.sleep(0.1)
        p_wait1 = time.perf_counter()
        if self.tracer.enabled:
            mig_children.append(span_at(
                "drain_wait", p_drain0, p_wait1, replica=name,
                drained=report["drained"],
            ))
        if pinned and handle.state_dir:
            with self._lock:
                candidates = [
                    h for h in self._replicas.values()
                    if h.alive and not h.draining and h.name != name
                ]
                target = min(
                    candidates, key=lambda h: (h.score(), h.name)
                ) if candidates else None
            if target is not None:
                p_adopt0 = time.perf_counter()
                try:
                    resp = _http_json(
                        target.url + "/sessions/adopt",
                        self.proxy_timeout_s, method="POST",
                        body=json.dumps({
                            "state_dir": handle.state_dir,
                            "sessions": pinned,
                        }).encode(),
                    )
                    adopted = resp.get("adopted") or []
                    p_adopt1 = time.perf_counter()
                    if self.tracer.enabled:
                        mig_children.append(span_at(
                            "sessions_adopt", p_adopt0, p_adopt1,
                            source=name, target=target.name,
                            sessions=len(adopted),
                        ))
                    with self._lock:
                        for sid in adopted:
                            self._affinity[sid] = target.name
                        self.migrations += len(adopted)
                    if adopted:
                        self._c_migrations.inc(len(adopted))
                    if self.tracer.enabled:
                        mig_children.append(span_at(
                            "repin", p_adopt1, time.perf_counter(),
                            target=target.name,
                            sessions=len(adopted),
                        ))
                    report["sessions_migrated"] = adopted
                    report["migrated_to"] = target.name
                except (urllib.error.URLError, OSError, ValueError) as e:
                    report["migrate_error"] = f"{type(e).__name__}: {e}"
                    if self.tracer.enabled:
                        mig_children.append(span_at(
                            "sessions_adopt", p_adopt0,
                            time.perf_counter(), source=name,
                            target=target.name, error=str(e),
                        ))
        p_done = time.perf_counter()
        migration_ms = round((p_done - p_drain0) * 1000.0, 3)
        report["migration_ms"] = migration_ms
        self._h_migration.observe(migration_ms)
        if self.tracer.enabled:
            root = span_at(
                "drain_migration", p_drain0, p_done, replica=name,
                drained=report["drained"],
                migrated_to=report["migrated_to"],
                sessions=len(report["sessions_migrated"]),
            )
            root.children.extend(mig_children)
            self.tracer.attach_tree(root)
        self._write_discovery()
        return report

    # ------------------------------------------------------ discovery
    def discovery(self) -> Dict[str, Any]:
        """The replica-discovery doc `ia-synth obs --targets FILE`
        consumes: `targets` lists every live scrape surface (replicas
        + the router itself — ia_route_* families ride the same
        registry protocol)."""
        with self._lock:
            reps = [h.snapshot() for h in self._replicas.values()]
        return {
            "schema_version": ROUTER_SCHEMA_VERSION,
            "kind": DISCOVERY_KIND,
            "router": self.live.url if self.live is not None else None,
            "replicas": reps,
            "targets": (
                [r["url"] for r in reps]
                + ([self.live.url] if self.live is not None else [])
            ),
        }

    def _write_discovery(self) -> None:
        if not self.discovery_path:
            return
        from ..utils.io import atomic_write_json

        try:
            atomic_write_json(self.discovery_path, self.discovery())
        except OSError:
            pass

    # --------------------------------------------------------- routes
    def _route_fleet(self, _body):
        with self._lock:
            snap = {
                "router": self.live.url if self.live else None,
                "replicas": [h.snapshot()
                             for h in self._replicas.values()],
                "affinity": {
                    "sessions": len(self._affinity),
                    **self.affinity_counts,
                },
                "requests": {
                    "proxied": self.proxied,
                    "errors": self.proxy_errors,
                    "retries": self.retries,
                },
                "migrations_total": self.migrations,
            }
        return 200, _json_bytes(snap), "application/json"

    def _route_replicas(self, _body):
        return 200, _json_bytes(self.discovery()), "application/json"

    def _route_slo(self, _body):
        """Router-grade /slo: the standard objective evaluation over
        the router's OWN duration family (`ia_route_duration_ms`,
        graded by the same SloEngine the replicas use — round-22
        satellite) plus the fleet anomaly watches, so the observatory
        scrapes the router exactly like a replica."""
        from ..telemetry.anomaly import fleet_watches
        from ..telemetry.slo import ROUTE_DURATION_METRIC, SloEngine

        if self._slo_engine is None:
            self._slo_engine = SloEngine(
                self.registry, metric=ROUTE_DURATION_METRIC
            )
        report = self._slo_engine.evaluate()
        report["anomalies"] = fleet_watches(
            self.replicas(), self.registry
        )
        return 200, _json_bytes(report), "application/json"

    def _route_request(self, _body, _headers, ctx):
        """GET /request?id=<rid>: the router half of one request's
        fleet trace — its access-log record plus the route_request
        span-tree events still in the flight ring.  Mirrors the
        replica's endpoint so `ia-synth trace <id> --fleet` walks both
        with one code path."""
        from ..telemetry.flight import tree_events
        from .accesslog import find_request

        rid = (ctx.get("query") or {}).get("id") if ctx else None
        if not rid:
            return 400, _json_bytes(
                {"status": "rejected", "error": "id query param "
                 "required"}
            ), "application/json"
        entry = (find_request(self.access.path, rid)
                 if self.access is not None else None)
        events = (tree_events(self.flight.to_dict(), rid)
                  if self.flight is not None else [])
        if entry is None and not events:
            return 404, _json_bytes(
                {"status": "unknown", "request_id": rid}
            ), "application/json"
        return 200, _json_bytes({
            "request_id": rid,
            "kind": "router",
            "request": entry,
            "flight_events": events,
        }), "application/json"

    def _route_incidents(self, _body, _headers, ctx):
        """GET /incidents: the fleet's black-box index — fan out to
        every replica's /incidents (round 23, telemetry/archive.py)
        and merge, tagged by replica.  `?id=` proxies one full bundle
        from whichever replica has it.  A replica with the archive
        plane off (404) or unreachable mid-fan-out is stated per
        replica, never silently dropped — same honesty rule as the
        observatory's degraded-fleet scrape."""
        inc_id = (ctx.get("query") or {}).get("id") if ctx else None
        with self._lock:
            handles = list(self._replicas.values())
        if inc_id:
            import urllib.parse as _parse

            q = _parse.quote(inc_id, safe="")
            errors = []
            for h in handles:
                try:
                    doc = _http_json(
                        f"{h.url}/incidents?id={q}", 10.0
                    )
                except (urllib.error.URLError, OSError, ValueError) \
                        as e:
                    errors.append(
                        f"{h.name}: {type(e).__name__}: {e}"
                    )
                    continue
                doc["replica"] = h.name
                return 200, _json_bytes(doc), "application/json"
            return 404, _json_bytes({
                "error": f"incident {inc_id!r} unknown to every "
                         "replica",
                "id": inc_id,
                "errors": errors,
            }), "application/json"
        merged = []
        for h in handles:
            rec: Dict[str, Any] = {
                "replica": h.name, "url": h.url, "error": None,
                "incidents": [],
            }
            try:
                doc = _http_json(f"{h.url}/incidents", 10.0)
                rec["incidents"] = doc.get("incidents") or []
                rec["captured"] = doc.get("captured")
                rec["suppressed"] = doc.get("suppressed")
            except (urllib.error.URLError, OSError, ValueError) as e:
                rec["error"] = f"{type(e).__name__}: {e}"
            merged.append(rec)
        return 200, _json_bytes({
            "replicas": merged,
            "incidents_total": sum(
                len(r["incidents"]) for r in merged
            ),
        }), "application/json"

    def _route_add(self, body):
        try:
            doc = json.loads((body or b"{}").decode("utf-8"))
            url = doc.get("url")
            if not isinstance(url, str) or not url:
                raise ValueError("url is required")
            handle = self.add_replica(
                url, name=doc.get("name"),
                state_dir=doc.get("state_dir"),
            )
        except (ValueError, UnicodeDecodeError) as e:
            return 400, _json_bytes(
                {"status": "rejected", "error": str(e)}
            ), "application/json"
        return 200, _json_bytes(
            {"status": "ok", "replica": handle.snapshot()}
        ), "application/json"

    def _route_remove(self, body):
        try:
            doc = json.loads((body or b"{}").decode("utf-8"))
            name = doc.get("name")
            if not isinstance(name, str) or not name:
                raise ValueError("name is required")
        except (ValueError, UnicodeDecodeError) as e:
            return 400, _json_bytes(
                {"status": "rejected", "error": str(e)}
            ), "application/json"
        removed = self.remove_replica(name)
        return 200 if removed else 404, _json_bytes(
            {"status": "ok" if removed else "unknown", "name": name}
        ), "application/json"

    def _route_drain_replica(self, body):
        try:
            doc = json.loads((body or b"{}").decode("utf-8"))
            name = doc.get("name")
            if not isinstance(name, str) or not name:
                raise ValueError("name is required")
            wait_s = float(doc.get("wait_s", 120.0))
        except (ValueError, UnicodeDecodeError) as e:
            return 400, _json_bytes(
                {"status": "rejected", "error": str(e)}
            ), "application/json"
        try:
            report = self.drain_replica(name, wait_s=wait_s)
        except KeyError as e:
            return 404, _json_bytes(
                {"status": "unknown", "error": str(e)}
            ), "application/json"
        return 200, _json_bytes(
            {"status": "ok", **report}
        ), "application/json"


def _json_bytes(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode("utf-8")


def load_discovery(path: str) -> Dict[str, Any]:
    """Read a router discovery file; raises ValueError on wrong kind
    (the obs CLI surfaces it as a usage error)."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or doc.get("kind") != DISCOVERY_KIND:
        raise ValueError(
            f"{path}: not a fleet discovery file (kind="
            f"{doc.get('kind') if isinstance(doc, dict) else None!r})"
        )
    return doc
