"""Video analogies: temporal synthesis subsystem (round 14).

Frame-sequence synthesis layered on the batch engine — NNF warm-start
between consecutive frames, a temporal-coherence term in the candidate
metric (`SynthConfig.tau`), and delta-cost scheduling of warm frames.
See `sequence` for the mechanics and the `IA_VIDEO_WARM` seam.
"""

from .sequence import (  # noqa: F401
    VideoStream,
    field_delta,
    flicker_metric,
    frame_delta,
    set_warm_mode,
    synthesize_video,
    warm_enabled,
    warm_mode,
    warm_schedule,
)

__all__ = [
    "VideoStream",
    "field_delta",
    "flicker_metric",
    "frame_delta",
    "set_warm_mode",
    "synthesize_video",
    "warm_enabled",
    "warm_mode",
    "warm_schedule",
]
