"""Video analogies — temporal synthesis over the batch engine (round 14).

Frame sequences share one (A, A') style pair, and consecutive frames of
real video are nearly identical — so the per-frame batch runner
(`parallel/batch.synthesize_batch`), which synthesizes every frame from
a cold random init, re-pays the full pyramid schedule F times for work
that barely changes.  This module layers three temporal mechanisms on
the EXISTING engine (no forked level bodies; the batch machinery is
called with different state, not reimplemented):

1. **NNF warm-start** (`IA_VIDEO_WARM=on|off`, `set_warm_mode`): EVERY
   pyramid level of frame t is seeded with frame t-1's CONVERGED
   (nnf, B') state at that level through `_level_state_glue`'s
   ``prev_kind="direct"`` arm, replacing the random init (coarsest) and
   the upsample chain (finer levels).  Coarsest-only seeding — the
   obvious smaller design — measured ~1.7 dB below cold at the minimum
   warm schedule, because finer levels restarted from a single-sweep
   upsample; per-level seeding starts each level at the previous
   frame's optimum so one sweep suffices on low-delta frames.  ``off``
   dispatches the whole sequence to
   `synthesize_batch(frames_per_step=1)` — bit identity with the
   per-frame batch runner is structural, not an equality proof.

2. **Temporal-coherence term** (`cfg.tau`, plumbed like kappa through
   the matcher interface): warm frames pass frame t-1's converged field
   at EVERY level as the matcher's `temporal` anchor, and PatchMatch
   candidates pay `models/patchmatch.temporal_penalty_fn` for diverging
   from it.  tau == 0 is a trace-time gate — those frames dispatch the
   exact `_batch_level_fn` graphs the batch runner compiles
   (`_video_level_fn` is a separate cached twin, so the tau=0 path
   cannot even reach a changed graph).

3. **Delta-cost scheduling** (`warm_schedule`): warm frames run a
   shortened PM/EM schedule sized by the measured change fraction
   between the incoming frame and the frame whose converged state seeds
   it (`frame_delta` — the converged FIELD's own change fraction is
   dominated by optimizer stochasticity, see `field_delta`'s docstring),
   quantized to `_SCALE_BUCKETS` so the compile count stays bounded.
   The shortened schedule is a `dataclasses.replace` of (pm_iters,
   em_iters) — the cost/byte models (`level_eta_cost_units`, the
   sentinel ledger) are parameterized on cfg, so warm frames are priced
   by the SAME model evaluated at the warm schedule (one-model
   discipline; no second formula to drift).

Per-run accounting: `ia_warm_start_frames_total`,
`ia_warm_start_sweeps_total{mode=warm|cold_equiv}` (sentinel
`warm_start` check), and the `ia_video_flicker` gauge
(`flicker_metric`: mean per-pixel temporal delta of the stylized
output — the quantity the tau term exists to reduce).

`VideoStream` is the stateful per-frame entry (the serving daemon's
session-affinity path drives it one request at a time);
`synthesize_video` wraps a whole in-memory stack with checkpoint/resume
parity (per-frame `frames_{t:05d}` subdirectories — the SAME layout the
chunked batch runner writes, so cold-frame checkpoints interoperate).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import time
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig
from ..models.analogy import (
    _assemble_fa_fn,
    _save_level,
    _strip_noncompute,
    level_eta_cost_units,
    make_em_step,
    plan_level,
    record_level_span,
    record_prologue,
    resume_prologue,
    shard_sync_walls,
)
from ..ops.color import rgb_to_yiq
from ..ops.features import assemble_features
from ..ops.remap import luminance_stats
from ..parallel.batch import (
    _MESHES,
    _batch_feature_table_bytes,
    _batch_level_fn,
    _batch_prologue_fn,
    _finalize_batch,
    _mesh_token,
    _nnf_host_stack,
    synthesize_batch,
)
from ..parallel.mesh import BATCH_AXIS, batch_sharding, make_mesh, replicated


# ---------------------------------------------------------------------------
# Warm-start seam.

_WARM_MODES = ("on", "off")
_WARM_MODE = os.environ.get("IA_VIDEO_WARM", "on")


def warm_mode() -> str:
    return _WARM_MODE


def warm_enabled() -> bool:
    return _WARM_MODE != "off"


def set_warm_mode(mode: str) -> None:
    """Install the video warm-start mode process-wide (`IA_VIDEO_WARM`).

    Unlike the polish/compression seams this does NOT clear the
    compiled level caches: no cached graph resolves `_WARM_MODE` at
    trace time — the seam only selects which Python driver path runs
    (per-frame batch dispatch vs the warm loop), and both paths' graphs
    stay valid across a flip."""
    global _WARM_MODE
    if mode not in _WARM_MODES:
        raise ValueError(
            f"video warm mode {mode!r} names neither 'on' nor 'off'"
        )
    _WARM_MODE = mode


# ---------------------------------------------------------------------------
# Temporal signals.

# Frame-change fraction at (or above) which a warm frame runs the FULL
# schedule.  Below it the schedule scales down linearly: a static scene
# measures delta ~0 and runs the minimum bucket.
_DELTA_FULL = 0.5
# Schedule scale is quantized to this many buckets (1/N .. N/N): every
# bucket is a distinct (pm_iters, em_iters) replace and therefore a
# distinct set of compiled level graphs, so the quantization bounds the
# compile count per run at _SCALE_BUCKETS + 1 (cold).
_SCALE_BUCKETS = 3


def field_delta(nnf_a, nnf_b) -> float:
    """Fraction of pixels whose mapping changed between two converged
    (..., H, W, 2) fields.

    Observability metric, NOT the warm scheduler's signal: PatchMatch
    converges to one of many near-equivalent optima per pixel and the
    per-frame PRNG stream makes consecutive frames land on different
    ones, so this fraction has a measured noise floor of ~25-45% EVEN
    ON A STATIC SCENE (and distance-thresholding does not rescue it —
    competing matches differ by far more than a few percent of the mean
    match distance at practical iteration counts).  The scheduler uses
    `frame_delta` instead."""
    a = np.asarray(nnf_a)
    b = np.asarray(nnf_b)
    if a.shape != b.shape:
        return 1.0
    return float(np.mean(np.any(a != b, axis=-1)))


def frame_delta(frame_a, frame_b, eps: float = 1.0 / 255.0) -> float:
    """Fraction of pixels that changed (any channel by more than `eps`)
    between two input frames — the warm scheduler's change signal.

    The NNF field's own change fraction is dominated by optimizer
    stochasticity (see `field_delta`), so the schedule is sized from
    the signal the field change is a RESPONSE to: how much of the
    incoming frame actually differs from the one whose converged state
    seeds it.  Host-side, costs one array compare, and is available
    BEFORE the frame is synthesized — the schedule reacts to this
    frame's change, not the previous frame's.  `eps` defaults to one
    8-bit quantization step."""
    a = np.asarray(frame_a, np.float32)
    b = np.asarray(frame_b, np.float32)
    if a.shape != b.shape:
        return 1.0
    diff = np.abs(a - b) > eps
    if diff.ndim == 3:
        diff = np.any(diff, axis=-1)
    return float(np.mean(diff))


def warm_schedule(cfg: SynthConfig, delta: float):
    """(pm_iters, em_iters) for a warm frame that measured change
    fraction `delta` against the frame seeding it (`frame_delta`).

    Linear in delta up to `_DELTA_FULL`, quantized to `_SCALE_BUCKETS`
    scale levels, floored at TWO PM sweeps (or cfg.pm_iters if fewer)
    and one EM iteration — a warm seed still needs propagation over the
    new frame's features (the seed is last frame's optimum, not this
    frame's), and a single sweep measured ~0.3-0.5 dB below the cold
    schedule on the static-scene gate where two sweeps hold it."""
    frac = min(1.0, max(0.0, float(delta)) / _DELTA_FULL)
    bucket = max(1, int(math.ceil(frac * _SCALE_BUCKETS)))
    scale = bucket / float(_SCALE_BUCKETS)
    pm_floor = min(2, cfg.pm_iters)
    pm_w = max(pm_floor, int(round(cfg.pm_iters * scale)))
    em_w = max(1, int(round(cfg.em_iters * scale)))
    return pm_w, em_w


def flicker_metric(outputs) -> float:
    """Mean per-pixel temporal delta of the stylized output: the mean
    over consecutive frame pairs of mean |out_t - out_{t-1}|.  The
    temporal-coherence term exists to push this down; the bench records
    it with and without tau.  0.0 for sequences shorter than 2."""
    out = np.asarray(outputs, np.float32)
    if out.shape[0] < 2:
        return 0.0
    return float(np.mean(np.abs(out[1:] - out[:-1])))


# ---------------------------------------------------------------------------
# Temporal level function: `_batch_level_fn_cached` with ONE extra
# sharded argument.


def _video_level_fn(cfg: SynthConfig, level: int, has_coarse: bool,
                    mesh_key, fa_external: bool = False,
                    prev_kind: str = "stacked"):
    return _video_level_fn_cached(
        _strip_noncompute(cfg), level, has_coarse, mesh_key, fa_external,
        prev_kind,
    )


@functools.lru_cache(maxsize=64)
def _video_level_fn_cached(cfg: SynthConfig, level: int, has_coarse: bool,
                           mesh_key, fa_external: bool = False,
                           prev_kind: str = "stacked"):
    """`parallel/batch._batch_level_fn_cached` with one extra sharded
    argument: the previous frame's converged field at this level,
    threaded into every EM step as the matcher's `temporal` anchor.

    Kept as a separate cached twin instead of a parameter on the batch
    function so the tau=0 / warm-off / batch paths keep dispatching
    exactly the graphs they always compiled — their bit-identity to the
    historical runner is by construction, not by equality proof.  Only
    the fused patchmatch regime comes here (the caller gates on
    `cfg.tau > 0`, `not plan.lean`, `plan.fuse`, matcher ==
    "patchmatch"); with a temporal anchor present the matcher takes the
    XLA sweep path, which never consumes kernel A-planes — so unlike
    the batch twin this body skips `_level_plan`/`prepare_a_planes`
    entirely rather than relying on XLA to dead-code them."""
    mesh = _MESHES[mesh_key]
    shard = batch_sharding(mesh)
    repl = replicated(mesh)
    step_final = make_em_step(cfg, level, has_coarse)
    step_mid = (
        make_em_step(cfg, level, has_coarse, polish_iters=0)
        if cfg.pm_polish_final_only
        else step_final
    )

    def run_level(src_a_l, flt_a_l, src_a_c, flt_a_c, src_b_l, src_b_c,
                  raw_b_l, copy_a_l, prev_nnf, prev_bp, level_key,
                  frame_idx, temporal, f_a_ext=None, proj_ext=None):
        from ..models.analogy import _level_state_glue
        from ..ops.pca import fit_and_project

        h, w = src_b_l.shape[1:3]
        ha, wa = src_a_l.shape[:2]
        if fa_external:
            f_a, proj = f_a_ext, proj_ext
        else:
            f_a = assemble_features(
                src_a_l, flt_a_l, cfg, src_a_c, flt_a_c
            )
            f_a, proj = fit_and_project(f_a, cfg.pca_dims)

        def frame_keys(base_key):
            return jax.vmap(
                lambda i: jax.random.fold_in(base_key, i)
            )(frame_idx)

        nnf, flt_bp, flt_bp_coarse = _level_state_glue(
            False, prev_kind, prev_nnf, prev_bp, raw_b_l, h, w, ha, wa,
            frame_keys(jax.random.fold_in(level_key, 0x1217)),
            batched=True,
        )

        mk_vstep = lambda s: jax.vmap(  # noqa: E731
            s,
            in_axes=(0, 0, 0, 0, None, None, 0, 0, None, None, 0),
        )
        vstep_final, vstep_mid = mk_vstep(step_final), mk_vstep(step_mid)
        dist = bp = None
        for em in range(cfg.em_iters):
            vstep = (
                vstep_final if em == cfg.em_iters - 1 else vstep_mid
            )
            nnf, dist, bp = vstep(
                src_b_l,
                flt_bp,
                src_b_c if has_coarse else src_b_l,
                flt_bp_coarse if has_coarse else flt_bp,
                f_a,
                copy_a_l,
                nnf,
                frame_keys(jax.random.fold_in(level_key, em)),
                proj,
                None,
                temporal,
            )
            flt_bp = bp
        return nnf, dist, bp

    return jax.jit(
        run_level,
        in_shardings=(
            repl, repl, repl, repl, shard, shard, shard, repl,
            shard, shard, repl, repl, shard, repl, repl,
        ),
        out_shardings=(shard, shard, shard),
    )


def _pad_rows(x, n_pad: int):
    """Re-pad a trimmed (1, ...) state array to the mesh's frame grain
    (repeat the single real row — same ballast rule as the batch
    runner's `_pad_tail`)."""
    x = jnp.asarray(x)
    if n_pad:
        x = jnp.concatenate(
            [x, jnp.repeat(x[-1:], n_pad, axis=0)], axis=0
        )
    return x


def _ckpt_bps(resume_dir: Optional[str], levels: int):
    """Per-level B' from a resumed frame's checkpoint tree
    (`resume_prologue`'s aux fill carries per-level (nnf, dist) but not
    per-level bp — the resume contract only needs the FINEST bp; the
    per-level warm seed needs every level's).  Best-effort: a level
    whose checkpoint is missing or unreadable is simply absent, and the
    next frame seeds the levels it can."""
    bps = {}
    if not resume_dir:
        return bps
    for level in range(levels):
        path = os.path.join(resume_dir, f"level_{level}.npz")
        try:
            with np.load(path) as z:
                bps[level] = np.asarray(z["bp"])
        except Exception:  # noqa: BLE001 - seed is best-effort
            continue
    return bps


class VideoStream:
    """Stateful per-frame warm-start synthesis: one stream == one video.

    Each `step(frame)` runs one frame through the batch engine's level
    machinery on a 1-frame stack (default mesh: `make_mesh(1)` — a
    single frame sharded over an N-device mesh is 1 real row plus N-1
    ballast rows, and outputs are mesh-invariant, so the solo mesh just
    skips the ballast).  Frame 0 runs the full cold schedule and is
    bit-identical to the batch runner's frame 0 (same prologue, level
    graphs, whole-stack remap stats when provided, and frame-index PRNG
    identity).  Later frames warm-start from the carried state when the
    seam is on.

    Style luminance statistics freeze on whatever `b_stats` the
    constructor gets (the whole-stack stats from `synthesize_video`, a
    luma-bucket from the serving daemon) or, when omitted, on the first
    frame's own stats — a stream must remap every frame against the
    SAME style normalization or the style itself would flicker.
    """

    def __init__(self, a, ap, cfg: Optional[SynthConfig] = None,
                 mesh=None, b_stats=None, n_stack: Optional[int] = None,
                 progress=None, registry=None):
        from ..telemetry.spans import as_tracer

        self.cfg = cfg or SynthConfig()
        self.registry = registry  # None: process default at book time
        self.mesh = mesh or make_mesh(1)
        self.token = _mesh_token(self.mesh)
        self.a = jnp.asarray(a, jnp.float32)
        self.ap = jnp.asarray(ap, jnp.float32)
        self.b_stats = b_stats
        self.n_stack = n_stack
        self.tracer = as_tracer(progress)
        self.t = 0
        # Carried warm state (all trimmed to the 1 real frame):
        self._fields = None       # {level: (1, h, w, 2) np} converged
        self._bps = None          # {level: (1, h, w[, C]) np} conv. B'
        self._prev_finest = None  # frame t-1 finest field (field_delta)
        self._prev2_finest = None
        self._prev_frame = None   # frame t-1 input (the delta signal)
        self.finest_history = []  # per-frame (h, w, 2) converged fields
        # Per-run accounting (the bench/aux consumers):
        self.deltas = []          # measured delta per frame (None: cold)
        self.schedules = []       # (pm_iters, em_iters) actually run
        self.warm_frames = 0
        self.run_units = 0.0      # modeled units actually scheduled
        self.cold_units = 0.0     # modeled units of the cold equivalent
        # Serving request id per frame (None outside the daemon) — the
        # round-15 trace join: which request produced stream frame t.
        self.request_ids = []

    def step(self, frame, *, resume_root: Optional[str] = None,
             resume_strict: bool = False,
             request_id: Optional[str] = None):
        """Synthesize the next frame; returns stylized (H, W[, 3]).

        `resume_root`: root checkpoint directory of a prior run — this
        frame resumes from `frames_{t:05d}` under it (the same per-item
        subdirectory layout the chunked batch runner uses, so warm-off
        and warm-on runs share checkpoint trees for cold frames).
        `request_id`: the serving request driving this frame, recorded
        on `self.request_ids` for the trace/accounting join."""
        self.request_ids.append(request_id)
        cfg = self.cfg
        t = self.t
        can_warm = (
            warm_enabled() and t > 0
            and self._fields is not None and bool(self._bps)
        )
        if can_warm:
            # Sized from THIS frame's measured change against the frame
            # whose converged state seeds it (frame_delta docstring).
            delta = (
                1.0 if self._prev_frame is None
                else frame_delta(frame, self._prev_frame)
            )
            pm_w, em_w = warm_schedule(cfg, delta)
            run_cfg = dataclasses.replace(
                cfg, pm_iters=pm_w, em_iters=em_w
            )
            self.deltas.append(delta)
        else:
            run_cfg = cfg
            self.deltas.append(None)
        self.schedules.append((run_cfg.pm_iters, run_cfg.em_iters))

        out, fields, bps, shapes, seeded, ran = self._run_frame(
            frame, run_cfg, can_warm, resume_root, resume_strict
        )

        reg = self.registry
        if reg is None:
            from ..telemetry.metrics import get_registry

            reg = get_registry()
        if t == 0:
            reg.counter(
                "ia_video_streams_total",
                "video streams started (each stream's head frame is "
                "cold)",
            ).inc()
        if ran:
            # Fully-resumed frames scheduled no synthesis: the ledger
            # (and the modeled-unit tally) records THIS run's work.
            reg.counter(
                "ia_video_frames_total",
                "video frames synthesized, by schedule mode",
            ).inc(labels={"mode": "warm" if seeded else "cold"})
            self.run_units += sum(
                level_eta_cost_units(
                    run_cfg, shapes, self.a.shape[:2], runner="batch"
                ).values()
            )
            self.cold_units += sum(
                level_eta_cost_units(
                    cfg, shapes, self.a.shape[:2], runner="batch"
                ).values()
            )
        if seeded:
            self.warm_frames += 1
            _book_warm_frame(cfg, run_cfg, len(shapes), reg)

        finest = fields.get(0)
        self._prev2_finest = self._prev_finest
        self._prev_finest = finest
        self._prev_frame = np.asarray(frame, np.float32)
        if finest is not None:
            self.finest_history.append(np.asarray(finest)[0])
        self._fields = fields
        self._bps = bps
        self.t += 1
        return out

    # -- drain handoff (round 16): carry warm state across processes --

    def save_state(self, state_dir: str) -> dict:
        """Snapshot the carried warm-start state (per-level converged
        fields + B', the previous input frame, the frame counter, the
        frozen style stats) under `state_dir` — the serving daemon's
        drain path calls this per session so a takeover successor's
        next frame warm-starts exactly where the predecessor stopped
        instead of re-paying a cold frame.  Atomic (tmp + replace) so
        a kill mid-drain leaves either the previous generation or the
        new one, never a torn file."""
        import json as _json

        os.makedirs(state_dir, exist_ok=True)
        arrays = {}
        levels = sorted((self._fields or {}).keys())
        for lv in levels:
            arrays[f"field_{lv}"] = np.asarray(self._fields[lv])
            if self._bps and lv in self._bps:
                arrays[f"bp_{lv}"] = np.asarray(self._bps[lv])
        if self._prev_frame is not None:
            arrays["prev_frame"] = np.asarray(self._prev_frame)
        if self.b_stats is not None:
            arrays["b_stats"] = np.asarray(self.b_stats)
        npz_path = os.path.join(state_dir, "stream_state.npz")
        tmp = npz_path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp, npz_path)
        meta = {"t": int(self.t), "levels": levels,
                "has_b_stats": self.b_stats is not None}
        meta_path = os.path.join(state_dir, "stream_meta.json")
        tmp = meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            _json.dump(meta, fh, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, meta_path)
        return meta

    def restore_state(self, state_dir: str) -> bool:
        """Load a `save_state` snapshot into this (fresh) stream.
        Best-effort: False (stream unchanged, next frame runs cold)
        when the snapshot is missing or unreadable — restoring warm
        state is an optimization, never a correctness gate."""
        import json as _json

        npz_path = os.path.join(state_dir, "stream_state.npz")
        meta_path = os.path.join(state_dir, "stream_meta.json")
        try:
            with open(meta_path, "r", encoding="utf-8") as fh:
                meta = _json.load(fh)
            fields = {}
            bps = {}
            with np.load(npz_path) as z:
                for lv in meta.get("levels") or []:
                    lv = int(lv)
                    fields[lv] = np.asarray(z[f"field_{lv}"])
                    if f"bp_{lv}" in z:
                        bps[lv] = np.asarray(z[f"bp_{lv}"])
                prev = (
                    np.asarray(z["prev_frame"])
                    if "prev_frame" in z else None
                )
                if meta.get("has_b_stats") and "b_stats" in z:
                    self.b_stats = tuple(
                        np.asarray(z["b_stats"]).tolist()
                    )
        except Exception:  # noqa: BLE001 - snapshot is best-effort
            return False
        if not fields:
            return False
        self._fields = fields
        self._bps = bps
        self._prev_frame = prev
        self.t = int(meta.get("t", 0))
        return True

    # -- one frame through the batch level machinery -------------------

    def _run_frame(self, frame, run_cfg: SynthConfig, warm: bool,
                   resume_root, resume_strict):
        from ..runtime.faults import fire as _fault_fire

        cfg, mesh, token, tracer = self.cfg, self.mesh, self.token, \
            self.tracer
        t = self.t
        frames = jnp.asarray(frame, jnp.float32)
        if frames.ndim == 2 or (frames.ndim == 3 and frames.shape[-1] in (1, 3)):
            frames = frames[None]
        if self.b_stats is None and cfg.color_mode == "luminance" \
                and cfg.luminance_remap:
            y = rgb_to_yiq(frames)[..., 0] if frames.ndim == 4 else frames
            self.b_stats = luminance_stats(y)

        save_root = cfg.save_level_artifacts
        if save_root:
            run_cfg = dataclasses.replace(
                run_cfg,
                save_level_artifacts=os.path.join(
                    save_root, f"frames_{t:05d}"
                ),
            )
        resume_dir = (
            os.path.join(resume_root, f"frames_{t:05d}")
            if resume_root else None
        )

        n_pad = (-1) % mesh.devices.size
        # xfer injection point: this frame's host->device transfer.
        _fault_fire("xfer", 0)
        if n_pad:
            frames = jnp.concatenate(
                [frames, jnp.repeat(frames[-1:], n_pad, axis=0)], axis=0
            )
        frames = jax.device_put(frames, batch_sharding(mesh))

        levels = cfg.clamp_levels(self.a.shape[:2], frames.shape[1:3])
        key = jax.random.PRNGKey(cfg.seed)
        frame_idx = jnp.full((frames.shape[0],), t, dtype=jnp.int32)
        # Checkpoint identity: exactly the batch runner's per-chunk
        # fingerprint for a frames_per_step=1 run — (1, H, W[, C],
        # whole-stack length, this frame's offset) — so cold frames'
        # checkpoints interoperate between warm-off and warm-on runs
        # (warm frames stamp run_cfg's shortened schedule and bind to
        # it).  Streams with unknown total length identify as t+1.
        n_stack = self.n_stack if self.n_stack is not None else t + 1
        fp_shape = (1,) + tuple(frames.shape[1:]) + (n_stack, t)

        start_level = levels - 1
        bp = nnf = None
        aux = {}
        resumed = resume_prologue(
            resume_dir, levels, run_cfg, fp_shape, tracer,
            strict=resume_strict,
        )
        if resumed is not None:
            start_level, nnf, bp, aux = resumed
            if n_pad:
                def _pad_tail(x):
                    return jnp.concatenate(
                        [x, jnp.repeat(x[-1:], n_pad, axis=0)], axis=0
                    )

                nnf = (
                    tuple(_pad_tail(p) for p in nnf)
                    if isinstance(nnf, tuple) else _pad_tail(nnf)
                )
                bp = _pad_tail(bp)
            if start_level < 0:
                # Fully-checkpointed frame: finalize directly; the
                # carried warm state comes from the checkpoint's own
                # per-level fields (aux) + a direct coarsest-B' read.
                yiq_b = (
                    jax.vmap(rgb_to_yiq)(frames)
                    if cfg.color_mode == "luminance" and frames.ndim == 4
                    else None
                )
                out = _finalize_batch(bp, yiq_b, frames, run_cfg)[:1]
                fields = {
                    lv: np.asarray(a_nnf)[:1]
                    for lv, (a_nnf, _d) in aux.items()
                }
                bps = _ckpt_bps(resume_dir, levels)
                # Nothing ran: a fully-checkpointed frame books no warm
                # work (the ledger records THIS run's scheduling).
                return (
                    np.asarray(out[0]), fields, bps,
                    _pyr_shapes(frames.shape[1:3], levels), False, False,
                )

        prologue_t0 = time.perf_counter()
        (
            pyr_src_a, pyr_flt_a, pyr_copy_a, pyr_src_b, pyr_raw_b, yiq_b
        ) = _batch_prologue_fn(cfg, levels, token)(
            self.a, self.ap, frames, self.b_stats
        )
        record_prologue(
            tracer, pyr_raw_b, levels, prologue_t0, cfg=run_cfg,
            a_hw=self.a.shape[:2], batched=True, runner="video",
        )

        seed_fields = self._fields if warm else None
        seed_bps = self._bps if warm else None
        fields = {}
        bps = {}
        seeded = False
        shapes = [
            [int(s) for s in pyr_raw_b[lv].shape[1:3]]
            for lv in range(levels)
        ]
        for level in range(start_level, -1, -1):
            _fault_fire("level", level)
            level_t0 = time.perf_counter()
            h, w = pyr_src_b[level].shape[1:3]
            has_coarse = level < levels - 1
            ha, wa = pyr_src_a[level].shape[:2]
            plan = plan_level(
                run_cfg, level, pyr_src_a[level], pyr_flt_a[level],
                has_coarse, h, w, prev_nnf=nnf,
                table_bytes=_batch_feature_table_bytes(
                    frames.shape[0], h, w, ha, wa
                ),
                work_scale=frames.shape[0],
                brute_lean=False,
            )
            prev_kind = plan.prev_kind
            if (
                warm and resumed is None
                and seed_fields is not None and level in seed_fields
                and tuple(np.shape(seed_fields[level])[1:3]) == (h, w)
                and seed_bps is not None and level in seed_bps
                and tuple(np.shape(seed_bps[level])[1:3]) == (h, w)
                and not plan.lean
                and (
                    not has_coarse
                    or (
                        level + 1 in seed_bps
                        and tuple(np.shape(seed_bps[level + 1])[1:3])
                        == tuple(pyr_src_b[level + 1].shape[1:3])
                    )
                )
            ):
                # Warm seed: last frame's converged state at THIS level
                # stands in for the init ('direct' glue arm) — every
                # level, not just the coarsest (coarsest-only seeding
                # measured ~1.7 dB below cold at the minimum warm
                # schedule; module docstring).  A non-coarsest level
                # additionally hands the glue the coarse-resolution B'
                # as the second element of a (fine, coarse) tuple — the
                # EM features consume the coarse plane at its own
                # resolution.
                prev_kind = "direct"
                nnf = _pad_rows(seed_fields[level], n_pad)
                bp = _pad_rows(seed_bps[level], n_pad)
                if has_coarse:
                    bp = (bp, _pad_rows(seed_bps[level + 1], n_pad))
                seeded = True
            use_temporal = (
                warm and cfg.tau > 0.0 and cfg.matcher == "patchmatch"
                and not plan.lean and plan.fuse
                and seed_fields is not None and level in seed_fields
                and tuple(np.shape(seed_fields[level])[1:3]) == (h, w)
            )
            f_a_ext = proj_ext = None
            if plan.fa_external:
                f_a_ext, proj_ext = _assemble_fa_fn(
                    run_cfg, has_coarse
                )(
                    pyr_src_a[level],
                    pyr_flt_a[level],
                    pyr_src_a[level + 1] if has_coarse else None,
                    pyr_flt_a[level + 1] if has_coarse else None,
                )
            args = (
                pyr_src_a[level],
                pyr_flt_a[level],
                pyr_src_a[level + 1] if has_coarse else None,
                pyr_flt_a[level + 1] if has_coarse else None,
                pyr_src_b[level],
                pyr_src_b[level + 1] if has_coarse else None,
                pyr_raw_b[level],
                pyr_copy_a[level],
                nnf,
                bp,
                jax.random.fold_in(key, level),
                frame_idx,
            )
            _fault_fire("kernel", level)
            if use_temporal:
                run = _video_level_fn(
                    run_cfg, level, has_coarse, token, plan.fa_external,
                    prev_kind,
                )
                temporal = _pad_rows(seed_fields[level], n_pad)
                nnf, dist, bp = run(*args, temporal, f_a_ext, proj_ext)
            else:
                run = _batch_level_fn(
                    run_cfg, level, has_coarse, token, plan.fa_external,
                    plan.lean, prev_kind, plan.fuse,
                )
                nnf, dist, bp = run(*args, f_a_ext, proj_ext)

            if tracer.enabled:
                n_sh = int(mesh.devices.size)
                per = dist.shape[0] // n_sh
                walls = shard_sync_walls(
                    level_t0,
                    [dist[i * per:(i + 1) * per] for i in range(n_sh)],
                ) if per else None
                record_level_span(
                    tracer, run_cfg, level_t0, level, h, w,
                    float(dist.mean()), shard_walls=walls,
                    shard_axis=BATCH_AXIS,
                )
            fields[level] = _nnf_host_stack(nnf, 1)
            bps[level] = np.asarray(bp[:1])
            if run_cfg.save_level_artifacts:
                nnf_save = nnf
                if isinstance(nnf, tuple):
                    nnf_save = np.stack(
                        [np.asarray(nnf[0]), np.asarray(nnf[1])],
                        axis=-1,
                    )
                _save_level(
                    run_cfg.save_level_artifacts, level, nnf_save[:1],
                    dist[:1], bp[:1], run_cfg, fp_shape,
                )

        # Partial resume: levels finer than the resume point ran live;
        # already-checkpointed coarser levels' (field, B') come from the
        # aux fill plus a direct checkpoint read, so the next frame
        # still has every level's seed.
        for lv, (a_nnf, _d) in aux.items():
            fields.setdefault(lv, np.asarray(a_nnf)[:1])
        if resume_dir:
            for lv, b in _ckpt_bps(resume_dir, levels).items():
                bps.setdefault(lv, b)

        out = _finalize_batch(bp, yiq_b, frames, run_cfg)[:1]
        return np.asarray(out[0]), fields, bps, shapes, seeded, True


def _pyr_shapes(hw, levels: int):
    """Host-side pyramid shape ladder ((h, w) per level, finest first)
    for cost-model pricing when the pyramids themselves were skipped
    (fully-resumed frames)."""
    h, w = int(hw[0]), int(hw[1])
    return [
        [max(1, h // (2 ** lv)), max(1, w // (2 ** lv))]
        for lv in range(levels)
    ]


def _book_warm_frame(cfg: SynthConfig, run_cfg: SynthConfig,
                     levels: int, registry=None) -> None:
    """Ledger one warm-started frame: the frame count the sentinel
    `warm_start` check reconciles, plus the scheduled-vs-cold sweep
    counts priced by the SAME (levels x em_iters x pm_iters) product
    the cost model uses — evaluated at the warm replace and at the base
    cfg respectively (one model, two operating points)."""
    reg = registry
    if reg is None:
        from ..telemetry.metrics import get_registry

        reg = get_registry()
    reg.counter(
        "ia_warm_start_frames_total",
        "video frames synthesized from a warm-start seed",
    ).inc()
    sweeps = reg.counter(
        "ia_warm_start_sweeps_total",
        "scheduled PM sweeps on warm-started frames vs their cold "
        "equivalent",
    )
    sweeps.inc(
        float(levels * run_cfg.em_iters * run_cfg.pm_iters),
        labels={"mode": "warm"},
    )
    sweeps.inc(
        float(levels * cfg.em_iters * cfg.pm_iters),
        labels={"mode": "cold_equiv"},
    )


def synthesize_video(
    a,
    ap,
    frames,
    cfg: Optional[SynthConfig] = None,
    mesh=None,
    progress=None,
    resume_from: Optional[str] = None,
    resume_strict: bool = False,
    return_aux: bool = False,
):
    """Stylized B' for a frame SEQUENCE ((F, H, W[, 3])) against one
    style pair, with temporal warm-starting (module docstring).

    Returns the stacked outputs shaped like `frames`; `return_aux=True`
    returns `(outputs, aux)` where aux carries the per-run temporal
    accounting: per-frame finest fields, measured deltas, the schedules
    actually run, the flicker metric, and the modeled cost of the run
    vs its cold equivalent (`run_units` / `cold_units` — the VIDEO
    bench's warm_cost_ratio numerator/denominator).

    With the seam OFF (`IA_VIDEO_WARM=off` / `set_warm_mode("off")`)
    the sequence dispatches to `synthesize_batch(frames_per_step=1)`:
    every frame cold, bit-identical to the per-frame batch runner by
    construction (chunking invariance is a tested batch property).
    Checkpoint layout (`frames_{t:05d}` per-frame subdirectories under
    `cfg.save_level_artifacts`, resumed from `resume_from`) is shared
    between both modes, so a warm-off checkpoint tree resumes a
    warm-on run's cold frames and vice versa — frame-granular resume
    rides the existing per-level checkpoints."""
    cfg = cfg or SynthConfig()
    frames = np.asarray(frames, np.float32)
    if frames.ndim not in (3, 4):
        raise ValueError(
            f"frames has shape {frames.shape}; expected (F, H, W[, C])"
        )
    from ..telemetry.metrics import get_registry

    if not warm_enabled():
        res = synthesize_batch(
            a, ap, frames, cfg, mesh=mesh, progress=progress,
            frames_per_step=1, resume_from=resume_from,
            resume_strict=resume_strict, return_nnf=return_aux,
        )
        out, nnf = res if return_aux else (res, None)
        flick = flicker_metric(out)
        get_registry().gauge(
            "ia_video_flicker",
            "mean per-pixel temporal delta of the stylized output",
        ).set(flick)
        if return_aux:
            aux = {
                "mode": "off",
                "fields": np.asarray(nnf),
                "deltas": [None] * frames.shape[0],
                "schedules": [
                    (cfg.pm_iters, cfg.em_iters)
                ] * frames.shape[0],
                "flicker": flick,
                "warm_frames": 0,
                "run_units": None,
                "cold_units": None,
            }
            return out, aux
        return out

    b_stats = None
    if cfg.color_mode == "luminance" and cfg.luminance_remap:
        # Whole-stack style normalization, exactly the batch runner's:
        # frame 0 of a warm run must be bit-identical to frame 0 of the
        # batch run over the same stack.
        fr = jnp.asarray(frames, jnp.float32)
        y_all = rgb_to_yiq(fr)[..., 0] if fr.ndim == 4 else fr
        b_stats = luminance_stats(y_all)
    stream = VideoStream(
        a, ap, cfg=cfg, mesh=mesh, b_stats=b_stats,
        n_stack=frames.shape[0], progress=progress,
    )
    outs = [
        stream.step(
            frames[t], resume_root=resume_from,
            resume_strict=resume_strict,
        )
        for t in range(frames.shape[0])
    ]
    out = jnp.stack([jnp.asarray(o) for o in outs], axis=0)
    flick = flicker_metric(out)
    get_registry().gauge(
        "ia_video_flicker",
        "mean per-pixel temporal delta of the stylized output",
    ).set(flick)
    if return_aux:
        aux = {
            "mode": "on",
            "fields": (
                np.stack(stream.finest_history, axis=0)
                if stream.finest_history else np.zeros((0,), np.int32)
            ),
            "deltas": list(stream.deltas),
            "schedules": list(stream.schedules),
            "flicker": flick,
            "warm_frames": stream.warm_frames,
            "run_units": stream.run_units,
            "cold_units": stream.cold_units,
        }
        return out, aux
    return out
