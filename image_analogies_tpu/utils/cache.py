"""Persistent XLA compilation cache (SURVEY.md §7 step 8, host-sync
minimization).  First compiles on the tunneled TPU platform cost 20-40 s
per jitted level step; caching them on disk makes every later process
(bench reruns, CLI invocations) start warm."""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")


def enable_compilation_cache(path: str | None = None) -> None:
    import jax

    cache_dir = os.path.abspath(path or os.environ.get(
        "IA_TPU_COMPILE_CACHE", _DEFAULT_DIR
    ))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
