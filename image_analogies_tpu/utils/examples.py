"""Procedural example assets (SURVEY.md §2 C14).

The reference ships an `examples/` directory of A, A', B image triples
[BASELINE.json config 1].  This environment has no network, so equivalents
are generated procedurally with a fixed seed — one generator per benchmark
config family:

  - `texture_by_numbers`: label maps -> per-label procedural textures
    (config 1),
  - `artistic_filter`: photo-like base -> "watercolor" rendition
    (config 2: smoothed + edge-darkened + quantized),
  - `super_resolution`: A = blurred, A' = sharp (config 3),
  - `npr_frames`: a short synthetic "video" for the batched runner
    (config 5).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(seed)


def _smooth_noise(rng, h, w, octaves: int = 4) -> np.ndarray:
    """Multi-octave value noise in [0,1] (cheap Perlin stand-in)."""
    out = np.zeros((h, w), np.float32)
    amp, total = 1.0, 0.0
    for o in range(octaves):
        gh, gw = max(2, h >> (octaves - o)), max(2, w >> (octaves - o))
        grid = rng.random((gh, gw)).astype(np.float32)
        ys = np.linspace(0, gh - 1, h)
        xs = np.linspace(0, gw - 1, w)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, gh - 1)
        x1 = np.minimum(x0 + 1, gw - 1)
        fy = (ys - y0)[:, None]
        fx = (xs - x0)[None, :]
        v = (
            grid[np.ix_(y0, x0)] * (1 - fy) * (1 - fx)
            + grid[np.ix_(y1, x0)] * fy * (1 - fx)
            + grid[np.ix_(y0, x1)] * (1 - fy) * fx
            + grid[np.ix_(y1, x1)] * fy * fx
        )
        out += amp * v
        total += amp
        amp *= 0.55
    return out / total


def _voronoi_labels(rng, h, w, n_cells: int) -> np.ndarray:
    """Integer label map from nearest-seed (Voronoi) regions."""
    pts = rng.random((n_cells, 2)) * [h, w]
    yy, xx = np.mgrid[0:h, 0:w]
    d = (yy[..., None] - pts[:, 0]) ** 2 + (xx[..., None] - pts[:, 1]) ** 2
    return np.argmin(d, axis=-1) % 3


def _texture_for_label(rng, label: int, h: int, w: int) -> np.ndarray:
    """(H, W, 3) procedural texture, distinct statistics per label."""
    base = _smooth_noise(rng, h, w, octaves=5)
    if label == 0:  # grass-ish: high-freq green
        hf = rng.random((h, w)).astype(np.float32)
        tex = np.stack([0.15 + 0.2 * hf, 0.45 + 0.35 * base, 0.1 + 0.1 * hf], -1)
    elif label == 1:  # water-ish: smooth blue waves
        wave = 0.5 + 0.5 * np.sin(
            np.linspace(0, 20, w)[None, :] + 6 * base
        ).astype(np.float32)
        tex = np.stack([0.1 + 0.1 * base, 0.3 + 0.2 * wave, 0.55 + 0.35 * wave], -1)
    else:  # rock-ish: gray granular
        grain = 0.5 * base + 0.5 * rng.random((h, w)).astype(np.float32)
        tex = np.stack([0.45 + 0.3 * grain] * 3, -1)
    return tex.astype(np.float32)


def _label_colors(labels: np.ndarray) -> np.ndarray:
    palette = np.array(
        [[0.2, 0.8, 0.2], [0.2, 0.3, 0.9], [0.6, 0.6, 0.6]], np.float32
    )
    return palette[labels]


def texture_by_numbers(
    size: int = 256, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(A, A', B): A/B are flat-color label maps, A' the textured render."""
    rng = _rng(seed)
    lab_a = _voronoi_labels(rng, size, size, 12)
    lab_b = _voronoi_labels(rng, size, size, 9)
    a = _label_colors(lab_a)
    b = _label_colors(lab_b)
    textures = [_texture_for_label(rng, k, size, size) for k in range(3)]
    ap = np.stack(
        [np.choose(lab_a, [t[..., c] for t in textures]) for c in range(3)], -1
    )
    return a, ap.astype(np.float32), b


def _photo_like(rng, h, w) -> np.ndarray:
    """Smooth colorful synthetic 'photo'."""
    r = _smooth_noise(rng, h, w, 4)
    g = _smooth_noise(rng, h, w, 5)
    bl = _smooth_noise(rng, h, w, 3)
    return np.stack([r, g, bl], -1).astype(np.float32)


def _box_blur(img: np.ndarray, k: int) -> np.ndarray:
    """Separable (2k+1)-tap box blur with edge padding (host-side helper)."""
    out = img.astype(np.float32)
    for axis in (0, 1):
        pad = [(0, 0)] * out.ndim
        pad[axis] = (k, k)
        p = np.pad(out, pad, mode="edge")
        acc = np.zeros_like(out)
        for off in range(2 * k + 1):
            acc += np.take(p, range(off, off + out.shape[axis]), axis=axis)
        out = acc / (2 * k + 1)
    return out


def watercolor(img: np.ndarray, levels: int = 6) -> np.ndarray:
    """Cheap 'watercolor' filter: smooth then quantize then edge-soften."""
    sm = _box_blur(img, 3)
    quant = np.round(sm * levels) / levels
    return (0.8 * quant + 0.2 * sm).astype(np.float32)


def artistic_filter(
    size: int = 512, seed: int = 1
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(A, A', B): A' = watercolor(A); analogy transfers the filter to B."""
    rng = _rng(seed)
    a = _photo_like(rng, size, size)
    b = _photo_like(rng, size, size)
    return a, watercolor(a), b


def super_resolution(
    size: int = 1024, seed: int = 2
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(A, A', B): A = blurred A', B = blurred target — B' 'deblurs' B."""
    rng = _rng(seed)
    ap = _photo_like(rng, size, size)
    sharp_b = _photo_like(rng, size, size)
    return _box_blur(ap, 2), ap, _box_blur(sharp_b, 2)


def texture_transfer(
    size: int = 256, seed: int = 4
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(A, A', B): texture transfer (Hertzmann §4.4) — A and A' are both
    the *texture* (identity filter), B is an arbitrary target image;
    synthesized B' re-renders B out of the texture's material.  Run with
    kappa > 0 so coherent texture patches survive the luminance match."""
    rng = _rng(seed)
    tex = _texture_for_label(rng, 1, size, size)
    b = _photo_like(rng, size, size)
    return tex, tex.copy(), b


def npr_frames(
    n_frames: int = 8, size: int = 1024, seed: int = 3
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(A, A', frames): shared style pair + a drifting synthetic video.

    Frames are shifted/evolving views of one noise field so consecutive
    frames are temporally coherent, like the reference's NPR video use-case
    [BASELINE.json config 5].
    """
    rng = _rng(seed)
    a = _photo_like(rng, size, size)
    ap = watercolor(a)
    big = _photo_like(rng, size + 8 * n_frames, size + 8 * n_frames)
    frames = np.stack(
        [big[8 * i : 8 * i + size, 8 * i : 8 * i + size] for i in range(n_frames)]
    )
    return a, ap, frames
