"""Image I/O at the host edge (SURVEY.md §2 C1).

PIL handles codec work on the host; everything after `load_image` is device
arrays in [0, 1] float32.  This is the only host<->device boundary of the
pipeline (one transfer in, one out — SURVEY.md §3.1).
"""

from __future__ import annotations

import numpy as np


def load_image(path: str, gray: bool = False) -> np.ndarray:
    """PNG/JPEG -> float32 [0,1], (H,W,3) or (H,W) when `gray`."""
    from PIL import Image

    img = Image.open(path)
    img = img.convert("L" if gray else "RGB")
    return np.asarray(img, dtype=np.float32) / 255.0


def save_image(path: str, img) -> None:
    """float [0,1] array -> 8-bit PNG/JPEG."""
    from PIL import Image

    arr = np.asarray(img)
    arr = np.clip(arr * 255.0 + 0.5, 0, 255).astype(np.uint8)
    Image.fromarray(arr).save(path)


def atomic_write_json(path: str, obj) -> None:
    """JSON to `path` via tmp + rename, so a kill mid-write never
    leaves a truncated file where a consumer would trip over it — the
    same discipline the checkpoint writer applies to its .npz
    artifacts (models/analogy._save_level).  Used for every telemetry
    artifact (host_spans.json, report.json)."""
    import json
    import os

    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    """Text twin of `atomic_write_json` (same tmp + rename contract) —
    for non-JSON telemetry artifacts like the Prometheus exposition
    (`metrics.prom`)."""
    import os

    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
