"""Quality metrics — PSNR is the framework's acceptance currency
(north-star: ">= 35 dB PSNR vs CPU ref", BASELINE.json:2)."""

from __future__ import annotations

import numpy as np


def psnr(x, y, peak: float = 1.0) -> float:
    """Peak signal-to-noise ratio in dB between two [0,peak] images."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    mse = float(np.mean((x - y) ** 2))
    if mse == 0.0:
        return float("inf")
    return 10.0 * np.log10(peak * peak / mse)


def nnf_energy(dist) -> float:
    """Mean match distance — the PatchMatch convergence monitor
    (SURVEY.md §4 'iteration monotonicity')."""
    return float(np.mean(np.asarray(dist)))
