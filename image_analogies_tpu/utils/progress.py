"""Observability: stdlib logging + JSON-lines progress events
(SURVEY.md §5 metrics/logging).

`ProgressWriter` is the legacy JSONL event sink; since round 6 it is
usually driven as the sink of a `telemetry.Tracer` (the span layer
emits the same event stream as its backward-compatible view) but
remains directly usable.  Each event record carries both the relative
`t` (seconds since writer construction, the historic field) and an
absolute ISO-8601 UTC `ts` so streams from different hosts/runs can be
aligned without knowing each writer's epoch.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import time
from typing import Optional

logger = logging.getLogger("image_analogies_tpu")

_LEVELS = ("debug", "info", "warning", "error", "critical")


def configure_logging(level: Optional[str]) -> None:
    """Attach a stderr handler + formatter to the package logger at
    `level` ('debug' | 'info' | ...; None = leave logging untouched).

    Without this the package logs into a handler-less logger, which
    under Python's default config prints nothing below WARNING — the
    CLI's `--log-level` flag routes here so `--log-level info` actually
    surfaces the per-event log lines.  Idempotent: re-configuring
    adjusts the level instead of stacking handlers.
    """
    if level is None:
        return
    level = level.lower()
    if level not in _LEVELS:
        raise ValueError(f"log level {level!r} not in {_LEVELS}")
    logger.setLevel(getattr(logging, level.upper()))
    for h in logger.handlers:
        if getattr(h, "_ia_cli_handler", False):
            h.setLevel(getattr(logging, level.upper()))
            return
    handler = logging.StreamHandler()
    handler._ia_cli_handler = True  # type: ignore[attr-defined]
    handler.setLevel(getattr(logging, level.upper()))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"
        )
    )
    logger.addHandler(handler)


def _iso_now(offset_ms: float = 0.0) -> str:
    """ISO-8601 UTC timestamp, optionally shifted by `offset_ms`
    (negative = in the past — telemetry spans recorded after the fact
    backdate their start this way)."""
    t = _dt.datetime.now(_dt.timezone.utc)
    if offset_ms:
        t += _dt.timedelta(milliseconds=offset_ms)
    return t.isoformat(timespec="milliseconds").replace("+00:00", "Z")


class ProgressWriter:
    """Append one JSON object per event to a .jsonl file (or log only).

    The file is opened ONCE, line-buffered, on the first emit and held
    for the writer's lifetime — the original implementation reopened
    the file per event, an O(events) syscall tax that also left no
    single handle for consumers to tail reliably.  Line buffering
    keeps the durability property the per-event reopen provided: every
    event is flushed to the OS as soon as its line is written, so a
    killed run's stream is complete up to the crash.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._t0 = time.perf_counter()
        self._f = None

    def emit(self, event: str, **fields) -> None:
        rec = {
            "event": event,
            "t": round(time.perf_counter() - self._t0, 4),
            "ts": _iso_now(),
        }
        rec.update(fields)
        logger.info("%s %s", event, fields)
        if self.path:
            if self._f is None:
                self._f = open(self.path, "a", buffering=1)
            self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "ProgressWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort: line buffering already flushed
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
