"""Observability: stdlib logging + JSON-lines progress events
(SURVEY.md §5 metrics/logging)."""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

logger = logging.getLogger("image_analogies_tpu")


class ProgressWriter:
    """Append one JSON object per event to a .jsonl file (or log only)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._t0 = time.perf_counter()

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "t": round(time.perf_counter() - self._t0, 4)}
        rec.update(fields)
        logger.info("%s %s", event, fields)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
