"""Shared tile-kernel microbench harness (used by bench.py and
tools/tune_kernel.py so the published utilization numbers and the
recorded tuning results measure the SAME kernel setup by construction).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp


def sync(x) -> float:
    """Completion barrier: force x with a scalar readback —
    `block_until_ready()` can return early on the tunnelled axon
    platform (see bench.py)."""
    return float(jnp.sum(x))


def sweep_setup(cfg, size: int):
    """Build a steady-state all-bands tile_sweep closure at the
    (size x size, coarse-channel) geometry.

    Returns (one_iter, state0, meta) where one_iter(oy, ox, d) runs one
    full pm-iteration's band calls, state0 is the initial blocked state,
    and meta carries (specs, geom, n_bands, a_planes).  Candidates come
    from a RANDOM field, so no slots dedup away and timings measure the
    all-candidates-evaluated upper bound the static FLOP model assumes.
    Returns None when the geometry is kernel-ineligible.
    """
    from ..kernels.patchmatch_tile import (
        K_TOTAL,
        LANE,
        band_bounds,
        plan_channels,
        prepare_a_planes,
        resolve_cand_dtype,
        resolve_packed,
        resolve_prune,
        sample_candidates,
        tile_geometry,
        tile_sweep,
        to_blocked,
    )

    plan = plan_channels(1, 1, cfg, True, size, size, size, size)
    if plan is None:
        return None
    specs, use_coarse, n_bands = plan
    geom = tile_geometry(size, size, specs)
    rng = np.random.default_rng(0)
    mk = lambda *s: jnp.asarray(rng.random(s, np.float32))  # noqa: E731
    a_planes = prepare_a_planes(
        mk(size, size), mk(size, size),
        mk(size // 2, size // 2) if use_coarse else None,
        mk(size // 2, size // 2) if use_coarse else None,
        specs, n_bands=n_bands,
    )
    # True channel count from the plan (the packed A layout's sublane
    # axis is 2C, so a_planes.shape[2] is layout-dependent).
    n_chan = len(specs)
    b_blocked = jnp.stack(
        [to_blocked(mk(size, size), geom) for _ in range(n_chan)]
    )
    thp, n_ty, n_tx = geom.thp, geom.n_ty, geom.n_tx
    oy = jnp.zeros((n_ty * thp, n_tx * LANE), jnp.int32)
    ox = jnp.zeros((n_ty * thp, n_tx * LANE), jnp.int32)
    d = jnp.full((n_ty * thp, n_tx * LANE), jnp.inf, jnp.float32)
    ry = jnp.asarray(rng.integers(-size, size, (size, size), dtype=np.int32))
    rx = jnp.asarray(rng.integers(-size, size, (size, size), dtype=np.int32))
    cand_y, cand_x, cand_valid = sample_candidates(
        ry, rx, jax.random.PRNGKey(0), geom, size, size,
    )
    prune = resolve_prune()
    if prune is not None:
        # Compressed-path harness (round 11): keep only the first M
        # slots valid so the timed kernel's pl.when(ok) skip moves
        # exactly the modeled exact-fetch budget (the coarse ranking
        # itself is XLA work outside the timed sweep — priced by the
        # byte model, not this harness).
        cand_valid = cand_valid * (
            jnp.arange(K_TOTAL) < prune[1]
        ).astype(cand_valid.dtype)
    bounds = band_bounds(size, n_bands)

    def one_iter(oy, ox, d):
        for band_planes, band in zip(a_planes, bounds):
            oy, ox, d = tile_sweep(
                band_planes, b_blocked, cand_y, cand_x, oy, ox, d, band,
                cand_valid,
                specs=specs, geom=geom, ha=size, wa=size, coh_factor=1.0,
                cand_budget=prune[1] if prune else None,
            )
        return oy, ox, d

    meta = {
        "specs": specs,
        "geom": geom,
        "n_bands": n_bands,
        "a_planes": a_planes,
        "n_chan": n_chan,
        # The layout/compression this setup prepared and sweeps under —
        # bench.py's byte model reads these so the published traffic
        # matches what the timed kernel actually moved.
        "packed": resolve_packed(),
        "cand_dtype": resolve_cand_dtype(),
        "prune": prune,
    }
    return one_iter, (oy, ox, d), meta


def sweep_time_ms(cfg, size: int, iters: int = 16):
    """Steady-state ms per full sweep, plus the setup meta.  None when
    ineligible.

    Differenced timing: the closing scalar-readback barrier costs a
    full tunnel round trip (~75-105 ms measured on this box), which at
    16 iterations inflated a naive (loop + sync)/N by ~5-7 ms/sweep —
    round 3's published 12.9 ms sweep carried that bias.  Timing N and
    2N iterations and differencing cancels the constant sync cost."""
    setup = sweep_setup(cfg, size)
    if setup is None:
        return None
    one_iter, (oy, ox, d), meta = setup
    oy, ox, d = one_iter(oy, ox, d)  # warm/compile
    sync(d)

    def timed(n):
        s = (oy, ox, d)
        t0 = time.perf_counter()
        for _ in range(n):
            s = one_iter(*s)
        sync(s[2])
        return time.perf_counter() - t0

    t_n = timed(iters)
    t_2n = timed(2 * iters)
    return (t_2n - t_n) / iters * 1000, meta


def sweep_time_device_loop_ms(cfg, size: int, iters: int = 24,
                              reps: int = 5):
    """Steady-state ms per full sweep with the iteration loop ON DEVICE
    — the round-5 replacement for `sweep_time_ms` as the published
    figure (VERDICT r4: one committed run reported an HBM roofline
    fraction of 1.159, physically impossible; host-differenced timing
    is contaminated when a tunnel stall lands inside the t_n window and
    SUBTRACTS from the difference).

    Three defenses, in order of importance:
      1. `lax.fori_loop` runs N sweeps as ONE device execution, so
         per-iteration dispatch/queue effects cannot enter the number
         at all — the only host cost is one tunnel round trip.
      2. N and 2N executions are timed separately, each taking the MIN
         over `reps` runs (stalls only ever ADD time, so min is the
         clean-run estimator), and the mins are differenced to cancel
         the round trip.
      3. The loop-carried state makes each iteration depend on the
         last, so XLA cannot elide or overlap iterations.

    Returns (ms_per_sweep, meta) or None when kernel-ineligible."""
    setup = sweep_setup(cfg, size)
    if setup is None:
        return None
    one_iter, s0, meta = setup

    def make_run(n):
        return jax.jit(
            lambda s: jax.lax.fori_loop(
                0, n, lambda _, st: one_iter(*st), s
            )
        )

    run_n, run_2n = make_run(iters), make_run(2 * iters)
    sync(run_n(s0)[2])  # compile + warm
    sync(run_2n(s0)[2])

    def best_of(run):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            sync(run(s0)[2])
            best = min(best, time.perf_counter() - t0)
        return best

    t_n = best_of(run_n)
    t_2n = best_of(run_2n)
    return (t_2n - t_n) / iters * 1000, meta


def sweep_time_trace_ms(cfg, size: int, iters: int = 16,
                        trace_dir: str = None):
    """Device-trace-derived ms per sweep: run `iters` sweeps inside
    `jax.profiler.trace` (compiles warmed beforehand) and read the
    device plane's total op busy time from the xplane files
    (utils/xplane.py — no TensorBoard dependency).  This is the
    instrument-grade number: pure on-device execution time, immune to
    host clocks, tunnel stalls, and dispatch overhead entirely.

    Returns (ms_per_sweep, meta, {op_name: total_ms}) or None when the
    geometry is kernel-ineligible OR the backend does not forward
    device traces (a tunnelled PJRT plugin may not) — callers fall
    back to `sweep_time_device_loop_ms`."""
    import shutil
    import tempfile

    from .xplane import device_op_totals

    setup = sweep_setup(cfg, size)
    if setup is None:
        return None
    one_iter, s0, meta = setup
    s = one_iter(*s0)
    sync(s[2])  # warm/compile outside the trace window
    d = trace_dir or tempfile.mkdtemp(prefix="kernelbench_trace_")
    try:
        with jax.profiler.trace(d):
            for _ in range(iters):
                s = one_iter(*s)
            sync(s[2])
        totals = device_op_totals(d)
    finally:
        if trace_dir is None:
            shutil.rmtree(d, ignore_errors=True)
    if not totals:
        return None
    per_op: dict = {}
    for ops in totals.values():
        for name, ms in ops.items():
            per_op[name] = per_op.get(name, 0.0) + ms
    busy = sum(per_op.values())
    if busy <= 0.0:
        # Device plane present but no op timeline matched the filter —
        # treat as "traces not forwarded" rather than publishing 0 ms.
        return None
    return busy / iters, meta, per_op
