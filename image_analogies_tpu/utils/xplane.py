"""Minimal XPlane (jax.profiler / XProf) trace reader (SURVEY.md §5).

`jax.profiler.trace` writes protobuf `*.xplane.pb` files (TF XSpace
schema) that normally need TensorBoard's profile plugin to read; this
module decodes just enough of the wire format to answer the question
the kernel bench needs: *how long did the device actually run each
op?* — without any TensorFlow dependency (not in this image).

Wire schema decoded (tensorflow/core/profiler/protobuf/xplane.proto,
stable field numbers):

    XSpace  { repeated XPlane planes = 1; }
    XPlane  { int64 id = 1; string name = 2; repeated XLine lines = 3;
              map<int64, XEventMetadata> event_metadata = 4; }
    XLine   { int64 id = 1; string name = 2; int64 timestamp_ns = 3;
              repeated XEvent events = 4; int64 duration_ps = 9;
              int64 display_id = 10; string display_name = 11; }
    XEvent  { int64 metadata_id = 1; int64 offset_ps = 2;
              int64 duration_ps = 3; repeated XStat stats = 4; }
    XEventMetadata { int64 id = 1; string name = 2;
                     string display_name = 3; }

Unknown fields are skipped by wire type, so schema additions are
harmless.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, Optional, Tuple

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError(
                f"truncated varint at byte {pos} (buffer of {n})"
            )
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message buffer.
    LEN fields yield the raw bytes; varints the int; fixed widths the
    raw little-endian bytes (unused here).  A buffer that ends inside a
    field raises ValueError instead of yielding a silently-truncated
    payload — a half-written trace must fail loudly, not decode to
    wrong totals with exit 0."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                raise ValueError(
                    f"truncated length-delimited field {field}: "
                    f"{ln} bytes declared, {n - pos} remain"
                )
            val = buf[pos : pos + ln]
            pos += ln
        elif wire == _WIRE_I64:
            if pos + 8 > n:
                raise ValueError(f"truncated fixed64 field {field}")
            val = buf[pos : pos + 8]
            pos += 8
        elif wire == _WIRE_I32:
            if pos + 4 > n:
                raise ValueError(f"truncated fixed32 field {field}")
            val = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_event(buf: bytes) -> Tuple[int, int]:
    """(metadata_id, duration_ps)."""
    mid = dur = 0
    for field, _w, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 3:
            dur = val
    return mid, dur


def _parse_line(buf: bytes):
    """(name, [(metadata_id, duration_ps)])."""
    name = disp = ""
    events = []
    for field, _w, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 11 and val:
            disp = val.decode("utf-8", "replace")
        elif field == 4:
            events.append(_parse_event(val))
    return disp or name, events


def _parse_metadata_entry(buf: bytes) -> Tuple[int, str]:
    """map<int64, XEventMetadata> entry -> (id, name)."""
    mid = 0
    name = ""
    for field, _w, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            for f2, _w2, v2 in _fields(val):
                if f2 == 2 and not name:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 3 and v2:
                    name = v2.decode("utf-8", "replace")
    return mid, name


def _parse_plane(buf: bytes):
    """(name, {metadata_id: name}, [(line_name, events)])."""
    name = ""
    meta: Dict[int, str] = {}
    lines = []
    for field, _w, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            lines.append(_parse_line(val))
        elif field == 4:
            mid, mname = _parse_metadata_entry(val)
            meta[mid] = mname
    return name, meta, lines


def parse_xspace(path: str):
    """[(plane_name, {metadata_id: name}, [(line_name, events)])]."""
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for field, _w, val in _fields(buf):
        if field == 1:
            planes.append(_parse_plane(val))
    return planes


def find_xplane_files(trace_dir: str):
    """All *.xplane.pb under a jax.profiler.trace output directory."""
    return sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )


def device_op_totals(
    trace_dir: str, line_filter: Optional[str] = "XLA Ops"
) -> Dict[str, Dict[str, float]]:
    """Per-op total device time in ms, per device plane.

    Returns {plane_name: {op_name: total_ms}} for planes that look like
    accelerator devices (name contains 'TPU' or 'GPU', or '/device:'
    but not 'CPU'/'Host').  `line_filter` selects the op-level timeline
    (the 'XLA Ops' line on TPU planes; pass None to sum every line —
    beware module/op double counting)."""
    out: Dict[str, Dict[str, float]] = {}
    for path in find_xplane_files(trace_dir):
        for pname, meta, lines in parse_xspace(path):
            lname = pname.lower()
            is_dev = ("tpu" in lname or "gpu" in lname) or (
                "/device:" in lname
                and "cpu" not in lname
                and "host" not in lname
            )
            if not is_dev:
                continue
            ops = out.setdefault(pname, {})
            for line_name, events in lines:
                if line_filter is not None and line_filter not in line_name:
                    continue
                for mid, dur_ps in events:
                    name = meta.get(mid, f"op_{mid}")
                    ops[name] = ops.get(name, 0.0) + dur_ps / 1e9
    return out


def scope_totals(ops: Dict[str, float], tag_pattern: str
                 ) -> Dict[str, float]:
    """Group already-decoded per-op totals `{op_name: ms}` by the first
    regex capture of `tag_pattern`; ops that don't match are dropped.

    This is how the run-report joiner (telemetry/report.py) attributes
    device time to pyramid levels / EM iterations / matcher phases:
    the instrumented drivers wrap those regions in `jax.named_scope`
    tags (`tlm_L<level>`, `tlm_em<i>`, `tlm_<phase>`), XLA threads the
    scope path into op metadata, and the profiler surfaces it as the
    op display name — so a scope's device cost is the sum over ops
    whose name carries its tag.  Taking pre-decoded totals lets one
    (slow, pure-Python) trace decode feed several groupings.
    Best-effort by design: a backend that strips framework op names
    (or forwards no device planes at all) yields {} and the report
    records nulls, never guesses."""
    import re

    pat = re.compile(tag_pattern)
    out: Dict[str, float] = {}
    for name, ms in ops.items():
        m = pat.search(name)
        if m:
            tag = m.group(1)
            out[tag] = out.get(tag, 0.0) + ms
    return out


def device_scope_totals(
    trace_dir: str, tag_pattern: str,
    line_filter: Optional[str] = "XLA Ops",
) -> Dict[str, float]:
    """`scope_totals` over a trace directory's decoded op totals — the
    one-shot convenience form; callers grouping by several patterns
    should decode once via `device_op_totals` and call `scope_totals`
    per pattern (telemetry/report.py does)."""
    flat: Dict[str, float] = {}
    for ops in device_op_totals(trace_dir, line_filter).values():
        for name, ms in ops.items():
            flat[name] = flat.get(name, 0.0) + ms
    return scope_totals(flat, tag_pattern)


def device_busy_ms(trace_dir: str) -> Optional[float]:
    """Total device op time (ms) summed over accelerator planes' op
    timelines, or None when the trace carries no device plane (a
    tunnelled PJRT backend may not forward device traces)."""
    totals = device_op_totals(trace_dir)
    if not totals:
        return None
    return sum(sum(ops.values()) for ops in totals.values())
