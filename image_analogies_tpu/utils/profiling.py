"""Tracing/profiling harness (SURVEY.md §5 tracing/profiling).

`device_trace(dir)` wraps a region in `jax.profiler.trace`, producing
Perfetto/XProf traces (TensorBoard-loadable) of every XLA executable and
Pallas kernel launch in the region — the TPU-native replacement for the
host profilers a CPU reference would use.  Wall-clock per-level timings
come from the drivers themselves (models/analogy.py runs under
`telemetry.Tracer` spans with a single sync per level), not from this
module.

`telemetry_session` is the one-stop wrapper the CLI drives: device
trace + host span tracer + end-of-run artifact writes (host_spans.json,
metrics.json, metrics.prom) into the same trace directory, which is
exactly the layout `telemetry.report.build_report` (the `report`
subcommand) joins.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace when a directory is given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


_SAME_AS_TRACE_DIR = object()


@contextlib.contextmanager
def telemetry_session(trace_dir: Optional[str], sink=None,
                      enabled: bool = True,
                      artifact_dir=_SAME_AS_TRACE_DIR,
                      metrics_port: Optional[int] = None,
                      flight_capacity: Optional[int] = None):
    """Device trace + span tracer + telemetry artifact writes.

    Yields a `telemetry.Tracer` (disabled when `enabled` is False, so
    un-instrumented runs stay zero-cost).  An enabled session owns a
    FRESH metrics registry, installed as the process default for the
    session's duration — so `metrics.json` reports this run's counts,
    not everything the process has ever accumulated (kernel-launch and
    sharded-gather counters record through `get_registry()` and land
    in the session's registry too).

    Round-10 live layer: an enabled session with an `artifact_dir`
    installs a flight recorder (telemetry/flight.py — span-event ring
    buffer flushed to `<artifact_dir>/flight.json` on SIGTERM/SIGINT/
    atexit/sentinel violation and at teardown; `flight_capacity`
    overrides the event-ring size — None resolves --flight-ring/
    IA_FLIGHT_RING/512 via flight.resolve_ring_capacity), and
    `metrics_port`
    (the CLI's `--metrics-port`; 0 = ephemeral) additionally serves
    /metrics, /healthz and /progress from an in-process HTTP exporter
    (telemetry/live.py), announcing the bound endpoint in
    `<artifact_dir>/live.json`.

    On exit — crash included, a partial run's telemetry is exactly
    when you want the evidence — writes into `artifact_dir` (default:
    `trace_dir`; the CLI passes them separately so the historic
    device-trace-only `--profile` dir stays artifact-free):

      host_spans.json   the span tree (telemetry/spans.py schema)
      metrics.json      the registry's JSON exposition
      metrics.prom      the registry's Prometheus text exposition
      flight.json       the flight recorder's final dump

    every one via tmp + rename (a crash mid-epilogue must never leave
    the truncated artifact the sentinel would then have to refuse),
    alongside whatever `*.xplane.pb` files `jax.profiler.trace` left,
    making the directory self-contained input for the `report`
    subcommand."""
    import os

    from ..telemetry import NULL_TRACER, MetricsRegistry, Tracer
    from ..telemetry.metrics import set_registry

    if artifact_dir is _SAME_AS_TRACE_DIR:
        artifact_dir = trace_dir
    flight = live = None
    if enabled:
        reg = MetricsRegistry()
        tracer = Tracer(sink=sink, registry=reg)
        prev_reg = set_registry(reg)
    else:
        tracer = NULL_TRACER
        reg = prev_reg = None
    # The flight/live setup lives INSIDE the try: a failed exporter
    # bind (e.g. EADDRINUSE on a fixed --metrics-port) must unwind the
    # registry swap and the recorder's signal/atexit handlers through
    # the same finally the run itself uses — not leak them into the
    # process for the next session to trip over.
    try:
        if enabled:
            if artifact_dir:
                from ..telemetry.flight import (
                    install_for_session,
                    resolve_ring_capacity,
                )

                flight = install_for_session(
                    tracer, reg, artifact_dir,
                    capacity=resolve_ring_capacity(flight_capacity),
                )
                # Handle for epilogues that run AFTER session teardown
                # (the CLI health epilogue flushes on a violated
                # verdict).
                tracer.flight_recorder = flight
            if metrics_port is not None:
                from ..telemetry.live import LiveTelemetryServer

                live = LiveTelemetryServer(
                    tracer, reg, port=metrics_port, flight=flight
                ).start()
                if artifact_dir:
                    live.announce(artifact_dir)
        with device_trace(trace_dir):
            yield tracer
    finally:
        if live is not None:
            live.stop()
        if flight is not None:
            flight.uninstall()  # final flush, reason "session-end"
        if enabled:
            set_registry(prev_reg)
        if artifact_dir and tracer.enabled:
            from ..utils.io import atomic_write_json, atomic_write_text

            os.makedirs(artifact_dir, exist_ok=True)
            tracer.write(os.path.join(artifact_dir, "host_spans.json"))
            atomic_write_json(
                os.path.join(artifact_dir, "metrics.json"), reg.to_dict()
            )
            atomic_write_text(
                os.path.join(artifact_dir, "metrics.prom"),
                reg.to_prometheus(),
            )
