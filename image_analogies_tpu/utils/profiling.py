"""Tracing/profiling harness (SURVEY.md §5 tracing/profiling).

`device_trace(dir)` wraps a region in `jax.profiler.trace`, producing
Perfetto/XProf traces (TensorBoard-loadable) of every XLA executable and
Pallas kernel launch in the region — the TPU-native replacement for the
host profilers a CPU reference would use.  Wall-clock per-level timings
come from the drivers themselves (models/analogy.py emits `level_done`
events with a single block_until_ready sync per level), not from this
module.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace when a directory is given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
