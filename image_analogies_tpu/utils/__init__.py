"""Host-edge utilities: image I/O, metrics, progress logging, procedural
example assets (SURVEY.md §2 C1/C14, §5)."""

from .io import load_image, save_image
from .metrics import psnr, nnf_energy
from .progress import ProgressWriter, logger

__all__ = [
    "load_image",
    "save_image",
    "psnr",
    "nnf_energy",
    "ProgressWriter",
    "logger",
]
