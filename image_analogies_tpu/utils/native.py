"""ctypes loader/builder for the native C++ helpers (SURVEY.md §2 C8).

pybind11 is not available in this environment, so native code is plain
C ABI compiled with g++ and loaded via ctypes.  The shared library is
built on first use into native/build/ (next to the sources) and cached;
build failure degrades gracefully — callers treat `load_ann() is None`
as "native backend unavailable" and fall back to the XLA path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "ann.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libia_ann.so")

_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_failed = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Compile to a process-private path and rename into place so a
    # concurrent process never dlopens a half-written .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
        "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(tmp, _LIB)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        log.warning("native ANN build failed: %s", detail.strip()[:500])
        if os.path.exists(tmp):
            os.unlink(tmp)
        return False


def load_ann() -> Optional[ctypes.CDLL]:
    """The ANN library with argtypes configured, or None if unbuildable.

    Builds (once per process) when the cached .so is missing or older
    than the source.
    """
    global _cached, _failed
    with _lock:
        if _cached is not None:
            return _cached
        if _failed:
            return None
        stale = not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        )
        if stale and not _compile():
            _failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            log.warning("native ANN load failed: %s", e)
            _failed = True
            return None
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ann_build.argtypes = [f32p, ctypes.c_int, ctypes.c_int]
        lib.ann_build.restype = ctypes.c_void_p
        lib.ann_query.argtypes = [
            ctypes.c_void_p, f32p, ctypes.c_int, ctypes.c_float, i32p, f32p,
        ]
        lib.ann_query.restype = None
        lib.ann_free.argtypes = [ctypes.c_void_p]
        lib.ann_free.restype = None
        _cached = lib
        return lib


def ann_available() -> bool:
    return load_ann() is not None
