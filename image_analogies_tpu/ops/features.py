"""Feature-vector assembly (SURVEY.md §2 C5; Hertzmann §3.1).

Per pixel q at level l the feature vector concatenates:
  - a `patch` x `patch` neighborhood of the *source* channels at level l
    (B-side: B; A-side: A),
  - the same neighborhood of the *filtered* channels at level l
    (B-side: current B' estimate; A-side: A'),
  - a `coarse_patch` x `coarse_patch` neighborhood of both at level l+1,
    sampled at q//2 (absent at the coarsest level).

Neighborhood extraction is one `jax.lax.conv_general_dilated_patches` call
per image (an im2col conv — XLA tiles it onto the MXU/VPU, no Python
per-pixel loop), on edge-padded inputs so border pixels get full windows.

The Gaussian-weighted norm of the paper is baked in by scaling each feature
channel by sqrt(w): plain L2 on the assembled vectors then equals the
weighted patch distance, so every matcher (brute matmul, PatchMatch kernel)
inherits the weighting for free.

Sequential-vs-parallel note (SURVEY.md §7 "hard parts"): the paper's B'
windows are *causal* (only already-synthesized pixels, scan order).  The TPU
reformulation synthesizes whole levels iteratively (EM over full windows of
the previous B' estimate), so windows here are full; parity with the causal
formulation is asserted via PSNR, not pixel equality [BASELINE.json metric].
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import SynthConfig


def extract_patches(img: jnp.ndarray, patch: int) -> jnp.ndarray:
    """(H, W) or (H, W, C) -> (H, W, C*patch*patch) edge-padded windows.

    Channel-major layout: index [c*patch*patch + dy*patch + dx] is channel c
    at window offset (dy, dx).

    Layout note: the conv-im2col lowering materializes a
    (1, C*p^2, H, W) intermediate whose TPU layout pads ~5x; at 1024^2
    that is a few hundred MB of temp and compiles fine, and levels big
    enough for it to matter run the LEAN path, which assembles in row
    slabs and never sees full-image patch tensors.  (A shifted-slice +
    stack formulation was tried and is WORSE: stacking 2-D planes on a
    new trailing axis makes XLA pad each (H, W, 1) input 128x on the
    unit lane axis — 27 GB of temps at 1024^2.)
    """
    if img.ndim == 2:
        img = img[..., jnp.newaxis]
    h, w, c = img.shape
    r = patch // 2
    x = jnp.pad(img, ((r, r), (r, r), (0, 0)), mode="edge")
    x = jnp.moveaxis(x, -1, 0)[jnp.newaxis]  # (1, C, H+2r, W+2r)
    patches = jax.lax.conv_general_dilated_patches(
        x, (patch, patch), (1, 1), "VALID"
    )  # (1, C*patch*patch, H, W), channel-major spatial minor
    return jnp.moveaxis(patches[0], 0, -1)


def _gauss_weights(patch: int, sigma_frac: float = 0.4) -> np.ndarray:
    """Per-offset Gaussian weights for one window, normalized to sum 1."""
    r = patch // 2
    sigma = max(patch * sigma_frac, 1e-3)
    y, x = np.mgrid[-r : r + 1, -r : r + 1].astype(np.float32)
    w = np.exp(-(x**2 + y**2) / (2 * sigma**2))
    return (w / w.sum()).reshape(-1)


def feature_weights(
    n_src: int,
    n_flt: int,
    cfg: SynthConfig,
    has_coarse: bool,
    coarse_scale: float = 1.0,
) -> np.ndarray:
    """sqrt-weight vector matching the layout of `assemble_features`.

    `n_src`/`n_flt` are the channel counts of the source/filtered images
    (they differ in steerable mode: the bank augments source images only).
    Windows are Gaussian-weighted and normalized per window; the
    coarse-level block is scaled by `coarse_scale` relative to the fine
    block.  Returned as sqrt so it multiplies features directly.
    """
    if cfg.gaussian_weighting:
        wf = _gauss_weights(cfg.patch_size)
        wc = _gauss_weights(cfg.coarse_patch_size)
    else:
        wf = np.full(cfg.patch_size**2, 1.0 / cfg.patch_size**2, np.float32)
        wc = np.full(
            cfg.coarse_patch_size**2, 1.0 / cfg.coarse_patch_size**2, np.float32
        )
    blocks = [np.tile(wf, n_src + n_flt)]  # src block then filtered block
    if has_coarse:
        blocks.append(np.tile(wc, n_src + n_flt) * coarse_scale)
    return np.sqrt(np.concatenate(blocks)).astype(np.float32)


def assemble_features(
    src: jnp.ndarray,
    flt: jnp.ndarray,
    cfg: SynthConfig,
    src_coarse: Optional[jnp.ndarray] = None,
    flt_coarse: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Build the per-pixel feature tensor (H, W, D) for one pyramid level.

    `src`/`flt` are (H, W[, C]) match-channel images at level l; the coarse
    pair, when given, is the level-(l+1) images ((H+1)//2, (W+1)//2[, C]).
    The l+1 windows are sampled at q//2 via nearest-neighbor upsampling of
    the coarse patch tensor — exactly the paper's parent-pixel lookup.
    """
    h, w = src.shape[:2]
    n_src = 1 if src.ndim == 2 else src.shape[-1]
    n_flt = 1 if flt.ndim == 2 else flt.shape[-1]
    parts = [
        extract_patches(src, cfg.patch_size),
        extract_patches(flt, cfg.patch_size),
    ]
    has_coarse = src_coarse is not None
    if has_coarse:
        # q -> q//2 parent lookup as row/col gathers (values identical
        # to repeat-then-crop): jnp.repeat materializes an
        # (H, W/2, 2, D) intermediate whose trailing-dim lane pad
        # expands 14x — four 2 GB temps in the 2048^2 brute-oracle
        # graph, the difference between fitting HBM and OOM.
        iy = jnp.arange(h) // 2
        ix = jnp.arange(w) // 2
        for img in (src_coarse, flt_coarse):
            p = extract_patches(img, cfg.coarse_patch_size)
            p = jnp.take(jnp.take(p, iy, axis=0), ix, axis=1)
            parts.append(p)
    feats = jnp.concatenate(parts, axis=-1)
    wvec = jnp.asarray(feature_weights(n_src, n_flt, cfg, has_coarse))
    return feats * wvec
