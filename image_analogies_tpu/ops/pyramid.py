"""Gaussian pyramids (SURVEY.md §2 C3).

The reference builds pyramids with scipy/cv2.pyrDown-style host calls
[RECONSTRUCTED]; here the whole pyramid is built under `jit` with separable
convolutions (`jax.lax.conv_general_dilated`) and stays HBM-resident for the
entire run [BASELINE.json north star].

Conventions:
  - level 0 is the *finest* level (full resolution); level L-1 the coarsest.
  - images are (H, W) or (H, W, C) float32.
  - downsampling is blur + stride-2; upsampling is resize + blur (classic
    Burt-Adelson pyrUp without the x4 gain since we interpolate, not inject).
"""

from __future__ import annotations

from typing import List

import numpy as np
import jax
import jax.numpy as jnp

# 5-tap binomial approximation to a Gaussian (Burt & Adelson kernel).
# Host-side constant; converted lazily so importing never touches a device.
_KERNEL_1D = np.array([1.0, 4.0, 6.0, 4.0, 1.0], dtype=np.float32) / 16.0


def _to_nchw(img: jnp.ndarray):
    """(H,W) or (H,W,C) -> (1, C, H, W), remembering the original rank."""
    if img.ndim == 2:
        return img[jnp.newaxis, jnp.newaxis], True
    return jnp.moveaxis(img, -1, 0)[jnp.newaxis], False


def _from_nchw(x: jnp.ndarray, was_2d: bool) -> jnp.ndarray:
    if was_2d:
        return x[0, 0]
    return jnp.moveaxis(x[0], 0, -1)


def _sep_conv(x: jnp.ndarray, k1d: jnp.ndarray) -> jnp.ndarray:
    """Depthwise separable 2D convolution of (1,C,H,W) with SAME edge pad."""
    c = x.shape[1]
    r = k1d.shape[0] // 2
    # Reflect-pad so borders don't darken (edge-consistent with feature
    # extraction, ops/features.py).
    x = jnp.pad(x, ((0, 0), (0, 0), (r, r), (r, r)), mode="edge")
    kv = jnp.tile(k1d.reshape(1, 1, -1, 1), (c, 1, 1, 1))
    kh = jnp.tile(k1d.reshape(1, 1, 1, -1), (c, 1, 1, 1))
    dn = jax.lax.conv_dimension_numbers(x.shape, kv.shape, ("NCHW", "OIHW", "NCHW"))
    x = jax.lax.conv_general_dilated(
        x, kv, (1, 1), "VALID", dimension_numbers=dn, feature_group_count=c
    )
    x = jax.lax.conv_general_dilated(
        x, kh, (1, 1), "VALID", dimension_numbers=dn, feature_group_count=c
    )
    return x


def gaussian_blur(img: jnp.ndarray) -> jnp.ndarray:
    """Binomial 5x5 Gaussian blur, edge-padded, any (H,W[,C]) image."""
    x, was_2d = _to_nchw(img)
    return _from_nchw(_sep_conv(x, _KERNEL_1D), was_2d)


def downsample(img: jnp.ndarray) -> jnp.ndarray:
    """Blur + stride-2 subsample (pyrDown)."""
    blurred = gaussian_blur(img)
    return blurred[::2, ::2]


def upsample(img: jnp.ndarray, target_shape) -> jnp.ndarray:
    """Bilinear resize to `target_shape` (H, W) — used for B'/s-map
    initialization when moving a level finer."""
    if img.ndim == 2:
        return jax.image.resize(img, target_shape, method="bilinear")
    return jax.image.resize(
        img, (*target_shape, img.shape[-1]), method="bilinear"
    )


def build_pyramid(img: jnp.ndarray, levels: int) -> List[jnp.ndarray]:
    """[level0(finest), ..., level_{L-1}(coarsest)].

    A plain Python loop: `levels` is static (<= ~6) so this unrolls into one
    XLA graph; every level stays on device.
    """
    pyr = [img]
    for _ in range(levels - 1):
        pyr.append(downsample(pyr[-1]))
    return pyr
