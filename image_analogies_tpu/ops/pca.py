"""PCA projection of feature vectors (SURVEY.md §2 C5; Hertzmann §3.1).

The paper projects concatenated neighborhood vectors onto their top
principal components before matching ("we use PCA to reduce the
dimensionality of the feature vectors", Hertzmann §3.1) — on CPU that
cut ANN query cost; on TPU it cuts the matcher's HBM traffic (the
dominant cost of NN-field evaluation, SURVEY.md §3 hot loop 2) by
D/pca_dims while the projection itself is one (N, D) x (D, k) MXU
matmul per EM step.

The basis is fit per level on the A-side features (the search database);
B-side features are projected with the same basis inside the jitted EM
step.  Features arrive pre-scaled by the sqrt-Gaussian window weights
(ops/features.py), so the PCA operates in the weighted metric and
projected L2 distances approximate the weighted patch distances the
matchers optimize.

Centering note: matching compares feature *differences*, and for an
orthonormal basis P, P^T(x - y) is identical whether or not x and y were
mean-centered first — so the basis is fit on centered data (the
covariance), but raw features are projected without re-centering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pca_basis(x_flat: jnp.ndarray, k: int) -> jnp.ndarray:
    """Top-k principal directions of (N, D) rows; returns (D, k).

    Uses the D x D covariance eigendecomposition — D is a few dozen
    neighborhood taps, so the eigh is negligible next to the (D, N)x(N, D)
    covariance matmul (MXU).  Columns are orthonormal, ordered by
    decreasing eigenvalue.  `k` is clamped to D.
    """
    n, d = x_flat.shape
    k = min(k, d)
    x = x_flat.astype(jnp.float32)
    mu = jnp.mean(x, axis=0, keepdims=True)
    xc = x - mu
    cov = (xc.T @ xc) / jnp.maximum(n - 1, 1)
    _, vecs = jnp.linalg.eigh(cov)  # ascending eigenvalues
    return vecs[:, ::-1][:, :k]


def project(f: jnp.ndarray, basis: jnp.ndarray) -> jnp.ndarray:
    """(..., D) features -> (..., k) in the PCA basis (one MXU matmul)."""
    return jnp.einsum(
        "...d,dk->...k", f, basis, preferred_element_type=jnp.float32
    )


def fit_and_project(f_a: jnp.ndarray, k) -> tuple:
    """Per-level A-side PCA: fit the basis on the (H, W, D) feature field
    and project it.  Returns (f_a_projected, basis) — or (f_a, None) when
    `k` is falsy.  Single entry point for every synthesis driver so the
    fit policy cannot diverge between them."""
    if not k:
        return f_a, None
    basis = pca_basis(f_a.reshape(-1, f_a.shape[-1]), k)
    return project(f_a, basis), basis
