"""Luminance remapping (SURVEY.md §2 C2; Hertzmann §3.4).

Affine-matches the A/A' luminance statistics to B's before any matching:

    Y_A <- (sigma_B / sigma_A) * (Y_A - mu_A) + mu_B

Both A and A' are remapped with *A's* statistics (they must move together so
the analogy A:A' is preserved).  Pure `jax.numpy` reductions — runs on device
as part of preprocessing [BASELINE.json north star: "luminance remapping
moves to jax.scipy"].
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def luminance_stats(y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean and standard deviation of a luminance image."""
    mu = jnp.mean(y)
    sigma = jnp.std(y)
    return mu, sigma


def remap_luminance(
    y_a: jnp.ndarray,
    y_ap: jnp.ndarray,
    y_b: jnp.ndarray,
    eps: float = 1e-6,
    b_stats: Tuple[jnp.ndarray, jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Remap (Y_A, Y_A') to B's luminance statistics using A's statistics.

    Returns the remapped (Y_A, Y_A').  `eps` guards flat images
    (sigma_A ~ 0), where the scale collapses to 0 instead of exploding.
    `b_stats` overrides B's (mu, sigma) — the batched runner passes the
    whole frame stack's statistics so microbatched chunks share one
    style normalization.
    """
    mu_a, sigma_a = luminance_stats(y_a)
    mu_b, sigma_b = b_stats if b_stats is not None else luminance_stats(y_b)
    scale = sigma_b / jnp.maximum(sigma_a, eps)
    return (
        scale * (y_a - mu_a) + mu_b,
        scale * (y_ap - mu_a) + mu_b,
    )
