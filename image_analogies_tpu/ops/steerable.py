"""Steerable-filter feature bank (SURVEY.md §2 C4; Hertzmann §3.1).

Oriented first-derivative-of-Gaussian responses appended to the feature
vectors for config 4 [BASELINE.json]. One batched
`jax.lax.conv_general_dilated` per level computes all orientations at once —
the filters are expressed as one OIHW weight tensor so XLA maps the whole
bank onto a single conv (MXU-friendly) instead of n_orient separate passes.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _dog_bank(n_orientations: int, size: int = 5, sigma: float = 1.0) -> np.ndarray:
    """(n_orient, 1, size, size) bank of oriented derivative-of-Gaussian
    filters G_theta = cos(theta) Gx + sin(theta) Gy (steerable basis)."""
    r = size // 2
    y, x = np.mgrid[-r : r + 1, -r : r + 1].astype(np.float32)
    g = np.exp(-(x**2 + y**2) / (2 * sigma**2))
    gx = -x / sigma**2 * g
    gy = -y / sigma**2 * g
    # Normalize the basis so responses are O(1) on [0,1] images.
    norm = np.sqrt((gx**2).sum())
    gx, gy = gx / norm, gy / norm
    filters = []
    for i in range(n_orientations):
        theta = np.pi * i / n_orientations
        filters.append(np.cos(theta) * gx + np.sin(theta) * gy)
    return np.stack(filters)[:, None]  # OIHW with I=1


def steerable_responses(
    y: jnp.ndarray, n_orientations: int = 4, size: int = 5
) -> jnp.ndarray:
    """(H, W) luminance -> (H, W, n_orientations) oriented responses."""
    bank = jnp.asarray(_dog_bank(n_orientations, size=size))
    r = size // 2
    x = jnp.pad(y, ((r, r), (r, r)), mode="edge")[jnp.newaxis, jnp.newaxis]
    dn = jax.lax.conv_dimension_numbers(x.shape, bank.shape, ("NCHW", "OIHW", "NCHW"))
    out = jax.lax.conv_general_dilated(
        x, bank, (1, 1), "VALID", dimension_numbers=dn
    )
    return jnp.moveaxis(out[0], 0, -1)
