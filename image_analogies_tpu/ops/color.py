"""Color-space ops: RGB <-> YIQ as 3x3 matmuls (SURVEY.md §2 C1).

The reference does color conversion on CPU with NumPy/PIL [RECONSTRUCTED];
here it is a jitted matmul so it fuses into device-side preprocessing and the
image never round-trips to host between load and synthesis.

All images are float arrays in [0, 1], shape (H, W, 3) for color or (H, W)
for single-channel luminance.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# NTSC YIQ transform (the color space Hertzmann §3.4 prescribes for
# luminance-only matching: Y carries luminance, I/Q carry chroma).  The
# inverse is the exact matrix inverse, not the truncated textbook
# constants, so the round trip is lossless to float32 precision.
# Kept as host numpy at module scope: materializing jnp arrays at import
# time would initialize the device backend for every importer, including
# host-only code paths (and blocks when another process holds the TPU).
_RGB2YIQ = np.array(
    [
        [0.299, 0.587, 0.114],
        [0.595716, -0.274453, -0.321263],
        [0.211456, -0.522591, 0.311135],
    ],
    dtype=np.float64,
)
_YIQ2RGB = np.linalg.inv(_RGB2YIQ)


def rgb_to_yiq(rgb: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) RGB in [0,1] -> (..., 3) YIQ."""
    m = jnp.asarray(_RGB2YIQ, dtype=jnp.float32)
    return jnp.einsum("...c,dc->...d", rgb, m, precision="highest")


def yiq_to_rgb(yiq: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) YIQ -> (..., 3) RGB (not clipped)."""
    m = jnp.asarray(_YIQ2RGB, dtype=jnp.float32)
    return jnp.einsum("...c,dc->...d", yiq, m, precision="highest")


def luminance(img: jnp.ndarray) -> jnp.ndarray:
    """Y channel of an (H, W, 3) RGB image, or the image itself if 2D."""
    if img.ndim == 2:
        return img
    y_row = jnp.asarray(_RGB2YIQ[0], dtype=jnp.float32)
    return jnp.einsum("...c,c->...", img, y_row, precision="highest")
