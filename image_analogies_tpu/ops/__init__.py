"""Device-side ops: color, remapping, pyramids, steerable features,
feature assembly (SURVEY.md §2 C1-C5)."""

from .color import rgb_to_yiq, yiq_to_rgb, luminance
from .remap import remap_luminance, luminance_stats
from .pyramid import gaussian_blur, downsample, upsample, build_pyramid
from .steerable import steerable_responses
from .features import extract_patches, assemble_features, feature_weights
from .pca import pca_basis, project

__all__ = [
    "pca_basis",
    "project",
    "rgb_to_yiq",
    "yiq_to_rgb",
    "luminance",
    "remap_luminance",
    "luminance_stats",
    "gaussian_blur",
    "downsample",
    "upsample",
    "build_pyramid",
    "steerable_responses",
    "extract_patches",
    "assemble_features",
    "feature_weights",
]
