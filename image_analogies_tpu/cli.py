"""CLI (SURVEY.md §2 C13) — `python -m image_analogies_tpu.cli`.

Subcommands:
  synth     A + A' + B -> B'   (the reference's main entry point)
  batch     A + A' + frame dir -> stylized frames (config 5)
  examples  generate the procedural example assets (C14)
  report    merge a traced run's host spans + device trace into
            report.json (telemetry/report.py)

Flags mirror the reference's knob surface (levels, patch size, kappa,
matcher) plus `--device {cpu,tpu}` to pick the JAX backend [north star].
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _add_common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--log-level", default=None,
        choices=["debug", "info", "warning", "error", "critical"],
        help="attach a stderr handler to the image_analogies_tpu "
        "logger at this level (default: leave logging unconfigured)",
    )


def _add_synth_flags(p: argparse.ArgumentParser) -> None:
    _add_common_flags(p)
    p.add_argument("--levels", type=int, default=5)
    p.add_argument("--patch-size", type=int, default=5)
    p.add_argument("--coarse-patch-size", type=int, default=3)
    p.add_argument("--kappa", type=float, default=0.0)
    # choices: a matcher typo must fail at parse time, before the
    # (possibly large) image loads.
    p.add_argument(
        "--matcher", default="patchmatch",
        choices=("brute", "patchmatch", "ann"),
        help="brute | patchmatch | ann (native C++ kd-tree, CPU backend)",
    )
    p.add_argument(
        "--ann-eps", type=float, default=0.5,
        help="ann matcher approximation factor; 0 = exact tree search",
    )
    p.add_argument(
        "--color-mode", default="luminance", choices=["luminance", "rgb"]
    )
    p.add_argument("--steerable", action="store_true")
    p.add_argument("--no-luminance-remap", action="store_true")
    p.add_argument("--em-iters", type=int, default=3)
    p.add_argument("--pm-iters", type=int, default=6)
    p.add_argument(
        "--pca-dims", type=int, default=None,
        help="project features to this many principal components before "
        "matching (Hertzmann-style PCA; default off)",
    )
    p.add_argument(
        "--cand-dtype", default=None, choices=("bf16", "int8"),
        help="candidate-table compression mode (round 11): bf16 = the "
        "uncompressed historical tables (default), int8 = quantized "
        "sweep planes + per-patch-scaled polish rows, dequantized next "
        "to the distance math.  Sets the process-wide kernel mode "
        "(IA_CAND_DTYPE); quality pinned by the exact-NN oracle gates",
    )
    p.add_argument(
        "--pca-prune", default=None, metavar="K:M",
        help="PCA coarse-distance pre-prune (round 11): project "
        "candidates to K dims through the level's pca_basis and "
        "exact-fetch only the top M of each tile's shared candidates "
        "per sweep (e.g. '16:8'); 'off' disables.  Sets the "
        "process-wide kernel mode (IA_CAND_PRUNE)",
    )
    p.add_argument(
        "--tau", type=float, default=0.0,
        help="temporal-coherence weight (video subsystem): warm frames "
        "penalize match candidates by tau x normalized squared "
        "divergence from the previous frame's converged mapping; 0 "
        "keeps the historic graphs bit-identical (the kappa of the "
        "time axis)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--feature-bytes-budget", type=int, default=None,
        help="per-level f32 feature-table HBM budget in bytes; levels "
        "above it take the lean path (bf16 chunked tables, plane-pair "
        "field).  Default: config default (2 GiB)",
    )
    p.add_argument(
        "--brute-lean-bytes", type=int, default=None,
        help="f32 feature-table bytes above which BRUTE levels run the "
        "lean-brute exact oracle (bf16 tables, chunked eager "
        "executions).  Default: config default (10 GiB)",
    )
    p.add_argument("--device", default=None, choices=["cpu", "tpu"])
    p.add_argument(
        "--pallas-mode",
        default="auto",
        choices=["auto", "off", "interpret"],
        help="Pallas kernel selection: auto (compiled on TPU, XLA twin "
        "elsewhere) | off (pure XLA) | interpret (debug)",
    )
    p.add_argument("--save-level-artifacts", default=None)
    p.add_argument(
        "--resume-from", default=None, metavar="DIR",
        help="resume mid-pyramid from a --save-level-artifacts directory",
    )
    p.add_argument(
        "--strict-resume", action="store_true",
        help="error out (naming the directory and every rejection, "
        "fingerprint mismatches included) when --resume-from holds no "
        "usable checkpoint, instead of warning and recomputing from "
        "scratch",
    )
    p.add_argument(
        "--supervise", action="store_true",
        help="run under the supervised execution layer "
        "(runtime/supervisor.py): per-level watchdog deadlines from "
        "the cost model, retry-with-resume from the per-level "
        "checkpoints (save-level-artifacts is forced on), a graceful-"
        "degradation ladder over the engine's fallback seams, and a "
        "validated flight dump + exit != 0 when it finally gives up.  "
        "Implies instrumentation (one host sync per level)",
    )
    p.add_argument(
        "--max-retries", type=int, default=2, metavar="N",
        help="supervised mode: retries per degradation-ladder rung "
        "before stepping down (default 2)",
    )
    p.add_argument(
        "--watchdog-slack", type=float, default=None, metavar="X",
        help="supervised mode: level deadline = modeled cost x "
        "calibrated rate x this slack factor (default 4.0)",
    )
    p.add_argument(
        "--watchdog-static-deadline", type=float, default=None,
        metavar="SECONDS",
        help="supervised mode: conservative per-level bound applied "
        "before the cost model is calibrated (default 900)",
    )
    p.add_argument("--progress", default=None, help="JSONL progress path")
    trace = p.add_mutually_exclusive_group()
    trace.add_argument(
        "--trace-dir", dest="trace_dir", default=None, metavar="DIR",
        help="telemetry directory: a jax.profiler (Perfetto/XProf) "
        "device trace of the synthesis plus the run's host span tree "
        "(host_spans.json), metrics exposition (metrics.json/.prom), "
        "and flight-recorder dump (flight.json — flushed BEFORE the "
        "process dies on SIGTERM/SIGINT, so killed runs leave a "
        "post-mortem) "
        "— self-contained input for the `report` subcommand.  Enables "
        "per-level host spans (one sync per level, like --progress)",
    )
    trace.add_argument(
        "--profile", dest="profile", default=None, metavar="DIR",
        help="device-trace-only directory (the historic flag): no "
        "telemetry artifacts are written, and the flag itself adds no "
        "per-level host syncs (the run is only instrumented if "
        "--progress also asks for it).  Use --trace-dir for the full "
        "telemetry layout",
    )
    p.add_argument(
        "--health", action="store_true",
        help="evaluate the run sentinel at the end of the run "
        "(expected-vs-observed model checks + span/energy invariants, "
        "telemetry/sentinel.py), print the verdict, and write "
        "health.json beside the other --trace-dir artifacts.  Implies "
        "instrumentation (one host sync per level)",
    )
    p.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve live telemetry mid-run on 127.0.0.1:PORT "
        "(0 = ephemeral port): /metrics (Prometheus exposition), "
        "/healthz (incremental sentinel verdict; HTTP 503 when "
        "violated), /progress (open span stack + model-calibrated "
        "ETA).  The bound endpoint is announced in "
        "<trace-dir>/live.json when --trace-dir is set.  Implies "
        "instrumentation (one host sync per level)",
    )


def _config_from(args) -> "SynthConfig":
    from .config import SynthConfig

    budget = (
        {}
        if args.feature_bytes_budget is None
        else {"feature_bytes_budget": args.feature_bytes_budget}
    )
    if args.brute_lean_bytes is not None:
        budget["brute_lean_bytes"] = args.brute_lean_bytes
    return SynthConfig(
        **budget,
        levels=args.levels,
        patch_size=args.patch_size,
        coarse_patch_size=args.coarse_patch_size,
        kappa=args.kappa,
        matcher=args.matcher,
        color_mode=args.color_mode,
        steerable=args.steerable,
        luminance_remap=not args.no_luminance_remap,
        em_iters=args.em_iters,
        pm_iters=args.pm_iters,
        pca_dims=args.pca_dims,
        ann_eps=args.ann_eps,
        tau=args.tau,
        seed=args.seed,
        pallas_mode=args.pallas_mode,
        save_level_artifacts=args.save_level_artifacts,
    )


def _emit_health(tracer, trace_dir, context: str) -> None:
    """Run the sentinel over the finished run's tracer/registry, print
    the verdict, and (when a telemetry dir exists) write health.json
    beside the other artifacts — the synth/batch `--health` epilogue."""
    from .telemetry.sentinel import (
        HEALTH_FILE,
        evaluate_health,
        render_health,
        write_health,
    )

    health = evaluate_health(
        spans=tracer.to_dict() if tracer.enabled else None,
        metrics=(
            tracer.registry.to_dict()
            if tracer.registry is not None else None
        ),
        context=context,
    )
    if trace_dir:
        write_health(health, os.path.join(trace_dir, HEALTH_FILE))
    # A violated verdict preserves the flight recorder's event window
    # alongside the verdict (telemetry/flight.py): the dump is the
    # "what was happening" half of the post-mortem.  The session has
    # already torn down by this point (outputs save before the health
    # epilogue), so the recorder is reached through the handle the
    # session left on the tracer, not the installed-recorder hook.
    recorder = getattr(tracer, "flight_recorder", None)
    if recorder is not None and health["verdict"] == "violated":
        recorder.flush("violation")
    print(render_health(health))


def _apply_cand_compression(args) -> None:
    """Install the --cand-dtype/--pca-prune knobs process-wide (they
    are kernel module globals, not config fields — the _POLISH_MODE
    rationale) before any level function compiles.  A malformed prune
    spec fails at startup, before the (possibly large) images load."""
    if args.cand_dtype is None and args.pca_prune is None:
        return
    from .kernels.patchmatch_tile import set_cand_compression

    try:
        set_cand_compression(args.cand_dtype, args.pca_prune)
    except ValueError as e:
        raise SystemExit(f"--cand-dtype/--pca-prune: {e}")


def _select_device(device: str | None) -> None:
    from .utils.cache import enable_compilation_cache

    enable_compilation_cache()
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    # 'tpu' / None: keep the default platform (TPU when present).


def cmd_synth(args) -> int:
    _apply_cand_compression(args)
    _select_device(args.device)
    from .models.analogy import create_image_analogy
    from .utils.io import load_image, save_image
    from .utils.profiling import telemetry_session
    from .utils.progress import ProgressWriter

    progress = ProgressWriter(args.progress)
    a = load_image(args.a)
    ap = load_image(args.ap)
    b = load_image(args.b)
    cfg = _config_from(args)
    # Start the host->device input copies ASYNC before any tracing
    # begins: jnp.asarray dispatches the transfer and returns without
    # waiting, so the copy (the dominant first-run cost on a tunnelled
    # backend — 2.37 s vs 0.574 s of synthesis at the 1024^2 headline,
    # VERDICT r5 item 8) proceeds while the prologue/level functions
    # trace and compile on the host; the runner's own jnp.asarray then
    # re-sees device arrays and moves nothing.  Round 7 landed the
    # overlap; its e2e delta could not be measured on the tunnel this
    # round (no TPU backend reachable — LAYOUT_r07.json records the
    # attempt), so the measured answer to "does the tunnel serialize
    # anyway?" is still owed by the next hardware session.
    import jax.numpy as jnp

    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    t0 = time.perf_counter()

    # Per-level spans cost one host sync per level; only pay when the
    # user asked for a progress stream or a telemetry dir (north-star:
    # minimal host syncs).  The historic --profile keeps its original
    # meaning — a device trace of the UN-instrumented run — so it does
    # NOT enable spans; --trace-dir (the telemetry layout) does.
    instrument = bool(
        args.progress or args.trace_dir or args.health
        or args.metrics_port is not None or args.supervise
    )
    if args.bands is not None and args.bands > 1 and not args.spatial:
        raise SystemExit(
            "--bands requires --spatial (it names the A-band axis of "
            "the 2-D bands x slabs mesh); for A-side banding alone use "
            "--sharded-a"
        )
    cfg, ckpt_dir, ckpt_ephemeral = _force_ckpt_dir(args, cfg)
    # Telemetry artifacts go ONLY to --trace-dir; a --profile dir is
    # device-trace-only (its documented contract).
    with telemetry_session(
        args.trace_dir or args.profile, sink=progress,
        enabled=instrument, artifact_dir=args.trace_dir,
        metrics_port=args.metrics_port,
    ) as tracer:
        # Disabled tracer: events still reach the JSONL/log stream
        # directly through the writer (the historic behavior).
        events = tracer if tracer.enabled else progress
        events.emit("start", shape=list(b.shape), matcher=cfg.matcher)
        level_progress = tracer if instrument else None
        runner_state = {
            "mode": (
                "spatial" if args.spatial
                else "sharded_a" if args.sharded_a else "single"
            )
        }
        strict_state = {"first": True}

        def _dispatch(resume_from):
            mode = runner_state["mode"]
            if mode == "spatial":
                import jax

                from .parallel.mesh import make_mesh
                from .parallel.plan2d import override_plan, plan_mesh_shape
                from .parallel.spatial import synthesize_spatial

                n_dev = args.n_devices or len(jax.devices())
                if args.bands is not None:
                    # Explicit --bands/--mesh-rows: the user decided;
                    # the run plan records the override.
                    if n_dev % args.bands:
                        raise SystemExit(
                            f"--bands {args.bands} must divide the "
                            f"device count ({n_dev})"
                        )
                    plan = override_plan(
                        args.bands, n_dev // args.bands
                    )
                else:
                    # Default: the mesh-shape planner picks the
                    # (bands, slabs) factorization from the modeled
                    # collective + candidate traffic (de-leaned
                    # levels penalized; parallel/plan2d.py); decision
                    # and rejected alternatives land on the run plan.
                    plan = plan_mesh_shape(
                        n_dev, a.shape[:2], b.shape[:2], cfg
                    )
                if plan.n_bands > 1:
                    mesh = make_mesh(
                        n_dev, axis_names=("bands", "slabs"),
                        shape=(plan.n_bands, plan.n_slabs),
                    )
                else:
                    mesh = make_mesh(args.n_devices)
                return synthesize_spatial(
                    a, ap, b, cfg, mesh,
                    progress=level_progress,
                    resume_from=resume_from,
                    resume_strict=_resume_strict_for(args, resume_from, strict_state),
                    mesh_plan=plan.as_attrs(),
                )
            if mode == "sharded_a":
                from .parallel.mesh import make_mesh
                from .parallel.sharded_a import synthesize_sharded_a

                return synthesize_sharded_a(
                    a, ap, b, cfg,
                    make_mesh(args.n_devices, axis_names=("bands",)),
                    progress=level_progress,
                    resume_from=resume_from,
                    resume_strict=_resume_strict_for(args, resume_from, strict_state),
                )
            return create_image_analogy(
                a, ap, b, cfg, progress=level_progress,
                resume_from=resume_from,
                resume_strict=_resume_strict_for(args, resume_from, strict_state),
            )

        if args.supervise:
            bp = _run_supervised(
                args, _dispatch, runner_state, ckpt_dir, tracer,
                ckpt_ephemeral,
            )
        else:
            try:
                bp = _dispatch(args.resume_from)
            except _resume_error_type() as e:
                raise SystemExit(str(e))
        # Materialize on the host before stopping the clock: under the
        # tunnelled axon platform block_until_ready can return before
        # remote execution finishes, which would report dispatch time.
        import numpy as np

        bp = np.asarray(bp)
        events.emit("done", wall_s=round(time.perf_counter() - t0, 3))
    save_image(args.out, bp)
    print(f"wrote {args.out} ({time.perf_counter() - t0:.2f}s)")
    # Sentinel epilogue runs AFTER the output is saved: a verdict/IO
    # failure must never discard a finished synthesis.
    if args.health:
        _emit_health(tracer, args.trace_dir, "synth")
    return 0


def _force_ckpt_dir(args, cfg):
    """Supervised mode needs checkpoints to retry from: force
    save_level_artifacts on (the knob is stripped from jit cache keys,
    so the graphs are unchanged — _strip_noncompute).  Shared by
    cmd_synth and cmd_batch; returns (cfg, ckpt_dir, ephemeral) with
    ckpt_dir None when not supervising and `ephemeral` True when the
    dir is a run-private tempdir to remove after success."""
    if not args.supervise:
        return cfg, None, False
    import dataclasses
    import tempfile

    ephemeral = False
    ckpt_dir = cfg.save_level_artifacts
    if not ckpt_dir and args.trace_dir:
        ckpt_dir = os.path.join(args.trace_dir, "supervisor_ckpt")
    elif not ckpt_dir:
        # Nobody asked to keep these checkpoints: clean them up after
        # a successful supervised run (a give-up keeps them — they are
        # the manual-resume half of the post-mortem).  At the 4096^2
        # scales each run's per-level state is multi-GB; leaking one
        # temp dir per run would fill /tmp.
        ckpt_dir = tempfile.mkdtemp(prefix="ia_supervisor_ckpt_")
        ephemeral = True
    return dataclasses.replace(
        cfg, save_level_artifacts=ckpt_dir
    ), ckpt_dir, ephemeral


def _resume_error_type():
    """Lazy ResumeError accessor (models.analogy imports jax; the CLI
    front matter must stay import-light)."""
    from .models.analogy import ResumeError

    return ResumeError


def _resume_strict_for(args, resume_from, state) -> bool:
    """--strict-resume binds to the USER's resume source on the FIRST
    attempt only (`state` is a per-command {"first": True} consumed
    here): a supervisor-internal retry must stay lenient even when the
    forced checkpoint dir string-equals the user's --resume-from (the
    natural continuation invocation `--resume-from D
    --save-level-artifacts D`), because the retry's artifacts may
    legitimately be partial or — under an injected truncate — corrupt;
    the loader's skip-and-warn is exactly the healing path."""
    first = state.pop("first", False)
    return bool(
        first
        and args.strict_resume
        and resume_from is not None
        and resume_from == args.resume_from
    )


def _run_supervised(args, dispatch, runner_state, ckpt_dir, tracer,
                    ckpt_ephemeral=False):
    """Shared synth/batch supervised entry: build the ladder (the
    default process-seam rungs plus a mesh->single-device rung when a
    parallel runner is active), run under `runtime.supervisor`, and
    turn a give-up into a clean nonzero exit — the flight dump has
    already been flushed by then."""
    from .runtime.supervisor import (
        STATIC_DEADLINE_S,
        WATCHDOG_SLACK,
        Rung,
        SupervisorGaveUp,
        default_ladder,
        supervise,
    )

    ladder = default_ladder()
    if runner_state["mode"] != "single":
        ladder.append(Rung(
            "mesh_to_single_device", "mesh", "single",
            applies=lambda: runner_state["mode"] != "single",
            apply=lambda: runner_state.update(mode="single"),
            # The parallel runners are pinned bit-identical to
            # single-device synthesis (spatial halo geometry; sharded-A
            # at lean levels), so stepping off the mesh trades only
            # wall clock.
            bit_safe=True,
        ))
    try:
        result = supervise(
            dispatch,
            ckpt_dir=ckpt_dir,
            tracer=tracer,
            initial_resume=args.resume_from,
            max_retries=args.max_retries,
            watchdog_slack=(
                args.watchdog_slack if args.watchdog_slack is not None
                else WATCHDOG_SLACK
            ),
            static_deadline_s=(
                args.watchdog_static_deadline
                if args.watchdog_static_deadline is not None
                else STATIC_DEADLINE_S
            ),
            ladder=ladder,
        )
    except SupervisorGaveUp as e:
        # The checkpoints stay (even an ephemeral dir): they are the
        # manual-resume half of the post-mortem.
        raise SystemExit(f"supervised synthesis gave up: {e}")
    except _resume_error_type() as e:
        # Strict-resume config error: the supervisor re-raises it
        # instead of retrying (a retry would silently recompute from
        # scratch — the outcome --strict-resume forbids).
        raise SystemExit(str(e))
    if ckpt_ephemeral:
        import shutil

        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return result


def cmd_batch(args) -> int:
    _apply_cand_compression(args)
    _select_device(args.device)
    import numpy as np

    from .parallel.batch import ingest_frame_dir, synthesize_batch
    from .parallel.mesh import make_mesh
    from .utils.io import load_image, save_image
    from .utils.profiling import telemetry_session
    from .utils.progress import ProgressWriter

    progress = ProgressWriter(args.progress)
    a = load_image(args.a)
    ap = load_image(args.ap)
    # Per-frame fault isolation (round 12): an unreadable/undecodable
    # frame is skipped and recorded instead of aborting the batch;
    # --strict-frames restores abort-on-first-error.
    frames, names, frame_failures = ingest_frame_dir(
        args.frames, strict=args.strict_frames
    )
    cfg = _config_from(args)
    mesh = make_mesh(args.n_devices)
    t0 = time.perf_counter()

    # --profile keeps its historic un-instrumented-trace meaning (see
    # cmd_synth); only --progress / --trace-dir / --health /
    # --metrics-port / --supervise enable spans, and telemetry
    # artifacts land only in --trace-dir.
    instrument = bool(
        args.progress or args.trace_dir or args.health
        or args.metrics_port is not None or args.supervise
    )
    cfg, ckpt_dir, ckpt_ephemeral = _force_ckpt_dir(args, cfg)
    with telemetry_session(
        args.trace_dir or args.profile, sink=progress,
        enabled=instrument, artifact_dir=args.trace_dir,
        metrics_port=args.metrics_port,
    ) as tracer:
        if frame_failures and tracer.enabled:
            from .telemetry.metrics import get_registry

            c = get_registry().counter(
                "ia_frames_failed_total",
                "batch-ingest frames skipped for per-frame faults "
                "(unreadable/undecodable; --strict-frames aborts "
                "instead)",
            )
            for rec in frame_failures:
                c.inc(labels={
                    "reason": rec["reason"].split(":", 1)[0],
                })
            tracer.emit(
                "frame_failures",
                n=len(frame_failures),
                frames=[rec["path"] for rec in frame_failures],
            )
        runner_state = {"mode": "mesh" if mesh.devices.size > 1 else "single"}
        strict_state = {"first": True}

        def _dispatch(resume_from):
            run_mesh = (
                mesh if runner_state["mode"] == "mesh" else make_mesh(1)
            )
            return synthesize_batch(
                a, ap, frames, cfg, run_mesh,
                progress=tracer if instrument else None,
                frames_per_step=args.frames_per_step,
                resume_from=resume_from,
                resume_strict=_resume_strict_for(args, resume_from, strict_state),
            )

        if args.supervise:
            bps = np.asarray(
                _run_supervised(
                    args, _dispatch, runner_state, ckpt_dir, tracer,
                    ckpt_ephemeral,
                )
            )
        else:
            try:
                bps = np.asarray(_dispatch(args.resume_from))
            except _resume_error_type() as e:
                raise SystemExit(str(e))
    os.makedirs(args.out, exist_ok=True)
    for name, bp in zip(names, bps):
        save_image(os.path.join(args.out, name), bp)
    print(
        f"wrote {len(names)} frames to {args.out} "
        f"({time.perf_counter() - t0:.2f}s on {mesh.devices.size} devices)"
    )
    # Batch epilogue: the per-frame fault ledger (path + reason), so a
    # partially-ingested batch is explicit in the run's own output.
    for rec in frame_failures:
        print(f"frame FAILED (skipped): {rec['path']} — {rec['reason']}")
    if frame_failures:
        print(
            f"{len(frame_failures)} frame(s) skipped; rerun with "
            "--strict-frames to abort on ingest errors instead"
        )
    # Sentinel epilogue after the frames are on disk (see cmd_synth).
    if args.health:
        _emit_health(tracer, args.trace_dir, "batch")
    return 0


def cmd_video(args) -> int:
    """Frame-SEQUENCE stylization with temporal warm-starting
    (round 14, video/): NNF warm-start between consecutive frames,
    tau-weighted temporal coherence, and delta-cost scheduling — same
    ingest, telemetry, --health, and --supervise surfaces as `batch`
    (frame-granular resume rides the per-frame `frames_{t:05d}`
    checkpoint subdirectories both modes share)."""
    _apply_cand_compression(args)
    _select_device(args.device)
    import numpy as np

    from .parallel.batch import ingest_frame_dir
    from .parallel.mesh import make_mesh
    from .utils.io import load_image, save_image
    from .utils.profiling import telemetry_session
    from .utils.progress import ProgressWriter
    from .video import set_warm_mode, synthesize_video

    if args.warm:
        set_warm_mode(args.warm)
    progress = ProgressWriter(args.progress)
    a = load_image(args.a)
    ap = load_image(args.ap)
    frames, names, frame_failures = ingest_frame_dir(
        args.frames, strict=args.strict_frames
    )
    cfg = _config_from(args)
    # Default mesh: the warm path loops single frames, so extra devices
    # would only carry padding ballast (outputs are mesh-invariant);
    # --n-devices still forces a mesh for the warm-off batch dispatch.
    mesh = make_mesh(args.n_devices) if args.n_devices else None
    t0 = time.perf_counter()

    instrument = bool(
        args.progress or args.trace_dir or args.health
        or args.metrics_port is not None or args.supervise
    )
    cfg, ckpt_dir, ckpt_ephemeral = _force_ckpt_dir(args, cfg)
    with telemetry_session(
        args.trace_dir or args.profile, sink=progress,
        enabled=instrument, artifact_dir=args.trace_dir,
        metrics_port=args.metrics_port,
    ) as tracer:
        if frame_failures and tracer.enabled:
            from .telemetry.metrics import get_registry

            c = get_registry().counter(
                "ia_frames_failed_total",
                "batch-ingest frames skipped for per-frame faults "
                "(unreadable/undecodable; --strict-frames aborts "
                "instead)",
            )
            for rec in frame_failures:
                c.inc(labels={
                    "reason": rec["reason"].split(":", 1)[0],
                })
            tracer.emit(
                "frame_failures",
                n=len(frame_failures),
                frames=[rec["path"] for rec in frame_failures],
            )
        runner_state = {
            "mode": (
                "mesh"
                if mesh is not None and mesh.devices.size > 1
                else "single"
            )
        }
        strict_state = {"first": True}

        def _dispatch(resume_from):
            run_mesh = (
                mesh if runner_state["mode"] == "mesh"
                else (make_mesh(1) if mesh is not None else None)
            )
            return synthesize_video(
                a, ap, frames, cfg, mesh=run_mesh,
                progress=tracer if instrument else None,
                resume_from=resume_from,
                resume_strict=_resume_strict_for(
                    args, resume_from, strict_state
                ),
            )

        if args.supervise:
            bps = np.asarray(
                _run_supervised(
                    args, _dispatch, runner_state, ckpt_dir, tracer,
                    ckpt_ephemeral,
                )
            )
        else:
            try:
                bps = np.asarray(_dispatch(args.resume_from))
            except _resume_error_type() as e:
                raise SystemExit(str(e))
    os.makedirs(args.out, exist_ok=True)
    for name, bp in zip(names, bps):
        save_image(os.path.join(args.out, name), bp)
    print(
        f"wrote {len(names)} frames to {args.out} "
        f"({time.perf_counter() - t0:.2f}s, warm={args.warm or 'on'})"
    )
    for rec in frame_failures:
        print(f"frame FAILED (skipped): {rec['path']} — {rec['reason']}")
    if frame_failures:
        print(
            f"{len(frame_failures)} frame(s) skipped; rerun with "
            "--strict-frames to abort on ingest errors instead"
        )
    if args.health:
        _emit_health(tracer, args.trace_dir, "video")
    return 0


def cmd_serve(args) -> int:
    """Synthesis-as-a-service (round 13, serving/): a long-lived
    daemon over one style pair, serving POST /synthesize with a
    compiled-executable cache, continuous batching, and admission
    control — on the same HTTP server and telemetry surface
    (/metrics, /healthz, live.json rendezvous) every traced run uses."""
    _apply_cand_compression(args)
    _select_device(args.device)
    import dataclasses

    from .parallel.mesh import make_mesh
    from .serving.daemon import SynthDaemon
    from .serving.excache import load_warmup_manifest
    from .utils.io import load_image
    from .utils.profiling import telemetry_session

    a = load_image(args.a)
    ap = load_image(args.ap)
    cfg = _config_from(args)
    if cfg.save_level_artifacts:
        # The daemon owns per-dispatch checkpoint dirs (retry-with-
        # resume inside one dispatch); a shared user path would make
        # concurrent dispatches clobber each other's state.
        print(
            "serve: ignoring --save-level-artifacts (checkpoints are "
            "per-dispatch-managed)", file=sys.stderr,
        )
        cfg = dataclasses.replace(cfg, save_level_artifacts=None)
    warm_entries = None
    if args.warmup:
        try:
            warm_entries = load_warmup_manifest(args.warmup)
        except (OSError, ValueError) as e:
            raise SystemExit(f"serve: --warmup: {e}")
    # --takeover DIR is --state-dir DIR made explicit: both restart
    # paths restore sessions, merge observed warmup, and replay the
    # journal's pending entries (a restart IS a takeover of your own
    # state dir).
    state_dir = args.takeover or args.state_dir
    mesh = make_mesh(args.n_devices)
    # Daemon-lifetime telemetry session: trace_dir=None (no device
    # trace over an unbounded lifetime), artifacts + flight recorder
    # into --trace-dir.  SIGTERM flushes flight.json then re-delivers
    # (telemetry/flight.py), so a killed daemon leaves a post-mortem.
    anomaly_config = None
    if args.baseline:
        from .telemetry.anomaly import (
            AnomalyConfig,
            baseline_from_record,
        )

        baseline_p99 = baseline_from_record(args.baseline)
        if baseline_p99 is None:
            print(
                f"serve: --baseline {args.baseline}: no "
                "pipeline.p99_warm_ms — latency watch will report "
                "no_data", file=sys.stderr,
            )
        anomaly_config = AnomalyConfig(baseline_p99_ms=baseline_p99)
    lattice_plan = None
    if getattr(args, "lattice", None):
        from .serving.lattice import parse_lattice_spec, plan_lattice

        try:
            lattice_cfg = parse_lattice_spec(args.lattice)
        except ValueError as e:
            raise SystemExit(f"serve: {e}")
        if lattice_cfg is not None:
            lattice_plan = plan_lattice(lattice_cfg)
            lat = lattice_plan.lattice
            print(
                f"lattice[{lattice_plan.source}]: rungs "
                f"{list(lat.rungs)} x channels "
                f"{list(lat.config.channels)} = {lat.size} buckets "
                f"(growth {lat.growth:g}, "
                f"{len(lattice_plan.rejected)} candidate(s) rejected)",
                flush=True,
            )
    with telemetry_session(
        None, enabled=True, artifact_dir=args.trace_dir,
        metrics_port=None, flight_capacity=args.flight_ring,
    ) as tracer:
        daemon = SynthDaemon(
            a, ap, cfg,
            registry=tracer.registry,
            tracer=tracer,
            mesh=mesh,
            host=args.host,
            port=args.port,
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            max_queue_depth=args.max_queue_depth,
            cache_capacity=args.cache_capacity,
            max_retries=args.max_retries,
            max_sessions=args.max_sessions,
            flight=getattr(tracer, "flight_recorder", None),
            # Access log beside the other trace artifacts, where the
            # `ia-synth trace` CLI looks (daemon default: its private
            # work dir, which dies with it).
            access_log_path=(
                os.path.join(args.trace_dir, "access.jsonl")
                if args.trace_dir else None
            ),
            state_dir=state_dir,
            warm_dir=args.warm_dir,
            drain_deadline_s=args.drain_deadline_s,
            dispatch_deadline_s=args.dispatch_deadline_s,
            pipeline_window=args.pipeline_window,
            warmup_workers=args.warmup_workers,
            obs_interval_s=args.obs_interval_s,
            obs_capacity=args.obs_capacity,
            anomaly_config=anomaly_config,
            lattice=lattice_plan,
            archive_dir=args.archive_dir,
            archive_interval_s=args.archive_interval_s,
        )
        try:
            daemon.start()
        except RuntimeError as e:
            # The double-takeover guard: the state dir's lockfile
            # names a pid that is still alive.
            raise SystemExit(f"serve: {e}")
        # Graceful drain on SIGTERM (round 16): override the flight
        # recorder's flush-and-die disposition — in-flight requests
        # and their response writes complete, hand-off state lands in
        # the state dir, the flight dump carries reason=drain, and the
        # main loop below exits 0.
        import signal as _signal

        _signal.signal(
            _signal.SIGTERM,
            lambda signum, frame: daemon.begin_drain(reason="sigterm"),
        )
        try:
            restored = daemon.restore_sessions() if state_dir else 0
            if restored:
                print(f"takeover: restored {restored} session(s)")
            report = daemon.warmup(warm_entries or [])
            for rec in report:
                print(
                    f"warmup: {rec['key']} compiled in "
                    f"{rec['wall_ms']:.0f} ms"
                )
            replayed = daemon.replay_journal() if state_dir else 0
            if replayed:
                print(
                    f"takeover: replaying {replayed} journaled "
                    "request(s)"
                )
            # Rendezvous AFTER warmup: a live.json reader may assume
            # the manifest's shapes are already warm.
            if args.trace_dir:
                daemon.live.announce(args.trace_dir)
            print(
                f"serving on {daemon.url} (POST /synthesize /drain; "
                "GET /serving /slo /journal /obs/window /request "
                "/incidents /archive /metrics /metrics.json /healthz "
                "/progress)",
                flush=True,
            )
            while not daemon.drained.wait(1.0):
                pass
            print("serve: drained, exiting", flush=True)
        except KeyboardInterrupt:
            print("serve: interrupted, draining")
        finally:
            daemon.stop()
    return 0


def cmd_examples(args) -> int:
    import numpy as np

    from .utils import examples as ex
    from .utils.io import save_image

    os.makedirs(args.out, exist_ok=True)
    sets = {
        "texture_by_numbers": ex.texture_by_numbers(args.size),
        "artistic_filter": ex.artistic_filter(args.size),
        "super_resolution": ex.super_resolution(args.size),
        "texture_transfer": ex.texture_transfer(args.size),
    }
    for name, (a, ap, b) in sets.items():
        for tag, img in [("A", a), ("Ap", ap), ("B", b)]:
            save_image(os.path.join(args.out, f"{name}_{tag}.png"), img)
    a, ap, frames = ex.npr_frames(4, args.size)
    save_image(os.path.join(args.out, "npr_A.png"), a)
    save_image(os.path.join(args.out, "npr_Ap.png"), ap)
    for i, f in enumerate(np.asarray(frames)):
        save_image(os.path.join(args.out, f"npr_frame_{i}.png"), f)
    print(f"wrote example assets to {args.out}")
    return 0


def cmd_report(args) -> int:
    """Merge a traced run's host spans with its device trace into
    report.json + a human-readable table (telemetry/report.py)."""
    import json

    from .telemetry.report import (
        REPORT_FILE,
        build_report,
        render_table,
        write_report,
    )

    try:
        report = build_report(
            trace_dir=args.trace_dir, progress_path=args.progress
        )
    except (FileNotFoundError, ValueError) as e:
        raise SystemExit(f"report: {e}")
    out = args.out or os.path.join(args.trace_dir, REPORT_FILE)
    write_report(report, out)
    if args.format == "json":
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    print(f"wrote {out}")
    return 0


def cmd_health(args) -> int:
    """Offline run sentinel: evaluate a traced run's telemetry
    directory (host_spans.json + metrics.json) against the analytic
    models and invariants, write health.json beside them, and exit
    nonzero on a violated verdict (telemetry/sentinel.py)."""
    import json

    from .telemetry.sentinel import (
        HEALTH_FILE,
        health_from_trace_dir,
        render_health,
        write_health,
    )

    try:
        health = health_from_trace_dir(args.trace_dir)
    except (FileNotFoundError, ValueError) as e:
        # ValueError: corrupt metrics.json (unparseable label keys) —
        # a clean message + exit code, not a traceback.
        raise SystemExit(f"health: {e}")
    out = args.out or os.path.join(args.trace_dir, HEALTH_FILE)
    write_health(health, out)
    if args.format == "json":
        print(json.dumps(health, indent=1))
    else:
        print(render_health(health))
    print(f"wrote {out}")
    return 1 if health["verdict"] == "violated" else 0


def _cmd_trace_fleet(args) -> int:
    """`ia-synth trace ID --fleet DISCOVERY`: the round-22 one-command
    cross-process waterfall.  Walks the router's discovery file, asks
    every process `GET /request?id=`, joins router + replica records by
    the forwarded span context, and renders one waterfall with the
    clock-skew bound and the honest unattributed gap."""
    import json

    from .serving.fleettrace import (
        fetch_fleet_trace,
        join_fleet_trace,
        render_fleet_waterfall,
    )
    from .serving.router import load_discovery

    try:
        discovery = load_discovery(args.fleet)
    except (OSError, ValueError) as e:
        raise SystemExit(f"trace: discovery file {args.fleet}: {e}")
    fetched = fetch_fleet_trace(discovery, args.request_id,
                                timeout=10.0)
    router_doc = fetched.get("router") or {}
    router_rec = router_doc.get("request")
    replica_recs = []
    replica_events = {}
    for rep in fetched.get("replicas") or []:
        doc = rep.get("doc") or {}
        rec = doc.get("request")
        if rec is not None:
            replica_recs.append(rec)
            replica_events[str(rep.get("name"))] = (
                doc.get("flight_events") or []
            )
    if router_rec is None and not replica_recs:
        detail = "; ".join(fetched.get("errors") or [])
        raise SystemExit(
            f"trace: request {args.request_id!r} unknown to every "
            "process in the discovery file"
            + (f" ({detail})" if detail else "")
        )
    joined = join_fleet_trace(
        router_rec, replica_recs, args.request_id,
        router_events=router_doc.get("flight_events") or [],
        replica_events=replica_events,
    )
    if fetched.get("errors"):
        joined.setdefault("notes", []).extend(
            f"unreachable mid-fetch: {e}" for e in fetched["errors"]
        )
    if args.format == "json":
        print(json.dumps(joined, indent=1))
    else:
        print(render_fleet_waterfall(joined))
    return 0


def cmd_trace(args) -> int:
    """Reconstruct one serving request's critical path (round 15): the
    structured access log is the source of truth for phase attribution
    (queue/compile/execute/demux millis the daemon booked at response
    time), joined — when the artifacts exist — with the request's
    `serve_request` span tree from flight.json for the span-side view.
    Round 19: `--url` asks a LIVE daemon instead (GET /request?id=),
    so tracing needs no filesystem access to the daemon's artifacts.
    Round 22: `--fleet DISCOVERY` walks the router's replica-discovery
    file, pulls the router-side AND replica-side records for the id,
    joins them by the forwarded `X-Parent-Span` context, and renders
    ONE cross-process waterfall with an explicit clock-skew bound and
    an honest unattributed gap (never imputed).
    Prints a phase-attributed waterfall; exits nonzero when the id is
    not in the (possibly rotated) log / not known to the daemon."""
    import json

    from .serving.accesslog import phase_fields

    modes = [bool(args.url), bool(args.trace_dir),
             bool(getattr(args, "fleet", None))]
    if sum(modes) != 1:
        raise SystemExit(
            "trace: exactly one of --url (live daemon), --trace-dir "
            "(post-mortem artifacts) or --fleet (router discovery "
            "file) is required"
        )
    if getattr(args, "fleet", None):
        return _cmd_trace_fleet(args)
    if args.url:
        import urllib.error
        import urllib.parse
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith(("http://", "https://")):
            base = "http://" + base
        url = (base + "/request?id="
               + urllib.parse.quote(args.request_id, safe=""))
        try:
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode("utf-8")).get(
                    "error", ""
                )
            except (ValueError, OSError):
                detail = ""
            raise SystemExit(
                f"trace: request {args.request_id!r}: daemon answered "
                f"{e.code}" + (f" ({detail})" if detail else "")
            )
        except (urllib.error.URLError, OSError) as e:
            raise SystemExit(f"trace: cannot reach {args.url}: {e}")
        rec = doc.get("request") or {}
        flight_evs = doc.get("flight_events") or []
    else:
        from .serving.accesslog import find_request

        log_path = args.access_log or os.path.join(
            args.trace_dir, "access.jsonl"
        )
        rec = find_request(log_path, args.request_id)
        if rec is None:
            raise SystemExit(
                f"trace: request {args.request_id!r} not found in "
                f"{log_path} (or its .1 rotation)"
            )
        # Optional flight-side join: the daemon replays each settled
        # request's span tree through the flight recorder, so a request
        # still inside the ring's window has events here too.
        flight_evs = []
        flight_path = os.path.join(args.trace_dir, "flight.json")
        if os.path.exists(flight_path):
            from .telemetry.flight import read_flight, request_events

            try:
                flight_evs = request_events(
                    read_flight(flight_path), args.request_id
                )
            except (OSError, ValueError):
                flight_evs = []
    if args.format == "json":
        print(json.dumps(
            {"access": rec, "flight_events": flight_evs}, indent=1
        ))
        return 0
    total_ms = float(rec.get("total_ms") or 0.0)
    phases = phase_fields(rec)
    print(
        f"request {rec.get('request_id')}  outcome={rec.get('outcome')}"
        f"  http={rec.get('http_status')}  cache={rec.get('cache', '-')}"
        f"  session={rec.get('session_id') or '-'}"
    )
    if rec.get("exec_key"):
        print(f"  exec_key {rec['exec_key']}")
    if rec.get("ts"):
        print(f"  ts {rec['ts']}  bytes_in {rec.get('bytes_in', 0)}"
              f"  bytes_out {rec.get('bytes_out', 0)}")
    width = 32
    for name, ms in phases:
        frac = ms / total_ms if total_ms > 0 else 0.0
        bar = "#" * max(1, int(round(frac * width))) if ms > 0 else ""
        print(f"  {name:8s} {ms:10.3f} ms  {100.0 * frac:5.1f}%  {bar}")
    attributed = sum(ms for _, ms in phases)
    gap = total_ms - attributed
    gap_pct = 100.0 * gap / total_ms if total_ms > 0 else 0.0
    print(
        f"  {'phases':8s} {attributed:10.3f} ms  vs total "
        f"{total_ms:.3f} ms (gap {gap:.3f} ms, {gap_pct:.2f}%)"
    )
    if flight_evs:
        closes = [ev for ev in flight_evs if ev.get("kind") == "close"]
        root = next(
            (ev for ev in closes if ev.get("name") == "serve_request"),
            None,
        )
        extra = (
            f"; serve_request wall {root['wall_ms']:.3f} ms"
            if root and root.get("wall_ms") is not None else ""
        )
        print(f"  flight: {len(flight_evs)} span events{extra}")
    return 0


def cmd_obs(args) -> int:
    """Multi-replica serving observatory (round 19): scrape every
    target daemon's /metrics.json + /slo + /obs/window, pool the
    registries (sum counters, merge histogram buckets — fleet burn
    rates are request-weighted, never replica-averaged), render the
    terminal dashboard, and optionally write the OBS record
    tools/check_obs.py validates.  Exits 1 when no target answered or
    the pooled SLO verdict is violated."""
    import json

    from .serving.observatory import (
        aggregate,
        parse_targets,
        render_dashboard,
        write_obs,
    )

    try:
        targets = parse_targets(args.targets)
    except ValueError as e:
        raise SystemExit(f"obs: {e}")
    record = aggregate(targets, span_s=args.span, timeout=args.timeout)
    if args.format == "json":
        print(json.dumps(record, indent=1))
    else:
        print(render_dashboard(record), end="")
    if args.out:
        write_obs(record, args.out)
        print(f"obs: wrote {args.out}", file=sys.stderr)
    fleet = record.get("fleet") or {}
    if not fleet.get("replicas_live"):
        print("obs: no live replicas", file=sys.stderr)
        return 1
    if (fleet.get("slo") or {}).get("verdict") == "violated":
        return 1
    return 0


def cmd_history(args) -> int:
    """Restart-lineage view over a durable telemetry archive (round
    23, telemetry/archive.py): group the archived snapshots by boot,
    summarize each boot's window (obs generation span, SLO verdict,
    latency p99, the anomaly baseline it graded against), diff
    consecutive boots, and list the incidents captured along the way.
    With --targets, each live endpoint is probed too — an endpoint
    that is down while its archive is present renders FROM THE
    ARCHIVE with an explicit degraded-fleet warning, never a silent
    drop.  Exits 1 only when the archive itself holds no records."""
    import json
    import urllib.error
    import urllib.request
    from collections import OrderedDict

    from .telemetry.archive import list_incidents, read_archive_entries

    boots = OrderedDict()
    records = 0
    for rec in read_archive_entries(args.archive_dir):
        records += 1
        bid = rec.get("boot_id")
        if not isinstance(bid, str):
            continue
        boot = boots.setdefault(bid, {
            "boot_id": bid, "first_ts": rec.get("ts"),
            "last_ts": rec.get("ts"), "snapshots": 0,
            "incidents": [], "generation": None, "baseline": None,
            "verdict": None, "p99_ms": None, "final": False,
        })
        boot["last_ts"] = rec.get("ts", boot["last_ts"])
        kind = rec.get("kind")
        if kind == "snapshot":
            boot["snapshots"] += 1
            g = rec.get("obs_generation")
            if isinstance(g, int):
                boot["generation"] = g
            b = rec.get("anomaly_baseline_p99_ms")
            if isinstance(b, (int, float)):
                boot["baseline"] = float(b)
            boot["final"] = bool(rec.get("final"))
            slo = rec.get("slo") or {}
            boot["verdict"] = slo.get("verdict", boot["verdict"])
            lat = next(
                (o for o in slo.get("objectives", [])
                 if o.get("kind") == "latency"), None,
            )
            if lat and lat.get("observed_p99_ms") is not None:
                boot["p99_ms"] = float(lat["observed_p99_ms"])
        elif kind == "incident":
            boot["incidents"].append(rec.get("id"))
    warnings = []
    if getattr(args, "targets", None):
        from .serving.observatory import parse_targets

        try:
            targets = parse_targets(args.targets)
        except ValueError as e:
            raise SystemExit(f"history: {e}")
        for t in targets:
            try:
                with urllib.request.urlopen(
                    f"{t}/healthz", timeout=args.timeout
                ):
                    pass
            except (urllib.error.URLError, OSError) as e:
                warnings.append(
                    f"target {t} unreachable ({type(e).__name__}: "
                    f"{e}); history rendered from the archive only"
                )
    incidents = list_incidents(args.archive_dir)
    if args.format == "json":
        print(json.dumps({
            "archive_dir": args.archive_dir,
            "records": records,
            "boots": list(boots.values()),
            "incidents": incidents,
            "warnings": warnings,
        }, indent=1))
        return 0 if boots else 1
    print(
        f"telemetry history — {args.archive_dir}: "
        f"{len(boots)} boot(s), {records} record(s), "
        f"{len(incidents)} incident bundle(s)"
    )
    prev = None
    for boot in boots.values():

        def _ts(v):
            return (
                time.strftime("%H:%M:%S", time.gmtime(v))
                if isinstance(v, (int, float)) else "-"
            )

        p99 = boot["p99_ms"]
        base = boot["baseline"]
        print(
            f"boot {boot['boot_id']:<22} "
            f"{_ts(boot['first_ts'])}→{_ts(boot['last_ts'])}  "
            f"snaps={boot['snapshots']:<4} "
            f"gen={boot['generation'] if boot['generation'] is not None else '-':<4} "
            f"verdict={boot['verdict'] or '-':<9} "
            f"p99={f'{p99:.1f}ms' if p99 is not None else '-':<10} "
            f"baseline={f'{base:.1f}ms' if base is not None else '-'}"
            + ("  [drained]" if boot["final"] else "")
        )
        for inc in boot["incidents"]:
            print(f"  incident {inc}")
        if prev is not None:
            pp, np_ = prev["p99_ms"], boot["p99_ms"]
            carried = (
                prev["baseline"] is not None
                and boot["baseline"] == prev["baseline"]
            ) or (
                prev["p99_ms"] is None and boot["baseline"] is not None
            )
            diff = (
                f"p99 {pp:.1f}→{np_:.1f}ms"
                if pp is not None and np_ is not None else "p99 -"
            )
            print(
                f"  ↳ restart diff vs {prev['boot_id']}: {diff}, "
                f"baseline "
                + ("carried" if boot["baseline"] is not None
                   else "absent")
            )
        prev = boot
    for warn in warnings:
        print(f"WARNING (fleet degraded): {warn}")
    if not boots:
        print("history: archive holds no records", file=sys.stderr)
        return 1
    return 0


def cmd_incident(args) -> int:
    """Render one black-box incident bundle (round 23): the trigger,
    the config/backend fingerprint, the graded SLO objectives and
    anomaly watches at capture time, the access-log tail, and the
    slowest tail request's phase waterfall — from the archive dir on
    disk, or proxied live from a daemon/router URL."""
    import json
    import urllib.error
    import urllib.parse
    import urllib.request

    from .serving.accesslog import phase_fields

    doc = None
    if bool(args.url) == bool(args.archive_dir):
        raise SystemExit(
            "incident: exactly one of --archive-dir (on disk) or "
            "--url (live daemon/router) is required"
        )
    if args.url:
        base = args.url.rstrip("/")
        if not base.startswith(("http://", "https://")):
            base = "http://" + base
        q = urllib.parse.quote(args.incident_id, safe="")
        try:
            with urllib.request.urlopen(
                f"{base}/incidents?id={q}", timeout=10.0
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            raise SystemExit(
                f"incident: {args.incident_id!r}: endpoint answered "
                f"{e.code}"
            )
        except (urllib.error.URLError, OSError) as e:
            raise SystemExit(f"incident: cannot reach {args.url}: {e}")
    else:
        from .telemetry.archive import load_incident

        doc = load_incident(args.archive_dir, args.incident_id)
        if doc is None:
            raise SystemExit(
                f"incident: {args.incident_id!r} not found under "
                f"{args.archive_dir}/incidents"
            )
    if args.format == "json":
        print(json.dumps(doc, indent=1))
        return 0
    trig = doc.get("trigger") or {}
    print(
        f"incident {doc.get('id')}  trigger={trig.get('kind')}  "
        f"ts={doc.get('ts')}"
    )
    if trig.get("watches"):
        print(f"  watches firing: {', '.join(trig['watches'])}")
    for o in trig.get("objectives") or []:
        print(
            f"  objective {o.get('name')}: {o.get('status')} "
            f"(burn={o.get('burn_rate')})"
        )
    fp = doc.get("fingerprint") or {}
    print(
        f"  daemon: pid={fp.get('pid')} backend={fp.get('backend')} "
        f"devices={fp.get('devices')} boot={fp.get('boot_id')}"
    )
    slo = doc.get("slo") or {}
    print(f"  slo verdict at capture: {slo.get('verdict', '-')}")
    for o in slo.get("objectives") or []:
        burn = o.get("burn_rate")
        print(
            f"    {o.get('name'):<24} {o.get('status'):<10} "
            f"burn={'-' if burn is None else f'{burn:.4f}'}"
        )
    anom = doc.get("anomaly") or {}
    if anom:
        print(
            f"  anomaly verdict: {anom.get('verdict', '-')} "
            f"(firing: "
            f"{', '.join(anom.get('firing') or []) or 'none'})"
        )
    flight = doc.get("flight") or {}
    if flight:
        print(
            f"  flight: {len(flight.get('events') or [])} span "
            f"event(s) in ring, flushed_on="
            f"{flight.get('flushed_on')}"
        )
    tail = doc.get("access_tail") or []
    print(f"  access tail: {len(tail)} request(s)")
    for rec in tail[-args.tail:]:
        print(
            f"    {str(rec.get('request_id')):<24} "
            f"{str(rec.get('outcome')):<9} "
            f"http={rec.get('http_status')} "
            f"total={rec.get('total_ms')}ms"
        )
    served = [r for r in tail if r.get("total_ms") is not None]
    if served:
        worst = max(served, key=lambda r: float(r["total_ms"]))
        total_ms = float(worst.get("total_ms") or 0.0)
        print(
            f"  slowest tail request {worst.get('request_id')} "
            f"({total_ms:.3f} ms):"
        )
        width = 32
        for name, ms in phase_fields(worst):
            frac = ms / total_ms if total_ms > 0 else 0.0
            bar = ("#" * max(1, int(round(frac * width)))
                   if ms > 0 else "")
            print(f"    {name:8s} {ms:10.3f} ms  "
                  f"{100.0 * frac:5.1f}%  {bar}")
    return 0


def cmd_route(args) -> int:
    """Fleet router (round 21, serving/router.py): spread POST
    /synthesize across N daemon replicas — least outstanding work with
    queue-depth awareness from each replica's /serving snapshot,
    session affinity for video streams, drain-time session migration —
    and keep a replica-discovery file current for `ia-synth obs`.
    Round 22: with --trace-dir, every proxied request gets a span tree
    (received/pick/proxy_attempt/respond) in the router's flight ring,
    a line in the router's own access.jsonl, and the `X-Parent-Span`/
    `X-Trace-Hop` headers it forwards join the replica's serve_request
    tree to this hop (`ia-synth trace ID --fleet DISCOVERY`).
    Imports no JAX; this process is pure coordination."""
    import signal as _signal
    import threading

    from .serving.router import FleetRouter
    from .utils.profiling import telemetry_session

    try:
        from .serving.observatory import parse_targets

        targets = parse_targets(args.targets)
    except ValueError as e:
        raise SystemExit(f"route: {e}")
    with telemetry_session(
        None, enabled=True, artifact_dir=args.trace_dir,
        metrics_port=None,
    ) as tracer:
        router = FleetRouter(
            tracer.registry,
            tracer=tracer,
            host=args.host,
            port=args.port,
            poll_interval_s=args.poll_interval_s,
            discovery_path=args.discovery_out,
            proxy_timeout_s=args.proxy_timeout_s,
            flight=getattr(tracer, "flight_recorder", None),
            access_log_path=(
                os.path.join(args.trace_dir, "access.jsonl")
                if args.trace_dir else None
            ),
        ).start()
        stop = threading.Event()
        _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
        try:
            for url in targets:
                handle = router.add_replica(url)
                state = "up" if handle.alive else "DOWN"
                print(f"route: replica {handle.name} {handle.url} "
                      f"[{state}]")
            if args.trace_dir:
                os.makedirs(args.trace_dir, exist_ok=True)
                router.live.announce(args.trace_dir)
            print(
                f"routing on {router.url} (POST /synthesize "
                "/replicas/add /replicas/remove /drain_replica; GET "
                "/fleet /replicas /request /slo /metrics /metrics.json "
                "/healthz)",
                flush=True,
            )
            if args.discovery_out:
                print(f"route: discovery file at {args.discovery_out} "
                      "(pass to `ia-synth obs --targets` and "
                      "`ia-synth trace --fleet`)")
            while not stop.wait(1.0):
                pass
            print("route: exiting", flush=True)
        except KeyboardInterrupt:
            print("route: interrupted")
        finally:
            router.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="image_analogies_tpu",
        description="TPU-native Image Analogies (A : A' :: B : B')",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("synth", help="synthesize B' from A, A', B")
    p.add_argument("--a", required=True)
    p.add_argument("--ap", required=True)
    p.add_argument("--b", required=True)
    p.add_argument("--out", required=True)
    mode = p.add_mutually_exclusive_group()
    mode.add_argument(
        "--spatial", action="store_true",
        help="shard B' row-slabs over the device mesh (halo-exchange "
        "spatial parallelism) instead of single-device synthesis",
    )
    mode.add_argument(
        "--sharded-a", action="store_true",
        help="band-shard the A-side feature tables over the device "
        "mesh (style pairs beyond one device's budget); bit-identical "
        "to single-device synthesis at lean levels",
    )
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument(
        "--bands", "--mesh-rows", dest="bands", type=int, default=None,
        help="with --spatial: band-shard the A side over this many "
        "mesh rows (2-D bands x slabs mesh — style pair AND target "
        "beyond one chip).  Must divide the device count.  Default: "
        "the mesh-shape planner (parallel/plan2d.py) picks the "
        "factorization from the modeled comms volume + per-device "
        "residency; pass an explicit value (1 = flat 1-D mesh) to "
        "override it",
    )
    _add_synth_flags(p)
    p.set_defaults(fn=cmd_synth)

    p = sub.add_parser("batch", help="stylize a directory of frames")
    p.add_argument("--a", required=True)
    p.add_argument("--ap", required=True)
    p.add_argument("--frames", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument(
        "--frames-per-step", type=int, default=None,
        help="process frames in sequential microbatches of this size "
        "(bounds HBM on small meshes; full-scale 8x1024 budgets one "
        "frame per chip)",
    )
    p.add_argument(
        "--strict-frames", action="store_true",
        help="abort on the first unreadable/undecodable frame instead "
        "of skipping it with a recorded per-frame status (the round-12 "
        "fault-isolation default)",
    )
    _add_synth_flags(p)
    p.set_defaults(fn=cmd_batch)

    p = sub.add_parser(
        "video",
        help="stylize a frame SEQUENCE with temporal warm-starting "
        "(video/): NNF warm-start between consecutive frames, "
        "tau-weighted temporal coherence, delta-cost scheduling",
    )
    p.add_argument("--a", required=True)
    p.add_argument("--ap", required=True)
    p.add_argument("--frames", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument(
        "--warm", default=None, choices=["on", "off"],
        help="NNF warm-start seam (IA_VIDEO_WARM): 'off' dispatches "
        "every frame cold through the batch runner, bit-identical to "
        "`batch --frames-per-step 1` (default: on, or the "
        "IA_VIDEO_WARM environment value)",
    )
    p.add_argument(
        "--strict-frames", action="store_true",
        help="abort on the first unreadable/undecodable frame instead "
        "of skipping it with a recorded per-frame status",
    )
    _add_synth_flags(p)
    p.set_defaults(fn=cmd_video)

    p = sub.add_parser(
        "serve",
        help="synthesis-as-a-service daemon: request queue + "
        "compiled-executable cache + continuous batching + admission "
        "control over HTTP (serving/)",
    )
    p.add_argument("--a", required=True)
    p.add_argument("--ap", required=True)
    p.add_argument(
        "--port", type=int, default=0,
        help="bind port (0 = ephemeral; the bound endpoint prints on "
        "stdout and announces in <trace-dir>/live.json)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument(
        "--max-batch", type=int, default=None, metavar="N",
        help="continuous-batching dispatch grain (default: device "
        "count).  Every dispatch pads to exactly this many frames so "
        "repeat request shapes share one compiled executable",
    )
    p.add_argument(
        "--max-wait-ms", type=float, default=25.0, metavar="MS",
        help="longest the queue head waits for co-batchable requests "
        "before a partial batch flushes (default 25)",
    )
    p.add_argument(
        "--max-queue-depth", type=int, default=32, metavar="N",
        help="admission limit: requests beyond this backlog "
        "(queued + in-flight) are shed with 429 + Retry-After; the "
        "limit halves while the backend's straggler/degradation "
        "gauges read degraded (default 32)",
    )
    p.add_argument(
        "--cache-capacity", type=int, default=8, metavar="N",
        help="compiled-executable cache residency (default 8).  "
        "Eviction is epoch-grained (serving/excache.py): size this "
        "so eviction is rare",
    )
    p.add_argument(
        "--warmup", default=None, metavar="MANIFEST",
        help="serve_warmup JSON manifest of expected request shapes, "
        "compiled through the real dispatch path before the endpoint "
        "announces — the first client request of each listed shape "
        "is then a cache hit",
    )
    p.add_argument(
        "--max-sessions", type=int, default=16, metavar="N",
        help="video session-affinity streams held live (LRU; round "
        "14).  A /synthesize request carrying session_id pins to a "
        "per-session warm-start stream; the least-recently-used "
        "stream beyond this count is dropped and its next frame runs "
        "cold (default 16)",
    )
    p.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="crash-resilience state dir (round 16): every admitted "
        "request journals to DIR/journal.jsonl before its ack, drain "
        "snapshots hand-off state there, and a restart replays the "
        "journal's unfinished entries (bit-identical by the isolation "
        "contract).  DIR/daemon.lock refuses a second live daemon",
    )
    p.add_argument(
        "--warm-dir", default=None, metavar="DIR",
        help="fleet-shared warm tier (round 21): root the disk "
        "executable cache and warmup.observed.json here instead of "
        "the per-replica --state-dir, so N replicas share one sealed-"
        "executable set and one merged observed-shape union — a "
        "freshly spawned replica precompiles the fleet's working set "
        "before its port announce.  Journal, lock, and session "
        "snapshots stay in --state-dir (per-replica)",
    )
    p.add_argument(
        "--takeover", default=None, metavar="DIR",
        help="take over a dead/drained daemon's state dir: restore "
        "its snapshotted sessions, merge its runtime-observed warmup "
        "shapes, and replay its journaled unfinished requests "
        "(equivalent to --state-dir DIR; refused while the previous "
        "holder's pid is alive)",
    )
    p.add_argument(
        "--drain-deadline-s", type=float, default=30.0, metavar="S",
        help="graceful-drain budget (SIGTERM or POST /drain): new "
        "requests 503 immediately; queued + in-flight work and their "
        "response writes get this long to finish before the hand-off "
        "snapshot is cut and the daemon exits 0 (default 30)",
    )
    p.add_argument(
        "--dispatch-deadline-s", type=float, default=None, metavar="S",
        help="bound one batch dispatch: past this wall the "
        "dispatcher's abort token fires and the wedged attempt "
        "unwinds as a failed (500) batch instead of freezing the "
        "daemon (default: unbounded)",
    )
    p.add_argument(
        "--pipeline-window", type=int, default=2, metavar="N",
        help="pipelined-dispatch in-flight window (round 18): up to N "
        "dispatched batches may be unsettled at once, so host-side "
        "demux/response work of batch t overlaps device execution of "
        "batch t+1.  1 = the serial round-13 loop (default 2)",
    )
    p.add_argument(
        "--warmup-workers", type=int, default=4, metavar="N",
        help="threads compiling distinct warmup shapes concurrently "
        "before the endpoint announces (round 18; default 4, 1 = "
        "sequential)",
    )
    p.add_argument(
        "--obs-interval-s", type=float, default=5.0, metavar="S",
        help="time-series ring sampling interval (round 19): every S "
        "seconds the registry snapshots into the windowed-rate ring "
        "GET /obs/window serves, and the anomaly watches re-grade.  "
        "<= 0 disables the observatory plane (default 5)",
    )
    p.add_argument(
        "--obs-capacity", type=int, default=120, metavar="N",
        help="time-series ring length in snapshots (default 120 = a "
        "10-minute window at the default interval; memory is N "
        "serialized registry snapshots)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="RECORD",
        help="committed SERVE-record JSON (e.g. SERVE_r18.json) whose "
        "pipeline.p99_warm_ms anchors the anomaly detector's latency "
        "envelope; omitted = the latency watch reports no_data",
    )
    p.add_argument(
        "--archive-dir", default=None, metavar="DIR",
        help="durable telemetry archive + black box (round 23): "
        "observatory windows, SLO state, and anomaly baselines "
        "persist to DIR as segmented JSONL (atomic sealing, "
        "torn-tail-tolerant reload), so a restart with the same DIR "
        "resumes its anomaly watches against pre-restart baselines; "
        "an SLO fast_burn or firing watch atomically captures a "
        "self-contained incident bundle under DIR/incidents "
        "(rate-limited, disk-budgeted; GET /incidents, "
        "`ia-synth history`, `ia-synth incident <id>`)",
    )
    p.add_argument(
        "--archive-interval-s", type=float, default=30.0, metavar="S",
        help="archive snapshot cadence (default 30; <= 0 keeps the "
        "archive open for boot/drain records and incidents but skips "
        "the periodic snapshots)",
    )
    p.add_argument(
        "--flight-ring", type=int, default=None, metavar="N",
        help="flight-recorder event-ring capacity (default: "
        "IA_FLIGHT_RING env or 512; memory scales linearly, "
        "~200-500 bytes per event)",
    )
    p.add_argument(
        "--lattice", default=None, metavar="SPEC",
        help="shape-lattice admission (round 20): canonicalize "
        "sessionless frames onto a geometric grid of bucket shapes "
        "(edge-pad at ingest, crop at demux) so exec-key cardinality "
        "is bounded by the lattice, not by traffic, and warmup "
        "precompiles EVERY bucket before the endpoint announces.  "
        "SPEC: off (default) | on (32:512, planner-chosen growth) | "
        "MIN:MAX (planner-chosen growth) | MIN:MAX:GROWTH (explicit "
        "override).  Frames over the top rung bypass to the exact-key "
        "path; a takeover successor must run the SAME spec for "
        "bit-identical journal replay",
    )
    _add_synth_flags(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "route",
        help="fleet router: spread POST /synthesize across N serve "
        "replicas with session affinity, queue-aware least-"
        "outstanding routing, drain-time session migration, and a "
        "discovery file for `ia-synth obs` (round 21)",
    )
    _add_common_flags(p)
    p.add_argument(
        "--targets", required=True, metavar="HOST:PORT,... | FILE",
        help="initial replica endpoints: comma-separated host:port / "
        "http:// URLs, or an existing discovery file (replicas can "
        "also join later via POST /replicas/add)",
    )
    p.add_argument(
        "--port", type=int, default=0,
        help="router bind port (0 = ephemeral; announces in "
        "<trace-dir>/live.json when --trace-dir is set)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--poll-interval-s", type=float, default=0.5, metavar="S",
        help="replica /serving scrape cadence feeding the queue-"
        "depth-aware routing scores (default 0.5)",
    )
    p.add_argument(
        "--proxy-timeout-s", type=float, default=600.0, metavar="S",
        help="per-proxy HTTP timeout (default 600 — must outlast a "
        "cold compile on the slowest replica)",
    )
    p.add_argument(
        "--discovery-out", default=None, metavar="JSON",
        help="replica-discovery file, rewritten atomically on every "
        "membership/drain change; `ia-synth obs --targets FILE` "
        "scrapes exactly this fleet",
    )
    p.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="announce the router endpoint in DIR/live.json",
    )
    p.set_defaults(fn=cmd_route)

    p = sub.add_parser(
        "obs",
        help="multi-replica serving observatory: scrape N daemons' "
        "/metrics.json + /slo + /obs/window, pool registries into "
        "fleet burn rates, render a dashboard, write OBS json "
        "(round 19)",
    )
    _add_common_flags(p)
    p.add_argument(
        "--targets", required=True, metavar="HOST:PORT,HOST:PORT,...",
        help="comma-separated daemon endpoints (host:port or full "
        "http:// URLs)",
    )
    p.add_argument(
        "--span", type=float, default=None, metavar="S",
        help="window span (seconds) requested from each replica's "
        "/obs/window (default: each replica's whole ring)",
    )
    p.add_argument(
        "--timeout", type=float, default=10.0, metavar="S",
        help="per-scrape HTTP timeout (default 10)",
    )
    p.add_argument(
        "--out", default=None, metavar="JSON",
        help="write the OBS record here (the artifact "
        "tools/check_obs.py validates)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_obs)

    p = sub.add_parser(
        "history",
        help="restart-lineage view over a durable telemetry archive: "
        "per-boot window summaries, cross-restart diffs, incident "
        "index (round 23)",
    )
    _add_common_flags(p)
    p.add_argument(
        "--archive-dir", required=True, metavar="DIR",
        help="the daemon's --archive-dir (segmented archive.jsonl + "
        "incidents/ live here)",
    )
    p.add_argument(
        "--targets", default=None, metavar="HOST:PORT,... | FILE",
        help="optionally probe these live endpoints too (same "
        "grammar as `ia-synth obs --targets`, discovery files "
        "included); an endpoint that is down while its archive is "
        "present renders from the archive with a degraded-fleet "
        "warning, never a silent drop",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0, metavar="S",
        help="per-probe HTTP timeout (default 5)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_history)

    p = sub.add_parser(
        "incident",
        help="render one black-box incident bundle: trigger, "
        "fingerprint, SLO/anomaly state at capture, access-log tail "
        "+ slowest-request waterfall (round 23)",
    )
    _add_common_flags(p)
    p.add_argument(
        "incident_id",
        help="the bundle id (from `ia-synth history`, GET "
        "/incidents, or the incidents/ directory)",
    )
    p.add_argument(
        "--archive-dir", default=None, metavar="DIR",
        help="read the bundle from DIR/incidents on disk; exactly "
        "one of --archive-dir/--url",
    )
    p.add_argument(
        "--url", default=None, metavar="URL",
        help="fetch the bundle live from a daemon or router "
        "(GET /incidents?id=); exactly one of --archive-dir/--url",
    )
    p.add_argument(
        "--tail", type=int, default=10, metavar="N",
        help="access-tail rows to print (default 10)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_incident)

    p = sub.add_parser("examples", help="generate procedural example assets")
    _add_common_flags(p)
    p.add_argument("--out", default="examples")
    p.add_argument("--size", type=int, default=256)
    p.set_defaults(fn=cmd_examples)

    p = sub.add_parser(
        "report",
        help="merge a traced run's host spans + device trace into "
        "report.json (input: a synth/batch --trace-dir directory)",
    )
    _add_common_flags(p)
    p.add_argument(
        "--trace-dir", required=True, metavar="DIR",
        help="telemetry directory a traced run wrote "
        "(host_spans.json / metrics.json / *.xplane.pb)",
    )
    p.add_argument(
        "--progress", default=None, metavar="JSONL",
        help="legacy progress stream to reconstruct host spans from "
        "when the trace dir has no host_spans.json",
    )
    p.add_argument(
        "--out", default=None,
        help="report path (default: <trace-dir>/report.json)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "health",
        help="run sentinel over a traced run's telemetry directory: "
        "expected-vs-observed model checks + run invariants -> "
        "health.json (exit 1 on a violated verdict)",
    )
    _add_common_flags(p)
    p.add_argument(
        "--trace-dir", required=True, metavar="DIR",
        help="telemetry directory a traced run wrote "
        "(host_spans.json / metrics.json)",
    )
    p.add_argument(
        "--out", default=None,
        help="health path (default: <trace-dir>/health.json)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_health)

    p = sub.add_parser(
        "trace",
        help="reconstruct one serving request's critical path from the "
        "daemon's access log (+ flight.json span join): phase-"
        "attributed waterfall for a request id (exit 1 if not found)",
    )
    _add_common_flags(p)
    p.add_argument(
        "request_id",
        help="the request id to reconstruct (echoed in every "
        "/synthesize response and error body)",
    )
    p.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="the serve daemon's --trace-dir (access.jsonl + "
        "flight.json live here); exactly one of --trace-dir/--url",
    )
    p.add_argument(
        "--url", default=None, metavar="URL",
        help="ask a LIVE daemon over HTTP instead of reading "
        "artifacts (GET /request?id=; round 19); exactly one of "
        "--trace-dir/--url/--fleet",
    )
    p.add_argument(
        "--fleet", default=None, metavar="DISCOVERY",
        help="cross-process waterfall (round 22): walk the router's "
        "replica-discovery file (ia-synth route --discovery-out), "
        "pull the router-side and replica-side records for this id, "
        "join them by the forwarded X-Parent-Span context, and render "
        "ONE waterfall with a clock-skew bound and an honest "
        "unattributed gap; exactly one of --trace-dir/--url/--fleet",
    )
    p.add_argument(
        "--access-log", default=None, metavar="JSONL",
        help="explicit access-log path (default: "
        "<trace-dir>/access.jsonl)",
    )
    p.add_argument("--format", default="table", choices=["table", "json"])
    p.set_defaults(fn=cmd_trace)

    args = parser.parse_args(argv)
    from .utils.progress import configure_logging

    configure_logging(getattr(args, "log_level", None))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
