"""Supervised synthesis — runs that SURVIVE, not just runs that are
observable (round 12 tentpole, with runtime/faults.py).

Before this round a hung level, a failed kernel launch, or a mid-level
crash left only a flight dump and a manual restart.  The engine already
had every ingredient a supervisor needs: bit-exact per-level
checkpoint/resume (models/analogy.py — a resumed run is bit-identical
to an uninterrupted one because per-level PRNG keys derive from the
level index), a per-level cost model with calibrated seconds-per-unit
(round 10's `run_plan` mark, the live /progress ETA), and a fleet of
default-off fallback seams each pinned bit-safe or quality-bounded.
This module composes them into four cooperating pieces:

1. WATCHDOG — each pyramid level gets a deadline
       max(min_deadline_s,
           eta_cost_units[level] x seconds_per_unit x slack)
   where seconds_per_unit is calibrated from the walls of the levels
   this run has already completed (exactly the /progress ETA's rate);
   before any level completes, the conservative static bound
   `static_deadline_s` applies instead (and also bounds a run that
   hangs before its first level opens).  The watchdog is a tracer
   OBSERVER (the flight recorder's hook): it learns the plan from the
   `run_plan` mark and level walls from level span open/close, so
   supervision adds ZERO graph changes and no extra device syncs.  A
   breach flushes the flight recorder with a `watchdog` reason, books
   `ia_watchdog_breaches_total{level}`, and aborts the attempt.

2. RETRY-WITH-RESUME — supervised mode forces `save_level_artifacts`
   on, so on any attempt failure (exception, watchdog breach, injected
   fault) the supervisor retries with exponential backoff, resuming
   from the last intact checkpoint: the retried run replays only the
   failed level, and — when the ladder never steps — stays
   bit-identical to an undisturbed run (the resume path's existing
   path-independence guarantee).  Every failure books
   `ia_retries_total{stage, reason}`.

3. DEGRADATION LADDER — after `max_retries` failures at one mode the
   supervisor steps down a pre-declared, config-ordered ladder of the
   engine's EXISTING seams (`default_ladder`: stream->sequential
   polish, int8->bf16 candidate tables, pruned->full candidates,
   packed->unpacked A-plane layout; the CLI appends mesh->
   single-device for parallel runners), applying each through its
   single-point setter (which clears the compiled level/EM caches so
   a flipped mode can never reuse a stale graph), records a
   `degradation` mark + `ia_degradations_total{from, to}`, resets the
   retry budget, and tries again.  Rung order is safest-first: the
   first four rungs are bit-identical or strictly-quality-improving
   fallbacks (stream==sequential and packed==unpacked are test-pinned
   bit-identical; bf16 tables and full candidate sets are the exact
   historical path the compressed modes approximate), so a healed-
   but-degraded run is never WORSE than the uncompressed baseline —
   only slower.

4. GIVE-UP — with the ladder exhausted and the retry budget spent, the
   supervisor flushes a final validated flight dump and raises
   `SupervisorGaveUp`; the CLI turns that into exit != 0.  A
   supervised run therefore ends in exactly one of: healed (output
   bit-identical when the ladder never stepped), degraded (recorded,
   never silent — the sentinel's `recovery` check refuses to grade a
   degraded run clean), or a clean post-mortem death.

Attempts run on daemon WORKER THREADS: a hung attempt cannot be killed
in-process, so a breached attempt is ABANDONED — its thread-local
abort token (runtime/faults.set_abort_token) makes the injected-hang
sleep and the next level boundary raise `LevelAborted`, unwinding the
worker promptly; the supervisor waits up to `abort_grace_s` for that
unwind before retrying (a truly wedged device call may outlive the
grace window — the retry still proceeds; checkpoint writes are atomic
and bit-identical across attempts, so a late write from a zombie
attempt is content-equal to the retry's own).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from . import faults

# Conservative pre-calibration per-level bound: long enough that no
# legitimate compile+execute of one level at the published scales trips
# it, short enough that an operator's "it's been stuck for a quarter
# hour" intuition is automated.  Post-calibration deadlines come from
# the cost model instead; min_deadline_s floors them so a 64^2 coarse
# level's microsecond-scale units can't produce a hair-trigger.
STATIC_DEADLINE_S = 900.0
MIN_DEADLINE_S = 10.0
WATCHDOG_SLACK = 4.0


class SupervisorGaveUp(RuntimeError):
    """Retries and ladder exhausted; the flight dump is the
    post-mortem.  Carries the last attempt's error as __cause__."""


class AbortToken:
    """Per-attempt abort flag shared between the watchdog (setter),
    the supervisor loop (reader), and the attempt's injection points
    (runtime/faults.fire raises LevelAborted when set)."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: Optional[str] = None

    def set(self, reason: str) -> None:
        self.reason = reason
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()


class DispatchDeadline:
    """Armed wall-clock bound on ONE dispatch (round 16, the serving
    daemon's anti-wedge guard): a timer that sets an AbortToken after
    `seconds`, so a hung batch — a `serve_hang` injection, a wedged
    collective — unwinds at its next `fire("level", ...)` / hang-slice
    check instead of wedging the dispatcher thread forever.  Use as a
    context manager around the dispatch; `cancel()` (or exit) disarms
    the timer, and `expired` says whether the bound fired.

    This is deliberately the same token type the supervisor's watchdog
    sets: one abort channel through runtime/faults, two setters."""

    def __init__(self, seconds: float, token: Optional[AbortToken]
                 = None):
        self.seconds = float(seconds)
        self.token = token if token is not None else AbortToken()
        self._timer: Optional[threading.Timer] = None

    def arm(self) -> "DispatchDeadline":
        self._timer = threading.Timer(
            self.seconds,
            lambda: self.token.set("dispatch-deadline"),
        )
        self._timer.daemon = True
        self._timer.start()
        return self

    def __enter__(self) -> "DispatchDeadline":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.cancel()

    def cancel(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    @property
    def expired(self) -> bool:
        return self.token.is_set() \
            and self.token.reason == "dispatch-deadline"


@dataclass(frozen=True)
class Rung:
    """One degradation-ladder step over an existing seam.

    `applies()` answers "is the process currently in the mode this
    rung steps DOWN from?"; `apply()` installs the degraded mode
    through the seam's single-point setter (which owns the compiled-
    cache invalidation).  `bit_safe` documents whether the step
    preserves bit-identity to the pre-step mode (ARCHITECTURE.md
    carries the per-rung rationale)."""

    name: str
    from_label: str
    to_label: str
    applies: Callable[[], bool]
    apply: Callable[[], None]
    bit_safe: bool = True


def default_ladder() -> List[Rung]:
    """The config-ordered ladder over the engine's process-wide seams,
    safest/cheapest first.  Each rung only engages when the process is
    actually in its from-mode (a default-mode run has at most the
    packed->unpacked rung available)."""
    from ..kernels import patchmatch_tile as _pt
    from ..models import patchmatch as _pm

    return [
        Rung(
            "polish_stream_to_sequential", "stream", "sequential",
            applies=lambda: _pm._POLISH_MODE == "stream",
            apply=lambda: _pm.set_polish_mode("sequential"),
            bit_safe=True,  # pinned bit-identical (round 8)
        ),
        Rung(
            "cand_int8_to_bf16", "int8", "bf16",
            applies=lambda: _pt.resolve_cand_dtype() == "int8",
            apply=lambda: _pt.set_cand_compression(cand_dtype="bf16"),
            bit_safe=False,  # bf16 IS the exact historical path —
            # quality-improving, but not bit-equal to the int8 arm
        ),
        Rung(
            "cand_pruned_to_full", "pruned", "full",
            applies=lambda: _pt.resolve_prune() is not None,
            apply=lambda: _pt.set_cand_compression(prune="off"),
            bit_safe=False,  # full candidate set >= pruned set
        ),
        Rung(
            "a_plane_packed_to_unpacked", "packed", "unpacked",
            applies=lambda: _pt.resolve_packed(),
            apply=lambda: _pt.set_packed_layout("unpacked"),
            bit_safe=True,  # pinned bit-identical (round 7)
        ),
    ]


class _Watchdog:
    """Tracer-observer deadline monitor for one supervise() call.

    State is reset per attempt (`arm`); the observer ignores events
    from threads other than the current attempt's worker, so a zombie
    abandoned attempt can neither calibrate nor false-trigger the
    fresh one."""

    def __init__(self, tracer, registry, slack: float,
                 static_deadline_s: float, min_deadline_s: float):
        self.tracer = tracer
        self.registry = registry
        self.slack = float(slack)
        self.static_deadline_s = float(static_deadline_s)
        self.min_deadline_s = float(min_deadline_s)
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._token: Optional[AbortToken] = None
        self._reset_state()

    def _reset_state(self) -> None:
        self.units: Dict[int, float] = {}
        self.done_wall_s = 0.0
        self.done_units = 0.0
        self.open_level: Optional[int] = None
        self.open_t: Optional[float] = None
        self.last_level: Optional[int] = None
        self.attempt_t0 = time.perf_counter()
        # Last forward progress: any level close restarts this clock,
        # so the BETWEEN-levels window (where the engine's eager glue,
        # checkpoint writes, and the parallel runners' whole level
        # bodies live — their level spans are recorded close-only,
        # after the fact) is monitored too, against the NEXT level's
        # deadline.
        self.last_progress_t = self.attempt_t0

    # -- observer (runs on the worker thread) -------------------------
    def observe(self, kind: str, sp) -> None:
        if self._worker is not threading.current_thread():
            return
        with self._lock:
            if kind == "mark" and sp.name == "run_plan":
                raw = (sp.attrs or {}).get("eta_cost_units") or {}
                try:
                    self.units = {int(k): float(v) for k, v in raw.items()}
                except (TypeError, ValueError):
                    self.units = {}
            elif sp.name == "level":
                lvl = (sp.attrs or {}).get("level")
                if kind == "open":
                    self.open_level = lvl
                    self.open_t = time.perf_counter()
                    self.last_level = lvl
                elif kind == "close":
                    if sp.wall_ms is not None and lvl is not None:
                        u = self.units.get(int(lvl))
                        if u:
                            self.done_wall_s += sp.wall_ms / 1000.0
                            self.done_units += u
                    if lvl is not None:
                        self.last_level = lvl
                    self.open_level = None
                    self.open_t = None
                    self.last_progress_t = time.perf_counter()

    # -- per-attempt lifecycle ---------------------------------------
    def arm(self, worker: threading.Thread, token: AbortToken) -> None:
        with self._lock:
            self._worker = worker
            self._token = token
            self._reset_state()

    def level_deadline_s(self, level: Optional[int]) -> float:
        """The breach bound for the currently-open level (or for the
        pre-first-level window when `level` is None)."""
        if level is None:
            return self.static_deadline_s
        if self.done_units > 0 and self.done_wall_s > 0:
            rate = self.done_wall_s / self.done_units
            u = self.units.get(int(level))
            if u:
                return max(self.min_deadline_s, u * rate * self.slack)
        return self.static_deadline_s

    def check(self) -> bool:
        """Poll once; returns True (and aborts the attempt) on a
        breach."""
        with self._lock:
            token = self._token
            if token is None or token.is_set():
                return False
            if self.open_t is not None:
                level, elapsed = (
                    self.open_level,
                    time.perf_counter() - self.open_t,
                )
            else:
                # No open span: the pre-first-level window (prologue /
                # transfer), the between-levels glue, or a parallel
                # runner's level body (their spans record close-only).
                # The clock is time-since-last-progress; the bound is
                # the NEXT level's deadline once one is known.
                level = (
                    self.last_level - 1
                    if self.last_level is not None and self.last_level > 0
                    else None
                )
                elapsed = time.perf_counter() - self.last_progress_t
            deadline = self.level_deadline_s(level)
        if elapsed <= deadline:
            return False
        self.registry.counter(
            "ia_watchdog_breaches_total",
            "supervised level deadlines breached (cost-model deadline "
            "x slack, or the static pre-calibration bound)",
        ).inc(labels={"level": str(level if level is not None else "prologue")})
        recorder = getattr(self.tracer, "flight_recorder", None)
        if recorder is not None:
            recorder.flush("watchdog")
        import logging

        logging.getLogger("image_analogies_tpu").warning(
            "watchdog: level %s exceeded its %.1f s deadline "
            "(%.1f s elapsed) — aborting the attempt",
            level if level is not None else "prologue", deadline, elapsed,
        )
        token.set("watchdog")
        return True


def _has_checkpoint(ckpt_dir: str) -> bool:
    """Whether the supervisor's checkpoint dir holds ANY per-level
    artifact yet (chunked batch runs write level files into frames_*
    subdirectories, so the walk covers those too).  Until it does, a
    retry must fall back to the caller's original resume source — a
    failure at the coarsest level would otherwise resume from an empty
    directory, discarding a user-supplied --resume-from's progress
    (and, under --strict-resume, deterministically erroring every
    retry into a spurious give-up)."""
    import re

    try:
        for _root, _dirs, files in os.walk(ckpt_dir):
            if any(re.fullmatch(r"level_\d+\.npz", f) for f in files):
                return True
    except OSError:
        pass
    return False


def _drain_span_stack(tracer) -> None:
    """Pop every open span off the shared tracer's stack after an
    abandoned attempt outlived its abort grace: the zombie thread can
    create no further spans (its next fault checkpoint raises
    LevelAborted before any span opens), but its still-open run/level
    spans would otherwise become the PARENT of the fresh attempt's
    spans, mis-rooting the tree and the /progress stack.  List ops are
    GIL-atomic (the stack_snapshot pattern), and Tracer._close pops
    only when its own span is top-of-stack, so the zombie's eventual
    unwinding closes its (already-recorded) spans without touching the
    fresh attempt's.  A zombie that NEVER unwinds leaves its spans
    open and the sentinel's span_tree check flags the run — an honest
    signal that a wedged thread is still holding a device call."""
    while getattr(tracer, "_stack", None):
        try:
            tracer._stack.pop()
        except IndexError:
            break


def _failure_reason(token: AbortToken, error: Optional[BaseException]
                    ) -> str:
    if token.is_set() and token.reason == "watchdog":
        return "watchdog"
    if isinstance(error, faults.InjectedTransferError):
        return "transfer"
    if isinstance(error, faults.InjectedFault):
        return "injected"
    return "exception"


def supervise(
    attempt_fn: Callable[[Optional[str]], Any],
    *,
    ckpt_dir: str,
    tracer=None,
    registry=None,
    initial_resume: Optional[str] = None,
    max_retries: int = 2,
    watchdog_slack: float = WATCHDOG_SLACK,
    static_deadline_s: float = STATIC_DEADLINE_S,
    min_deadline_s: float = MIN_DEADLINE_S,
    backoff_s: float = 0.5,
    max_backoff_s: float = 30.0,
    ladder: Optional[List[Rung]] = None,
    abort_grace_s: float = 10.0,
    poll_s: float = 0.05,
):
    """Run `attempt_fn` under supervision and return its result.

    `attempt_fn(resume_from)` is one synthesis attempt — a closure the
    CLI builds around the chosen runner, whose cfg has
    `save_level_artifacts=ckpt_dir` forced on.  The first attempt gets
    `initial_resume` (the user's --resume-from, usually None); every
    retry resumes from `ckpt_dir`, the checkpoints the failed attempts
    left behind.

    `ladder=None` installs `default_ladder()`; pass [] for no ladder
    (clean-death after the retry budget).  `max_retries` is the retry
    budget PER LADDER RUNG — stepping down a rung resets it.
    """
    from ..telemetry.metrics import get_registry

    if registry is None:
        registry = (
            tracer.registry
            if tracer is not None and getattr(tracer, "registry", None)
            is not None
            else get_registry()
        )
    rungs = list(default_ladder() if ladder is None else ladder)
    watch = _Watchdog(
        tracer, registry, watchdog_slack, static_deadline_s,
        min_deadline_s,
    )
    observing = (
        tracer is not None and getattr(tracer, "enabled", False)
    )
    if observing:
        tracer.add_observer(watch.observe)
    # One booking per supervise() CALL (round 13): a serving daemon
    # makes one call per dispatch, so the sentinel's recovery ledger
    # scales its attempts-vs-retries invariant by this count instead
    # of assuming the one-call-per-run CLI shape.
    registry.counter(
        "ia_supervisor_invocations_total",
        "supervise() invocations (one per supervised run or serving "
        "dispatch)",
    ).inc()
    attempts_c = registry.counter(
        "ia_supervisor_attempts_total",
        "supervised synthesis attempts started (first try + retries)",
    )
    retries_c = registry.counter(
        "ia_retries_total",
        "supervised attempt failures, by failing stage (pyramid level "
        "or 'prologue'/'run') and reason",
    )
    degr_c = registry.counter(
        "ia_degradations_total",
        "graceful-degradation ladder steps taken {from, to}",
    )

    failures_at_rung = 0
    attempt_idx = 0
    last_error: Optional[BaseException] = None
    try:
        while True:
            token = AbortToken()
            box: Dict[str, Any] = {}
            # Retries resume from the supervisor's checkpoints once any
            # exist; before that (a coarsest-level/prologue failure)
            # the caller's original resume source still applies.
            resume = (
                ckpt_dir
                if attempt_idx > 0 and _has_checkpoint(ckpt_dir)
                else initial_resume
            )

            def _body(resume=resume, token=token, box=box):
                faults.set_abort_token(token)
                try:
                    box["result"] = attempt_fn(resume)
                except BaseException as e:  # noqa: BLE001 - reaped below
                    box["error"] = e

            worker = threading.Thread(
                target=_body, name=f"ia-supervised-attempt-{attempt_idx}",
                daemon=True,
            )
            watch.arm(worker, token)
            attempts_c.inc()
            attempt_idx += 1
            worker.start()
            while worker.is_alive() and not token.is_set():
                worker.join(poll_s)
                if worker.is_alive() and observing:
                    # No observer -> no event source: a watchdog that
                    # cannot see levels would clock a healthy long run
                    # against the static bound and falsely breach it.
                    # Without a tracer the supervisor still retries on
                    # exceptions; only deadline enforcement is off.
                    watch.check()
            if token.is_set() and worker.is_alive():
                # Breached: give the abandoned attempt a bounded window
                # to unwind through its abort checkpoints.
                worker.join(abort_grace_s)
                if worker.is_alive():
                    # Truly wedged (a hung device call the abort token
                    # cannot interrupt): clear its open spans off the
                    # shared stack so the retry's tree roots correctly
                    # (_drain_span_stack docstring has the safety
                    # argument).
                    import logging

                    logging.getLogger("image_analogies_tpu").warning(
                        "supervisor: abandoned attempt still alive "
                        "after %.0f s grace — proceeding; its open "
                        "spans are detached from the live stack",
                        abort_grace_s,
                    )
                    _drain_span_stack(tracer)
            if "result" in box and not token.is_set():
                return box["result"]

            error = box.get("error")
            if isinstance(error, (KeyboardInterrupt, SystemExit)):
                raise error
            from ..models.analogy import ResumeError

            if isinstance(error, ResumeError):
                # A strict-resume failure is a CONFIG error, not a
                # transient fault: retrying would recompute from
                # scratch and exit 0 — the exact outcome the flag
                # exists to forbid.
                raise error
            last_error = error or SupervisorGaveUp(
                f"attempt aborted: {token.reason}"
            )
            reason = _failure_reason(token, error)
            stage = (
                str(watch.last_level)
                if watch.last_level is not None else "prologue"
            )
            retries_c.inc(labels={"stage": stage, "reason": reason})
            failures_at_rung += 1
            import logging

            log = logging.getLogger("image_analogies_tpu")
            if failures_at_rung > max_retries:
                # Retry budget spent at this mode: step the ladder.
                rung = next((r for r in rungs if r.applies()), None)
                if rung is None:
                    recorder = getattr(tracer, "flight_recorder", None)
                    if recorder is not None:
                        recorder.flush("violation")
                    raise SupervisorGaveUp(
                        f"supervised synthesis failed after "
                        f"{attempt_idx} attempts (retries and "
                        "degradation ladder exhausted) — see the "
                        "flight dump"
                    ) from last_error
                rung.apply()
                degr_c.inc(labels={
                    "from": rung.from_label, "to": rung.to_label,
                })
                if tracer is not None and getattr(
                    tracer, "enabled", False
                ):
                    tracer.annotate(
                        "degradation", rung=rung.name,
                        from_mode=rung.from_label, to_mode=rung.to_label,
                        bit_safe=rung.bit_safe,
                    )
                log.warning(
                    "supervisor: stepping degradation ladder %s "
                    "(%s -> %s) after %d failures",
                    rung.name, rung.from_label, rung.to_label,
                    failures_at_rung,
                )
                failures_at_rung = 0
            else:
                log.warning(
                    "supervisor: attempt %d failed at stage %s "
                    "(%s: %s) — retrying from %s",
                    attempt_idx, stage, reason, last_error, ckpt_dir,
                )
            if backoff_s > 0:
                time.sleep(min(
                    max_backoff_s,
                    backoff_s * (2.0 ** max(0, failures_at_rung - 1)),
                ))
    finally:
        if observing:
            tracer.remove_observer(watch.observe)
