"""Deterministic fault injection — the chaos half of the supervised
execution layer (round 12, with runtime/supervisor.py).

The supervisor's promise is falsifiable only if every fault class it
claims to survive can be reproduced on demand: a hung level, a failed
kernel launch, a checkpoint truncated mid-write, a device transfer
that dies.  This module plants NAMED INJECTION POINTS in the engine's
eager glue (the host-side level loop, never inside a jitted body —
an injected fault must fire per execution, not per trace):

    level    start of one pyramid level's host iteration (key = level)
    kernel   immediately before the level's compiled executable
             launches (key = level)
    ckpt     the per-level checkpoint write, `_save_level`
             (key = level; `truncate` corrupts the artifact AFTER the
             atomic rename — the partial-write-survived-on-disk case
             the resume loader must skip)
    xfer     the host->device input transfer / prologue dispatch
             (key = ordinal: 0 for the first transfer of a run)

plus the SERVING-PLANE points (round 16 — serving/daemon.py and
serving/journal.py; their `fail` action is returned to the caller,
which performs the simulated failure, rather than raised):

    serve_crash     between journal-append and the response write
                    (key = journal write ordinal): daemon hard-exits,
                    simulating SIGKILL with an acked request on disk
    serve_hang      dispatcher, before a batch executes (key =
                    dispatch ordinal): interruptible hang, bounded by
                    the daemon's dispatch deadline
    serve_evict     dispatcher (key = dispatch ordinal): forced
                    executable-cache epoch eviction before lookup
    serve_diskfull  the journal write syscall (key = write ordinal):
                    OSError counted on journal.errors, never raised

armed by a FAULT PLAN (`IA_FAULT_PLAN` env var or `set_fault_plan`):
comma/semicolon-separated entries

    <point>:<key>:<action>[:<arg>]

    level:2:raise        raise InjectedFault at level 2's start
    level:1:hang:30      hang level 1's start for 30 s (interruptible:
                         a supervisor abort or a signal ends it early)
    ckpt:1:truncate      truncate level 1's checkpoint after writing
    xfer:0:fail          raise InjectedTransferError at transfer 0
    kernel:0:raise:3     raise at level 0's kernel launch, 3 times

Each entry is armed for a finite count (default 1; the optional 4th
field is the count for raise/fail/truncate and the sleep seconds for
hang) and DISARMS as it fires — so a supervised retry that replays the
failed level heals deterministically instead of dying forever.  Every
firing books `ia_fault_injections_total{point, action}` in the session
registry, which is what lets the sentinel's `recovery` check price the
observed retries/breaches against the plan.

The `level` point doubles as the supervisor's ABORT CHECKPOINT: each
supervised attempt runs on a worker thread carrying a thread-local
abort token (`set_abort_token`); a watchdog breach sets the token, and
the next `fire("level", ...)` on that thread — including the wake-up
from an interrupted `hang` — raises `LevelAborted`, so an abandoned
attempt unwinds at its next level boundary instead of racing the
retry.  Unsupervised runs carry no token and pay one falsy check.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

POINTS = (
    "level", "kernel", "ckpt", "xfer",
    # Serving-plane points (round 16, serving/daemon.py + journal.py).
    # Their "fail" action is CALLER-INTERPRETED, not raised: the
    # serving glue turns it into the simulated failure (hard process
    # exit, journal-write OSError, forced cache epoch) — the engine's
    # raising semantics would instead fail a supervised attempt that
    # does not exist at these points.
    "serve_crash",     # between journal-append and response (key =
    #                    journal write ordinal): daemon hard-exits
    "serve_hang",      # dispatcher, before executing a batch (key =
    #                    dispatch ordinal): interruptible hang
    "serve_evict",     # dispatcher (key = dispatch ordinal): forced
    #                    executable-cache epoch eviction
    "serve_diskfull",  # journal write (key = write ordinal): OSError,
    #                    counted-not-raised
    "archive_crash",   # telemetry-archive append (key = archive write
    #                    ordinal): half the line hits disk, then the
    #                    process hard-exits — SIGKILL mid-append
)
ACTIONS = ("raise", "hang", "truncate", "fail")

# Serving-plane points: "fail" returns to the caller instead of
# raising, and only the actions below are grammatical per point.
SERVE_POINTS = ("serve_crash", "serve_hang", "serve_evict",
                "serve_diskfull", "archive_crash")
_SERVE_ACTIONS = {
    "serve_crash": ("fail",),
    "serve_hang": ("hang",),
    "serve_evict": ("fail",),
    "serve_diskfull": ("fail",),
    "archive_crash": ("fail",),
}

# Actions that raise out of the injection point (and therefore fail a
# supervised attempt) vs. actions the CALLER interprets (`truncate`
# returns to `_save_level`, which corrupts the artifact it just wrote).
RAISING_ACTIONS = ("raise", "fail")


class InjectedFault(RuntimeError):
    """A planned `raise` injection fired."""


class InjectedTransferError(InjectedFault):
    """A planned `fail` injection fired (simulated device-transfer /
    launch failure — a distinct type so tests can assert the class)."""


class LevelAborted(RuntimeError):
    """The supervisor's abort token was set for this attempt: the
    worker unwinds at the next level boundary (never user-visible —
    the supervisor eats it when it reaps the abandoned attempt)."""


@dataclass
class _Entry:
    point: str
    key: int
    action: str
    arg: float  # hang seconds, or remaining-count for other actions
    remaining: int = 1


@dataclass
class FaultPlan:
    """A parsed, mutable (entries disarm as they fire) fault plan.

    `match` is locked: a zombie abandoned attempt that outlived its
    abort grace and the fresh retry can reach the same armed point
    concurrently, and a single-count entry must fire exactly once —
    a double-firing would both kill the retry and double-book the
    injection counter the sentinel's recovery check prices."""

    entries: List[_Entry] = field(default_factory=list)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    @classmethod
    def parse(cls, spec: Optional[str]) -> Optional["FaultPlan"]:
        """Parse the IA_FAULT_PLAN grammar; None/"" -> None (no plan).
        Malformed specs raise ValueError at parse time — a typo'd plan
        must fail at startup, not silently never fire."""
        if not spec or not str(spec).strip():
            return None
        entries: List[_Entry] = []
        for raw in str(spec).replace(";", ",").split(","):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":")
            if len(parts) not in (3, 4):
                raise ValueError(
                    f"fault-plan entry {raw!r} is not "
                    "'point:key:action[:arg]'"
                )
            point, key_s, action = parts[0], parts[1], parts[2]
            if point not in POINTS:
                raise ValueError(
                    f"fault-plan point {point!r} names none of {POINTS}"
                )
            if action not in ACTIONS:
                raise ValueError(
                    f"fault-plan action {action!r} names none of "
                    f"{ACTIONS}"
                )
            if action == "truncate" and point != "ckpt":
                raise ValueError(
                    f"fault-plan entry {raw!r}: 'truncate' only "
                    "applies to the 'ckpt' point"
                )
            if point in _SERVE_ACTIONS and \
                    action not in _SERVE_ACTIONS[point]:
                raise ValueError(
                    f"fault-plan entry {raw!r}: point {point!r} only "
                    f"supports {_SERVE_ACTIONS[point]}"
                )
            try:
                key = int(key_s)
            except ValueError:
                raise ValueError(
                    f"fault-plan key {key_s!r} is not an integer"
                ) from None
            arg_s = parts[3] if len(parts) == 4 else None
            if action == "hang":
                try:
                    arg = float(arg_s) if arg_s is not None else 5.0
                except ValueError:
                    raise ValueError(
                        f"fault-plan hang seconds {arg_s!r} is not a "
                        "number"
                    ) from None
                count = 1
            else:
                try:
                    count = int(arg_s) if arg_s is not None else 1
                except ValueError:
                    raise ValueError(
                        f"fault-plan count {arg_s!r} is not an integer"
                    ) from None
                arg = 0.0
            if count < 1:
                raise ValueError(
                    f"fault-plan entry {raw!r}: count must be >= 1"
                )
            entries.append(_Entry(point, key, action, arg, count))
        return cls(entries)

    def match(self, point: str, key: int) -> Optional[_Entry]:
        """The first still-armed entry for (point, key), disarmed by
        one firing — or None."""
        with self._lock:
            for e in self.entries:
                if e.point == point and e.key == key and e.remaining > 0:
                    e.remaining -= 1
                    return e
        return None

    def armed(self) -> List[Tuple[str, int, str]]:
        return [
            (e.point, e.key, e.action)
            for e in self.entries if e.remaining > 0
        ]


# Process-wide plan: parsed once from the environment (subprocess tests
# and the CLI arm it with IA_FAULT_PLAN), replaceable in-process via
# set_fault_plan (the chaos suite / unit tests).  The _PLAN_RESOLVED
# latch keeps the un-armed fast path to one None check.
_PLAN: Optional[FaultPlan] = None
_PLAN_RESOLVED = False
_PLAN_LOCK = threading.Lock()


def resolve_fault_plan() -> Optional[FaultPlan]:
    global _PLAN, _PLAN_RESOLVED
    if not _PLAN_RESOLVED:
        with _PLAN_LOCK:
            if not _PLAN_RESOLVED:
                _PLAN = FaultPlan.parse(os.environ.get("IA_FAULT_PLAN"))
                _PLAN_RESOLVED = True
    return _PLAN


def set_fault_plan(spec_or_plan) -> Optional[FaultPlan]:
    """Install a plan process-wide (None disarms): accepts a grammar
    string or an already-parsed FaultPlan.  Returns the installed
    plan."""
    global _PLAN, _PLAN_RESOLVED
    with _PLAN_LOCK:
        _PLAN = (
            spec_or_plan if isinstance(spec_or_plan, (FaultPlan,
                                                      type(None)))
            else FaultPlan.parse(spec_or_plan)
        )
        _PLAN_RESOLVED = True
    return _PLAN


# Per-thread abort token (runtime/supervisor.AbortToken): each
# supervised attempt installs its own on its worker thread, so a stale
# abandoned attempt keeps seeing its (set) token while the fresh
# attempt runs clean.
_TLS = threading.local()


def set_abort_token(token) -> None:
    _TLS.token = token


def current_abort_token():
    return getattr(_TLS, "token", None)


def _record_injection(point: str, action: str) -> None:
    from ..telemetry.metrics import get_registry

    get_registry().counter(
        "ia_fault_injections_total",
        "planned fault injections fired (runtime/faults.py; the "
        "sentinel's recovery check prices retries against these)",
    ).inc(labels={"point": point, "action": action})


def fire(point: str, key: int) -> Optional[str]:
    """The injection point: called by the engine's eager glue.

    Checks the thread-local abort token first (raising LevelAborted at
    `level` points when set — the supervisor's attempt-abandonment
    boundary), then the armed plan.  Returns the action name for
    caller-interpreted actions ("truncate"), None otherwise; raising
    actions raise.  The un-armed, un-supervised fast path is two falsy
    checks."""
    token = getattr(_TLS, "token", None)
    if token is not None and point == "level" and token.is_set():
        raise LevelAborted(
            f"supervisor aborted this attempt (level {key})"
        )
    plan = _PLAN if _PLAN_RESOLVED else resolve_fault_plan()
    if plan is None:
        return None
    entry = plan.match(point, key)
    if entry is None:
        return None
    _record_injection(point, entry.action)
    import logging

    logging.getLogger("image_analogies_tpu").warning(
        "fault injection: %s:%d:%s fired", point, key, entry.action
    )
    if entry.action == "hang":
        _hang(entry.arg, token, point, key)
        return None
    if point in SERVE_POINTS:
        # Serving-plane faults are caller-interpreted: the daemon /
        # journal glue performs the simulated failure (hard exit,
        # counted OSError, forced eviction) — and the serving sentinel
        # (`check_serving_recovery`), not the engine's recovery check,
        # grades the aftermath.
        return entry.action
    if entry.action == "raise":
        raise InjectedFault(f"injected fault at {point}:{key}")
    if entry.action == "fail":
        raise InjectedTransferError(
            f"injected transfer failure at {point}:{key}"
        )
    return entry.action  # "truncate": the ckpt writer interprets it


def _hang(seconds: float, token, point: str, key: int) -> None:
    """Interruptible hang: sleeps in short slices so a supervisor
    abort (watchdog breach) or a delivered signal ends it promptly; an
    aborted hang raises LevelAborted so the abandoned worker unwinds
    instead of finishing the level it was hung at."""
    deadline = time.perf_counter() + float(seconds)
    while time.perf_counter() < deadline:
        if token is not None and token.is_set():
            raise LevelAborted(
                f"supervisor aborted a hung attempt at {point}:{key}"
            )
        time.sleep(min(0.05, max(0.0, deadline - time.perf_counter())))
