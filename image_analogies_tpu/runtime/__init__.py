"""Supervised execution layer (round 12).

`runtime/faults.py` — deterministic fault injection: named injection
points the engine's eager glue calls at level start, kernel launch,
checkpoint write, and device transfer, armed by `IA_FAULT_PLAN` so
tests and the chaos suite (tools/chaos_suite.py) can prove each fault
class either heals or produces a clean post-mortem.

`runtime/supervisor.py` — the supervisor itself: per-level watchdog
deadlines from the round-10 cost model, retry-with-resume from the
bit-exact per-level checkpoints, a config-ordered graceful-degradation
ladder over the engine's existing default-off seams, and a validated
flight dump when it finally gives up.  Wired as `synth|batch
--supervise` (cli.py).
"""

from .faults import (  # noqa: F401
    FaultPlan,
    InjectedFault,
    LevelAborted,
    fire,
    resolve_fault_plan,
    set_fault_plan,
)
from .supervisor import (  # noqa: F401
    Rung,
    SupervisorGaveUp,
    default_ladder,
    supervise,
)
