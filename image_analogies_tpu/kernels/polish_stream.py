"""DMA-streamed candidate-row gather for the per-pixel polish
(VERDICT r5 next-round 5 — the probe this round's ISSUE makes the
tentpole).

Why a kernel at all
-------------------
The polish pass (models/patchmatch.py: the sequential 12-gather
cascade after the tile kernel's bulk search) is bound by XLA's per-row
gather lowering: random (128-lane-padded) bf16 feature rows move at a
measured 16-19 GB/s regardless of index distribution — sorted, iota,
and coherent-field index sets all sit at the same floor
(tools/profile_gather.py, 2026-07-31), so the cost is per-row issue
overhead in the lowering, not HBM physics.  At 4096^2 the polish is
~61 % of the 8.17 s level-0 wall (SCALE_r05).  The one hardware path
that floor cannot bind is the DMA engines: the sweep kernel
(patchmatch_tile.py) already streams its candidate windows as explicit
HBM->VMEM `make_async_copy`s and its fetches run at an achieved
~570 GB/s aggregate.  This module points the same machinery at the
polish's 256 B rows.

Why the kernel is ONLY the gather
---------------------------------
The polish's output contract is argmin-tie-equality with the pure-XLA
cascade (the oracle twin the PSNR gates rest on).  Distances must
therefore be BITWISE equal between the two paths — accept tests
compare with `<` and `==`, so any reassociated f32 sum flips accepts.
Measured on this toolchain (2026-08-04): `jnp.sum` over a zero-padded
128-lane row is NOT bitwise equal to the sum over the unpadded
feature width (XLA regroups the tree reduction), so a kernel that
re-implemented the distance math could never pin bit-identity.  The
kernel therefore does pure DATA MOVEMENT — fetch row idx[q] of the
padded A table into the output block — and the distance arithmetic
stays in the SAME `candidate_dist{,_lean}` code the cascade runs (a
`gather_fn` hook swaps `jnp.take` for this kernel; see
models/matcher.py).  Row fetch is bitwise-exact by construction, so
streamed-vs-cascade bit-identity reduces to "the kernel returns
exactly the table rows" — pinned directly by
tests/test_polish_stream.py.

Structure (per grid step, `_ROWS_PER_BLOCK` query rows):
  - candidate indices arrive as SMEM scalars (8-row blocked like the
    sweep kernel's candidate tables);
  - each row is ONE (1, LANE) DMA from the HBM-resident padded table
    into a VMEM slot row, issued back-to-back with a semaphore ring of
    depth `_PREFETCH_DEPTH` (4 GB/s per in-flight fetch at the sweep
    kernel's measured ~3.5 us DMA service time needs ~depth-16 to
    clear the XLA gather floor; 32 gives 2x margin and costs nothing —
    the slots are the output block itself, the ring is just
    semaphores);
  - one vector copy hands the landed block to the Pallas output
    pipeline.

Hardware risks, pre-recorded (no accelerator was reachable this round
— POLISH_r08.json carries the recipe):
  - bf16 dynamic sublane slicing is broken for VECTOR loads on this
    toolchain (patchmatch_tile.py module header); whether the DMA
    path shares the restriction is unverified.  Fallback, plan B: the
    table rows bitcast-pack to (Na, 64) f32 pairs on the XLA side
    (same bytes, f32 row DMA — the op class the sweep kernel ships)
    and unpack with two shift/bitcast VPU ops in the consumer.
  - per-row DMA issue rate: 256 B rows mean the fetch is
    issue-bound, not bandwidth-bound.  The kill criterion is stated
    on the RATE (tools/polish_stream_ab.py): the streamed polish
    ships only if its measured level-0 polish beats the cascade's.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128

# Semaphore-ring depth: how many row fetches are in flight at once.
# ≫ the sweep kernel's 6 on purpose — its fetches were 288 KB (DMA
# service time amortized over a large payload); these are 256 B, so
# only queue depth amortizes the per-DMA fixed cost.
_PREFETCH_DEPTH = 32

# Query rows gathered per grid step (one SMEM index row, one output
# block).  The per-step unrolled issue loop is `rows` long, so this
# also bounds kernel code size.
_ROWS_PER_BLOCK = 256


def prepare_polish_table(f_a_tab: jnp.ndarray) -> jnp.ndarray:
    """(Na, D<=LANE) table -> (Na, LANE) zero-col-padded copy the
    kernel DMAs whole rows from.  Zero pad columns are sliced back off
    by `candidate_dist{,_lean}` after the gather (their existing
    wider-A-than-B rule), so distances are bitwise unchanged.  The
    gathered row is LANE lanes either way — XLA's gather also moves
    the 128-lane-padded row — so padding here changes residency
    (~2x at the headline's D=68), not fetch bytes; the trade is
    recorded in POLISH_r08.json."""
    na, d = f_a_tab.shape
    if d == LANE:
        return f_a_tab
    if d > LANE:
        raise ValueError(f"feature width {d} > {LANE} lanes")
    return jnp.pad(f_a_tab, ((0, 0), (0, LANE - d)))


# Per-patch scale row cost of the int8 quantized table (round 11,
# stage 1): one f32 scale gathered per candidate row, dequantizing the
# row next to the distance math.  The scale is useful bytes — the
# distance sum consumes it — and rides the row fetch's pricing so the
# ledger stays one joinable pair per mode.
_SCALE_BYTES = 4


def polish_dma_bytes_per_fetch(
    d_useful: int, itemsize: int = 2, cand_dtype: str = "bf16"
) -> Tuple[int, int]:
    """(moved, useful) HBM bytes of ONE candidate-row fetch.

    `moved` is the whole 128-lane padded row every fetch transfers —
    identical for the streamed DMA and for XLA's gather lowering (both
    move the padded row; the streamed path changes the RATE, not the
    bytes).  `useful` is the unpadded feature width the distance sum
    consumes.  `cand_dtype="int8"` (round 11) prices the quantized
    table: itemsize-1 rows plus the per-patch f32 scale row each fetch
    dequantizes with (`_SCALE_BYTES`, counted on both sides — the
    scale is consumed) — 256 B bf16 rows become 132 B, a ~1.94x cut of
    the polish's dominant traffic term.  Widths past LANE price at the
    next 128-lane multiple (round 11: a (N, D) table lane-pads per
    128-lane tile; the STREAMED table stays capped at one lane block —
    prepare_polish_table — but the XLA take paths gather wide rows,
    the int8 take engine included).  The ONE byte model shared by
    the telemetry counters (`ia_polish_dma_bytes_total`), bench.py's
    `kernel_bytes_per_polish*` fields, and tools/check_polish.py —
    same discipline as `candidate_dma_bytes_per_fetch` (round 7)."""
    if d_useful <= 0:
        raise ValueError(f"d_useful {d_useful} must be positive")
    scale = _SCALE_BYTES if cand_dtype == "int8" else 0
    lanes = -(-d_useful // LANE) * LANE
    return lanes * itemsize + scale, d_useful * itemsize + scale


def quantize_rows(tab: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(N, D) feature table -> ((N, D) int8, (N, 1) f32 per-patch scale
    rows): symmetric per-row quantization q = round(x / s), s =
    max|row| / 127 — each patch's feature row keeps its own dynamic
    range (rows are windowed patch vectors with heterogeneous norms,
    unlike the A planes' globally-normalized images).  Dequant is
    q * s next to the distance math (models/patchmatch's polish
    gather_fn), so the error per element is bounded by s/2."""
    x = tab.astype(jnp.float32)
    s = jnp.maximum(
        jnp.max(jnp.abs(x), axis=1, keepdims=True), 1e-12
    ) * (1.0 / 127.0)
    q = jnp.clip(jnp.round(x / s), -127.0, 127.0).astype(jnp.int8)
    return q, s


def polish_eval_rows(
    n_queries: int, iters: int, n_random: int
) -> int:
    """Candidate-row evaluations of one polish call: the entry
    re-evaluation plus, per sweep, 4 shifted + 4 unshifted propagation
    candidates and `n_random` shrinking-radius probes — the sequential
    cascade's exact gather count (models/patchmatch.py
    patchmatch_sweeps{,_lean}), which the streamed path reproduces
    fetch-for-fetch (same candidates, same order)."""
    return n_queries * (1 + iters * (8 + n_random))


def _make_gather_kernel(rows: int, depth: int):
    """Row-gather kernel body: `rows` single-row DMAs from the HBM
    table into the VMEM slot block, issued through a depth-`depth`
    semaphore ring (fetch q waits on fetch q-depth before reusing its
    semaphore — at most `depth` in flight, exactly the sweep kernel's
    slot discipline with the slot buffer replaced by distinct output
    rows, so no fetch ever overwrites an unconsumed one)."""

    def kernel(idx_ref, a_ref, out_ref, slots_ref, sems_ref):
        i = pl.program_id(0)
        row = i % 8  # 8-row SMEM blocking, as in the sweep kernel

        def copy_for(q):
            r = idx_ref[row, q]
            return pltpu.make_async_copy(
                a_ref.at[pl.ds(r, 1), :],
                slots_ref.at[pl.ds(q, 1), :],
                sems_ref.at[q % depth],
            )

        for q in range(rows):
            if q >= depth:
                # The ring slot comes free when fetch q-depth lands
                # ((q-depth) % depth == q % depth); its target row is
                # distinct from ours, so waiting here only sequences
                # the SEMAPHORE, not the data.
                copy_for(q - depth).wait()
            copy_for(q).start()
        for q in range(max(0, rows - depth), rows):
            copy_for(q).wait()
        out_ref[:] = slots_ref[:]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("rows", "interpret")
)
def _gather_rows_jit(f_a_pad, idx2, *, rows: int, interpret: bool):
    n_blocks = idx2.shape[0]
    pad8 = (-n_blocks) % 8
    if pad8:
        idx2 = jnp.pad(idx2, ((0, pad8), (0, 0)))
    kernel = _make_gather_kernel(rows, _PREFETCH_DEPTH)
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            # Index rows in SMEM, blocked 8 grid steps at a time (the
            # sweep kernel's candidate-table pattern: Mosaic wants
            # equal-dividing SMEM blocks, and 8 rows keeps the window
            # tiny at any M).
            pl.BlockSpec(
                (8, rows), lambda i: (i // 8, 0),
                memory_space=pltpu.SMEM,
            ),
            # The padded table stays in HBM; every fetch is an
            # explicit row DMA from it.
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((rows, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n_blocks * rows, LANE), f_a_pad.dtype
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, LANE), f_a_pad.dtype),
            pltpu.SemaphoreType.DMA((_PREFETCH_DEPTH,)),
        ],
        interpret=interpret,
    )(idx2, f_a_pad)


def gather_rows(
    f_a_pad: jnp.ndarray,
    idx: jnp.ndarray,
    *,
    interpret: bool = False,
    useful_width: Optional[int] = None,
    rows_per_block: Optional[int] = None,
    cand_dtype: str = "bf16",
) -> jnp.ndarray:
    """DMA-streamed row gather: rows `idx` (any shape, flattened) of
    the (Na, LANE) padded table, returned as (idx.size, LANE) in
    `idx` order — the drop-in replacement for
    `jnp.take(f_a_pad, idx.reshape(-1), axis=0)` behind the
    `gather_fn` hook of models/matcher.candidate_dist{,_lean}.

    `useful_width` (the unpadded feature width) feeds the trace-time
    `ia_polish_dma_bytes_total` counter; None counts the whole row as
    useful.  `cand_dtype` labels and prices the counters: "int8"
    (round 11, the quantized table) adds the per-patch scale row each
    fetch dequantizes with to BOTH sides of the pricing — the caller
    gathers the scales beside this kernel's rows (one site owns the
    whole mode's ledger, so counter and model cannot drift).
    Out-of-range indices are clamped (callers already clip —
    this mirrors jnp.take's TPU clamp semantics defensively)."""
    from ..telemetry.metrics import (
        count_polish_dma_bytes,
        count_polish_dma_rows,
    )

    if f_a_pad.shape[1] != LANE:
        raise ValueError(
            f"table must be LANE-padded (got {f_a_pad.shape}); "
            "run prepare_polish_table first"
        )
    flat = idx.reshape(-1).astype(jnp.int32)
    m = flat.shape[0]
    rows = rows_per_block or _ROWS_PER_BLOCK
    rows = min(rows, max(8, m))
    n_blocks = -(-m // rows)
    moved_b, useful_b = polish_dma_bytes_per_fetch(
        useful_width if useful_width is not None else LANE,
        jnp.dtype(f_a_pad.dtype).itemsize,
        cand_dtype,
    )
    count_polish_dma_bytes(
        useful=m * useful_b, padded=m * (moved_b - useful_b),
        dtype=cand_dtype,
    )
    # Structural twin: row count + fetch pricing, so the run sentinel
    # can recompute the expected bytes from the shared model
    # (telemetry/sentinel.py polish-DMA check).
    count_polish_dma_rows(
        m,
        useful_width if useful_width is not None else LANE,
        jnp.dtype(f_a_pad.dtype).itemsize,
        cand_dtype,
    )
    pad = n_blocks * rows - m
    if pad:
        flat = jnp.pad(flat, (0, pad))  # row 0: harmless, sliced off
    flat = jnp.clip(flat, 0, f_a_pad.shape[0] - 1)
    out = _gather_rows_jit(
        f_a_pad, flat.reshape(n_blocks, rows), rows=rows,
        interpret=interpret,
    )
    return out[:m] if pad else out
