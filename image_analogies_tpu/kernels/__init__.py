"""Pallas TPU kernels (SURVEY.md §2 C7/C9/C10, §3.3).

Kernel selection contract: every kernel here has a pure-XLA twin in
`models/` with identical semantics (same argmin tie-breaking, same
metric).  `resolve_pallas(cfg)` decides per call site whether to run the
Pallas kernel compiled, interpreted (CPU tests — catches OOB indexing,
SURVEY.md §5 "race detection/sanitizers"), or not at all:

  - cfg.pallas_mode == "auto":      compiled kernels iff a TPU backs the
                                    computation; XLA twin otherwise (CPU,
                                    GPU — the kernels use pltpu memory
                                    spaces and TPU sequential-grid
                                    accumulation, so only TPU qualifies).
  - cfg.pallas_mode == "off":       always the XLA twin.
  - cfg.pallas_mode == "interpret": Pallas in interpreter mode (tests).
"""

from __future__ import annotations

from typing import Optional

# Platform names that run Mosaic-compiled kernels.  "axon" is the
# tunnelled v5e PJRT platform in this environment (SURVEY.md §7).
_TPU_PLATFORMS = ("tpu", "axon")


def _computation_platform() -> str:
    """Platform of the device that will back newly-traced computations.

    Honors a `jax.default_device(...)` override (e.g. bench.py's CPU
    oracle phase on a TPU host) before falling back to the process-wide
    default backend.  Evaluated per call — no caching — so platform
    changes (`jax.config.update("jax_platforms", ...)`) are respected.
    """
    import jax

    try:
        default = jax.config.jax_default_device
        if default is not None:
            # jax.default_device accepts a Device or a platform-name str.
            return default if isinstance(default, str) else default.platform
        return jax.default_backend()
    except RuntimeError:
        return "cpu"


def resolve_pallas(cfg) -> Optional[bool]:
    """None = use XLA twin; False = compiled Pallas; True = interpreted."""
    mode = cfg.pallas_mode
    if mode == "off":
        return None
    if mode == "interpret":
        return True
    if mode == "auto":
        return False if _computation_platform() in _TPU_PLATFORMS else None
    raise ValueError(f"unknown pallas_mode {mode!r}")
