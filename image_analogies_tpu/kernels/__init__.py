"""Pallas TPU kernels (SURVEY.md C9): filled in by kernels modules."""
