"""Streaming exact-NN Pallas kernel (SURVEY.md §2 C7, §3.3).

The brute-force matcher's hot loop is `argmin_p ||f_b[q] - f_a[p]||^2`.
The XLA formulation (models/brute.py) computes it as chunked distance
tiles that round-trip through HBM.  This kernel is the TPU-native
streaming version: the grid walks (query-tile, A-tile) pairs, each step
does one (TQ, D) x (D, TA) contraction on the MXU and folds the tile's
row-minima into a VMEM accumulator — the (N_B, N_A) distance matrix is
never materialized anywhere.  TPU grids execute sequentially, so the
scratch accumulator carries the running (best distance, best index) per
query across all A tiles [pallas_guide: scratch + grid accumulation].

Distances use the expansion ||a||^2 - 2 b.a (the ||b||^2 term is constant
per query row and cannot change the argmin).  Tie-breaking is
lowest-flat-index, matching `jnp.argmin` in the XLA path bit-for-bit so
the two backends are interchangeable oracles.

Feature rows are zero-padded to lane multiples (128) and A rows to tile
multiples with +inf guard distances, so arbitrary (N, D) shapes tile
cleanly onto the 128x128 systolic array.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: TQ query rows x TA database rows per grid step.  (256, 512)
# keeps the f32 operand tiles (TQ*D + TA*D + TQ*TA) well under VMEM for
# D <= 512 while saturating the MXU.
_TQ = 256
_TA = 512

# Grid-size ceiling per pallas_call.  The axon TPU worker reproducibly
# crashes on very large sequential grids (measured 2026-07-30: the
# ~134M-step grid of a full 2048^2 all-pairs call kills the worker,
# while the 8.4M-step 1024^2 grid runs routinely).  Queries are chunked
# across multiple pallas_call invocations so no single grid exceeds
# this; 16M sits between the proven-safe 8.4M and the crashing 134M
# with margin on the safe side of the failure, and was validated by the
# round-4 full-synthesis 2048^2 oracle run (SCALE_r04).
_MAX_GRID_STEPS = 16_000_000


def _make_nn_kernel(ta: int):
    """Kernel closure over the A-tile row count (needed for the global
    index offset j * ta)."""

    def _nn_kernel(fb_ref, fa_ref, asq_ref, idx_ref, dist_ref, best_d,
                   best_i):
        """One (query-tile i, A-tile j) grid step."""
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            best_d[:] = jnp.full_like(best_d, jnp.inf)
            best_i[:] = jnp.zeros_like(best_i)

        # (TQ, D) x (D, TA) on the MXU; f32 accumulation.
        cross = jax.lax.dot_general(
            fb_ref[:],
            fa_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = asq_ref[:] - 2.0 * cross  # (TQ, TA); asq broadcasts (1, TA)

        local_min = jnp.min(d, axis=1, keepdims=True)  # (TQ, 1)
        local_arg = (
            jnp.argmin(d, axis=1).astype(jnp.int32)[:, None] + j * ta
        )

        better = local_min < best_d[:]
        best_i[:] = jnp.where(better, local_arg, best_i[:])
        best_d[:] = jnp.where(better, local_min, best_d[:])

        @pl.when(j == n_j - 1)
        def _write():
            idx_ref[:] = best_i[:]
            dist_ref[:] = best_d[:]

    return _nn_kernel


@functools.partial(
    jax.jit, static_argnames=("match_dtype", "interpret", "tq", "ta")
)
def exact_nn_pallas(
    f_b_flat: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    match_dtype=jnp.float32,
    interpret: bool = False,
    tq: int = _TQ,
    ta: int = _TA,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact NN via the streaming kernel; mirrors `brute.exact_nn`.

    Returns (idx (N,), dist (N,)) with `dist` recomputed exactly (direct
    subtraction in f32) for the winning rows, like the XLA path, so the
    kappa accept tests downstream see a cancellation-free metric.

    `tq`/`ta` override the query/database tile rows.  The kernel's HBM
    traffic is |B| + (N_B/tq) * |A| — the whole A table streams through
    VMEM once per query tile — so giant-A calls (the full-synthesis
    2048^2 oracle, the 4096^2 stratified probe) want the largest tq the
    (tq, ta) f32 distance tile leaves VMEM room for: (4096, 256) puts
    the distance tile at 4 MB and cuts A re-streaming 16x vs the
    (256, 512) default, which stays optimal for the small-N calls the
    synthesis pipeline makes.
    """
    n, d_feat = f_b_flat.shape
    n_a = f_a_flat.shape[0]
    match_dtype = jnp.dtype(match_dtype)

    # Pad D to lanes, N_B/N_A to tile multiples.
    d_pad = (-d_feat) % 128
    q_pad = (-n) % tq
    a_pad = (-n_a) % ta
    fb = jnp.pad(f_b_flat, ((0, q_pad), (0, d_pad))).astype(match_dtype)
    fa = jnp.pad(f_a_flat, ((0, a_pad), (0, d_pad))).astype(match_dtype)
    # ||a||^2 in f32; +inf on padded rows so they never win the argmin.
    a_sq = jnp.sum(
        f_a_flat.astype(jnp.float32) ** 2, axis=-1
    )
    a_sq = jnp.pad(a_sq, (0, a_pad), constant_values=jnp.inf)[None, :]

    grid_a = fa.shape[0] // ta
    # Chunk the query axis so no single pallas_call's grid exceeds
    # _MAX_GRID_STEPS (the ~134M-step full 2048^2 grid crashed the TPU
    # worker — see the constant above).  A-tiles never need chunking:
    # grid_a alone exceeding the cap would take an N_A beyond any
    # supported image.  Chunks are equal-sized (fb re-padded up to a
    # chunk multiple) so one compiled kernel serves every chunk.
    q_tiles = fb.shape[0] // tq
    chunk_tiles = max(1, min(q_tiles, _MAX_GRID_STEPS // grid_a))
    n_chunks = -(-q_tiles // chunk_tiles)
    chunk_rows = chunk_tiles * tq
    fb = jnp.pad(fb, ((0, n_chunks * chunk_rows - fb.shape[0]), (0, 0)))

    def one_chunk(fb_chunk):
        return pl.pallas_call(
            _make_nn_kernel(ta),
            grid=(chunk_tiles, grid_a),
            in_specs=[
                pl.BlockSpec(
                    (tq, fb_chunk.shape[1]), lambda i, j: (i, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (ta, fa.shape[1]), lambda i, j: (j, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ta), lambda i, j: (0, j), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=[
                pl.BlockSpec(
                    (tq, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (tq, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((fb_chunk.shape[0], 1), jnp.int32),
                jax.ShapeDtypeStruct((fb_chunk.shape[0], 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((tq, 1), jnp.float32),
                pltpu.VMEM((tq, 1), jnp.int32),
            ],
            interpret=interpret,
        )(fb_chunk, fa, a_sq)

    if n_chunks == 1:
        idx = one_chunk(fb)[0]
    else:
        idx = jnp.concatenate(
            [
                one_chunk(
                    jax.lax.slice(
                        fb, (c * chunk_rows, 0),
                        ((c + 1) * chunk_rows, fb.shape[1]),
                    )
                )[0]
                for c in range(n_chunks)
            ],
            axis=0,
        )

    idx = idx[:n, 0]
    # Exact winner distance (direct subtraction, f32), immune to the
    # ||a||^2 - 2ab expansion's cancellation error.
    rows = jnp.take(f_a_flat, idx, axis=0)
    diff = f_b_flat.astype(jnp.float32) - rows.astype(jnp.float32)
    return idx, jnp.sum(diff * diff, axis=-1)
