"""Streaming exact-NN Pallas kernel (SURVEY.md §2 C7, §3.3).

The brute-force matcher's hot loop is `argmin_p ||f_b[q] - f_a[p]||^2`.
The XLA formulation (models/brute.py) computes it as chunked distance
tiles that round-trip through HBM.  This kernel is the TPU-native
streaming version: the grid walks (query-tile, A-tile) pairs, each step
does one (TQ, D) x (D, TA) contraction on the MXU and folds the tile's
row-minima into a VMEM accumulator — the (N_B, N_A) distance matrix is
never materialized anywhere.  TPU grids execute sequentially, so the
scratch accumulator carries the running (best distance, best index) per
query across all A tiles [pallas_guide: scratch + grid accumulation].

Distances use the expansion ||a||^2 - 2 b.a (the ||b||^2 term is constant
per query row and cannot change the argmin).  Tie-breaking is
lowest-flat-index, matching `jnp.argmin` in the XLA path bit-for-bit so
the two backends are interchangeable oracles.

Feature rows are zero-padded to lane multiples (128) and A rows to tile
multiples with +inf guard distances, so arbitrary (N, D) shapes tile
cleanly onto the 128x128 systolic array.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: TQ query rows x TA database rows per grid step.  (256, 512)
# keeps the f32 operand tiles (TQ*D + TA*D + TQ*TA) well under VMEM for
# D <= 512 while saturating the MXU.
_TQ = 256
_TA = 512


def _nn_kernel(fb_ref, fa_ref, asq_ref, idx_ref, dist_ref, best_d, best_i):
    """One (query-tile i, A-tile j) grid step."""
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        best_d[:] = jnp.full_like(best_d, jnp.inf)
        best_i[:] = jnp.zeros_like(best_i)

    # (TQ, D) x (D, TA) on the MXU; f32 accumulation.
    cross = jax.lax.dot_general(
        fb_ref[:],
        fa_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    d = asq_ref[:] - 2.0 * cross  # (TQ, TA); asq broadcasts from (1, TA)

    local_min = jnp.min(d, axis=1, keepdims=True)  # (TQ, 1)
    local_arg = jnp.argmin(d, axis=1).astype(jnp.int32)[:, None] + j * _TA

    better = local_min < best_d[:]
    best_i[:] = jnp.where(better, local_arg, best_i[:])
    best_d[:] = jnp.where(better, local_min, best_d[:])

    @pl.when(j == n_j - 1)
    def _write():
        idx_ref[:] = best_i[:]
        dist_ref[:] = best_d[:]


@functools.partial(
    jax.jit, static_argnames=("match_dtype", "interpret")
)
def exact_nn_pallas(
    f_b_flat: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    match_dtype=jnp.float32,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact NN via the streaming kernel; mirrors `brute.exact_nn`.

    Returns (idx (N,), dist (N,)) with `dist` recomputed exactly (direct
    subtraction in f32) for the winning rows, like the XLA path, so the
    kappa accept tests downstream see a cancellation-free metric.
    """
    n, d_feat = f_b_flat.shape
    n_a = f_a_flat.shape[0]
    match_dtype = jnp.dtype(match_dtype)

    # Pad D to lanes, N_B/N_A to tile multiples.
    d_pad = (-d_feat) % 128
    q_pad = (-n) % _TQ
    a_pad = (-n_a) % _TA
    fb = jnp.pad(f_b_flat, ((0, q_pad), (0, d_pad))).astype(match_dtype)
    fa = jnp.pad(f_a_flat, ((0, a_pad), (0, d_pad))).astype(match_dtype)
    # ||a||^2 in f32; +inf on padded rows so they never win the argmin.
    a_sq = jnp.sum(
        f_a_flat.astype(jnp.float32) ** 2, axis=-1
    )
    a_sq = jnp.pad(a_sq, (0, a_pad), constant_values=jnp.inf)[None, :]

    grid = (fb.shape[0] // _TQ, fa.shape[0] // _TA)
    idx, dist = pl.pallas_call(
        _nn_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_TQ, fb.shape[1]), lambda i, j: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_TA, fa.shape[1]), lambda i, j: (j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, _TA), lambda i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            pl.BlockSpec((_TQ, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TQ, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((fb.shape[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((fb.shape[0], 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TQ, 1), jnp.float32),
            pltpu.VMEM((_TQ, 1), jnp.int32),
        ],
        interpret=interpret,
    )(fb, fa, a_sq)

    idx = idx[:n, 0]
    # Exact winner distance (direct subtraction, f32), immune to the
    # ||a||^2 - 2ab expansion's cancellation error.
    rows = jnp.take(f_a_flat, idx, axis=0)
    diff = f_b_flat.astype(jnp.float32) - rows.astype(jnp.float32)
    return idx, jnp.sum(diff * diff, axis=-1)
