"""Streaming exact-NN Pallas kernel (SURVEY.md §2 C7, §3.3).

The brute-force matcher's hot loop is `argmin_p ||f_b[q] - f_a[p]||^2`.
The XLA formulation (models/brute.py) computes it as chunked distance
tiles that round-trip through HBM.  This kernel is the TPU-native
streaming version: the grid walks (query-tile, A-tile) pairs, each step
does one (TQ, D) x (D, TA) contraction on the MXU and folds the tile's
row-minima into a VMEM accumulator — the (N_B, N_A) distance matrix is
never materialized anywhere.  TPU grids execute sequentially, so the
scratch accumulator carries the running (best distance, best index) per
query across all A tiles [pallas_guide: scratch + grid accumulation].

Distances use the expansion ||a||^2 - 2 b.a (the ||b||^2 term is constant
per query row and cannot change the argmin).  Tie-breaking is
lowest-flat-index, matching `jnp.argmin` in the XLA path bit-for-bit so
the two backends are interchangeable oracles.

Feature rows are zero-padded to lane multiples (128) and A rows to tile
multiples with +inf guard distances, so arbitrary (N, D) shapes tile
cleanly onto the 128x128 systolic array.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: TQ query rows x TA database rows per grid step.  (256, 512)
# keeps the f32 operand tiles (TQ*D + TA*D + TQ*TA) well under VMEM for
# D <= 512 while saturating the MXU.
_TQ = 256
_TA = 512

# Work ceiling per device EXECUTION, in distance-tile elements
# (grid_steps * tq * ta) — a wall-clock proxy that normalizes across
# tile sizes where a raw step count does not (per-step work is tq*ta).
# The axon TPU worker reproducibly kills long-running executions, and
# the boundary is per XLA execution, not per pallas_call: one
# 4.4e12-element call (~100 s, 2026-07-31) crashes it, and so does one
# jit containing four sequential 1.2e12-element pallas_calls (~110 s
# total, same day) — while single executions up to ~2.2e12 elements
# (~25-50 s: the 1024^2 all-pairs call on either tile geometry, and
# the fused 1024^2 brute oracle level) complete routinely.  Query
# chunks must therefore be SEPARATE executions: `exact_nn_pallas` is
# deliberately NOT jitted at the top level, so when called eagerly
# (the scale probes, the eager oracle levels) each chunk dispatches on
# its own and stays in the proven-safe regime.  Callers that trace it
# into a larger jit own the enclosing execution's budget — the driver
# un-fuses brute levels whose search exceeds it
# (models/analogy.py _SAFE_EXEC_DIST_ELEMS).  Validated by the
# round-4 full-synthesis 2048^2 oracle run (SCALE_r04).
_MAX_TILE_ELEMS = 1_200_000_000_000

# Grid dimensions must stay CLEARLY below 2^16 steps: a pallas_call
# whose A-axis grid hit exactly 65536 steps wedged the worker session
# indefinitely — no error, no progress, client asleep on a futex —
# while 16384/32768/49152-step grids ran normally (measured
# 2026-07-31, tools/_oracle_out probes; the 4096^2 oracle's
# N_A=16.8M / ta=256 landed exactly on the boundary).  `exact_nn_pallas`
# rescales (tq, ta) to keep every grid dim under this cap.
_MAX_GRID_DIM = 49152


def _make_nn_kernel(ta: int):
    """Kernel closure over the A-tile row count (needed for the global
    index offset j * ta)."""

    def _nn_kernel(fb_ref, fa_ref, asq_ref, idx_ref, dist_ref, best_d,
                   best_i):
        """One (query-tile i, A-tile j) grid step."""
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            best_d[:] = jnp.full_like(best_d, jnp.inf)
            best_i[:] = jnp.zeros_like(best_i)

        # (TQ, D) x (D, TA) on the MXU; f32 accumulation.
        cross = jax.lax.dot_general(
            fb_ref[:],
            fa_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = asq_ref[:] - 2.0 * cross  # (TQ, TA); asq broadcasts (1, TA)

        local_min = jnp.min(d, axis=1, keepdims=True)  # (TQ, 1)
        local_arg = (
            jnp.argmin(d, axis=1).astype(jnp.int32)[:, None] + j * ta
        )

        better = local_min < best_d[:]
        best_i[:] = jnp.where(better, local_arg, best_i[:])
        best_d[:] = jnp.where(better, local_min, best_d[:])

        @pl.when(j == n_j - 1)
        def _write():
            idx_ref[:] = best_i[:]
            dist_ref[:] = best_d[:]

    return _nn_kernel


@functools.partial(
    jax.jit, static_argnames=("tq", "ta", "interpret")
)
def _nn_chunk_call(fb_chunk, fa, a_sq, tq: int, ta: int, interpret: bool):
    """One query chunk's streaming search as its own jitted call — ONE
    device execution per chunk when the caller runs eagerly (see
    _MAX_TILE_ELEMS: the worker's kill boundary is per execution)."""
    grid_a = fa.shape[0] // ta
    chunk_tiles = fb_chunk.shape[0] // tq
    return pl.pallas_call(
        _make_nn_kernel(ta),
        grid=(chunk_tiles, grid_a),
        in_specs=[
            pl.BlockSpec(
                (tq, fb_chunk.shape[1]), lambda i, j: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ta, fa.shape[1]), lambda i, j: (j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ta), lambda i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (tq, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (tq, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((fb_chunk.shape[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((fb_chunk.shape[0], 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tq, 1), jnp.float32),
            pltpu.VMEM((tq, 1), jnp.int32),
        ],
        interpret=interpret,
    )(fb_chunk, fa, a_sq)


def exact_nn_pallas(
    f_b_flat: jnp.ndarray,
    f_a_flat: jnp.ndarray,
    match_dtype=jnp.float32,
    interpret: bool = False,
    tq: int = _TQ,
    ta: int = _TA,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact NN via the streaming kernel; mirrors `brute.exact_nn`.

    Returns (idx (N,), dist (N,)) with `dist` recomputed exactly (direct
    subtraction in f32) for the winning rows, like the XLA path, so the
    kappa accept tests downstream see a cancellation-free metric.

    `tq`/`ta` override the query/database tile rows.  The kernel's HBM
    traffic is |B| + (N_B/tq) * |A| — the whole A table streams through
    VMEM once per query tile — so giant-A calls (the full-synthesis
    2048^2 oracle, the 4096^2 stratified probe) want the largest tq
    that compiles: the scoped-VMEM footprint is ~5x the (tq, ta) f32
    distance tile (Mosaic keeps the cross product, the distance tile,
    and the select temporaries live at once), so at D=128 bf16 the
    ceiling is (2048, 256) — (3072+, 256) exceeds the 16 MB scoped
    limit (measured 2026-07-31: 22.26 MB at tq=4096).  (2048, 256)
    cuts A re-streaming 8x vs the (256, 512) default, which stays
    optimal for the small-N calls the synthesis pipeline makes.
    """
    from ..telemetry.metrics import count_kernel_launch

    count_kernel_launch("exact_nn")  # trace-time count (see helper)

    n, d_feat = f_b_flat.shape
    n_a = f_a_flat.shape[0]
    match_dtype = jnp.dtype(match_dtype)

    # Keep the A-axis grid under _MAX_GRID_DIM (65536-step grids wedge
    # the worker — see the constant).  Doubling ta while halving tq
    # keeps the per-step tile elements and the scoped-VMEM footprint
    # constant, so any compiling (tq, ta) pair stays compiling.
    while n_a // ta > _MAX_GRID_DIM and tq >= 16:
        ta *= 2
        tq = max(tq // 2, 8)
    if n_a // ta > _MAX_GRID_DIM:
        # ADVICE r4: the rescale loop exits once tq bottoms out, so an
        # extreme N_A (~8e8+ rows at default tiles) could still land
        # the A-axis grid on the 2^16 wedge boundary — fail loudly
        # instead of hanging the worker session.
        raise ValueError(
            f"exact_nn_pallas: A-axis grid {n_a // ta} exceeds the "
            f"{_MAX_GRID_DIM} wedge cap even at ta={ta} (N_A={n_a}); "
            "split the A table (lean-brute B-banding splits B, not A) "
            "or pass a larger ta explicitly"
        )

    # Pad D to lanes, N_B/N_A to tile multiples.  Pads and casts are
    # conditional: when the caller's tables are already tile-shaped and
    # in the match dtype (the lean-brute oracle pre-shapes its bf16
    # tables exactly so), no working copy is made — at 4096^2 an
    # unconditional pad+cast would co-host ~8.6 GB of dead copies next
    # to the resident tables.
    d_pad = (-d_feat) % 128
    q_pad = (-n) % tq
    a_pad = (-n_a) % ta
    fb = f_b_flat
    if q_pad or d_pad:
        fb = jnp.pad(fb, ((0, q_pad), (0, d_pad)))
    fb = fb.astype(match_dtype)
    fa = f_a_flat
    if a_pad or d_pad:
        fa = jnp.pad(fa, ((0, a_pad), (0, d_pad)))
    fa = fa.astype(match_dtype)
    # ||a||^2 in f32; +inf on padded rows so they never win the argmin.
    # Chunked: one whole-table f32 upcast of a giant A side (the 4096^2
    # probe's (16.8M, 128) bf16 table) peaks at 2 x 8.6 GB of temps.
    sq_rows = max(1, (256 << 20) // max(1, d_feat * 4))
    sq_parts = []
    for c in range(0, n_a, sq_rows):
        blk = f_a_flat[c : c + sq_rows].astype(jnp.float32)
        sq_parts.append(jnp.sum(blk * blk, axis=-1))
    a_sq = (
        sq_parts[0] if len(sq_parts) == 1
        else jnp.concatenate(sq_parts, axis=0)
    )
    a_sq = jnp.pad(a_sq, (0, a_pad), constant_values=jnp.inf)[None, :]

    grid_a = fa.shape[0] // ta
    # Chunk the query axis so no single device execution exceeds
    # _MAX_TILE_ELEMS of distance-tile work (long-running executions
    # crash the TPU worker — see the constant above).  This function
    # is NOT jitted: called eagerly, each chunk's `_nn_chunk_call` is
    # its own execution, which is the point; traced inside a caller's
    # jit, the loop inlines and the caller owns the execution budget.
    # A-tiles never need chunking: grid_a alone exceeding the cap
    # would take an N_A beyond any supported image.  Chunks are
    # equal-sized (fb re-padded up to a chunk multiple) so one
    # compiled kernel serves every chunk.
    q_tiles = fb.shape[0] // tq
    max_steps = max(1, _MAX_TILE_ELEMS // (tq * ta))
    # The query-axis grid dim must ALSO stay under _MAX_GRID_DIM (a
    # small-A / giant-B call could otherwise budget a 65536-tile query
    # chunk and land the OTHER grid dim on the wedge boundary).
    chunk_tiles = max(
        1, min(q_tiles, max_steps // grid_a, _MAX_GRID_DIM)
    )
    # Prefer the largest clean divisor within 2x of the budgeted chunk:
    # an uneven split pads fb up to a chunk multiple, and at giant-N
    # (the 4096^2 oracle: 16.8M rows) that pad is a 4.3 GB working
    # copy next to the resident tables for nothing.  Divisor chunks are
    # strictly smaller, so the per-execution budget still holds.
    for ct in range(chunk_tiles, max(chunk_tiles // 2, 1) - 1, -1):
        if q_tiles % ct == 0:
            chunk_tiles = ct
            break
    n_chunks = -(-q_tiles // chunk_tiles)
    chunk_rows = chunk_tiles * tq
    tail = n_chunks * chunk_rows - fb.shape[0]
    if tail:
        fb = jnp.pad(fb, ((0, tail), (0, 0)))

    if n_chunks == 1:
        idx = _nn_chunk_call(fb, fa, a_sq, tq, ta, interpret)[0]
    else:
        idx = jnp.concatenate(
            [
                _nn_chunk_call(
                    jax.lax.slice(
                        fb, (c * chunk_rows, 0),
                        ((c + 1) * chunk_rows, fb.shape[1]),
                    ),
                    fa, a_sq, tq, ta, interpret,
                )[0]
                for c in range(n_chunks)
            ],
            axis=0,
        )

    idx = idx[:n, 0]
    # The padded/cast working copies are dead past this point; drop the
    # references eagerly — at giant-A sizes (the 2048^2 oracle: two
    # 2.1 GB f32 tables resident in the caller) the re-rank below must
    # not co-reside with another ~2.2 GB of bf16 copies.
    del fb, fa, a_sq
    # Exact winner distance (direct subtraction, f32), immune to the
    # ||a||^2 - 2ab expansion's cancellation error.  Chunked so the
    # co-resident gathered-rows + diff temps peak at ~512 MB (2 x
    # 256 MiB f32 blocks) instead of 2x the full table.
    rerank_rows = max(1, (256 << 20) // max(1, d_feat * 4))
    dists = []
    for c in range(0, n, rerank_rows):
        sl = idx[c : c + rerank_rows]
        rows = jnp.take(f_a_flat, sl, axis=0).astype(jnp.float32)
        diff = f_b_flat[c : c + rerank_rows].astype(jnp.float32) - rows
        dists.append(jnp.sum(diff * diff, axis=-1))
    dist = dists[0] if len(dists) == 1 else jnp.concatenate(dists, axis=0)
    return idx, dist
