"""Pallas PatchMatch propagate + random-search kernel (SURVEY.md §2 C9+C10,
§3.3 — the centerpiece kernel the north star prescribes).

TPU reformulation
-----------------
GPU/CPU PatchMatch evaluates per-pixel candidate matches with random
gathers.  Mosaic's gather support is a single vreg along the gather
dimension (verified on this toolchain: `tpu.dynamic_gather` rejects larger
tables with "Multiple source vregs along gather dimension"), so per-pixel
gathers cannot be the TPU kernel's inner loop.  This kernel restructures
the algorithm around what the hardware is good at (SURVEY.md §7 "TPU hates
divergence"):

  - **Tile-shared candidates.**  Each 64x124 B'-tile evaluates K candidate
    *offsets* shared by every pixel in the tile.  Candidate evaluation for
    one offset is then a *dense* windowed-SSD between the B-tile and one
    contiguous slice of A — vector ops, no divergence, no gather.  The
    per-pixel NN-field still emerges: every pixel argmins over the K
    candidates independently, and candidates are resampled from the
    per-pixel state each sweep.
  - **Raw planes, not feature vectors.**  Distances are computed from the
    raw (source, filtered, upsampled-coarse) image planes with the
    separable Gaussian window applied in-kernel, so the A-side is C
    planes of (Ha, Wa) f32 instead of a (Ha*Wa, D) feature table (200 MB
    at 1024^2).  Planes are f32, not bf16: Mosaic on this toolchain
    cannot dynamically slice bf16 arrays on sublane dims at all
    (vector.load internal error even 8-aligned — verified).
  - **A stays in HBM; candidate slices stream in by DMA.**  The A planes
    are ONE HBM-resident operand (`memory_space=ANY`); each candidate's
    all-channel window is fetched into a double-buffered VMEM slot with
    `pltpu.make_async_copy`, prefetched one candidate ahead so the DMA
    hides behind the previous candidate's arithmetic.  Since round 7
    the default layout is PACKED (Hp, Wq, 2C, 128): sublane 2c+b of
    entry q holds lane-block q+b of channel c, so ONE (thp, 1, 2C, 128)
    DMA carries both lane blocks of every channel and — at the
    headline's 4 channels — every fetched sublane is useful data.  The
    round-4/5 layout ((Hp, Wq, C, 128), a (thp, 2, C→8-pad, 128) fetch
    whose sublane pad was half the moved bytes at C=4 — VERDICT r5
    "missing 2") remains selectable (`packed=False` /
    IA_A_PLANE_LAYOUT=unpacked) as the measured fallback should Mosaic
    reject the packed unpack on a future toolchain.  Rounds 1-3
    instead kept a whole A row-band
    VMEM-resident and called the sweep once per band; measured 2026-07-31
    (README kernel log), that design was PIPELINE-bound, not
    compute-bound: every band call re-streamed all B channel tiles and
    6 state planes, so a 3-band 1024^2 sweep spent 12.3 of its 12.9 ms
    moving tiles (copy-only kernel body) and a 17-band 4096^2 sweep paid
    the restream 17x.  With A in HBM there is exactly one sweep call per
    pm iteration at EVERY size, the B/state streaming happens once, and
    the channel plan no longer shrinks at large sizes — 2048^2/4096^2
    get the full coarse channel set back.  The banded path (ownership
    bounds + per-band calls) remains available behind an explicit
    budget for the spatially-sharded-A runner, where each device owns an
    A row range by construction.
  - **Lane alignment via dynamic rotate.**  Mosaic cannot dynamically
    slice the lane (minor) dimension at unaligned offsets.  A-planes
    store whole 128-lane blocks; a candidate column range [sx, sx+128)
    is materialized from the two adjacent blocks (sublane pair 2c/2c+1
    of the packed slot, or the 2-block axis of the unpacked one) with
    `pltpu.roll` (tpu.dynamic_rotate) + an iota select.
  - **Window sums on the MXU.**  The separable 5x5 window sum is two
    banded-matrix contractions: along lanes `xs = dq @ Wx` with a banded
    (LANE, LANE) weight matrix, along sublanes `d += Wy @ xs` with a
    banded (THP, THP) one — systolic-array work instead of the 10 serial
    VPU roll+mul+add passes per channel the round-3 kernel used (which
    held it at 7.3% of VPU peak with the MXU idle).  Channels sharing a
    window spec (fine vs dilated-coarse) are summed *before* the
    contraction, so a 4-channel candidate costs 4 diff-square passes and
    4 matmuls total.  The banded matrices clip at tile edges rather than
    wrapping like the rolls did; interior pixels (the only ones
    `from_blocked` keeps and the only ones sampled) are bit-identical
    because the halo always covers the window reach.
  - **Candidate generation stays in XLA.**  Sampling offsets from the
    NN-field state (own-tile samples = Ashikhmin coherence candidates,
    neighbor-tile samples = PatchMatch propagation, shrinking-radius
    perturbations = Barnes random search) is integer work on tiny
    (n_tiles, K) tensors — XLA does it between kernel sweeps, which also
    keeps PRNG in ordinary `jax.random` (deterministic under fixed keys).

The kernel is the bulk global-search engine; `models/patchmatch.py` merges
its result with the incoming field under the exact feature metric and runs
one per-pixel XLA polish sweep, so the matcher's output contract (exact
f32 distances, canonical tie-breaking) is identical to the pure-XLA twin.

Approximation note: coarse-level context is evaluated on 2x
repeat-upsampled coarse planes with a dilation-2 window at q rather than
the exact parent lookup at q//2 — an off-by-parity approximation of the
paper's metric, corrected by the exact-metric merge + polish.
"""

from __future__ import annotations

import functools
import math
import os
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import SynthConfig

LANE = 128

# A-plane layout default (round 7): 'packed' interleaves (channel x
# adjacent-lane-block) on the sublane axis so each candidate DMA is ONE
# (thp, 1, 2C, 128) fetch with zero sublane pad at C=4 — the escape
# VERDICT r5 task 1 named for the 50%-padding candidate fetch that
# dominated the HBM-bound sweep.  'unpacked' is the round-4/5
# (Hp, Wq, C, 128) layout, kept selectable (env IA_A_PLANE_LAYOUT or the
# explicit `packed=` args) as the measured fallback if a future Mosaic
# toolchain rejects the packed slot's static sublane-pair slice, and for
# the layout A/B (tools/layout_ab.py).  A module global, not a config
# knob: the layout is a kernel implementation detail both sides of the
# prepare/sweep contract must agree on, not user surface.
_PACKED_DEFAULT = os.environ.get("IA_A_PLANE_LAYOUT", "packed") != "unpacked"


def resolve_packed(packed: Optional[bool] = None) -> bool:
    """The ONE resolution point for the A-plane layout choice: explicit
    `packed=` wins, otherwise the module default.  Callers resolve
    BEFORE entering any jit/lru cache so a flipped default (tests,
    layout A/B) can never hit a stale `None`-keyed compilation."""
    return _PACKED_DEFAULT if packed is None else bool(packed)


# Candidate-table compression mode (round 11): "bf16" is the
# UNCOMPRESSED historical representation — f32 sweep planes here, bf16
# polish rows in kernels/polish_stream.py (the name tracks the polish
# table's dtype, the site the selector was designed around) — and is
# bit-identical to the pre-round-11 graphs by construction.  "int8"
# stores both candidate tables quantized (this module's A planes on a
# static [0, 1] affine grid, the polish rows with per-patch scale rows)
# and dequantizes next to the distance math.  A module global with env
# override, not a config knob, same rationale as _PACKED_DEFAULT /
# _POLISH_MODE: the representation is a measured performance decision
# both sides of the prepare/sweep contract must agree on.  Default
# stays "bf16" pending the hardware A/B (tools/quant_ab.py,
# QUANT_r11.json — no accelerator reachable in round 11).
_CAND_DTYPES = ("bf16", "int8")
_CAND_DTYPE = os.environ.get("IA_CAND_DTYPE", "bf16")

# int8 A-plane affine grid: planes are normalized images (raw src/flt
# channels and their repeat-upsampled coarse twins, all in [0, 1]), so
# the quantization range is static — q = round(x*254) - 127, dequant
# x^ = (q + 127) / 254; out-of-range values clip (quality is pinned by
# the exact-metric merge + the dist-ratio/PSNR gates, not here).
# Per-patch scale rows make no sense for a plane table (entries are
# image columns, not patches); the per-patch scales live with the
# polish row table (kernels/polish_stream.quantize_rows).
_Q_SCALE = 254.0
_Q_ZERO = 127.0


def resolve_cand_dtype(cand_dtype: Optional[str] = None) -> str:
    """`resolve_packed`-style single resolution point for the
    candidate-table compression mode: explicit arg wins, else the
    module default.  Resolve BEFORE any jit/lru cache."""
    dt = _CAND_DTYPE if cand_dtype is None else cand_dtype
    if dt not in _CAND_DTYPES:
        raise ValueError(
            f"cand_dtype {dt!r} names none of {_CAND_DTYPES}"
        )
    return dt


def parse_prune(spec) -> Optional[Tuple[int, int]]:
    """Parse a \"K:M\" PCA-prune spec (K = coarse PCA dims, M = exact
    fetches that survive the coarse ranking per tile per sweep) to
    (k, m), or None for off ("off"/""/None)."""
    if spec in (None, "", "off"):
        return None
    if isinstance(spec, (tuple, list)):
        k, m = spec
    else:
        try:
            k_s, m_s = str(spec).split(":")
            k, m = int(k_s), int(m_s)
        except ValueError:
            raise ValueError(
                f"pca-prune spec {spec!r} is not 'K:M' (e.g. '16:8') "
                "or 'off'"
            ) from None
    if not (1 <= k <= LANE):
        raise ValueError(f"pca-prune K={k} outside [1, {LANE}]")
    if not (1 <= m <= K_TOTAL):
        raise ValueError(f"pca-prune M={m} outside [1, {K_TOTAL}]")
    return int(k), int(m)


# PCA coarse-distance pre-prune (round 11, stage 2): "off" or "K:M".
# When on, the matcher projects candidate rows through a per-level
# pca_basis to K dims, ranks each tile's K_TOTAL shared candidates by
# coarse distance at _PRUNE_SAMPLES sample pixels, and zeroes
# `cand_valid` for all but the top M — the kernel's existing
# pl.when(ok) DMA skip then never moves the pruned candidates' bytes,
# turning the byte model from fetches x bytes_per_fetch into
# fetches x (coarse_bytes + survival x exact_bytes).  Default off
# pending the hardware A/B (tools/quant_ab.py).
_CAND_PRUNE = os.environ.get("IA_CAND_PRUNE", "off")


def resolve_prune(prune=None) -> Optional[Tuple[int, int]]:
    """Single resolution point for the PCA prune: explicit spec wins
    (string or (k, m) tuple; "off"/None-tuple meaning off must be
    passed as the string "off"), otherwise the module default."""
    return parse_prune(_CAND_PRUNE if prune is None else prune)


def set_cand_compression(cand_dtype: Optional[str] = None,
                         prune=None) -> None:
    """Install a compressed-candidate mode process-wide (the CLI's
    --cand-dtype/--pca-prune flags, bench.py, tools/quant_ab.py):
    validates, assigns the module globals, and clears the driver's
    cached level/EM compilations so a flip can never reuse a stale
    trace (the tools/polish_stream_ab.py discipline).  None leaves a
    knob untouched."""
    global _CAND_DTYPE, _CAND_PRUNE
    if cand_dtype is not None:
        _CAND_DTYPE = resolve_cand_dtype(cand_dtype)
    if prune is not None:
        parse_prune(prune)  # validate before assigning
        _CAND_PRUNE = prune
    if cand_dtype is not None or prune is not None:
        clear_compiled_level_caches()


def clear_compiled_level_caches() -> None:
    """Drop every cached level/EM compilation across all four runners.

    EVERY cached level/EM compilation resolves the process-wide kernel
    modes (_CAND_DTYPE/_CAND_PRUNE/_PACKED_DEFAULT here,
    models/patchmatch._POLISH_MODE) at trace time, so a mode flip must
    drop all of them — the parallel runners' lru entries included, or
    a flipped mode would silently reuse a stale arm's graphs (no dtype
    assert fires there: the cached fn prepared its own planes under
    the old mode).  Shared by `set_cand_compression`,
    `set_packed_layout`, and `models/patchmatch.set_polish_mode` (the
    round-12 degradation-ladder setters)."""
    from ..models import analogy as _an
    from ..parallel import batch as _pb
    from ..parallel import sharded_a as _psa
    from ..parallel import spatial as _psp

    for fn in (
        _an._level_fn, _an._em_step_fn,
        _pb._batch_step_fn_cached, _pb._lean_step_fn_cached,
        _pb._batch_prologue_fn_cached, _pb._batch_level_fn_cached,
        _psa._band_assemble_fn, _psa._sharded_level_fn,
        _psp._reslab_fn, _psp._banded_lean_step_fn,
    ):
        fn.cache_clear()
    # The video subsystem's temporal level twin joins the funnel only
    # when loaded (sys.modules probe: kernels must not import the video
    # driver that imports the parallel runners that import kernels).
    import sys

    _vid = sys.modules.get("image_analogies_tpu.video.sequence")
    if _vid is not None:
        _vid._video_level_fn_cached.cache_clear()
    # Round 18: the serving tier's persist hook holds its own table of
    # loaded/AOT-compiled executables — an epoch eviction must demote
    # those too (same honesty rule as the lru caches) while leaving
    # the DISK tier intact, so a demoted key's next use restores from
    # disk instead of recompiling.
    _pb.clear_persist_loaded()


def set_packed_layout(layout: str) -> None:
    """Install an A-plane layout process-wide (round 12: the
    supervisor's packed->unpacked degradation rung; also the layout
    A/B's programmatic entry): validates, assigns the module default,
    and clears the compiled level/EM caches — packed and unpacked are
    bit-identical through the full matcher path (round 7, test-pinned)
    so the rung is bit-safe; only the DMA geometry changes."""
    global _PACKED_DEFAULT
    if layout not in ("packed", "unpacked"):
        raise ValueError(
            f"A-plane layout {layout!r} names neither 'packed' nor "
            "'unpacked'"
        )
    packed = layout != "unpacked"
    if packed == _PACKED_DEFAULT:
        return
    _PACKED_DEFAULT = packed
    clear_compiled_level_caches()
# Tile geometry: the padded tile is exactly one lane block wide so the
# separable window never needs lane slicing.  P is the union halo of the
# fine window (patch//2) and the dilated coarse window (2*(coarse//2)).
TILE_H = 64

# Candidate budget per tile per sweep (static; SMEM-resident per tile).
# Tuned 2026-07-30 (tools/tune_kernel.py, recorded in README): 4/16/12/4
# beats the round-2 16/16/12/4 on every axis at the 1024^2 headline —
# sweep 12.6 ms vs 14.8, wall 1.137 s vs 1.181, PSNR 35.93 vs 35.91 dB.
# Converged fields make large own-sample sets redundant (the dedup mask
# already skipped most of them); propagation coverage stays full
# (K_PROP = 4*K_OWN, the neighbor tiles' whole sample set).
K_OWN = 4      # samples of the tile's own per-pixel offsets (coherence)
K_PROP = 16    # samples from the 4 neighbor tiles (propagation)
K_LOCAL = 12   # shrinking-radius perturbations (random search)
K_GLOBAL = 4   # uniform over A (random restart)
K_TOTAL = K_OWN + K_PROP + K_LOCAL + K_GLOBAL
K_COHERENT = K_OWN + K_PROP  # accepted at factor 1; rest at the kappa factor


class ChannelSpec(NamedTuple):
    """Static per-channel window description (hashable)."""

    dilation: int
    wy: Tuple[float, ...]
    wx: Tuple[float, ...]


class TileGeometry(NamedTuple):
    halo: int
    tile_h: int
    tile_w: int
    n_ty: int
    n_tx: int

    @property
    def thp(self) -> int:
        """Blocked tile rows: tile + halos, padded up to the 8-sublane
        granularity compiled Pallas requires.  The pad rows ([tile_h +
        2*halo, thp)) hold junk; window rolls with |dy| <= halo never pull
        them into interior rows, and from_blocked drops them."""
        return -(-(self.tile_h + 2 * self.halo) // 8) * 8


def _gauss1d(n: int, sigma_frac: float = 0.4) -> np.ndarray:
    """1-D factor of ops.features._gauss_weights (exactly separable)."""
    r = n // 2
    sigma = max(n * sigma_frac, 1e-3)
    x = np.arange(-r, r + 1, dtype=np.float32)
    g = np.exp(-(x**2) / (2 * sigma**2))
    return g / g.sum()


def channel_specs(
    n_src: int, n_flt: int, cfg: SynthConfig, has_coarse: bool,
    coarse_scale: float = 1.0,
) -> Tuple[ChannelSpec, ...]:
    """Window spec per plane, matching ops.features.feature_weights: fine
    src+flt channels get the patch_size window (weight mass 1 each), the
    upsampled-coarse channels get the dilated coarse window scaled by
    `coarse_scale`."""
    if cfg.gaussian_weighting:
        wf = _gauss1d(cfg.patch_size)
        wc = _gauss1d(cfg.coarse_patch_size)
    else:
        wf = np.full(cfg.patch_size, 1.0 / cfg.patch_size, np.float32)
        wc = np.full(
            cfg.coarse_patch_size, 1.0 / cfg.coarse_patch_size, np.float32
        )
    fine = ChannelSpec(1, tuple(wf.tolist()), tuple(wf.tolist()))
    specs = [fine] * (n_src + n_flt)
    if has_coarse:
        # sqrt(coarse_scale) on each 1-D factor => coarse_scale on the mass.
        s = math.sqrt(coarse_scale)
        wcy = tuple((wc * s).tolist())
        coarse = ChannelSpec(2, wcy, wcy)
        specs += [coarse] * (n_src + n_flt)
    return tuple(specs)


def halo_for(specs: Sequence[ChannelSpec]) -> int:
    return max(sp.dilation * (len(sp.wy) // 2) for sp in specs)


def tile_geometry(h: int, w: int, specs: Sequence[ChannelSpec]) -> TileGeometry:
    p = halo_for(specs)
    tile_w = LANE - 2 * p
    return TileGeometry(
        halo=p,
        tile_h=TILE_H,
        tile_w=tile_w,
        n_ty=-(-h // TILE_H),
        n_tx=-(-w // tile_w),
    )


# ---------------------------------------------------------------------------
# Plane preparation (XLA side)


def _split_channels(img: jnp.ndarray) -> list:
    if img.ndim == 2:
        return [img]
    return [img[..., c] for c in range(img.shape[-1])]


def _upsample2x(img: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """Nearest 2x repeat-upsample, cropped — the same parent-pixel lookup
    ops.features.assemble_features uses for the coarse block."""
    return jnp.repeat(jnp.repeat(img, 2, axis=0), 2, axis=1)[:h, :w]


def channel_images(
    src: jnp.ndarray,
    flt: jnp.ndarray,
    src_coarse: Optional[jnp.ndarray],
    flt_coarse: Optional[jnp.ndarray],
) -> list:
    """Ordered 2-D channel planes: fine src, fine flt, upsampled coarse
    src, upsampled coarse flt — the layout channel_specs describes."""
    h, w = src.shape[:2]
    chans = _split_channels(src) + _split_channels(flt)
    if src_coarse is not None:
        for img in (src_coarse, flt_coarse):
            chans += [
                _upsample2x(c, h, w) for c in _split_channels(img)
            ]
    return chans


def band_rows(ha: int, n_bands: int) -> int:
    """Rows of A per band (last band may be shorter; uniform arrays)."""
    return -(-ha // n_bands)


def band_bounds(ha: int, n_bands: int) -> list:
    """The (row0, rows_valid) int32 operand for each band's sweep call —
    the ONE band-bounds convention, shared by the matcher and the bench
    so they cannot drift apart."""
    rows_b = band_rows(ha, n_bands)
    return [
        jnp.asarray([i * rows_b, min(rows_b, ha - i * rows_b)], jnp.int32)
        for i in range(n_bands)
    ]


def prepare_a_planes(
    src: jnp.ndarray,
    flt: jnp.ndarray,
    src_coarse: Optional[jnp.ndarray],
    flt_coarse: Optional[jnp.ndarray],
    specs: Tuple[ChannelSpec, ...],
    n_bands: int = 1,
    packed: Optional[bool] = None,
    cand_dtype: Optional[str] = None,
) -> Tuple[jnp.ndarray, ...]:
    """A-side planes for the kernel: a tuple of `n_bands` arrays, each
    covering A rows [i*band_rows, (i+1)*band_rows) with window halos.

    `cand_dtype` (resolved like `packed` — explicit wins, else the
    module `_CAND_DTYPE`): "bf16" keeps the historical f32 planes;
    "int8" stores each plane on the static [0, 1] affine grid
    (q = round(x*_Q_SCALE) - _Q_ZERO, clipped) and the kernel
    dequantizes next to its distance math.  Both sides of the
    prepare/sweep contract must resolve the same mode (tile_sweep
    asserts the array dtype against its resolved mode).

    Default (packed=True, round 7): (rows, Wq-1, 2C, 128) f32 where
    sublane 2c+b of entry q holds lane-block q+b of channel c, so ONE
    (thp, 1, 2C, 128) DMA fetches both adjacent lane blocks of every
    channel.  At the headline's 4 channels the 2C=8 sublanes exactly
    fill the f32 (8, 128) tile: zero pad moved per candidate, half the
    round-5 fetch (VERDICT r5 "missing 2").  The adjacent-block pair is
    duplicated across entries (entry q and q+1 both carry block q+1),
    so the HBM footprint matches what the old layout's sublane pad
    already cost at C=4 — the duplication buys the zero-pad DMA, it
    does not add residency.

    packed=False: the round-4/5 layout — (rows, Wq, C, 128), candidate
    window fetched as (thp, 2, C, 128) with the C -> 8 sublane pad in
    HBM and in the DMA.  In BOTH layouts the channel content sits on
    the trailing (sublanes, 128) tile so the two dynamically-sliced
    axes (rows, Wq entries) stay untiled — Mosaic requires tiled-axis
    slices be whole/8-aligned, so a (.., Wq, C*128) packing whose Wq is
    the sublane axis cannot be sliced 2 blocks at a time (verified:
    "Slice shape along dimension 1 must be aligned to tiling (8)").

    The default is a single HBM-resident plane set (the kernel streams
    candidate windows from it by DMA).  With n_bands > 1, bands OWN a
    disjoint origin range [i*band_rows, (i+1)*band_rows) (the kernel's
    in_band test) but are RESIDENT for TILE_H-1 extra rows past it, so
    a tile origin anywhere in the owned range is evaluated at its true
    position — no origin is clamped/displaced at a band seam, and none
    is evaluated twice.  Banding is for callers that split A ownership
    across devices (parallel/spatial.py); single-device plans are
    always 1 band.

    Edge padding mirrors ops.features.extract_patches (windows at A's
    border replicate edge pixels).  One guard lane-block on the right
    keeps the adjacent-block candidate load in bounds for any clamped
    sx (packed folds it into every entry's b=1 sublanes).
    Pass `src_coarse=None` to build the fine-only channel subset.
    """
    return _prepare_a_planes_jit(
        src, flt, src_coarse, flt_coarse, specs, n_bands,
        resolve_packed(packed), resolve_cand_dtype(cand_dtype),
    )


@functools.partial(
    jax.jit, static_argnames=("specs", "n_bands", "packed", "cand_dtype")
)
def _prepare_a_planes_jit(
    src, flt, src_coarse, flt_coarse, specs, n_bands, packed, cand_dtype,
):
    p = halo_for(specs)
    chans = channel_images(src, flt, src_coarse, flt_coarse)
    ha, wa = chans[0].shape
    wq = -(-(wa + 2 * p) // LANE) + 1
    # Bottom rows beyond the valid range feed only the blocked-tile pad
    # rows (see TileGeometry.thp) — content there is never read into
    # interior output, edge values just keep the slice in bounds.
    geom = tile_geometry(ha, wa, specs)
    extra = geom.thp - (geom.tile_h + 2 * p)
    rows_b = band_rows(ha, n_bands)
    overlap = geom.tile_h - 1 if n_bands > 1 else 0
    full = []
    pad_bottom = p + extra + overlap + (n_bands * rows_b - ha)
    for c in chans:
        c = jnp.pad(
            c, ((p, pad_bottom), (p, wq * LANE - wa - p)), mode="edge"
        )
        c = c.astype(jnp.float32)
        if cand_dtype == "int8":
            # Static [0, 1] affine grid (edge padding replicates values,
            # so padding and pointwise quantization commute).
            c = jnp.clip(
                jnp.round(c * _Q_SCALE - _Q_ZERO), -127.0, 127.0
            ).astype(jnp.int8)
        full.append(c.reshape(c.shape[0], wq, LANE))
    if packed:
        # Interleave (channel x adjacent-lane-block) on the sublane
        # axis: entry q's sublane 2c+b is channel c's lane-block q+b.
        parts = []
        for c in full:
            parts.append(c[:, :-1, :])  # b = 0: block q
            parts.append(c[:, 1:, :])   # b = 1: block q+1
        stacked = jnp.stack(parts, axis=2)  # (Hp, Wq-1, 2C, LANE)
    else:
        stacked = jnp.stack(full, axis=2)   # (Hp, Wq, C, LANE)
    bands = []
    for i in range(n_bands):
        bands.append(
            jax.lax.slice_in_dim(
                stacked,
                i * rows_b,
                i * rows_b + rows_b + overlap + 2 * p + extra,
                axis=0,
            )
        )
    return tuple(bands)


def to_blocked(plane: jnp.ndarray, geom: TileGeometry) -> jnp.ndarray:
    """Compact (h, w) -> halo-blocked (n_ty*(TH+2P), n_tx*LANE) layout:
    tile (i, j) occupies rows [i*THP, (i+1)*THP) and owns compact rows
    [i*TH - P, i*TH + TH + P) (edge-padded), similarly columns."""
    p, th, tw = geom.halo, geom.tile_h, geom.tile_w
    thp = geom.thp
    h, w = plane.shape
    plane = jnp.pad(
        plane,
        (
            (p, geom.n_ty * th - h + p + (thp - th - 2 * p)),
            (p, geom.n_tx * tw - w + p),
        ),
        mode="edge",
    )
    rows = []
    for i in range(geom.n_ty):
        cols = []
        for j in range(geom.n_tx):
            cols.append(
                jax.lax.slice(
                    plane,
                    (i * th, j * tw),
                    (i * th + thp, j * tw + LANE),
                )
            )
        rows.append(jnp.concatenate(cols, axis=1))
    return jnp.concatenate(rows, axis=0)


def from_blocked(
    blocked: jnp.ndarray, geom: TileGeometry, h: int, w: int
) -> jnp.ndarray:
    """Inverse of to_blocked: keep each tile's interior, crop to (h, w)."""
    p, th, tw = geom.halo, geom.tile_h, geom.tile_w
    thp = geom.thp
    x = blocked.reshape(geom.n_ty, thp, geom.n_tx, LANE)
    x = x[:, p : p + th, :, p : p + tw]
    x = x.reshape(geom.n_ty * th, geom.n_tx * tw)
    return x[:h, :w]


# ---------------------------------------------------------------------------
# Candidate sampling (XLA side)


def _subgrid(key: jax.Array, geom: TileGeometry):
    """Jittered side x side in-tile sample coordinates (uy, ux)."""
    th, tw = geom.tile_h, geom.tile_w
    side = int(math.isqrt(K_OWN))
    jy = jax.random.randint(key, (2,), 0, min(th, tw))
    uy = (jy[0] + (th // side) * jnp.arange(side)) % th
    ux = (jy[1] + (tw // side) * jnp.arange(side)) % tw
    return uy, ux


def sample_candidates(
    off_y: jnp.ndarray,
    off_x: jnp.ndarray,
    key: jax.Array,
    geom: TileGeometry,
    ha: int,
    wa: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-tile candidate offsets (cand_y, cand_x, cand_valid), each
    (n_ty, n_tx, K_TOTAL) int32, from the COMPACT (h, w) state planes
    (`cand_valid` is the dedup mask — candidate_valid_mask).

    Layout (matching the kernel's static kappa split):
      [0, K_OWN)                 own-tile state samples     (coherent)
      [K_OWN, K_OWN+K_PROP)      neighbor-tile samples      (propagation)
      [.., +K_LOCAL)             shrinking-radius perturbations (random)
      [.., +K_GLOBAL)            uniform restarts           (random)
    """
    h, w = off_y.shape
    th, tw = geom.tile_h, geom.tile_w
    n_ty, n_tx = geom.n_ty, geom.n_tx
    k_jit, k_loc, k_gy, k_gx = jax.random.split(key, 4)

    # Own-tile samples: a jittered side x side (side = sqrt(K_OWN))
    # subgrid of each tile's offsets.
    uy, ux = _subgrid(k_jit, geom)
    py = jnp.clip(
        (jnp.arange(n_ty) * th)[:, None, None, None] + uy[None, None, :, None],
        0, h - 1,
    )
    px = jnp.clip(
        (jnp.arange(n_tx) * tw)[None, :, None, None] + ux[None, None, None, :],
        0, w - 1,
    )
    own_y = off_y[py, px].reshape(n_ty, n_tx, K_OWN)
    own_x = off_x[py, px].reshape(n_ty, n_tx, K_OWN)
    return _candidate_tables(
        own_y, own_x, k_loc, k_gy, k_gx, geom, ha, wa
    )


def candidate_valid_mask(cand_y: jnp.ndarray, cand_x: jnp.ndarray):
    """Dedup mask over the K_TOTAL axis: slot k is valid iff no earlier
    slot carries the same (oy, ox).  Converged fields make many own/prop
    samples identical; each duplicate would re-run the full windowed SSD
    for zero search value.  O(K^2) compare on (..., K, K) bools —
    trivial XLA work that preserves slot order (the kappa split is
    positional; an offset appearing in both a coherent and a random slot
    keeps its coherent factor, which is the correct Ashikhmin rule)."""
    same = (cand_y[..., :, None] == cand_y[..., None, :]) & (
        cand_x[..., :, None] == cand_x[..., None, :]
    )
    earlier = jnp.tril(
        jnp.ones((K_TOTAL, K_TOTAL), jnp.bool_), k=-1
    )
    return jnp.logical_not(
        jnp.any(same & earlier, axis=-1)
    ).astype(jnp.int32)


# Global-restart sampling mode (round 8, VERDICT r5 task 3): the
# K_GLOBAL slots draw "uniform" over A (the Barnes restart, and the
# DEFAULT — every published family was measured under it), or "coarse"
# — offsets read from the evolving field at random OTHER positions
# (`_field_restarts`).  At the first pm iteration of every EM step the
# field IS the parent level's converged field upsampled
# (models/analogy._level_state_glue), so "coarse" seeds each tile's
# restarts from coarse-level matches at stratified positions — the
# device-resident signal uniform restarts ignore while the 4096^2
# exact-distance ratio drifts (SCALE_r05 1.496 -> 1.668).  A module
# global, not a config knob (same rationale as _POLISH_MODE); env
# IA_RESTART_MODE flips it for the A/B (tools/restart_ab.py, kill
# criterion pre-stated there), hardware confirmation owed — default
# stays "uniform" until the 4096^2 arm runs.
_RESTART_MODE = os.environ.get("IA_RESTART_MODE", "uniform")


def _field_restarts(y4, x4, k_gy, k_gx, geom: TileGeometry):
    """K_GLOBAL field-informed restart offsets per tile: draw a random
    interior position q' elsewhere in B (stratified by the PRNG, not
    by tile adjacency — propagation already covers neighbors), read
    the field's offset there, and re-express its MATCH as an offset
    for this tile: cand = q' + off(q') - tile_origin, so the tile
    evaluates the A position the field already matched at q'.  The
    candidates land in the approximate (kappa-factored) slots exactly
    like uniform restarts — same accept rule, only the proposal
    distribution changes — and are re-evaluated under the kernel
    metric before any accept, so any stale/wrapped source is harmless.

    `y4`/`x4` are the blocked state planes reshaped
    (n_ty, thp, n_tx, LANE) — the same view `pick` samples own-tile
    candidates from."""
    p, th, tw = geom.halo, geom.tile_h, geom.tile_w
    n_ty, n_tx = geom.n_ty, geom.n_tx
    kt, ku = jax.random.split(k_gy)
    kj, kv = jax.random.split(k_gx)
    shape = (n_ty, n_tx, K_GLOBAL)
    si = jax.random.randint(kt, shape, 0, n_ty)
    sj = jax.random.randint(kj, shape, 0, n_tx)
    su = jax.random.randint(ku, shape, 0, th)
    sv = jax.random.randint(kv, shape, 0, tw)
    oy = y4[si, p + su, sj, p + sv]
    ox = x4[si, p + su, sj, p + sv]
    src_y = si * th + su
    src_x = sj * tw + sv
    ty0 = (jnp.arange(n_ty) * th)[:, None, None]
    tx0 = (jnp.arange(n_tx) * tw)[None, :, None]
    return src_y + oy - ty0, src_x + ox - tx0


def sample_candidates_blocked(
    oy_b: jnp.ndarray,
    ox_b: jnp.ndarray,
    key: jax.Array,
    geom: TileGeometry,
    ha: int,
    wa: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """`sample_candidates` reading own-tile samples straight from the
    halo-BLOCKED state planes, so the pm-iteration loop never needs the
    compact layout (round-2 VERDICT: `from_blocked` ran twice per pm
    iteration only to feed sampling, which reads a sqrt(K_OWN)-sided
    subgrid per tile).

    Equivalent up to edge tiles: compact sampling clamps out-of-image
    subgrid coordinates to the last row/col, while blocked interiors
    carry kernel-evolved state for those (edge-seeded) positions; both
    are valid candidate sources — candidates are always re-evaluated
    under the metric before acceptance.  PRNG streams match
    `sample_candidates` exactly (same key split, same subgrid jitter).
    """
    p, th, tw = geom.halo, geom.tile_h, geom.tile_w
    thp, n_ty, n_tx = geom.thp, geom.n_ty, geom.n_tx
    k_jit, k_loc, k_gy, k_gx = jax.random.split(key, 4)

    uy, ux = _subgrid(k_jit, geom)
    y4 = oy_b.reshape(n_ty, thp, n_tx, LANE)
    x4 = ox_b.reshape(n_ty, thp, n_tx, LANE)

    def pick(a4):
        t = jnp.take(a4, p + uy, axis=1)
        t = jnp.take(t, p + ux, axis=3)
        return t.transpose(0, 2, 1, 3).reshape(n_ty, n_tx, K_OWN)

    glob = (
        _field_restarts(y4, x4, k_gy, k_gx, geom)
        if _RESTART_MODE == "coarse"
        else None
    )
    return _candidate_tables(
        pick(y4), pick(x4), k_loc, k_gy, k_gx, geom, ha, wa, glob=glob
    )


def _candidate_tables(own_y, own_x, k_loc, k_gy, k_gx, geom, ha, wa,
                      glob=None):
    """Propagation / random-search / restart tail shared by both
    own-sample layouts; returns the (n_ty, n_tx, K_TOTAL) tables.
    `glob` optionally overrides the K_GLOBAL restart slots (the
    field-informed sampler — `_field_restarts`); None draws the
    uniform-over-A default, byte-identical to the historical stream
    (k_gy/k_gx are consumed by exactly one branch either way)."""
    th, tw = geom.tile_h, geom.tile_w
    n_ty, n_tx = geom.n_ty, geom.n_tx

    # Propagation: the 4 neighbor tiles' first K_PROP//4 samples each.
    per = K_PROP // 4
    prop_y, prop_x = [], []
    for shift in ((1, 0), (-1, 0), (0, 1), (0, -1)):
        prop_y.append(jnp.roll(own_y[..., :per], shift, axis=(0, 1)))
        prop_x.append(jnp.roll(own_x[..., :per], shift, axis=(0, 1)))
    prop_y = jnp.concatenate(prop_y, axis=-1)
    prop_x = jnp.concatenate(prop_x, axis=-1)

    # Random search: exponentially shrinking radii around own samples
    # (Barnes alpha = 0.5), one candidate per scale.
    m = max(ha, wa)
    radii = np.array(
        [max(1, m >> (s + 1)) for s in range(K_LOCAL)], np.int32
    )
    centers_y = jnp.concatenate(
        [own_y] * (-(-K_LOCAL // K_OWN)), axis=-1
    )[..., :K_LOCAL]
    centers_x = jnp.concatenate(
        [own_x] * (-(-K_LOCAL // K_OWN)), axis=-1
    )[..., :K_LOCAL]
    pert = jax.random.randint(
        k_loc, (2, n_ty, n_tx, K_LOCAL), -radii.max(), radii.max() + 1
    )
    scale = jnp.asarray(radii)[None, None, :]
    loc_y = centers_y + jnp.clip(pert[0], -scale, scale)
    loc_x = centers_x + jnp.clip(pert[1], -scale, scale)

    if glob is not None:
        glob_y, glob_x = glob
    else:
        # Uniform restarts over A's valid tile-origin range.
        ty0 = (jnp.arange(n_ty) * th)[:, None, None]
        tx0 = (jnp.arange(n_tx) * tw)[None, :, None]
        glob_y = jax.random.randint(
            k_gy, (n_ty, n_tx, K_GLOBAL), 0, max(ha - th, 1)
        ) - ty0
        glob_x = jax.random.randint(
            k_gx, (n_ty, n_tx, K_GLOBAL), 0, max(wa - tw, 1)
        ) - tx0

    cand_y = jnp.concatenate([own_y, prop_y, loc_y, glob_y], axis=-1)
    cand_x = jnp.concatenate([own_x, prop_x, loc_x, glob_x], axis=-1)
    cand_y = cand_y.astype(jnp.int32)
    cand_x = cand_x.astype(jnp.int32)
    # The dedup mask is a function of the tables alone; computing it here
    # (once per pm iteration) instead of in tile_sweep avoids re-running
    # the K^2 compare on every band call of a banded level.
    return cand_y, cand_x, candidate_valid_mask(cand_y, cand_x)


# ---------------------------------------------------------------------------
# The kernel


def spec_groups(
    specs: Tuple[ChannelSpec, ...],
) -> Tuple[Tuple[ChannelSpec, Tuple[int, ...]], ...]:
    """Channels grouped by identical window spec, preserving first-seen
    order: the windowed-SSD sum over a group's channels commutes with the
    (shared) window contraction, so each group needs one Wx/Wy matmul
    pair regardless of how many channels it holds."""
    groups: list = []
    for c, sp in enumerate(specs):
        for g, (gsp, chans) in enumerate(groups):
            if gsp == sp:
                groups[g] = (gsp, chans + (c,))
                break
        else:
            groups.append((sp, (c,)))
    return tuple(groups)


def _band_matrix(n: int, weights, dilation: int) -> np.ndarray:
    """Banded window matrix B with B[i, i + (t-r)*dilation] = weights[t]
    (rows clip at the edges — no wraparound; the halo keeps interior
    pixels' windows fully in range, so interiors match the roll
    formulation exactly while halo rows differ only in values that
    from_blocked drops)."""
    m = np.zeros((n, n), np.float32)
    r = len(weights) // 2
    idx = np.arange(n)
    for t, wgt in enumerate(weights):
        j = idx + (t - r) * dilation
        ok = (j >= 0) & (j < n)
        m[idx[ok], j[ok]] += wgt
    return m


def window_matrices(
    specs: Tuple[ChannelSpec, ...], thp: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(wx, wy) stacked per group: wx (G, LANE, LANE) with xs = dq @ wx[g]
    the lane-axis window sum, wy (G, THP, THP) with d = wy[g] @ xs the
    sublane-axis one."""
    groups = spec_groups(specs)
    wx = np.stack(
        [_band_matrix(LANE, sp.wx, sp.dilation).T for sp, _ in groups]
    )
    wy = np.stack(
        [_band_matrix(thp, sp.wy, sp.dilation) for sp, _ in groups]
    )
    return wx, wy


# Candidate-window prefetch depth: slot k%D is refilled for candidate
# k+D right after candidate k's arithmetic consumes it, so each DMA has
# D-1 candidate evaluations of latency cover (measured 2026-07-31: the
# candidate fetch runs ~3.5 us through the DMA engines vs ~1 us of
# per-candidate arithmetic, so depth 2 left the sweep DMA-latency-bound
# at 18.4 ms; deeper slots trade ~300 KB of VMEM each for full overlap).
_PREFETCH_DEPTH = 6


def _make_kernel(
    specs: Tuple[ChannelSpec, ...],
    geom: TileGeometry,
    ha: int,
    wa: int,
    coh_factor: float,
    packed: bool,
    cand_dtype: str = "bf16",
):
    """The SMEM `band_ref` (row0, rows_own) selects the A row *band*
    this call can match into (global origin rows [row0, row0+rows_own));
    single-device plans pass (0, ha).  A candidate counts only in the
    band OWNING its globally-clamped origin (the `ok` mask below), and
    the carried per-pixel best makes the union over band calls a global
    search — the ownership contract the spatial sharded-A runner needs.
    Bands are resident for TILE_H-1 rows past their owned range
    (prepare_a_planes), so every owned origin is evaluated at its true
    position — no seam displacement, no double evaluation.  The bounds
    are scalar operands, not static args, so one compiled kernel serves
    every band of a level.

    Structure (round-4 redesign, measured rationale in the module
    docstring): candidate windows are DMA-streamed from the HBM A
    operand into double-buffered VMEM slots; evaluation is straight-line
    (no lax.cond, no fori_loop — a round-3 bisect measured the serial
    cond+fori skeleton alone at 8.6 ms of the 12.9 ms sweep because each
    iteration's scalar->vector dependency chain serialized); masked-out
    candidates (out-of-band / dedup-duplicate) contribute +inf instead
    of branching.  Coherent and approximate candidates accumulate into
    two independent running minima merged once through the kappa factor
    — Hertzmann §3.2's actual rule (best coherent vs best approximate),
    order-independent, unlike the round-3 sequential cascade where an
    early-accepted random candidate's raw distance became the bar for
    later coherent ones."""
    p, th, tw = geom.halo, geom.tile_h, geom.tile_w
    thp = geom.thp
    groups = spec_groups(specs)
    sx_max = wa - tw

    def kernel(band_ref, cy_ref, cx_ref, valid_ref, wx_ref, wy_ref, a_ref,
               b_ref, oyi_ref, oxi_ref, di_ref, oyo_ref, oxo_ref, do_ref,
               slots_ref, sems_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        ty0 = i * th
        tx0 = j * tw
        # cy/cx arrive as the 8-row SMEM block containing this tile's
        # candidate row (flat tile index, padded to 8); SMEM loads must
        # be scalar, so candidates are read as cy_ref[row, k].
        row = (i * geom.n_tx + j) % 8
        row0 = band_ref[0]
        # Band-local slice bound: resident rows cover every owned origin
        # exactly (defensive clip only — `ok` already bounds sy).
        sy_cap = a_ref.shape[0] - thp

        def scalars(k):
            oy = cy_ref[row, k]
            ox = cx_ref[row, k]
            sy_g = jnp.clip(ty0 + oy, 0, ha - th)
            ok = (
                (sy_g >= row0)
                & (sy_g < row0 + band_ref[1])
                & (valid_ref[row, k] > 0)
            )
            sy = jnp.clip(sy_g - row0, 0, sy_cap)  # band-local
            sx = jnp.clip(tx0 + ox, 0, sx_max)
            return ok, sy, sx

        def copy_for(k, slot):
            """(ok, async copy) for candidate k's all-channel window
            from the HBM A operand into VMEM slot `slot` — packed: ONE
            (thp, 1, 2C, LANE) entry whose sublane pairs carry both
            lane blocks of every channel (zero sublane pad at C=4);
            unpacked: the round-4/5 (thp, 2, C, LANE) two-block fetch
            (the wait side rebuilds the same descriptor — it only
            decrements the slot's semaphore).  Both the start and the
            wait run under `pl.when(ok)`: ~30 % of slots are invalid in
            real sweeps (dedup mask + band bounds — measured 0.308 mean
            invalid fraction over a synthesis, 2026-08-01) and their
            bytes need not move at all.  `ok` is a pure function of SMEM
            scalars, so the start-side and wait-side predicates always
            agree and semaphores stay balanced.  An invalid candidate's
            eval reads whatever the slot holds — the last landed valid
            occupant, or UNINITIALIZED VMEM if no valid candidate has
            hit the slot yet — and is safe ONLY because every invalid
            candidate's distance is masked to inf below (jnp.where
            selects, it does not propagate slot garbage); do not weaken
            that mask."""
            ok, sy, sx = scalars(k)
            n_blocks = 1 if packed else 2
            return ok, pltpu.make_async_copy(
                a_ref.at[pl.ds(sy, thp), pl.ds(sx // LANE, n_blocks)],
                slots_ref.at[slot],
                sems_ref.at[slot],
            )

        def guarded_start(k, slot):
            ok, copy = copy_for(k, slot)

            @pl.when(ok)
            def _():
                copy.start()

        def guarded_wait(k, slot):
            ok, copy = copy_for(k, slot)

            @pl.when(ok)
            def _():
                copy.wait()

        for k in range(_PREFETCH_DEPTH):
            guarded_start(k, k)

        b_blk = b_ref[:].astype(jnp.float32)  # (C, THP, LANE)
        lane = jax.lax.broadcasted_iota(jnp.int32, (thp, LANE), 1)

        d_coh, y_coh, x_coh = di_ref[:], oyi_ref[:], oxi_ref[:]
        d_app = jnp.full((thp, LANE), jnp.inf, jnp.float32)
        y_app = jnp.zeros((thp, LANE), jnp.int32)
        x_app = jnp.zeros((thp, LANE), jnp.int32)
        for k in range(K_TOTAL):
            slot = k % _PREFETCH_DEPTH
            guarded_wait(k, slot)
            ok, sy, sx = scalars(k)
            xr = sx % LANE
            rot_amt = (LANE - xr) % LANE

            d = jnp.zeros((thp, LANE), jnp.float32)
            for g, (_sp, chans) in enumerate(groups):
                acc = None
                for c in chans:
                    # Two adjacent lane blocks -> rotate -> select: the
                    # unaligned 128-lane window [sx, sx+128) of plane c.
                    # Packed slots hold the block pair as sublanes
                    # 2c/2c+1 of the single fetched entry (a STATIC
                    # sublane-pair slice — the same op class as the
                    # unpacked path's static channel index); either way
                    # blk is (thp, 2, LANE) with axis 1 the block pair.
                    if packed:
                        blk = slots_ref[slot, :, 0, 2 * c : 2 * c + 2, :]
                    else:
                        blk = slots_ref[slot, :, :, c, :]
                    rot = pltpu.roll(blk, rot_amt, 2)
                    al = jnp.where(
                        lane < LANE - xr, rot[:, 0, :], rot[:, 1, :]
                    ).astype(jnp.float32)
                    if cand_dtype == "int8":
                        # Dequantize next to the distance math: the
                        # slot holds the static-affine int8 grid
                        # (prepare_a_planes); same formula as the
                        # host-side dequant, so an int8 sweep is
                        # bit-identical to the f32 sweep run on
                        # dequantized planes (test-pinned).
                        al = (al + _Q_ZERO) * (1.0 / _Q_SCALE)
                    dq = b_blk[c] - al
                    dq = dq * dq
                    acc = dq if acc is None else acc + dq
                # Separable window sum as two banded contractions on the
                # MXU (HIGHEST precision: bf16x6 passes, f32-accurate —
                # the interpret-mode oracle tests compare at rtol 1e-4
                # and the exact-metric merge downstream assumes a sane
                # kernel metric).
                xs = jax.lax.dot_general(
                    acc,
                    wx_ref[g],
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
                d = d + jax.lax.dot_general(
                    wy_ref[g],
                    xs,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
            d = jnp.where(ok, d, jnp.inf)
            oy_out = sy + row0 - ty0
            ox_out = sx - tx0
            if k < K_COHERENT:
                acc_c = d < d_coh
                d_coh = jnp.where(acc_c, d, d_coh)
                y_coh = jnp.where(acc_c, oy_out, y_coh)
                x_coh = jnp.where(acc_c, ox_out, x_coh)
            else:
                acc_a = d < d_app
                d_app = jnp.where(acc_a, d, d_app)
                y_app = jnp.where(acc_a, oy_out, y_app)
                x_app = jnp.where(acc_a, ox_out, x_app)
            if k + _PREFETCH_DEPTH < K_TOTAL:
                guarded_start(k + _PREFETCH_DEPTH, slot)

        take_app = d_app * coh_factor < d_coh
        do_ref[:] = jnp.where(take_app, d_app, d_coh)
        oyo_ref[:] = jnp.where(take_app, y_app, y_coh)
        oxo_ref[:] = jnp.where(take_app, x_app, x_coh)

    return kernel


def candidate_dma_bytes_per_fetch(
    n_chan: int, thp: int, packed: Optional[bool] = None,
    cand_dtype: Optional[str] = None,
) -> Tuple[int, int]:
    """(moved, useful) HBM bytes of ONE candidate-window DMA.

    `useful` is the window content both layouts deliver: 2 lane blocks x
    n_chan channels x thp rows at the table itemsize.  `moved` adds the
    physical sublane pad of the fetched entry's trailing
    (sublanes, 128) tile — packed fetches 1 entry of 2C sublanes,
    unpacked fetches 2 entries of C->granule-padded sublanes.  The
    sublane granule is dtype-dependent: 8 for the f32 ("bf16" mode)
    planes, 32 for int8 — which makes the int8 fetch TILE-GRANULE-BOUND
    at the headline's 4 channels (2C=8 sublanes pad to 32, so moved
    bytes exactly equal the f32 fetch; int8 only pays once 2C >= 32,
    i.e. the steerable 16+-channel sets — recorded in QUANT_r11.json;
    the compressed path's byte win at C=4 comes from the PCA prune).
    The ONE byte model shared by the kernel's telemetry counters and
    bench.py's roofline accounting, so the published efficiency claim
    and the observable counters cannot drift."""
    packed = resolve_packed(packed)
    dt = resolve_cand_dtype(cand_dtype)
    item = 1 if dt == "int8" else 4
    gran = 32 if dt == "int8" else 8
    useful = thp * 2 * n_chan * LANE * item
    if packed:
        moved = thp * (-(-2 * n_chan // gran) * gran) * LANE * item
    else:
        moved = thp * 2 * (-(-n_chan // gran) * gran) * LANE * item
    return moved, useful


# Sample pixels per tile for the coarse pre-prune ranking: the coarse
# distance of a tile-shared candidate is the summed projected-feature
# SSD at a 2x2 subgrid of quarter positions — one pixel is too noisy a
# proxy for a 64x124 tile, a dense evaluation would defeat the prune.
_PRUNE_SAMPLES = 4


def coarse_dma_bytes_per_row(k: int, itemsize: int = 4) -> Tuple[int, int]:
    """(moved, useful) HBM bytes of ONE coarse candidate-row fetch of
    the (Na, k) PCA-projected table.  `useful` is the k projected dims
    the ranking consumes; `moved` is the 128-lane-padded row XLA's
    gather lowering transfers (a k<=128 table tiles to 128 lanes — the
    same padded-row fact the polish model states).  The ONE coarse
    byte model shared by the prune's telemetry counters, bench.py's
    compressed sweep model, and the sentinel's coarse ledger."""
    if not 0 < k <= LANE:
        raise ValueError(f"coarse dims {k} outside (0, {LANE}]")
    return LANE * itemsize, k * itemsize


def tile_sample_positions(geom: TileGeometry, h: int, w: int):
    """(qy, qx), each (n_ty, n_tx, _PRUNE_SAMPLES) int32: the absolute
    B-image sample pixels the coarse prune ranks candidates at — a 2x2
    quarter-position subgrid per tile, clipped to the image (edge tiles
    sample their valid interior)."""
    th, tw = geom.tile_h, geom.tile_w
    sy = jnp.asarray([th // 4, th // 4, (3 * th) // 4, (3 * th) // 4])
    sx = jnp.asarray([tw // 4, (3 * tw) // 4, tw // 4, (3 * tw) // 4])
    qy = jnp.clip(
        (jnp.arange(geom.n_ty) * th)[:, None, None] + sy[None, None, :],
        0, h - 1,
    )
    qx = jnp.clip(
        (jnp.arange(geom.n_tx) * tw)[None, :, None] + sx[None, None, :],
        0, w - 1,
    )
    return (
        jnp.broadcast_to(qy, (geom.n_ty, geom.n_tx, _PRUNE_SAMPLES)),
        jnp.broadcast_to(qx, (geom.n_ty, geom.n_tx, _PRUNE_SAMPLES)),
    )


def prune_candidates(
    cand_y: jnp.ndarray,
    cand_x: jnp.ndarray,
    cand_valid: jnp.ndarray,
    proj_b_tiles: jnp.ndarray,
    qy: jnp.ndarray,
    qx: jnp.ndarray,
    proj_a_flat: jnp.ndarray,
    ha: int,
    wa: int,
    m_keep: int,
) -> jnp.ndarray:
    """PCA coarse-distance pre-prune (round 11, stage 2): rank each
    tile's K_TOTAL shared candidate offsets by their summed projected-
    feature SSD at the tile's sample pixels and return a cand_valid
    mask keeping only the top `m_keep` — already-invalid (dedup/out-of-
    range) candidates rank at +inf and never displace a valid one, so
    when fewer than m_keep are valid all valid candidates survive.

    The mask feeds tile_sweep's existing pl.when(ok) DMA skip, so a
    pruned candidate's window bytes never move: the byte model becomes
    K_TOTAL x coarse_row_bytes + m_keep x exact_fetch_bytes per tile.
    The kappa split is positional and pruning never reorders slots, so
    a surviving coherent candidate keeps its coherent accept factor.
    `proj_b_tiles` is (n_ty, n_tx, S, k) — the projected B rows at
    `tile_sample_positions` — and `proj_a_flat` the (Ha*Wa, k)
    projected A table (ops/pca.py: same basis, fit on the A side).
    Trace-time coarse-row counters mirror the candidate-DMA pair
    (telemetry/sentinel.py coarse ledger)."""
    from ..telemetry.metrics import (
        count_coarse_dma_bytes,
        count_coarse_dma_rows,
    )

    k = proj_a_flat.shape[-1]
    itemsize = jnp.dtype(proj_a_flat.dtype).itemsize
    py = jnp.clip(qy[..., None, :] + cand_y[..., :, None], 0, ha - 1)
    px = jnp.clip(qx[..., None, :] + cand_x[..., :, None], 0, wa - 1)
    n_rows = int(np.prod(py.shape))
    moved, useful = coarse_dma_bytes_per_row(k, itemsize)
    count_coarse_dma_bytes(
        useful=n_rows * useful, padded=n_rows * (moved - useful)
    )
    count_coarse_dma_rows(n_rows, k, itemsize)
    rows = jnp.take(
        proj_a_flat, (py * wa + px).reshape(-1), axis=0
    ).reshape(*py.shape, k)
    diff = rows.astype(jnp.float32) - proj_b_tiles[..., None, :, :].astype(
        jnp.float32
    )
    d = jnp.sum(diff * diff, axis=(-1, -2))  # (n_ty, n_tx, K_TOTAL)
    d = jnp.where(cand_valid > 0, d, jnp.inf)
    # Exact top-M via double argsort (rank of each slot in the coarse
    # ordering); stable sort keeps earlier slots on ties, which biases
    # survival toward the coherent end of the positional split.
    order = jnp.argsort(d, axis=-1)
    rank = jnp.argsort(order, axis=-1)
    keep = rank < m_keep
    return (keep & (cand_valid > 0)).astype(jnp.int32)


def tile_sweep(
    a_planes: jnp.ndarray,
    b_blocked: jnp.ndarray,
    cand_y: jnp.ndarray,
    cand_x: jnp.ndarray,
    off_y: jnp.ndarray,
    off_x: jnp.ndarray,
    dist: jnp.ndarray,
    band: Optional[jnp.ndarray] = None,
    cand_valid: Optional[jnp.ndarray] = None,
    *,
    specs: Tuple[ChannelSpec, ...],
    geom: TileGeometry,
    ha: int,
    wa: int,
    coh_factor: float,
    interpret: bool = False,
    packed: Optional[bool] = None,
    cand_dtype: Optional[str] = None,
    cand_budget: Optional[int] = None,
):
    """One propagate+random-search sweep over every tile, against the A
    band described by `band` = (row0, rows_own) int32 (None: all of A).

    `a_planes` is ONE array from `prepare_a_planes` — built with the
    SAME `packed`/`cand_dtype` choices passed here (all default to the
    module resolution points); it stays in HBM (`memory_space=ANY`) and
    the kernel DMA-streams each candidate's window from it (int8 slots
    dequantize in-kernel next to the distance math).
    `off_y/off_x/dist` are halo-blocked state planes; `dist` is carried
    in the kernel's metric across sweeps (monotone non-increasing per
    pixel).  `cand_valid` is the dedup mask the samplers produce (None:
    computed here — the samplers hoist it so multi-band callers don't
    recompute it per band call); a pruned mask (`prune_candidates`)
    rides the same operand.  `cand_budget` is the STATIC per-tile
    exact-fetch bound the mask enforces (the prune's M) — it only
    prices the trace-time DMA counters (the runtime skip is the mask),
    so the ledger stays exact on the compressed path.
    """
    return _tile_sweep_jit(
        a_planes, b_blocked, cand_y, cand_x, off_y, off_x, dist, band,
        cand_valid, specs=specs, geom=geom, ha=ha, wa=wa,
        coh_factor=coh_factor, interpret=interpret,
        packed=resolve_packed(packed),
        cand_dtype=resolve_cand_dtype(cand_dtype),
        cand_budget=cand_budget,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "specs", "geom", "ha", "wa", "coh_factor", "interpret", "packed",
        "cand_dtype", "cand_budget",
    ),
)
def _tile_sweep_jit(
    a_planes, b_blocked, cand_y, cand_x, off_y, off_x, dist, band,
    cand_valid, *, specs, geom, ha, wa, coh_factor, interpret, packed,
    cand_dtype, cand_budget,
):
    from ..telemetry.metrics import (
        count_candidate_dma_bytes,
        count_candidate_dma_fetches,
        count_kernel_launch,
    )

    count_kernel_launch("tile_sweep")  # trace-time count (see helper)

    expect_dtype = jnp.int8 if cand_dtype == "int8" else jnp.float32
    if a_planes.dtype != expect_dtype:
        raise ValueError(
            f"a_planes dtype {a_planes.dtype} does not match cand_dtype "
            f"{cand_dtype!r} (expected {expect_dtype.__name__}) — both "
            "sides of the prepare/sweep contract must resolve the same "
            "compression mode"
        )
    thp = geom.thp
    n_ty, n_tx = geom.n_ty, geom.n_tx
    # True channel count comes from the spec (the packed layout's
    # sublane axis is 2C, so a_planes.shape[2] is NOT the channel count
    # there).
    n_chan = len(specs)
    # Useful vs padded candidate-DMA bytes of this traced sweep, priced
    # at the resolved table dtype over the per-tile exact-fetch budget
    # (K_TOTAL, or the prune's M when a cand_budget is declared — the
    # runtime pl.when(ok) skip makes the moved figure an upper bound
    # for production sweeps, exact for the all-valid bench harness;
    # same caveat as the bench byte model).
    budget = K_TOTAL if cand_budget is None else min(cand_budget, K_TOTAL)
    n_fetch = n_ty * n_tx * budget
    moved_b, useful_b = candidate_dma_bytes_per_fetch(
        n_chan, thp, packed, cand_dtype
    )
    count_candidate_dma_bytes(
        useful=n_fetch * useful_b,
        padded=n_fetch * (moved_b - useful_b),
        dtype=cand_dtype,
    )
    # Structural twin of the byte counter: the fetch count plus the
    # geometry that prices a fetch, so the run sentinel can recompute
    # the expected bytes from the shared model and hold the two series
    # together (telemetry/sentinel.py candidate-DMA check).
    count_candidate_dma_fetches(
        n_fetch, n_chan, thp, resolve_packed(packed), cand_dtype
    )
    if band is None:
        band = jnp.asarray([0, ha], jnp.int32)
    if cand_valid is None:
        cand_valid = candidate_valid_mask(cand_y, cand_x)

    # Flatten the candidate tables to (n_tiles -> pad 8, K) for the
    # 8-row SMEM blocking (see in_specs below).
    n_tiles = n_ty * n_tx
    pad8 = (-n_tiles) % 8
    cand_y = jnp.pad(
        cand_y.reshape(n_tiles, K_TOTAL), ((0, pad8), (0, 0))
    )
    cand_x = jnp.pad(
        cand_x.reshape(n_tiles, K_TOTAL), ((0, pad8), (0, 0))
    )
    cand_valid = jnp.pad(
        cand_valid.reshape(n_tiles, K_TOTAL), ((0, pad8), (0, 0))
    )

    # Banded window matrices, one (Wx, Wy) pair per spec group; constant
    # across the grid, so the pipeline fetches them into VMEM once.
    wx_np, wy_np = window_matrices(specs, thp)
    wx = jnp.asarray(wx_np)
    wy = jnp.asarray(wy_np)

    kernel = _make_kernel(
        specs, geom, ha, wa, coh_factor, packed, cand_dtype
    )
    state_blk = lambda i, j: (i, j)  # noqa: E731
    out = pl.pallas_call(
        kernel,
        grid=(n_ty, n_tx),
        in_specs=[
            # Band bounds (row0, rows_valid) as SMEM scalars: dynamic
            # operands, so one compiled kernel serves every band.
            pl.BlockSpec((2,), lambda i, j: (0,), memory_space=pltpu.SMEM),
            # Candidate tables blocked into SMEM 8 tile-rows at a time:
            # a whole-array window ((n_tiles, K) i32) overflows the 1 MB
            # SMEM once the grid passes ~1300 tiles (4096^2 B'), and
            # Mosaic requires the trailing block dims be 8/equal-
            # divisible, so each grid step maps to the 8-row group
            # containing its flat tile index and selects its row.
            pl.BlockSpec(
                (8, K_TOTAL),
                lambda i, j, _n_tx=n_tx: ((i * _n_tx + j) // 8, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (8, K_TOTAL),
                lambda i, j, _n_tx=n_tx: ((i * _n_tx + j) // 8, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (8, K_TOTAL),
                lambda i, j, _n_tx=n_tx: ((i * _n_tx + j) // 8, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                wx.shape, lambda i, j: (0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                wy.shape, lambda i, j: (0, 0, 0), memory_space=pltpu.VMEM
            ),
            # The A planes stay in HBM; the kernel streams candidate
            # windows from them with manual async copies.
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (n_chan, thp, LANE), lambda i, j: (0, i, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
            pl.BlockSpec((thp, LANE), state_blk, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_ty * thp, n_tx * LANE), jnp.int32),
            jax.ShapeDtypeStruct((n_ty * thp, n_tx * LANE), jnp.int32),
            jax.ShapeDtypeStruct((n_ty * thp, n_tx * LANE), jnp.float32),
        ],
        scratch_shapes=[
            # Candidate-window DMA slots, shaped to match the fetch:
            # packed = one (thp, 1, 2C, LANE) entry per candidate,
            # unpacked = the two-block (thp, 2, C, LANE) window; dtype
            # follows the table (int8 slots dequantize in-kernel).
            pltpu.VMEM(
                (_PREFETCH_DEPTH, thp, 1, 2 * n_chan, LANE)
                if packed
                else (_PREFETCH_DEPTH, thp, 2, n_chan, LANE),
                jnp.int8 if cand_dtype == "int8" else jnp.float32,
            ),
            pltpu.SemaphoreType.DMA((_PREFETCH_DEPTH,)),
        ],
        interpret=interpret,
    )(band, cand_y, cand_x, cand_valid, wx, wy, a_planes, b_blocked, off_y,
      off_x, dist)
    return out  # (off_y, off_x, dist) blocked


# ---------------------------------------------------------------------------
# VMEM budgeting / eligibility


def vmem_estimate(
    specs, ha: int, wa: int, n_bands: int = 1,
    packed: Optional[bool] = None,
    cand_dtype: Optional[str] = None,
) -> int:
    """PHYSICAL bytes one prepared A band array occupies in HBM (f32
    planes, trailing-tile sublane pad included), with the TILE_H-1
    ownership-overlap rows banding adds (prepare_a_planes).  Since the
    round-4 HBM-streaming redesign this is HBM residency, not VMEM —
    it sizes the banded path's per-device A share for the spatial
    sharded-A runner, and the explicit-budget test path.  Round 7
    counts the tiled layout's actual footprint per A-plane layout:
    packed = (rows, Wq-1, 2C->8-mult, 128), unpacked =
    (rows, Wq, C->8-mult, 128) — at C=4 the two are within one
    Wq entry of equal (packing re-uses the pad the old layout already
    paid, it does not grow residency)."""
    packed = resolve_packed(packed)
    dt = resolve_cand_dtype(cand_dtype)
    item = 1 if dt == "int8" else 4
    gran = 32 if dt == "int8" else 8
    p = halo_for(specs)
    wq = -(-(wa + 2 * p) // LANE) + 1
    geom = tile_geometry(ha, wa, specs)
    extra = geom.thp - (geom.tile_h + 2 * p)
    overlap = geom.tile_h - 1 if n_bands > 1 else 0
    rows = band_rows(ha, n_bands) + overlap + 2 * p + extra
    n_chan = len(specs)
    if packed:
        return (
            rows * (wq - 1) * (-(-2 * n_chan // gran) * gran) * LANE * item
        )
    return rows * wq * (-(-n_chan // gran) * gran) * LANE * item


def kernel_vmem(specs, packed: Optional[bool] = None,
                cand_dtype: Optional[str] = None) -> int:
    """Static estimate of the kernel's VMEM per grid step (the A side is
    HBM-resident since the round-4 redesign, so this is the WHOLE VMEM
    story):

      - the B channel tile block, double-buffered across grid steps by
        the Pallas pipeline, plus its in-kernel f32 working copy;
      - 6 state planes (oy/ox/d in and out), double-buffered;
      - the candidate-window DMA slots — packed: (DEPTH, THP, 1,
        2C->8pad, LANE) f32 (the zero-pad fetch, ~half the unpacked
        slots at C=4); unpacked: (DEPTH, THP, 2, C->8pad, LANE);
      - the per-group banded window matrices (Wx (LANE, LANE) + Wy
        (THP, THP->LANE-padded) f32, fetched once);
      - evaluation temporaries (rotate result, aligned window, squared
        diff / group accumulator, matmul operand+result, two reduction
        chains — all (THP, LANE) f32).

    The SMEM candidate tables live in the separate 1 MB SMEM space and
    are not counted here.
    """
    packed = resolve_packed(packed)
    p = halo_for(specs)
    thp = -(-(TILE_H + 2 * p) // 8) * 8
    plane = thp * LANE * 4
    n_chan = len(specs)
    n_groups = len(spec_groups(specs))
    b_tiles = n_chan * plane * 3        # 2x pipeline buffers + f32 copy
    state = 6 * plane * 2               # 3 in + 3 out, double-buffered
    slot_bytes, _ = candidate_dma_bytes_per_fetch(
        n_chan, thp, packed, cand_dtype
    )
    slots = _PREFETCH_DEPTH * slot_bytes
    temps = 10 * plane                  # rotate/select/dq/matmul/chains
    wmats = n_groups * (LANE * LANE + thp * LANE) * 4
    return b_tiles + state + slots + temps + wmats


VMEM_SPEC = 16 * 1024 * 1024

# Bound on the banded path's band count (explicit-budget callers only:
# the spatial sharded-A runner and tests).  Single-device plans are
# always 1 band since the HBM-streaming redesign.
MAX_BANDS = 40


def _bands_needed(specs, ha: int, wa: int, budget: int) -> Optional[int]:
    """Smallest band count whose band array fits `budget`, or None.

    Any owned-row count >= 1 is valid under the ownership scheme (bands
    are resident TILE_H-1 rows past their owned range, so no clamp
    bound can invert)."""
    for n in range(1, MAX_BANDS + 1):
        if ha - (n - 1) * band_rows(ha, n) < 1:
            continue  # degenerate split: last band owns nothing
        if vmem_estimate(specs, ha, wa, n) <= budget:
            return n
    return None


def plan_channels(
    n_src: int, n_flt: int, cfg: SynthConfig, has_coarse: bool,
    h: int, w: int, ha: int, wa: int,
    budget: Optional[int] = None,
):
    """Resolve the kernel plan (specs, use_coarse, n_bands) for a level,
    or None when the level's geometry is kernel-ineligible.

    Since the round-4 HBM-streaming redesign the A side no longer
    competes for VMEM, so the default plan is always the FULL channel
    set (coarse context included whenever a coarser level exists) in a
    single band, at every image size — the former VMEM-driven landscape
    (1024^2 coarse/3 bands, 2048^2 coarse/10, 4096^2 fine-only/17,
    6144^2+ handed to the XLA gather path) is gone.  The static per-step
    VMEM (`kernel_vmem`, ~3 MB at 4 channels) is asserted against the
    16 MB spec as a sanity check.  An explicit `budget` forces the
    banded path (ownership-split A) — used by tests and by callers that
    shard A's rows across devices.

    Both the driver (A-plane prep) and the matcher (B-side prep) derive
    the same plan from the same static shapes, so the two sides always
    agree on the layout.
    """
    geom_ok = (
        min(h, w) >= LANE
        and ha >= TILE_H + 2 * halo_for(channel_specs(n_src, n_flt, cfg, False))
        and wa >= LANE
    )
    if not geom_ok:
        return None
    for coarse in ([True, False] if has_coarse else [False]):
        specs = channel_specs(n_src, n_flt, cfg, coarse)
        if budget is None:
            if kernel_vmem(specs) <= VMEM_SPEC // 2:
                return specs, coarse, 1
            continue
        n = _bands_needed(specs, ha, wa, budget)
        if n is not None:
            return specs, coarse, n
    return None
